"""graftflow: the static dataflow trio end to end.

Claims under test, by layer:

 * **model** (``seldon_tpu/servers/shape_lattice.py``): the closed-form
   ``dispatch_keys`` and the operational ``simulate_keys`` agree — zero
   holes (statically proven live retraces) and zero waste (warmup
   compiles nobody can reach) — over the full certifier grid; the
   historical blind spot (a prefix width bucketing to ``max_seq_len``
   when the top bucket fills the cache window) is IN the lattice;
 * **engine**: ``warmup()`` declares exactly ``static_lattice()``, and
   the blind-spot config serves a warm-prefix request with ZERO live
   retraces — the regression the certifier was built to prevent;
 * **shape-lattice pass**: dispatch-site keys are pinned to
   ``FAMILIES`` (tuple literal, registered tag, right arity), the
   ``_warm_key`` dispatcher must handle every family its file uses, and
   an injected closed-form/simulation disagreement surfaces as
   ``shape-lattice`` / ``shape-lattice-waste``;
 * **config-matrix pass**: branch-narrowing computes per-method
   (paged, chunked, prefix) reachability, flags flag-algebra-dead
   methods (waivable), and the real engine's dense-slab kill-list is
   non-empty with every entry provably paged_kv=False-only;
 * **shard pass**: undeclared PartitionSpec/collective axes, host pulls
   on shard_map/device_put results, and sharding-free ``jax.jit`` in
   sharding-centric files are flagged; engine-style files are exempt;
 * **wiring**: the checked-in ``docs/config_matrix.md`` is fresh, the
   CLI prints the kill-list headline, and the default lint target set
   covers the tools entry points.
"""

import os
import re
import subprocess
import sys
import textwrap
from pathlib import Path

import jax
import pytest

from seldon_tpu.models import init_params
from seldon_tpu.models.config import get_config
from seldon_tpu.models.sampling import SamplingParams
from seldon_tpu.servers import shape_lattice
from seldon_tpu.servers.engine import EngineConfig, InferenceEngine
from tools.graftlint import configmatrix, core, shapelattice, shardcheck
from tools.graftlint.__main__ import default_targets

REPO = Path(__file__).resolve().parents[1]

GREEDY = SamplingParams(temperature=0.0, max_new_tokens=8)


def lint(tmp_path, src, passes, name="fixture.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(src))
    files = core.load_tree([p], tmp_path)
    ctx = core.Context(tmp_path)
    return core.run_passes(files, ctx, passes)


def rules(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# Model: closed form vs operational simulation
# ---------------------------------------------------------------------------


def test_grid_closed_form_matches_simulation():
    specs = shape_lattice.grid()
    # Derived, not pinned: PR 13 and PR 15 each shipped a stale-pin fix
    # here; GRID_COUNT is now the single source of truth next to the
    # grid components it is computed from.
    assert len(specs) == shape_lattice.GRID_COUNT
    for spec in specs:
        holes, waste = shape_lattice.check_spec(spec)
        assert holes == [], (spec, holes)
        assert waste == [], (spec, waste)


def test_every_lattice_key_matches_registered_arity():
    for spec in shape_lattice.grid():
        for key in shape_lattice.dispatch_keys(spec):
            assert key[0] in shape_lattice.FAMILIES, key
            assert len(key) == shape_lattice.FAMILIES[key[0]], key


def test_window_width_prefix_is_in_lattice():
    # The historical warmup blind spot: buckets (16, 64) with
    # max_seq_len 64 — a 32-token trie match buckets to 64 == the cache
    # window, which a `b < max_seq_len` warmup filter skips.
    spec = shape_lattice.LatticeSpec(
        buckets=(16, 64), max_seq_len=64, max_slots=4, max_admit=2,
        decode_rungs=(4, 8), prefix=True)
    keys = shape_lattice.dispatch_keys(spec)
    assert ("admit-prefix", 64, 16, 1) in keys
    assert ("admit-prefix", 64, 16, 2) in keys
    # And the simulation derives the same fact independently.
    assert ("admit-prefix", 64, 16, 1) in shape_lattice.simulate_keys(spec)


def test_warmup_order_is_deterministic_and_ranked():
    spec = shape_lattice.grid()[0]
    keys = shape_lattice.dispatch_keys(spec)
    order = shape_lattice.warmup_order(keys)
    assert order == shape_lattice.warmup_order(set(order))
    assert order[0] == ("deactivate",)
    assert order[-1][0] == "decode"
    assert len(order) == len(keys)


def test_spec_validation():
    with pytest.raises(ValueError, match="ascend"):
        shape_lattice.LatticeSpec(
            buckets=(64, 32), max_seq_len=64, max_slots=4, max_admit=2,
            decode_rungs=(8,))
    with pytest.raises(ValueError, match="chunked"):
        shape_lattice.LatticeSpec(
            buckets=(32,), max_seq_len=64, max_slots=4, max_admit=2,
            decode_rungs=(8,), chunked=True)


# ---------------------------------------------------------------------------
# Engine: warmup declares static_lattice(); blind-spot regression
# ---------------------------------------------------------------------------


def test_warm_prefix_at_window_width_no_live_retrace(monkeypatch):
    """buckets (16, 64) under max_seq_len 64 + prefix cache: the second
    submission of a 48-token prompt admits behind a 32-token trie match,
    whose width buckets to 64 == max_seq_len. The pre-lattice warmup
    filtered widths with `b < max_seq_len` and skipped that variant, so
    this exact request paid a live retrace. Now warmup iterates
    dispatch_keys() and the lattice proves the variant in."""
    monkeypatch.setenv("COMPILE_LEDGER", "1")
    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    eng = InferenceEngine(params, cfg, EngineConfig(
        max_slots=4, max_seq_len=64, prompt_buckets=(16, 64),
        max_admit=2, prefix_cache=True))
    eng.warmup()

    # Warmup declared exactly the closed-form lattice, no ad-hoc keys.
    static = eng.static_lattice()
    comp = eng.debug_compile()
    assert comp["warmup_complete"] is True
    assert comp["declared_variants"] == len(static)
    dispatched = {e["key"] for e in comp["lattice"]}
    assert dispatched <= set(static)
    # The blind-spot variant is statically declared...
    assert "admit-prefix/64/16/1" in static

    eng.start()
    try:
        prompt = list(range(2, 50))  # 48 tokens: 3 trie blocks
        eng.generate_blocking(prompt, GREEDY)
        eng.generate_blocking(prompt, GREEDY)  # warm-prefix admission
        comp = eng.debug_compile()
        assert comp["live_retrace_count"] == 0, comp["live_retraces"]
        # ...and live traffic actually exercised a window-width prefix.
        hits = [e for e in comp["lattice"]
                if e["key"].startswith("admit-prefix/64/")]
        assert hits, sorted(e["key"] for e in comp["lattice"])
        assert all(e["declared"] for e in hits)
    finally:
        eng.stop()


def test_engine_lattice_spec_matches_config():
    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    eng = InferenceEngine(params, cfg, EngineConfig(
        max_slots=4, max_seq_len=64, prompt_buckets=(8, 32)))
    spec = eng.lattice_spec()
    assert spec.buckets == (8, 32)
    assert spec.max_seq_len == 64
    assert not (spec.paged or spec.chunked or spec.prefix)
    # static_lattice renders warmup_order(dispatch_keys) as key strings.
    want = [
        "/".join(str(p) for p in k)
        for k in shape_lattice.warmup_order(
            shape_lattice.dispatch_keys(spec))
    ]
    assert eng.static_lattice() == want


# ---------------------------------------------------------------------------
# shape-lattice pass: AST leg
# ---------------------------------------------------------------------------

LATTICE_BAD = """
    class Engine:
        def _dispatch(self, key, rid, tag):
            self._note_dispatch(key, rid, 0.1)
            self._note_dispatch((tag, 8), rid, 0.1)
            self._note_dispatch(("mystery", 8), rid, 0.1)
            self._note_dispatch(("decode", 8, 9), rid, 0.1)
"""

LATTICE_OK = """
    class Engine:
        def _dispatch(self, rid):
            self._note_dispatch(("decode", 8), rid, 0.1)
            self._note_dispatch(("admit", 32, 4), rid, 0.1)

        def _warm_key(self, key):
            kind = key[0]
            if kind == "decode":
                pass
            elif kind == "admit":
                pass
"""

WARM_GAP = """
    class Engine:
        def _dispatch(self, rid):
            self._note_dispatch(("decode", 8), rid, 0.1)
            self._note_dispatch(("cow",), rid, 0.1)

        def _warm_key(self, key):
            kind = key[0]
            if kind == "decode":
                pass
"""


def test_shapelattice_flags_unpinned_sites(tmp_path):
    fs = lint(tmp_path, LATTICE_BAD, [shapelattice.run])
    assert rules(fs) == ["shape-lattice"]
    assert len(fs) == 4
    msgs = " | ".join(f.message for f in fs)
    assert "not a non-empty tuple literal" in msgs
    assert "not a string constant" in msgs
    assert '"mystery" is not registered' in msgs
    assert "3 components here but FAMILIES registers 2" in msgs


def test_shapelattice_clean_sites(tmp_path):
    assert lint(tmp_path, LATTICE_OK, [shapelattice.run]) == []


def test_shapelattice_warm_key_must_cover_used_families(tmp_path):
    fs = lint(tmp_path, WARM_GAP, [shapelattice.run])
    assert len(fs) == 1
    assert fs[0].rule == "shape-lattice"
    assert "cow" in fs[0].message
    assert fs[0].qualname == "_warm_key"


def _numeric_leg(tmp_path, monkeypatch, grid_result):
    """Run the numeric leg on a minimal engine+model tree with an
    injected _check_grid result."""
    eng = tmp_path / "seldon_tpu" / "servers" / "engine.py"
    eng.parent.mkdir(parents=True, exist_ok=True)
    eng.write_text("class InferenceEngine:\n    pass\n")
    model = tmp_path / "seldon_tpu" / "servers" / "shape_lattice.py"
    model.write_text("def dispatch_keys(spec):\n    return set()\n")
    monkeypatch.setattr(shapelattice, "_check_grid", lambda: grid_result)
    files = core.load_tree([tmp_path / "seldon_tpu"], tmp_path)
    return core.run_passes(files, core.Context(tmp_path),
                           [shapelattice.run])


def test_shapelattice_numeric_hole_is_a_proven_retrace(tmp_path,
                                                       monkeypatch):
    fs = _numeric_leg(tmp_path, monkeypatch,
                      [("--X grid", [("chunk", 64, 2, 0)], [])])
    assert len(fs) == 1 and fs[0].rule == "shape-lattice"
    assert "static retrace proof" in fs[0].message
    assert fs[0].path == "seldon_tpu/servers/shape_lattice.py"


def test_shapelattice_numeric_waste_is_flagged(tmp_path, monkeypatch):
    fs = _numeric_leg(tmp_path, monkeypatch,
                      [("P-- grid", [], [("admit", 32, 8)])])
    assert len(fs) == 1 and fs[0].rule == "shape-lattice-waste"
    assert "warmup waste" in fs[0].message


def test_shapelattice_numeric_agreement_is_clean(tmp_path, monkeypatch):
    assert _numeric_leg(tmp_path, monkeypatch, [("--- grid", [], [])]) == []


# ---------------------------------------------------------------------------
# config-matrix pass
# ---------------------------------------------------------------------------

CM_FIXTURE = """
    class Engine:
        def __init__(self, ecfg):
            self.ecfg = ecfg
            self._paged = bool(ecfg)

        def warmup(self):
            pass

        def submit(self):
            if self._paged:
                self._paged_only()
                return
            self._dense_only()

        def _paged_only(self):
            self._both()

        def _dense_only(self):
            self._both()

        def _both(self):
            pass

        def _dead(self):
            pass
"""


def _cm_model(tmp_path, src, name="fixture.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(src))
    return configmatrix.analyze(core.load_tree([p], tmp_path))


def test_configmatrix_narrows_reachability(tmp_path):
    model = _cm_model(tmp_path, CM_FIXTURE)
    P = configmatrix._FLAGS["self._paged"]
    ALL = configmatrix.ALL
    assert model.reach["_paged_only"] == P
    assert model.reach["_dense_only"] == ALL & ~P
    assert model.reach["_both"] == ALL
    assert model.reach["_dead"] == 0
    assert model.kill_list() == ["_dense_only"]
    assert model.dead() == ["_dead"]


def test_configmatrix_dead_method_is_flagged_and_waivable(tmp_path):
    fs = lint(tmp_path, CM_FIXTURE, [configmatrix.run])
    assert [f.rule for f in fs] == ["config-matrix"]
    assert "_dead" in fs[0].message and "unreachable" in fs[0].message
    waived = CM_FIXTURE.replace(
        "def _dead(self):",
        "def _dead(self):  # graftlint: allow(config-matrix) external")
    assert lint(tmp_path, waived, [configmatrix.run]) == []


def _real_engine_model():
    files = core.load_tree(
        [REPO / "seldon_tpu" / "servers" / "engine.py"], REPO)
    model = configmatrix.analyze(files)
    assert model is not None
    return model


@pytest.mark.lint
def test_real_engine_kill_list_nonempty_and_dense_only():
    model = _real_engine_model()
    kill = model.kill_list()
    assert kill, "dense-slab kill-list empty — ROADMAP item 2 needle lost"
    dense = configmatrix._DENSE
    for name in kill:
        m = model.reach[name]
        assert m and not (m & ~dense), (name, bin(m))
    # The paged-path implementations must never land on the kill-list.
    assert "_paged_admit_impl" not in kill
    assert "_cow_copy_impl" not in kill


@pytest.mark.lint
def test_config_matrix_doc_is_fresh():
    # docs/config_matrix.md must match what --gen-config-matrix would
    # write for the real engine (the knobs-doc freshness idiom).
    want = configmatrix.generate_matrix_md(_real_engine_model())
    have = (REPO / "docs" / "config_matrix.md").read_text()
    assert have == want, "docs/config_matrix.md is stale: run " \
        "`python -m tools.graftlint --gen-config-matrix`"


# ---------------------------------------------------------------------------
# shard pass
# ---------------------------------------------------------------------------

AXIS_BAD = """
    import jax
    AXES = ("dp", "tp")

    def f(x, P):
        s = P("dp", "zz")
        y = jax.lax.psum(x, "rogue")
        return s, y
"""

AXIS_OK = """
    import jax
    AXES = ("dp", "tp")

    def f(x, P):
        s = P("dp", None)
        y = jax.lax.psum(x, "tp")
        return s, y
"""

PULL_BAD = """
    import numpy as np

    def g(mesh, f, xs, device_put):
        y = shard_map(f, mesh)(xs)
        z = device_put(xs)
        a = y.item()
        b = np.asarray(y)
        c = float(z)
        return a, b, c
"""

PULL_OK = """
    import numpy as np

    def g(compute, xs):
        y = compute(xs)
        return y.item(), np.asarray(y)
"""

JIT_BAD = """
    import jax
    from jax.sharding import PartitionSpec

    def h(f):
        return jax.jit(f)
"""

JIT_OK = """
    import jax
    from jax.sharding import PartitionSpec

    def h(f, shardings):
        return jax.jit(f, in_shardings=shardings)
"""

JIT_EXEMPT = """
    import jax

    def h(f):
        # engine-style file: no sharding vocabulary imported
        return jax.jit(f, donate_argnums=(0,))
"""


def test_shard_axis_undeclared_names(tmp_path):
    fs = lint(tmp_path, AXIS_BAD, [shardcheck.run])
    assert rules(fs) == ["shard-axis"]
    msgs = " | ".join(f.message for f in fs)
    assert '"zz"' in msgs and '"rogue"' in msgs


def test_shard_axis_declared_names_clean(tmp_path):
    assert lint(tmp_path, AXIS_OK, [shardcheck.run]) == []


def test_shard_axis_skipped_without_axes_decl(tmp_path):
    src = AXIS_BAD.replace('AXES = ("dp", "tp")', "")
    assert lint(tmp_path, src, [shardcheck.run]) == []


AXIS_ALIAS_BAD = """
    from jax.sharding import PartitionSpec

    AXES = ("dp", "tp")
    TP_AXIS = "tensor"
"""

AXIS_ALIAS_OK = """
    from jax.sharding import PartitionSpec

    AXES = ("dp", "tp")
    TP_AXIS = AXES[-1]
    DP_AXIS = "dp"
"""

AXIS_ALIAS_EXEMPT = """
    # Not a sharding file (no PartitionSpec/shard_map import): an _AXIS
    # constant here is not a mesh-axis alias.
    AXES = ("dp", "tp")
    RULE_AXIS = "shard-axis"
"""


def test_shard_axis_string_alias_outside_vocabulary(tmp_path):
    # graftmesh drift guard: a module-level *_AXIS alias re-declared as
    # a raw string must still name a declared mesh axis.
    fs = lint(tmp_path, AXIS_ALIAS_BAD, [shardcheck.run])
    assert rules(fs) == ["shard-axis"]
    assert "TP_AXIS" in fs[0].message and '"tensor"' in fs[0].message


def test_shard_axis_alias_derived_or_in_vocabulary_clean(tmp_path):
    assert lint(tmp_path, AXIS_ALIAS_OK, [shardcheck.run]) == []


def test_shard_axis_alias_non_sharding_file_exempt(tmp_path):
    assert lint(tmp_path, AXIS_ALIAS_EXEMPT, [shardcheck.run]) == []


def test_shard_host_pull_on_tainted_locals(tmp_path):
    fs = lint(tmp_path, PULL_BAD, [shardcheck.run])
    assert rules(fs) == ["shard-host-pull"]
    pulled = " | ".join(f.message for f in fs)
    assert "y.item()" in pulled
    assert "asarray(y)" in pulled
    assert "float(z)" in pulled


def test_shard_host_pull_untainted_clean(tmp_path):
    assert lint(tmp_path, PULL_OK, [shardcheck.run]) == []


PULL_TP_SHARDERS = """
    import numpy as np

    def g(mesh, cfg, params, state, tp_sharding):
        p = tp_sharding.shard_params(mesh, cfg, params)
        s = tp_sharding.shard_state(mesh, state)
        a = np.asarray(p)
        b = s.item()
        return a, b
"""


def test_shard_host_pull_on_tp_sharder_results(tmp_path):
    # graftmesh: shard_params / shard_state return NamedSharding-pinned
    # trees; pulling them to the host gathers the whole TP group.
    fs = lint(tmp_path, PULL_TP_SHARDERS, [shardcheck.run])
    assert rules(fs) == ["shard-host-pull"]
    pulled = " | ".join(f.message for f in fs)
    assert "asarray(p)" in pulled and "s.item()" in pulled


def test_shard_jit_without_shardings_in_sharding_file(tmp_path):
    fs = lint(tmp_path, JIT_BAD, [shardcheck.run])
    assert rules(fs) == ["shard-jit"]


def test_shard_jit_with_shardings_clean(tmp_path):
    assert lint(tmp_path, JIT_OK, [shardcheck.run]) == []


def test_shard_jit_engine_style_file_exempt(tmp_path):
    assert lint(tmp_path, JIT_EXEMPT, [shardcheck.run]) == []


@pytest.mark.lint
def test_real_parallel_tree_is_shard_clean():
    files = core.load_tree([REPO / "seldon_tpu" / "parallel"], REPO)
    fs = shardcheck.run(files, core.Context(REPO))
    assert fs == [], "\n".join(f.render() for f in fs)


@pytest.mark.lint
def test_real_graftmesh_layer_is_shard_clean():
    # The TP serving layer is scanned TOGETHER with parallel/ so its
    # P(...) specs and collectives are held to the real mesh.AXES
    # vocabulary (the axes declaration lives in parallel/mesh.py), and
    # the baseline stays empty — no waivers in the sharded layer.
    files = core.load_tree(
        [REPO / "seldon_tpu" / "parallel",
         REPO / "seldon_tpu" / "models" / "tp_sharding.py",
         REPO / "seldon_tpu" / "servers" / "mesh_engine.py",
         REPO / "seldon_tpu" / "servers" / "engine.py"], REPO)
    fs = shardcheck.run(files, core.Context(REPO))
    assert fs == [], "\n".join(f.render() for f in fs)


# ---------------------------------------------------------------------------
# CLI wiring
# ---------------------------------------------------------------------------


def _cli(*args, cwd=REPO):
    return subprocess.run(
        [sys.executable, "-m", "tools.graftlint", *args],
        cwd=cwd, capture_output=True, text=True,
        env={**os.environ, "PYTHONPATH": str(REPO)},
    )


@pytest.mark.lint
def test_cli_prints_kill_list_headline():
    r = _cli()
    assert r.returncode == 0, f"{r.stdout}\n{r.stderr}"
    m = re.search(r"dense-slab kill-list: (\d+) method", r.stdout)
    assert m, r.stdout
    assert int(m.group(1)) >= 1


def test_default_targets_cover_tools_entry_points():
    rels = {sf.rel for sf in core.load_tree(default_targets(REPO), REPO)}
    assert "tools/trace_view.py" in rels
    assert "tools/bench_compare.py" in rels
    assert "seldon_tpu/loadtester.py" in rels
    assert "seldon_tpu/servers/shape_lattice.py" in rels
