"""Deterministic fixed-output models for e2e tests (the reference's
fixed-model trick, testing/docker/fixed-model/ModelV1.py:10-21: hardwired
outputs let tests identify WHICH graph version served a request purely
from values + meta.requestPath)."""

import numpy as np


class ModelV1:
    def predict(self, X, names, meta=None):
        return np.tile([1.0, 2.0, 3.0, 4.0], (np.asarray(X).shape[0], 1))

    def tags(self):
        return {"version": "v1"}


class ModelV2:
    def predict(self, X, names, meta=None):
        return np.tile([5.0, 6.0, 7.0, 8.0], (np.asarray(X).shape[0], 1))

    def tags(self):
        return {"version": "v2"}


class DoublerTransformer:
    def transform_input(self, X, names, meta=None):
        return np.asarray(X) * 2.0

    def tags(self):
        return {"scaled": True}
