"""Analytics-stack consistency: every metric a dashboard panel or alert
rule queries must actually be exported by the code (reference ships
per-detector dashboards + alertmanager in seldon-core-analytics;
VERDICT r2 found the repo's dashboards referencing phantom names)."""

import glob
import json
import os
import re

import yaml

ANALYTICS = os.path.join(os.path.dirname(__file__), "..", "deploy", "analytics")

# Metric families genuinely exported by this codebase.
EXPORTED = {
    # runtime/metrics_server.py ServerMetrics
    "seldon_api_executor_server_requests_total",
    "seldon_api_executor_server_requests_seconds",  # histogram base
    "seldon_api_model_feedback_reward_total",
    "seldon_api_model_feedback_reward_negative_total",
    "seldon_api_model_feedback_total",
    "seldon_graph_ready",
    # components/outliers.py _TagMetricsMixin.metrics()
    "outlier_score_max",
    "outlier_score_mean",
    "outlier_threshold",
    "outliers_total",
    # servers/jaxserver.py metrics()
    "jaxserver_mean_ttft_ms",
    "jaxserver_tokens_out",
    "jaxserver_completed",
    "jaxserver_slots_busy",
    "jaxserver_decode_dispatches",
    "jaxserver_decode_steps",
}
# Series emitted by external exporters we integrate with (kube-state-metrics).
EXTERNAL = {"kube_statefulset_status_replicas_ready", "kube_statefulset_replicas"}

_PROM_FUNCS = {
    "sum", "rate", "irate", "avg", "max", "min", "count", "histogram_quantile",
    "by", "le", "deriv", "increase", "label_values", "instance", "on",
    "group_left", "group_right", "abs", "clamp_min", "clamp_max", "vector",
}


def _metric_names(expr: str):
    for name in re.findall(r"[a-zA-Z_:][a-zA-Z0-9_:]*", expr):
        if name in _PROM_FUNCS or name.startswith("$"):
            continue
        if re.match(r"^[0-9.]+$", name):
            continue
        # label matchers appear inside {...}; strip by only taking names
        # that look like series (contain '_' and not pure label keys).
        yield name


def _series_in(expr: str):
    # Remove label-matcher blocks so label keys/values don't false-positive.
    cleaned = re.sub(r"\{[^}]*\}", "", expr)
    for name in _metric_names(cleaned):
        if "_" in name:
            yield name


def _strip_histogram_suffix(name: str) -> str:
    for suf in ("_bucket", "_count", "_sum"):
        if name.endswith(suf):
            return name[: -len(suf)]
    return name


def test_dashboard_exprs_reference_exported_metrics():
    dashboards = glob.glob(os.path.join(ANALYTICS, "grafana-*.json"))
    assert len(dashboards) >= 6, dashboards  # serving + 4 detectors + rewards
    for path in dashboards:
        with open(path) as f:
            dash = json.load(f)
        exprs = [
            t["expr"]
            for p in dash.get("panels", [])
            for t in p.get("targets", [])
        ] + [
            v["query"]
            for v in dash.get("templating", {}).get("list", [])
            if v.get("type") == "query"
        ]
        assert exprs, f"{path} has no queries"
        for expr in exprs:
            e = expr.replace("label_values(", "").rstrip(")")
            for name in _series_in(e):
                base = _strip_histogram_suffix(name)
                assert base in EXPORTED | EXTERNAL, (
                    f"{os.path.basename(path)} queries {name!r} which nothing exports"
                )


def test_detector_dashboards_cover_every_family():
    families = ["mahalanobis", "vae", "isolation-forest", "seq2seq-lstm"]
    for fam in families:
        path = os.path.join(ANALYTICS, f"grafana-outlier-detection-{fam}.json")
        assert os.path.exists(path), f"missing dashboard for {fam}"
        with open(path) as f:
            dash = json.load(f)
        exprs = " ".join(
            t["expr"] for p in dash["panels"] for t in p["targets"]
        )
        assert "outlier_score_max" in exprs
        assert "outlier_threshold" in exprs


def test_alert_rules_reference_exported_metrics():
    with open(os.path.join(ANALYTICS, "prometheus-rules.yaml")) as f:
        rules = yaml.safe_load(f)
    exprs = [
        r["expr"]
        for g in rules["spec"]["groups"]
        for r in g["rules"]
    ]
    assert len(exprs) >= 5
    for expr in exprs:
        for name in _series_in(expr):
            base = _strip_histogram_suffix(name)
            assert base in EXPORTED | EXTERNAL, (
                f"alert rule queries {name!r} which nothing exports"
            )


def test_alertmanager_config_parses_and_receives():
    docs = list(yaml.safe_load_all(
        open(os.path.join(ANALYTICS, "alertmanager.yaml"))
    ))
    cm = [d for d in docs if d and d["kind"] == "ConfigMap"][0]
    cfg = yaml.safe_load(cm["data"]["alertmanager.yml"])
    assert cfg["route"]["receiver"] == "default"
    names = {r["name"] for r in cfg["receivers"]}
    assert cfg["route"]["receiver"] in names
    for route in cfg["route"].get("routes", []):
        assert route["receiver"] in names
    kinds = {d["kind"] for d in docs if d}
    assert kinds == {"ConfigMap", "Deployment", "Service"}


def test_exported_set_matches_code():
    """Guard the EXPORTED list against drift: the names must literally
    appear in the modules that register them."""
    import inspect

    from seldon_tpu.components import outliers
    from seldon_tpu.runtime import metrics_server
    from seldon_tpu.servers import jaxserver

    source = (
        inspect.getsource(metrics_server)
        + inspect.getsource(outliers)
        + inspect.getsource(jaxserver)
    )
    for name in EXPORTED:
        assert name in source, f"{name} not found in exporting modules"
