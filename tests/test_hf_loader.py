"""HF Llama checkpoint loader: LOGIT PARITY against transformers' own
forward pass on a randomly initialized tiny Llama — the strongest
possible check that weight mapping, transposes, RoPE convention, GQA
grouping, and norms all line up."""

import numpy as np
import pytest

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")


@pytest.fixture(scope="module")
def tiny_hf_checkpoint(tmp_path_factory):
    cfg = transformers.LlamaConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=3,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=64,
        rope_theta=10000.0,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(cfg)
    model.eval()
    path = tmp_path_factory.mktemp("hf-llama")
    model.save_pretrained(path, safe_serialization=True)
    return str(path), model


def test_hf_config_mapping(tiny_hf_checkpoint):
    from seldon_tpu.servers.hf_loader import load_hf_checkpoint

    path, _ = tiny_hf_checkpoint
    params, cfg = load_hf_checkpoint(path, dtype="float32")
    assert cfg.n_layers == 3 and cfg.n_heads == 4 and cfg.n_kv_heads == 2
    assert params["blocks"]["wq"].shape == (3, 64, 64)
    assert params["blocks"]["wk"].shape == (3, 64, 32)  # GQA: 2 kv heads
    assert params["blocks"]["w_gate"].shape == (3, 64, 128)
    assert params["lm_head"].shape == (64, 128)


def test_hf_logit_parity(tiny_hf_checkpoint):
    import dataclasses

    import jax.numpy as jnp

    from seldon_tpu.models import forward
    from seldon_tpu.servers.hf_loader import load_hf_checkpoint

    path, model = tiny_hf_checkpoint
    params, cfg = load_hf_checkpoint(path, dtype="float32")
    cfg = dataclasses.replace(cfg, dtype="float32")

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 128, size=(2, 10))
    with torch.no_grad():
        hf_logits = model(torch.tensor(tokens)).logits.numpy()
    ours = np.asarray(forward(params, jnp.asarray(tokens), cfg))
    # f32 end-to-end: tight tolerance proves the mapping is exact.
    np.testing.assert_allclose(ours, hf_logits, rtol=2e-4, atol=2e-4)


def test_hf_decode_matches_teacher_forcing(tiny_hf_checkpoint):
    """Greedy cached decode on the loaded weights equals transformers'
    greedy generate — the full serving path on an HF checkpoint."""
    import dataclasses

    import jax.numpy as jnp

    from seldon_tpu.models import transformer
    from seldon_tpu.servers.hf_loader import load_hf_checkpoint

    path, model = tiny_hf_checkpoint
    params, cfg = load_hf_checkpoint(path, dtype="float32")
    cfg = dataclasses.replace(cfg, dtype="float32")

    prompt = [[5, 17, 99, 3]]
    with torch.no_grad():
        hf_out = model.generate(
            torch.tensor(prompt), max_new_tokens=6, do_sample=False,
            pad_token_id=0,
        ).numpy()[0, 4:].tolist()

    cache = transformer.init_cache(cfg, 1, 32)
    logits, cache = transformer.prefill(
        params, jnp.asarray(prompt, jnp.int32), jnp.array([4]), cache, cfg
    )
    toks = [int(jnp.argmax(logits[0]))]
    pos = jnp.array([4], jnp.int32)
    for _ in range(5):
        lg, cache = transformer.decode_step(
            params, jnp.array([toks[-1]], jnp.int32), pos, cache, cfg
        )
        toks.append(int(jnp.argmax(lg[0])))
        pos = pos + 1
    assert toks == hf_out, (toks, hf_out)


def test_rejects_non_llama(tmp_path):
    import json

    from seldon_tpu.servers.hf_loader import config_from_hf

    with pytest.raises(ValueError):
        config_from_hf({"model_type": "gpt2"})


def test_jaxserver_serves_hf_checkpoint(tiny_hf_checkpoint):
    """JAXServer end-to-end on an HF checkpoint directory: load -> engine
    -> generate."""
    from seldon_tpu.servers.jaxserver import JAXServer

    path, _ = tiny_hf_checkpoint
    srv = JAXServer(model_uri=path, max_slots=2, max_seq_len=48)
    srv.load()
    try:
        out = srv.generate({"prompt": "ab", "max_new_tokens": 4, "seed": 1})
        assert out["completion_tokens"] >= 1
        assert srv.cfg.n_layers == 3  # config came from config.json
    finally:
        srv.engine.stop()


# ---------------------------------------------------------------------------
# RoPE scaling (Llama-3.1/3.2 long-context checkpoints)
# ---------------------------------------------------------------------------


def test_rope_scaling_llama3_matches_transformers():
    """inv_freq parity with transformers' _compute_llama3_parameters —
    the formula long-context Llama-3.1+ checkpoints declare. Ignoring it
    produces subtly wrong logits at every position (ADVICE r2)."""
    from seldon_tpu.models import transformer
    from seldon_tpu.servers.hf_loader import config_from_hf

    hf = {
        "model_type": "llama",
        "vocab_size": 128,
        "hidden_size": 64,
        "intermediate_size": 128,
        "num_hidden_layers": 2,
        "num_attention_heads": 4,
        "num_key_value_heads": 2,
        "max_position_embeddings": 131072,
        "rope_theta": 500000.0,
        "rope_scaling": {
            "rope_type": "llama3",
            "factor": 8.0,
            "low_freq_factor": 1.0,
            "high_freq_factor": 4.0,
            "original_max_position_embeddings": 8192,
        },
    }
    cfg = config_from_hf(hf)
    assert cfg.rope_scaling_type == "llama3"
    ours = np.asarray(transformer.rope_frequencies(cfg))

    from transformers.modeling_rope_utils import ROPE_INIT_FUNCTIONS

    hf_cfg = transformers.LlamaConfig(**hf)
    theirs, att = ROPE_INIT_FUNCTIONS["llama3"](hf_cfg, device="cpu")
    assert att == 1.0  # llama3 scheme has no attention scaling
    np.testing.assert_allclose(ours, theirs.numpy(), rtol=1e-6)
    # And the scaling actually bites: lowest frequency slowed ~8x.
    unscaled = 1.0 / (500000.0 ** (np.arange(8, dtype=np.float64) / 8))
    assert ours[-1] < unscaled[-1] / 4


def test_rope_scaling_linear_and_unknown():
    from seldon_tpu.models import transformer
    from seldon_tpu.models.config import get_config
    from seldon_tpu.servers.hf_loader import config_from_hf

    base = {
        "model_type": "llama", "vocab_size": 128, "hidden_size": 64,
        "intermediate_size": 128, "num_hidden_layers": 2,
        "num_attention_heads": 4, "rope_theta": 10000.0,
    }
    lin = config_from_hf({**base, "rope_scaling": {"type": "linear", "factor": 4.0}})
    plain = config_from_hf(base)
    np.testing.assert_allclose(
        np.asarray(transformer.rope_frequencies(lin)),
        np.asarray(transformer.rope_frequencies(plain)) / 4.0,
        rtol=1e-6,
    )
    with pytest.raises(ValueError, match="rope_scaling"):
        config_from_hf(
            {**base, "rope_scaling": {"rope_type": "yarn", "factor": 2.0}}
        )
    # rope_type=default passes through unscaled.
    dflt = config_from_hf(
        {**base, "rope_scaling": {"rope_type": "default"}}
    )
    assert dflt.rope_scaling_type is None
