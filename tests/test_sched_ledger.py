"""Sched ledger tests: per-boundary waste attribution + goodput decomposition.

The load-bearing claims, in test form:
 * env gating follows the None-attribute idiom (SCHED_LEDGER) and a
   disabled engine keeps every ``sched_*`` stats counter at zero;
 * the ledger is pure observation — greedy outputs are BIT-IDENTICAL
   with the ledger on vs off across all three dispatch paths (dense,
   paged-KV, chunked prefill);
 * the conservation invariant holds under real traffic: useful +
   bucket-pad + group-pad tokens re-sum to the dispatched cells, the
   per-shape rows re-sum to the totals, and ``audit()`` (run at every
   fetch boundary) reports zero breaches — while a ledger fed
   inconsistent numbers DOES breach (the audit is not vacuous);
 * unit semantics — wave-scoped boundary waste, frag only on starved
   budget passes, and the clamped priority attribution of queue wait.
"""

import time

import jax
import pytest

from seldon_tpu.models import init_params
from seldon_tpu.models.config import get_config
from seldon_tpu.models.sampling import SamplingParams
from seldon_tpu.servers import sched_ledger
from seldon_tpu.servers.engine import EngineConfig, InferenceEngine

GREEDY = SamplingParams(temperature=0.0, max_new_tokens=6)
# Mixed lengths so admission groups carry real bucket + group padding.
PROMPTS = [list(range(2, 2 + n)) for n in (5, 12, 24, 7)]

# The three dispatch paths whose outputs the ledger must not perturb.
MODES = {
    "dense": {},
    "paged": dict(paged_kv=True, kv_block=16, kv_pool_blocks=12,
                  prompt_buckets=(16, 32)),
    "chunked": dict(chunked_prefill=True, prefill_chunk=8, prefix_block=8),
}


def _engine(start=True, **ekw):
    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    ekw.setdefault("max_slots", 4)
    ekw.setdefault("max_seq_len", 64)
    ekw.setdefault("prompt_buckets", (8, 32))
    eng = InferenceEngine(params, cfg, EngineConfig(**ekw))
    if start:
        eng.start()
    return eng


def _collect(eng, prompts):
    """Submit concurrently (so admissions actually group), then drain
    each stream to its full greedy token list."""
    qs = [eng.submit(p, GREEDY) for p in prompts]
    outs = []
    for q in qs:
        toks = []
        while True:
            item = q.get(timeout=300)
            if item is None:
                break
            toks.extend(item["tokens"])
        outs.append(toks)
    return outs


# ---------------------------------------------------------------------------
# Unit semantics
# ---------------------------------------------------------------------------


def test_from_env_gating(monkeypatch):
    monkeypatch.delenv("SCHED_LEDGER", raising=False)
    assert sched_ledger.from_env() is None
    monkeypatch.setenv("SCHED_LEDGER", "0")
    assert sched_ledger.from_env() is None
    monkeypatch.setenv("SCHED_LEDGER", "1")
    assert sched_ledger.from_env() is not None


def test_boundary_waste_is_wave_scoped():
    led = sched_ledger.SchedLedger()
    led.note_group(("admit", 32, 4), 128, 96, 20, 12)
    led.note_boundary()
    assert led.boundary_waste() == pytest.approx(32 / 128)
    # The wave marks reset: a padless follow-up wave reports 0.
    led.note_group(("admit", 8, 2), 16, 16, 0, 0)
    led.note_boundary()
    assert led.boundary_waste() == 0.0
    # And an empty (no-group) boundary is not a division by zero.
    led.note_boundary()
    assert led.boundary_waste() == 0.0


def test_frag_counts_only_on_starved_passes():
    led = sched_ledger.SchedLedger()
    led.note_budget(256, 200, starved=False)  # light load: surplus, not waste
    assert led.snapshot()["frag_tokens"] == 0
    led.note_budget(256, 200, starved=True)
    snap = led.snapshot()
    assert snap["frag_tokens"] == 56
    assert snap["budget_starved_passes"] == 1
    assert snap["budget_offered_tokens"] == 512
    assert snap["budget_used_tokens"] == 400


def test_wait_attribution_clamped_priority():
    led = sched_ledger.SchedLedger()
    now = time.perf_counter()
    # Pool stall covered the first 30ms of a 50ms wait; the remainder
    # falls to the scheduler bucket — components re-sum to the total.
    led.note_pool_stall(1)
    led._wait_marks[1]["pool"] = now - 0.02
    led.note_first_dispatch(1, submitted_at=now - 0.05, now=now)
    wait = led.snapshot()["wait"]
    assert wait["requests"] == 1
    assert wait["total_ms"] == pytest.approx(50.0, abs=1.0)
    parts = (wait["pool_ms"] + wait["bucket_ms"] + wait["budget_ms"]
             + wait["sched_ms"])
    assert parts == pytest.approx(wait["total_ms"], abs=0.01)
    assert wait["pool_ms"] == pytest.approx(20.0, abs=1.0)
    assert led.snapshot()["pool_stall_requests"] == 1


def test_audit_flags_inconsistent_attribution():
    led = sched_ledger.SchedLedger()
    led.note_group(("admit", 32, 2), 64, 10, 10, 10)  # 30 != 64 cells
    led.audit()
    cons = led.snapshot()["conservation"]
    assert cons["checked"] == 1
    assert cons["breaches"] == 1
    assert cons["last_breach"]


# ---------------------------------------------------------------------------
# Engine integration
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("mode", sorted(MODES))
def test_greedy_bit_identical_ledger_on_vs_off(mode, monkeypatch):
    monkeypatch.delenv("SCHED_LEDGER", raising=False)
    eng = _engine(**MODES[mode])
    try:
        want = _collect(eng, PROMPTS)
        assert eng.debug_sched() is None
    finally:
        eng.stop()

    monkeypatch.setenv("SCHED_LEDGER", "1")
    eng = _engine(**MODES[mode])
    try:
        got = _collect(eng, PROMPTS)
        eng.drain(timeout=120)
        sched = eng.debug_sched()
    finally:
        eng.stop()

    assert got == want, f"{mode}: ledger perturbed greedy output"

    # Conservation under the traffic that just ran.
    assert sched["conservation"]["breaches"] == 0, (
        sched["conservation"]["last_breach"])
    cells = sched["dispatch_cells"]
    assert cells > 0 and sched["useful_tokens"] > 0
    assert (sched["useful_tokens"] + sched["bucket_pad_tokens"]
            + sched["group_pad_tokens"]) == cells
    assert sum(e["cells"] for e in sched["by_shape"]) == cells
    assert sched["wait"]["requests"] == len(PROMPTS)
    assert 0.0 <= sched["padding_waste_frac"] < 1.0


def test_disabled_engine_keeps_stats_at_zero(monkeypatch):
    monkeypatch.delenv("SCHED_LEDGER", raising=False)
    eng = _engine()
    try:
        _collect(eng, PROMPTS[:2])
        eng.drain(timeout=120)
        snap = eng.stats.snapshot()
    finally:
        eng.stop()
    # The stats mirror exists unconditionally (dashboards need no
    # existence checks) but never ticks while the ledger is off.
    for key in ("sched_boundaries", "sched_idle_boundaries",
                "sched_useful_tokens", "sched_bucket_pad_tokens",
                "sched_group_pad_tokens", "sched_frag_tokens"):
        assert snap[key] == 0, key
    assert snap["padding_waste_frac"] == 0.0
    assert sum(snap["waste_counts"]) == 0


def test_enabled_engine_mirrors_ledger_into_stats(monkeypatch):
    monkeypatch.setenv("SCHED_LEDGER", "1")
    eng = _engine()
    try:
        _collect(eng, PROMPTS[:2])
        eng.drain(timeout=120)
        sched = eng.debug_sched()
        snap = eng.stats.snapshot()
    finally:
        eng.stop()
    assert snap["sched_useful_tokens"] == sched["useful_tokens"]
    assert snap["sched_bucket_pad_tokens"] == sched["bucket_pad_tokens"]
    assert snap["sched_group_pad_tokens"] == sched["group_pad_tokens"]
    assert snap["sched_boundaries"] == sched["dispatch_boundaries"]
    assert sum(snap["waste_counts"]) == snap["sched_boundaries"]
    assert snap["padding_waste_frac"] == pytest.approx(
        sched["padding_waste_frac"], abs=1e-4)
