"""graftpilot tests: bounded feedback control with a decision ledger.

The load-bearing claims, in test form:
 * env gating follows the None-attribute idiom (PILOT) — a disabled
   engine keeps the raw dispatch path and ``debug_pilot() is None``;
   ``PILOT=hold`` flies EDF + the ledger with every knob frozen;
 * the control loop CONVERGES in both directions per knob — budget
   raises under starvation and lowers under surplus, admit halves under
   pool pressure and recovers after calm, bias drops under deadline
   expiry and relaxes after meeting — each from injected signal
   windows, no engine required;
 * it can NEVER misbehave: the first window only baselines, cooldowns
   block back-to-back moves, recovery needs consecutive calm windows
   (hysteresis), and at an envelope bound the rule goes silent instead
   of oscillating;
 * EDF ordering is stable, counts inversions, ages no-deadline
   requests via a virtual deadline (starvation-proof), and returns the
   SAME deque object for an already-ordered queue — the all-FIFO
   workload's dispatch stays byte-identical;
 * the pilot is pure observation at fixed knobs: greedy outputs are
   BIT-IDENTICAL pilot-on-vs-off across all three dispatch paths;
 * a mixed-deadline soak under the pilot keeps the conservation audit
   clean, every knob inside its envelope, and the engine leak-free.
"""

import collections
import os
import time
import types

import jax
import pytest

from seldon_tpu.models import init_params
from seldon_tpu.models.config import get_config
from seldon_tpu.models.sampling import SamplingParams
from seldon_tpu.servers import controller
from seldon_tpu.servers.engine import EngineConfig, InferenceEngine

GREEDY = SamplingParams(temperature=0.0, max_new_tokens=6)
PROMPTS = [list(range(2, 2 + n)) for n in (5, 12, 24, 7)]

# The three dispatch paths whose outputs the pilot must not perturb.
MODES = {
    "dense": {},
    "paged": dict(paged_kv=True, kv_block=16, kv_pool_blocks=12,
                  prompt_buckets=(16, 32)),
    "chunked": dict(chunked_prefill=True, prefill_chunk=8, prefix_block=8),
}


def _engine(start=True, **ekw):
    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    ekw.setdefault("max_slots", 4)
    ekw.setdefault("max_seq_len", 64)
    ekw.setdefault("prompt_buckets", (8, 32))
    eng = InferenceEngine(params, cfg, EngineConfig(**ekw))
    if start:
        eng.start()
    return eng


def _collect(eng, prompts):
    qs = [eng.submit(p, GREEDY) for p in prompts]
    outs = []
    for q in qs:
        toks = []
        while True:
            item = q.get(timeout=300)
            if item is None:
                break
            toks.extend(item["tokens"])
        outs.append(toks)
    return outs


# ---------------------------------------------------------------------------
# Signal injection harness (no engine: the controller sees only dicts)
# ---------------------------------------------------------------------------


class _Signals:
    """Cumulative signal source; tests advance() it between windows."""

    def __init__(self, **levels):
        self.cum = {k: 0 for k in controller._DELTA_KEYS}
        self.levels = {"goodput": 1.0, "queue_depth": 0, "free_slots": 4,
                       "roof_backlog_ms": 0.0, "heal_pressure": 0.0}
        self.levels.update(levels)

    def advance(self, **vals):
        for k, v in vals.items():
            if k in self.cum:
                self.cum[k] += v
            else:
                self.levels[k] = v

    def __call__(self):
        out = dict(self.cum)
        out.update(self.levels)
        return out


def _pilot(hold=False, budget=8):
    p = controller.PilotController(hold=hold)
    p.bind(chunked=True, prefill_chunk=8, max_slots=4, max_admit=4,
           dispatch_token_budget=budget)
    return p


def _window(pilot, sig):
    """Run one full decision window; return the decisions it took."""
    out = []
    for _ in range(controller.PERIOD_BOUNDARIES):
        out += pilot.on_boundary(sig)
    return out


# ---------------------------------------------------------------------------
# Gating
# ---------------------------------------------------------------------------


def test_from_env_gating(monkeypatch):
    monkeypatch.delenv("PILOT", raising=False)
    assert controller.from_env() is None
    monkeypatch.setenv("PILOT", "0")
    assert controller.from_env() is None
    monkeypatch.setenv("PILOT", "1")
    p = controller.from_env()
    assert p is not None and p.hold is False
    monkeypatch.setenv("PILOT", "hold")
    p = controller.from_env()
    assert p is not None and p.hold is True


def test_disabled_engine_keeps_raw_path(monkeypatch):
    monkeypatch.delenv("PILOT", raising=False)
    eng = _engine(start=False)
    try:
        assert eng._pilot is None
        assert eng.debug_pilot() is None
        with eng._book:
            # The admit cap resolves to the static config value — the
            # raw dispatch path, zero controller involvement.
            assert eng._admit_cap() == eng._max_admit
    finally:
        eng.stop()


def test_pilot_implies_sched_ledger(monkeypatch):
    monkeypatch.delenv("SCHED_LEDGER", raising=False)
    monkeypatch.setenv("PILOT", "1")
    eng = _engine(start=False)
    try:
        assert eng._pilot is not None
        assert eng._sled is not None  # the controller's signal source
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# Convergence: every knob moves in both directions from injected signals
# ---------------------------------------------------------------------------


def test_first_window_only_baselines():
    p = _pilot()
    sig = _Signals()
    sig.advance(budget_dispatches=8, budget_starved_passes=8,
                budget_offered_tokens=64, budget_used_tokens=64)
    assert _window(p, sig) == []  # nothing to delta against yet
    assert p.snapshot()["windows"] == 1


def test_budget_raises_under_starvation():
    p = _pilot()
    sig = _Signals()
    _window(p, sig)  # baseline
    sig.advance(budget_dispatches=4, budget_starved_passes=4,
                budget_offered_tokens=32, budget_used_tokens=32,
                queue_depth=6)
    (d,) = _window(p, sig)
    assert d["knob"] == controller.KNOB_BUDGET
    assert (d["old"], d["new"]) == (8, 16)
    assert "starved" in d["rationale"]
    assert d["expected_effect"]
    assert d["signal_snapshot"]["budget_starved_passes"] == 4
    assert d["effect"] is None  # effect window still open
    assert p.dispatch_budget() == 16
    snap = p.snapshot()
    assert snap["decisions_total"] == 1
    assert snap["decisions_by_knob"][controller.KNOB_BUDGET] == 1


def test_budget_lowers_under_surplus():
    p = _pilot(budget=32)
    sig = _Signals()
    _window(p, sig)  # baseline
    # 0/8 starved passes at 25% utilization: clear surplus.
    sig.advance(budget_dispatches=8, budget_offered_tokens=256,
                budget_used_tokens=64)
    (d,) = _window(p, sig)
    assert d["knob"] == controller.KNOB_BUDGET
    assert (d["old"], d["new"]) == (32, 16)
    assert "surplus" in d["rationale"]
    assert p.dispatch_budget() == 16


def test_budget_cooldown_then_stable_at_clamp():
    p = _pilot()  # envelope [8, 32]
    sig = _Signals()
    budgets = []
    for _ in range(8):
        sig.advance(budget_dispatches=4, budget_starved_passes=4,
                    budget_offered_tokens=32, budget_used_tokens=32)
        _window(p, sig)
        budgets.append(p.dispatch_budget())
    # Baseline, raise, 2-window cooldown, raise to the clamp, then
    # silence: permanent starvation cannot push past the envelope and
    # the controller never oscillates at the bound.
    assert budgets == [8, 16, 16, 32, 32, 32, 32, 32]
    snap = p.snapshot()
    assert snap["decisions_total"] == 2
    assert snap["knobs"]["dispatch_token_budget"] == snap["envelope"]["budget_max"]


def test_admit_halves_on_stall_then_recovers():
    p = _pilot()
    sig = _Signals()
    _window(p, sig)  # baseline
    sig.advance(pool_stall_events=2, preemptions=1)
    (d,) = _window(p, sig)
    assert d["knob"] == controller.KNOB_ADMIT
    assert (d["old"], d["new"]) == (4, 2)
    assert "pool pressure" in d["rationale"]
    assert p.admit_cap() == 2
    # One calm window is NOT enough (cooldown + hysteresis overlap);
    # the second calm window recovers.
    assert _window(p, sig) == []
    (d,) = _window(p, sig)
    assert d["knob"] == controller.KNOB_ADMIT
    assert (d["old"], d["new"]) == (2, 4)
    assert p.admit_cap() == 4


def test_bias_drops_on_expiry_then_relaxes():
    p = _pilot()
    sig = _Signals()
    _window(p, sig)  # baseline
    sig.advance(deadline_expired=3)
    (d,) = _window(p, sig)
    assert d["knob"] == controller.KNOB_BIAS
    assert (d["old"], d["new"]) == (0, -1)
    assert p.chunk_bias() == -1
    assert _window(p, sig) == []  # cooldown + single meet window
    (d,) = _window(p, sig)
    assert (d["old"], d["new"]) == (-1, 0)
    assert p.chunk_bias() == 0
    # Bias relaxes only back toward neutral — never above 0.
    for _ in range(4):
        assert _window(p, sig) == []
    assert p.chunk_bias() == 0


def test_counterfactual_effect_fills_next_window():
    p = _pilot()
    sig = _Signals()
    _window(p, sig)
    sig.advance(budget_dispatches=4, budget_starved_passes=4,
                budget_offered_tokens=32, budget_used_tokens=32)
    (d,) = _window(p, sig)
    assert d["effect"] is None
    sig.advance(goodput=0.75)  # next window measures the move
    _window(p, sig)
    entry = p.snapshot()["ledger"][0]
    assert entry["effect"] is not None
    assert entry["effect"]["goodput_delta"] == pytest.approx(-0.25)
    cf = p.snapshot()["counterfactual"]
    assert cf["windows"] == 1
    assert cf["goodput_delta"] == pytest.approx(-0.25)


def test_hold_mode_freezes_knobs():
    p = _pilot(hold=True)
    sig = _Signals()
    for _ in range(4):
        sig.advance(budget_dispatches=4, budget_starved_passes=4,
                    budget_offered_tokens=32, budget_used_tokens=32,
                    pool_stall_events=1, deadline_expired=1)
        assert _window(p, sig) == []
    snap = p.snapshot()
    assert snap["mode"] == "hold"
    assert snap["windows"] == 4  # the ledger half still flies
    assert snap["decisions_total"] == 0
    assert snap["knobs"] == {"dispatch_token_budget": 8, "max_admit": 4,
                             "chunk_bias": 0, "spec_k": 0}


# ---------------------------------------------------------------------------
# EDF ordering
# ---------------------------------------------------------------------------


def _req(deadline=None, submitted_at=None):
    return types.SimpleNamespace(
        deadline=deadline,
        submitted_at=time.perf_counter() if submitted_at is None
        else submitted_at,
    )


def test_edf_sorts_by_deadline_counts_inversions():
    p = _pilot()
    now = time.perf_counter()
    a = _req(deadline=now + 9.0)
    b = _req(deadline=now + 1.0)
    c = _req(deadline=now + 5.0)
    out = p.order_queue(collections.deque([a, b, c]))
    assert list(out) == [b, c, a]
    snap = p.snapshot()["edf"]
    assert snap["inversions"] == 1  # one out-of-order adjacent pair (a,b)
    assert snap["reorders"] == 1


def test_edf_fifo_queue_returned_untouched():
    p = _pilot()
    now = time.perf_counter()
    q = collections.deque(
        _req(submitted_at=now + i * 0.001) for i in range(5)
    )
    out = p.order_queue(q)
    assert out is q  # the SAME object: FIFO dispatch stays byte-identical
    assert p.snapshot()["edf"] == {"inversions": 0, "reorders": 0,
                                   "expired_at_pop": 0}


def test_edf_aging_outranks_far_deadline():
    p = _pilot()
    now = time.perf_counter()
    aged = _req(submitted_at=now - 2 * controller.AGE_HORIZON_S)
    urgent = _req(deadline=now + 5.0)
    out = p.order_queue(collections.deque([urgent, aged]))
    # The aged no-deadline request's virtual deadline (submit + horizon)
    # is already in the past — it outranks any future deadline, so
    # starvation is impossible.
    assert list(out) == [aged, urgent]


def test_edf_stable_on_equal_keys():
    p = _pilot()
    now = time.perf_counter()
    x = _req(deadline=now + 3.0)
    y = _req(deadline=now + 3.0)
    late = _req(deadline=now + 1.0)
    out = p.order_queue(collections.deque([x, y, late]))
    assert list(out) == [late, x, y]  # ties keep FIFO order


# ---------------------------------------------------------------------------
# Engine integration
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("mode", sorted(MODES))
def test_greedy_bit_identical_pilot_on_vs_off(mode, monkeypatch):
    monkeypatch.delenv("PILOT", raising=False)
    monkeypatch.delenv("SCHED_LEDGER", raising=False)
    eng = _engine(**MODES[mode])
    try:
        want = _collect(eng, PROMPTS)
        assert eng.debug_pilot() is None
    finally:
        eng.stop()

    monkeypatch.setenv("PILOT", "1")
    eng = _engine(**MODES[mode])
    try:
        got = _collect(eng, PROMPTS)
        eng.drain(timeout=120)
        pilot = eng.debug_pilot()
        sched = eng.debug_sched()
    finally:
        eng.stop()

    assert got == want, f"{mode}: pilot perturbed greedy output"
    assert pilot["enabled"] is True
    assert pilot["boundaries"] > 0
    # PILOT implied the sched ledger; its books stayed clean.
    assert sched["conservation"]["breaches"] == 0, (
        sched["conservation"]["last_breach"])


@pytest.mark.fuzz
def test_mixed_deadline_soak_conserves(monkeypatch):
    """Soak the pilot with a deadline-mixed wave on the chunked engine:
    generous TTLs, tight TTLs (some expire) and no-TTL requests
    interleaved. Whatever the controller decides, the conservation
    audit stays clean, every knob stays inside its envelope, and the
    engine ends leak-free."""
    monkeypatch.setenv("PILOT", "1")
    n = max(12, int(os.environ.get("FUZZ_EXAMPLES", "300")) // 12)
    eng = _engine(chunked_prefill=True, prefill_chunk=8, prefix_block=8,
                  max_queue=4 * n)
    try:
        qs = []
        for i in range(n):
            ttl = (0, 30_000, 20)[i % 3]  # none / generous / likely-expired
            qs.append(eng.submit(
                list(range(2, 2 + 5 + (i % 19))),
                SamplingParams(temperature=0.0, max_new_tokens=4,
                               deadline_ms=ttl),
            ))
        done = expired = 0
        for q in qs:
            while True:
                item = q.get(timeout=300)
                if item is None:
                    break
                if "error" in item:
                    assert item["kind"] == "deadline", item
                    expired += 1
            done += 1
        assert done == n
        eng.drain(timeout=120)
        pilot = eng.debug_pilot()
        sched = eng.debug_sched()
        assert sched["conservation"]["checked"] > 0
        assert sched["conservation"]["breaches"] == 0, (
            sched["conservation"]["last_breach"])
        env = pilot["envelope"]
        knobs = pilot["knobs"]
        assert env["budget_min"] <= knobs["dispatch_token_budget"] \
            <= env["budget_max"]
        assert env["admit_min"] <= knobs["max_admit"] <= env["admit_max"]
        assert env["bias_min"] <= knobs["chunk_bias"] <= env["bias_max"]
        assert isinstance(pilot["edf"]["expired_at_pop"], int)
        assert eng.debug_lifecycle_check() == {}
    finally:
        eng.stop()
