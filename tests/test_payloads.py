"""Codec tests, mirroring reference python/tests/test_utils.py coverage."""

import numpy as np
import pytest

from seldon_tpu.core import payloads
from seldon_tpu.proto import prediction_pb2 as pb


class TestDenseTensor:
    @pytest.mark.parametrize(
        "dtype",
        [np.float32, np.float64, np.int32, np.int64, np.uint8, np.float16, np.bool_],
    )
    def test_roundtrip_dtypes(self, dtype):
        arr = np.arange(12).reshape(3, 4).astype(dtype)
        out = payloads.dense_to_array(payloads.array_to_dense(arr))
        assert out.dtype == arr.dtype
        np.testing.assert_array_equal(out, arr)

    def test_bfloat16_roundtrip(self):
        import ml_dtypes

        arr = np.arange(8, dtype=np.float32).astype(ml_dtypes.bfloat16).reshape(2, 4)
        dense = payloads.array_to_dense(arr)
        assert dense.dtype == pb.DT_BFLOAT16
        out = payloads.dense_to_array(dense)
        assert out.dtype == np.dtype(ml_dtypes.bfloat16)
        np.testing.assert_array_equal(out.astype(np.float32), arr.astype(np.float32))

    def test_jax_array_input(self):
        import jax.numpy as jnp

        arr = jnp.ones((2, 3), dtype=jnp.bfloat16)
        out = payloads.dense_to_array(payloads.array_to_dense(arr))
        assert out.shape == (2, 3)

    def test_wire_roundtrip(self):
        arr = np.random.default_rng(0).normal(size=(4, 5)).astype(np.float32)
        msg = payloads.build_message(arr, kind="dense")
        wire = msg.SerializeToString()
        back = pb.SeldonMessage.FromString(wire)
        np.testing.assert_array_equal(payloads.get_data_from_message(back), arr)


class TestReferenceForms:
    def test_tensor_roundtrip(self):
        arr = np.array([[1.0, 2.0], [3.0, 4.0]])
        out = payloads.tensor_to_array(payloads.array_to_tensor(arr))
        np.testing.assert_array_equal(out, arr)

    def test_ndarray_roundtrip(self):
        arr = np.array([[1.0, 2.0], [3.0, 4.0]])
        out = payloads.listvalue_to_array(payloads.array_to_listvalue(arr))
        np.testing.assert_array_equal(out, arr)

    def test_bin_and_str_data(self):
        msg = payloads.build_message(b"\x00\x01binary")
        assert payloads.get_data_from_message(msg) == b"\x00\x01binary"
        msg = payloads.build_message("hello")
        assert payloads.get_data_from_message(msg) == "hello"

    def test_json_data(self):
        msg = payloads.build_message({"a": [1, 2], "b": "x"}, kind="jsonData")
        assert payloads.get_data_from_message(msg) == {"a": [1.0, 2.0], "b": "x"}

    def test_names(self):
        data = payloads.array_to_data(np.zeros((1, 2)), names=["f0", "f1"], kind="tensor")
        assert list(data.names) == ["f0", "f1"]


class TestConstructResponse:
    def test_mirrors_request_kind(self):
        for kind in ("dense", "tensor", "ndarray"):
            req = payloads.build_message(np.ones((2, 2)), kind=kind)
            resp = payloads.construct_response(None, False, req, np.zeros((2, 2)))
            assert payloads.data_kind(resp) == kind

    def test_propagates_puid(self):
        req = payloads.build_message(np.ones((1, 1)))
        req.meta.puid = "xyz"
        resp = payloads.construct_response(None, False, req, np.zeros((1, 1)))
        assert resp.meta.puid == "xyz"

    def test_tags_and_metrics(self):
        req = payloads.build_message(np.ones((1, 1)))
        resp = payloads.construct_response(
            None,
            False,
            req,
            np.zeros((1, 1)),
            tags={"version": "v2", "n": 3},
            metrics=[{"key": "k", "type": "GAUGE", "value": 1.5}],
        )
        assert resp.meta.tags["version"].string_value == "v2"
        assert resp.meta.tags["n"].number_value == 3
        assert resp.meta.metrics[0].key == "k"
        assert resp.meta.metrics[0].type == pb.Metric.GAUGE
        assert resp.meta.metrics[0].value == pytest.approx(1.5)

    def test_class_names_used(self):
        class M:
            def class_names(self):
                return ["c0", "c1"]

        req = payloads.build_message(np.ones((1, 2)), kind="tensor")
        resp = payloads.construct_response(M(), False, req, np.zeros((1, 2)))
        assert list(resp.data.names) == ["c0", "c1"]

    def test_passthrough_proto(self):
        req = payloads.build_message(np.ones((1, 1)))
        inner = payloads.build_message(np.zeros((1, 1)))
        resp = payloads.construct_response(None, False, req, inner)
        assert resp is inner


class TestJsonCodec:
    def test_dict_roundtrip(self):
        msg = payloads.build_message(np.ones((2, 2)), kind="tensor")
        msg.meta.puid = "p1"
        d = payloads.message_to_dict(msg)
        back = payloads.dict_to_message(d)
        assert back.meta.puid == "p1"
        np.testing.assert_array_equal(payloads.get_data_from_message(back), np.ones((2, 2)))

    def test_rest_style_ndarray_payload(self):
        d = {"data": {"names": ["a", "b"], "ndarray": [[1, 2], [3, 4]]}}
        msg = payloads.dict_to_message(d)
        np.testing.assert_array_equal(
            payloads.get_data_from_message(msg), np.array([[1, 2], [3, 4]])
        )

    def test_feedback_json(self):
        fb = payloads.json_to_feedback(
            {"request": {"data": {"ndarray": [[1]]}}, "reward": 0.5}
        )
        assert fb.reward == pytest.approx(0.5)
