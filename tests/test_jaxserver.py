"""Continuous-batching engine + JAXServer tests (tiny config, CPU mesh)."""

import queue
import threading
import time

import numpy as np
import pytest

from seldon_tpu.models.config import get_config
from seldon_tpu.models.sampling import SamplingParams
from seldon_tpu.servers.engine import EngineConfig, InferenceEngine
from seldon_tpu.servers.jaxserver import JAXServer
from seldon_tpu.servers.tokenizer import ByteTokenizer


@pytest.fixture(scope="module")
def engine():
    import jax

    from seldon_tpu.models import init_params

    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    eng = InferenceEngine(
        params,
        cfg,
        EngineConfig(max_slots=4, max_seq_len=64, prompt_buckets=(8, 16, 32)),
    )
    eng.start()
    yield eng
    eng.stop()


def test_engine_single_request(engine):
    res = engine.generate_blocking(
        [3, 4, 5], SamplingParams(temperature=0.0, max_new_tokens=8)
    )
    assert 1 <= len(res["token_ids"]) <= 8
    assert res["ttft_ms"] is not None and res["ttft_ms"] > 0


def test_engine_deterministic_greedy(engine):
    a = engine.generate_blocking(
        [7, 8, 9], SamplingParams(temperature=0.0, max_new_tokens=6)
    )
    b = engine.generate_blocking(
        [7, 8, 9], SamplingParams(temperature=0.0, max_new_tokens=6)
    )
    assert a["token_ids"] == b["token_ids"]


def test_engine_concurrent_matches_solo(engine):
    """Continuous batching must not change greedy outputs: run the same
    prompt alone vs alongside 3 other concurrent requests."""
    solo = engine.generate_blocking(
        [11, 12, 13], SamplingParams(temperature=0.0, max_new_tokens=6)
    )

    results = {}

    def worker(i, prompt):
        results[i] = engine.generate_blocking(
            prompt, SamplingParams(temperature=0.0, max_new_tokens=6)
        )

    threads = [
        threading.Thread(target=worker, args=(i, p))
        for i, p in enumerate(
            [[11, 12, 13], [20, 21], [30, 31, 32, 33], [40]]
        )
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert results[0]["token_ids"] == solo["token_ids"]


def test_engine_more_requests_than_slots(engine):
    """8 requests through 4 slots: all complete."""
    qs = [
        engine.submit([i + 2, i + 3], SamplingParams(temperature=0.5,
                                                     max_new_tokens=4))
        for i in range(8)
    ]
    done = 0
    for q_ in qs:
        while True:
            item = q_.get(timeout=60)
            if item is None:
                done += 1
                break
    assert done == 8


def test_engine_rejects_oversized_prompt(engine):
    with pytest.raises(ValueError):
        engine.submit(list(range(64)), SamplingParams())


def test_byte_tokenizer_roundtrip():
    tok = ByteTokenizer()
    s = "hello TPU ⚡"
    assert tok.decode(tok.encode(s)) == s


@pytest.fixture(scope="module")
def server():
    srv = JAXServer(preset="tiny", max_slots=4, max_seq_len=64)
    srv.load()
    yield srv
    srv.engine.stop()


def test_jaxserver_generate(server):
    out = server.generate(
        {"prompt": "hi", "max_new_tokens": 8, "temperature": 0.0}
    )
    assert out["completion_tokens"] >= 1
    assert out["ttft_ms"] > 0
    assert out["prompt_tokens"] == 2


def test_jaxserver_generate_stream(server):
    # None chunks are heartbeats (disconnect poll points between token
    # bursts) — transports drop them, and so do direct consumers.
    chunks = [
        c for c in server.generate_stream(
            {"prompt": "abc", "max_new_tokens": 5, "temperature": 0.0}
        ) if c is not None
    ]
    assert 1 <= len(chunks) <= 5
    assert chunks[0]["ttft_ms"] > 0


def test_loadtester_generate_against_live_server(server, capsys):
    """`loadtester --transport generate` driven at a LIVE /generate
    endpoint (the tiny JAXServer fixture behind the real REST app):
    tokens/s and completion accounting must be sane."""
    import asyncio
    import json as _json
    import threading

    from aiohttp import web

    from seldon_tpu.loadtester import main as lt_main
    from seldon_tpu.runtime.wrapper import build_rest_app

    holder, started = {}, threading.Event()

    async def amain():
        runner = web.AppRunner(build_rest_app(server))
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        holder["port"] = site._server.sockets[0].getsockname()[1]
        started.set()
        while not holder.get("stop"):
            await asyncio.sleep(0.05)
        await runner.cleanup()

    t = threading.Thread(target=lambda: asyncio.run(amain()), daemon=True)
    t.start()
    assert started.wait(30)
    try:
        lt_main([
            f"http://127.0.0.1:{holder['port']}", "--transport", "generate",
            "--clients", "2", "--seconds", "2", "--prompt", "hi",
            "--max-new-tokens", "4",
        ])
    finally:
        holder["stop"] = True
        t.join(timeout=10)
    out = _json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["metric"] == "loadtest_generate_req_per_s"
    assert out["value"] > 0
    d = out["detail"]
    assert d["errors"] == 0
    # Closed-loop accounting: every completed request produced >= 1 and
    # <= max_new_tokens tokens.
    assert d["requests"] >= 1
    assert d["requests"] <= d["completion_tokens"] <= 4 * d["requests"]
    assert d["tokens_per_s"] > 0
    # Default transport is now the NDJSON stream: per-stream TTFT/ITL
    # percentiles ride along in the summary.
    for q in (50, 95, 99):
        assert d[f"ttft_p{q}_ms"] > 0
        assert d[f"itl_p{q}_ms"] >= 0


def test_jaxserver_predict_scores(server):
    scores = server.predict(np.array([[3, 4, 5, 6]]), [])
    assert scores.shape == (1,)
    assert np.isfinite(scores).all()


def test_jaxserver_metrics_tags(server):
    server.generate({"prompt": "x", "max_new_tokens": 2})
    m = server.metrics()
    keys = {d["key"] for d in m}
    assert {"jaxserver_mean_ttft_ms", "jaxserver_slots_busy",
            "jaxserver_decode_dispatches",
            "jaxserver_decode_steps"} <= keys
    stats = {d["key"]: d["value"] for d in m}
    assert stats["jaxserver_decode_dispatches"] >= 1
    assert stats["jaxserver_decode_steps"] >= stats[
        "jaxserver_decode_dispatches"]
    assert server.tags()["server"] == "jaxserver"


def test_checkpoint_roundtrip(tmp_path):
    import jax

    from seldon_tpu.models import init_params
    from seldon_tpu.servers import checkpoint as ckpt

    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    path = str(tmp_path / "ckpt")
    ckpt.save_checkpoint(path, params, cfg)
    params2, cfg2 = ckpt.load_checkpoint(path)
    assert cfg2 == cfg
    flat1 = jax.tree.leaves(params)
    flat2 = jax.tree.leaves(params2)
    for a, b in zip(flat1, flat2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_engine_seed_reproducible_across_traffic(engine):
    """Same (seed, prompt) must reproduce the completion regardless of what
    else shares the batch (per-row position-keyed sampling)."""
    sp = SamplingParams(temperature=1.0, max_new_tokens=6, seed=42)
    solo = engine.generate_blocking([5, 6, 7], sp)
    # Re-run with 3 noisy co-scheduled requests.
    noise = [
        engine.submit([9, 9], SamplingParams(temperature=1.0, max_new_tokens=6,
                                             seed=i))
        for i in range(3)
    ]
    busy = engine.generate_blocking([5, 6, 7], sp)
    for q_ in noise:
        while q_.get(timeout=60) is not None:
            pass
    assert solo["token_ids"] == busy["token_ids"]


def test_engine_restart():
    import jax

    from seldon_tpu.models import init_params

    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    eng = InferenceEngine(
        params, cfg, EngineConfig(max_slots=2, max_seq_len=32,
                                  prompt_buckets=(8,))
    )
    eng.start()
    r1 = eng.generate_blocking([3, 4], SamplingParams(temperature=0.0,
                                                      max_new_tokens=3))
    eng.stop()
    eng.start()
    r2 = eng.generate_blocking([3, 4], SamplingParams(temperature=0.0,
                                                      max_new_tokens=3))
    eng.stop()
    assert r1["token_ids"] == r2["token_ids"]


def test_engine_buckets_clamped_to_window():
    import jax

    from seldon_tpu.models import init_params

    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    # No bucket fits the window: engine must clamp, not crash on submit.
    eng = InferenceEngine(
        params, cfg, EngineConfig(max_slots=2, max_seq_len=16,
                                  prompt_buckets=(32, 128))
    )
    eng.start()
    r = eng.generate_blocking([3, 4], SamplingParams(temperature=0.0,
                                                     max_new_tokens=2))
    eng.stop()
    assert len(r["token_ids"]) >= 1


def test_jaxserver_explicit_greedy(server):
    """temperature=0.0 must be honored (not replaced by a default)."""
    a = server.generate({"prompt": "zz", "max_new_tokens": 4, "temperature": 0.0})
    b = server.generate({"prompt": "zz", "max_new_tokens": 4, "temperature": 0.0})
    assert a["token_ids"] == b["token_ids"]


def test_storage_relative_key():
    from seldon_tpu.servers.storage import _relative_key

    assert _relative_key("models/a/x.bin", "models/a") == "x.bin"
    assert _relative_key("models/ab/x.bin", "models/a") is None
    assert _relative_key("models/a", "models/a") == "a"
    assert _relative_key("k", "") == "k"


def test_engine_bad_request_fails_cleanly(engine):
    """An admission failure must fail that request only (no wedged loop);
    the engine keeps serving afterwards. Also: absurd seeds are clamped,
    not fatal."""
    real_admit = engine._jit_admit

    def boom(*a, **k):
        raise ValueError("injected prefill failure")

    engine._jit_admit = boom
    try:
        with pytest.raises(RuntimeError, match="injected"):
            engine.generate_blocking(
                [3, 4], SamplingParams(temperature=0.0, max_new_tokens=2)
            )
    finally:
        engine._jit_admit = real_admit
    # Engine still serves, including a seed far beyond uint32.
    ok = engine.generate_blocking(
        [3, 4], SamplingParams(temperature=1.0, max_new_tokens=2, seed=2**80)
    )
    assert len(ok["token_ids"]) >= 1


def test_engine_async_dispatch_failure_fails_all_clients():
    """A dispatch error must fail EVERY in-flight request — including ones
    optimistically recycled out of the slot table and ones whose
    boundaries sit in the fetch queue — with an error + terminator, never
    a hang (round-3 review finding on the async fetcher)."""
    import jax

    from seldon_tpu.models import get_config, init_params
    from seldon_tpu.models.sampling import SamplingParams
    from seldon_tpu.servers.engine import EngineConfig, InferenceEngine

    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    eng = InferenceEngine(params, cfg, EngineConfig(
        max_slots=4, max_seq_len=48, prompt_buckets=(8,), decode_chunk=4))
    eng.warmup()

    real_chunks = dict(eng._jit_chunks)
    calls = {"n": 0}

    def flaky_for(n):
        def flaky(*a, **k):
            calls["n"] += 1
            if calls["n"] == 3:
                raise RuntimeError("injected device error")
            return real_chunks[n](*a, **k)
        return flaky

    eng._jit_chunks = {n: flaky_for(n) for n in eng._chunk_sizes}
    # 8 requests / 4 slots: two waves, so the failure lands while some
    # requests wait and some are mid-decode/recycled.
    qs = [eng.submit([3 + i] * 5, SamplingParams(
        temperature=0.5, max_new_tokens=12, seed=i)) for i in range(8)]
    eng.start()
    outcomes = []
    for q in qs:
        saw_error, toks, terminated = False, 0, False
        while True:
            item = q.get(timeout=60)  # a hang here IS the failure mode
            if item is None:
                terminated = True
                break
            if "error" in item:
                saw_error = True
            else:
                toks += len(item["tokens"])
            assert not (saw_error and "tokens" in item), \
                "tokens after error"
        outcomes.append((saw_error, toks, terminated))
    eng.stop()
    assert all(t for _, _, t in outcomes), outcomes
    # The injected error must have actually failed someone (not all
    # requests can have finished cleanly before call #3).
    assert any(e for e, _, _ in outcomes), outcomes


def test_engine_adaptive_chunk_policy():
    """Prefill-priority scheduling: chunk length scales with occupancy —
    empty slots -> min_chunk (frequent admission boundaries), full ->
    decode_chunk; adaptive_chunk=False pins the single configured size."""
    import jax

    from seldon_tpu.models import get_config, init_params
    from seldon_tpu.servers.engine import EngineConfig, InferenceEngine

    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    eng = InferenceEngine(params, cfg, EngineConfig(
        max_slots=8, max_seq_len=48, prompt_buckets=(8,),
        decode_chunk=32, min_chunk=4))
    assert eng._chunk_sizes == (4, 8, 32)
    assert eng._pick_chunk() == 4  # all free

    class _Stub:  # occupancy is counted from non-None slot entries
        finished = False

    eng._slots = [_Stub()] * 8
    assert eng._pick_chunk() == 32  # full -> saturated
    eng._slots = [_Stub()] * 4 + [None] * 4
    assert eng._pick_chunk() == 4  # real capacity -> fast admission
    # Bigger pool: free below max_admit -> saturated; free below a
    # quarter of the pool -> mid rung; plenty free -> min.
    big = InferenceEngine(params, cfg, EngineConfig(
        max_slots=64, max_seq_len=48, prompt_buckets=(8,),
        decode_chunk=32, min_chunk=4, max_admit=8))
    big._slots = [_Stub()] * 60 + [None] * 4
    assert big._pick_chunk() == 32
    big._slots = [_Stub()] * 52 + [None] * 12
    assert big._pick_chunk() == 8
    big._slots = [_Stub()] * 30 + [None] * 34
    assert big._pick_chunk() == 4

    fixed = InferenceEngine(params, cfg, EngineConfig(
        max_slots=8, max_seq_len=48, prompt_buckets=(8,),
        decode_chunk=32, adaptive_chunk=False))
    assert fixed._chunk_sizes == (32,)
    assert fixed._pick_chunk() == 32


def test_engine_ring_prefill_matches_xla():
    """Context-parallel (ring) prefill in the serving engine: greedy
    completions over an sp=4 mesh must match the plain XLA-attention
    engine bit-for-bit (ring attention is exact, not approximate) —
    SURVEY §5.7 long-context serving."""
    import dataclasses

    import jax

    from seldon_tpu.models import init_params
    from seldon_tpu.parallel import MeshPlan, make_mesh
    from seldon_tpu.parallel import sharding as shd

    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    prompts = [[7, 8, 9, 10, 11], [3, 4, 5]]

    def complete(cfg_used, mesh):
        if mesh is not None:
            shardings = shd.named_shardings(mesh, shd.param_pspecs(cfg_used))
            p = jax.device_put(params, shardings)
        else:
            p = params
        eng = InferenceEngine(
            p, cfg_used,
            EngineConfig(max_slots=2, max_seq_len=48, prompt_buckets=(8,),
                         max_admit=2, decode_chunk=4),
            mesh=mesh,
        )
        eng.start()
        try:
            return [
                eng.generate_blocking(
                    pr, SamplingParams(temperature=0.0, max_new_tokens=6)
                )["token_ids"]
                for pr in prompts
            ]
        finally:
            eng.stop()

    base = complete(cfg, None)

    ring_cfg = dataclasses.replace(cfg, attn_impl="ring")
    mesh = make_mesh(MeshPlan(sp=4, tp=2))
    ring = complete(ring_cfg, mesh)
    assert ring == base, (ring, base)
