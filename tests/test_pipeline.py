"""Pipeline parallelism ('pp'): GPipe microbatch schedule over shard_map.

Runs on the virtual 8-device CPU mesh (conftest). Checks exactness of the
pipelined forward against the plain forward, gradient flow, composition
with dp/tp, and the pp train step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from seldon_tpu.models import get_config, init_params, forward
from seldon_tpu.models.train import make_optimizer, make_sharded_train_step
from seldon_tpu.parallel import MeshPlan, make_mesh, sharding as shd
from seldon_tpu.parallel.pipeline import make_pipeline_forward, pp_param_pspecs


@pytest.fixture(scope="module")
def tiny_setup():
    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (4, 16), 0, cfg.vocab_size)
    return cfg, params, tokens


def test_pipeline_forward_matches_plain(tiny_setup):
    cfg, params, tokens = tiny_setup
    mesh = make_mesh(MeshPlan(dp=2, pp=2, tp=2))
    sharded = shd.shard_tree(params, pp_param_pspecs(cfg), mesh)
    fwd = make_pipeline_forward(mesh, cfg, n_microbatches=2)
    out, aux = jax.jit(fwd)(sharded, tokens)
    ref = forward(params, tokens, cfg)
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(out), rtol=2e-2, atol=2e-2
    )
    assert aux["moe_lb_loss"].shape == ()


def test_pipeline_forward_microbatch_counts(tiny_setup):
    cfg, params, tokens = tiny_setup
    mesh = make_mesh(MeshPlan(pp=2))
    sharded = shd.shard_tree(params, pp_param_pspecs(cfg), mesh)
    ref = forward(params, tokens, cfg)
    for m in (1, 4):
        fwd = make_pipeline_forward(mesh, cfg, n_microbatches=m)
        out, _ = jax.jit(fwd)(sharded, tokens)
        np.testing.assert_allclose(
            np.asarray(ref), np.asarray(out), rtol=2e-2, atol=2e-2
        )


def test_pipeline_grads_match_plain(tiny_setup):
    cfg, params, tokens = tiny_setup
    mesh = make_mesh(MeshPlan(pp=2))
    sharded = shd.shard_tree(params, pp_param_pspecs(cfg), mesh)
    fwd = make_pipeline_forward(mesh, cfg, n_microbatches=2)

    def pp_loss(p):
        logits, _ = fwd(p, tokens)
        return jnp.mean(logits.astype(jnp.float32) ** 2)

    def plain_loss(p):
        logits = forward(p, tokens, cfg)
        return jnp.mean(logits.astype(jnp.float32) ** 2)

    g_pp = jax.jit(jax.grad(pp_loss))(sharded)
    g_ref = jax.grad(plain_loss)(params)
    # Spot-check one early-layer and one late-layer leaf so both pipeline
    # stages' backward paths are covered.
    for key in ("wq", "w_down"):
        np.testing.assert_allclose(
            np.asarray(g_ref["blocks"][key], np.float32),
            np.asarray(g_pp["blocks"][key], np.float32),
            rtol=5e-2, atol=5e-3,
        )


def test_pp_train_step_runs_and_learns(tiny_setup):
    cfg, _, _ = tiny_setup
    mesh = make_mesh(MeshPlan(dp=2, pp=2, tp=2))
    optimizer = make_optimizer(total_steps=10)
    init_fn, step_fn = make_sharded_train_step(
        mesh, cfg, optimizer, seq_sharded=False, n_microbatches=2
    )
    state = init_fn(jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (4, 16), 0, cfg.vocab_size)
    mask = jnp.ones((4, 16), jnp.float32)
    losses = []
    for _ in range(3):
        state, metrics = step_fn(state, tokens, mask)
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]  # same batch every step: must descend
    # Layer axis is genuinely sharded over pp.
    wq_shard = state.params["blocks"]["wq"].sharding
    assert "pp" in wq_shard.spec[0] if isinstance(wq_shard.spec[0], tuple) \
        else wq_shard.spec[0] == "pp"


def test_pipeline_rejects_indivisible():
    import dataclasses

    cfg = get_config("tiny")
    mesh = make_mesh(MeshPlan(pp=2))
    bad = dataclasses.replace(cfg, n_layers=3)
    with pytest.raises(ValueError):
        make_pipeline_forward(mesh, bad, n_microbatches=2)
