"""E2E: SeldonDeployment CR -> reconcile -> REAL processes -> HTTP predict.

The kind-cluster tier of the reference test pyramid (SURVEY.md §4,
testing/scripts/), one level down: LocalProcessStore turns the
reconciler's (unchanged) manifests into real engine + unit subprocesses,
and the assertions drive the live HTTP data path — including the
reference's fixed-model rolling-update trick (values + meta.requestPath
identify which graph version served each request).

Unit classes ride the CR's `image` field as `local/<module.Class>:<tag>`
(the store's self-contained analogue of a baked image entrypoint), so
every apply path — including the reconciler's own resyncs — launches
identical processes."""

import json
import os
import urllib.request

import pytest

from seldon_tpu.operator import Reconciler, SeldonDeployment
from seldon_tpu.operator.localstore import LocalProcessStore

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.e2e

V1 = "local/tests.fixed_models.ModelV1:1"
V2 = "local/tests.fixed_models.ModelV2:1"


def _post(port: int, path: str, body, timeout=10):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _predict(port: int, rows, timeout=10):
    return _post(port, "/api/v0.1/predictions",
                 {"data": {"ndarray": rows}}, timeout)


def _cr(name="e2e", generation=1, image=V1, pred_name="main"):
    return SeldonDeployment.from_dict({
        "metadata": {"name": name, "namespace": "default",
                     "generation": generation},
        "spec": {
            "predictors": [{
                "name": pred_name,
                "replicas": 1,
                "graph": {"name": "clf", "type": "MODEL", "image": image},
            }],
        },
    })


def _reconcile_until_available(rec, store, sdep, timeout_s=120):
    """Reconcile -> wait for processes -> reconcile (the controller loop's
    resync behavior, compressed)."""
    import time

    deadline = time.time() + timeout_s
    while time.time() < deadline:
        status = rec.reconcile(sdep)
        if status.state == "Available":
            return status
        if status.state == "Failed":  # terminal: waiting can't fix it
            raise AssertionError(f"reconcile failed: {status}")
        store.wait_ready(30)
    raise AssertionError(f"never became Available: {status}")


def test_cr_to_live_http_predict():
    store = LocalProcessStore(repo_root=REPO)
    rec = Reconciler(store, istio_enabled=False)
    try:
        _reconcile_until_available(rec, store, _cr())
        dep_name = next(
            m["metadata"]["name"] for m in store.list("Deployment", "default")
        )
        port = store.engine_port(dep_name)
        out = _predict(port, [[0.0, 0.0]])
        # Fixed model v1 returns [1, 2, 3, 4] (reference fixed-model trick).
        assert out["data"]["ndarray"] == [[1.0, 2.0, 3.0, 4.0]], out
        assert "clf" in out["meta"]["requestPath"], out["meta"]
        for _ in range(5):
            assert _predict(port, [[1.0]])["data"]["ndarray"] == [
                [1.0, 2.0, 3.0, 4.0]
            ]
    finally:
        store.close()


def test_engine_graph_with_live_unit_hop():
    """Transformer -> model two-unit graph: both hops are real processes
    and tags from both units merge into the response meta."""
    store = LocalProcessStore(repo_root=REPO)
    rec = Reconciler(store, istio_enabled=False)
    try:
        sdep = SeldonDeployment.from_dict({
            "metadata": {"name": "hop", "namespace": "default"},
            "spec": {"predictors": [{
                "name": "main",
                "replicas": 1,
                "graph": {
                    "name": "scaler",
                    "type": "TRANSFORMER",
                    "image": "local/tests.fixed_models.DoublerTransformer:1",
                    "children": [
                        {"name": "clf", "type": "MODEL", "image": V1}
                    ],
                },
            }]},
        })
        _reconcile_until_available(rec, store, sdep)
        dep_name = next(
            m["metadata"]["name"] for m in store.list("Deployment", "default")
        )
        out = _predict(store.engine_port(dep_name), [[3.0]])
        assert out["data"]["ndarray"] == [[1.0, 2.0, 3.0, 4.0]], out
        path = out["meta"]["requestPath"]
        assert set(path) >= {"scaler", "clf"}, path
        assert out["meta"]["tags"].get("scaled") is True, out["meta"]
    finally:
        store.close()


def test_rolling_update_zero_downtime():
    """The reference's flagship e2e (test_rolling_updates.py): generation
    bump swaps the graph version; the OLD engine keeps serving until the
    new one is ready, then stale resources GC — and the served values
    identify the version at every step."""
    store = LocalProcessStore(repo_root=REPO)
    rec = Reconciler(store, istio_enabled=False)
    try:
        _reconcile_until_available(
            rec, store, _cr(generation=1, image=V1, pred_name="main")
        )
        v1_dep = next(m["metadata"]["name"]
                      for m in store.list("Deployment", "default"))
        v1_port = store.engine_port(v1_dep)
        assert _predict(v1_port, [[0.0]])["data"]["ndarray"] == [
            [1.0, 2.0, 3.0, 4.0]
        ]

        # Generation 2 renames the predictor -> new workload + processes.
        sdep2 = _cr(generation=2, image=V2, pred_name="canary")
        status = rec.reconcile(sdep2)
        if status.state != "Available":
            # Rollout window: BOTH generations' processes are live and the
            # old engine still serves v1 — zero downtime.
            assert _predict(v1_port, [[0.0]])["data"]["ndarray"] == [
                [1.0, 2.0, 3.0, 4.0]
            ]
            names = {m["metadata"]["name"]
                     for m in store.list("Deployment", "default")}
            assert len(names) == 2, names
            _reconcile_until_available(rec, store, sdep2)

        # Stale generation GC'd: old workload gone, processes terminated.
        remaining = {m["metadata"]["name"]
                     for m in store.list("Deployment", "default")}
        assert v1_dep not in remaining, remaining
        assert store.pods.get(v1_dep) is None
        v2_dep = next(iter(remaining))
        out = _predict(store.engine_port(v2_dep), [[0.0]])
        assert out["data"]["ndarray"] == [[5.0, 6.0, 7.0, 8.0]], out
    finally:
        store.close()


def test_bandit_feedback_shifts_routing():
    """A/B bandit over live processes (reference seldon-mab chart e2e):
    an EpsilonGreedy router unit + two fixed models; rewarding only v2's
    branch via /feedback makes the router concentrate traffic on it —
    reward routing follows meta.routing across real process hops."""
    store = LocalProcessStore(repo_root=REPO)
    rec = Reconciler(store, istio_enabled=False)
    try:
        sdep = SeldonDeployment.from_dict({
            "metadata": {"name": "mab", "namespace": "default"},
            "spec": {"predictors": [{
                "name": "main",
                "replicas": 1,
                "graph": {
                    "name": "eg",
                    "type": "ROUTER",
                    "image":
                        "local/seldon_tpu.components.EpsilonGreedy:1",
                    "parameters": [
                        {"name": "n_branches", "value": "2", "type": "INT"},
                        {"name": "epsilon", "value": "0.1",
                         "type": "FLOAT"},
                        {"name": "seed", "value": "7", "type": "INT"},
                    ],
                    "children": [
                        {"name": "model-a", "type": "MODEL", "image": V1},
                        {"name": "model-b", "type": "MODEL", "image": V2},
                    ],
                },
            }]},
        })
        _reconcile_until_available(rec, store, sdep)
        dep = next(m["metadata"]["name"]
                   for m in store.list("Deployment", "default"))
        port = store.engine_port(dep)

        def predict_full():
            return _predict(port, [[1.0]])

        def feedback(resp, reward):
            return _post(port, "/api/v0.1/feedback", {
                "request": {"data": {"ndarray": [[1.0]]}},
                "response": resp,
                "reward": reward,
            })

        # Teach: whenever v2's values come back, reward 1; v1 -> 0.
        # Self-stabilizing: keep going until exploration has rewarded v2
        # at least twice (so best_branch flips deterministically) rather
        # than betting on a specific seed's exploration schedule.
        v2_rewards = 0
        for _ in range(200):
            resp = predict_full()
            is_v2 = resp["data"]["ndarray"][0][0] == 5.0
            feedback(resp, 1.0 if is_v2 else 0.0)
            v2_rewards += int(is_v2)
            if v2_rewards >= 2:
                break
        assert v2_rewards >= 2, "router never explored branch 1 in 200 tries"

        # Exploit: the vast majority of traffic should now hit v2.
        v2_count = sum(
            predict_full()["data"]["ndarray"][0][0] == 5.0
            for _ in range(30)
        )
        assert v2_count >= 22, v2_count  # eps=0.1 -> expect ~27/30
    finally:
        store.close()
