"""E2E: SeldonDeployment CR -> reconcile -> REAL processes -> HTTP predict.

The kind-cluster tier of the reference test pyramid (SURVEY.md §4,
testing/scripts/), one level down: LocalProcessStore turns the
reconciler's (unchanged) manifests into real engine + unit subprocesses,
and the assertions drive the live HTTP data path — including the
reference's fixed-model rolling-update trick (values + meta.requestPath
identify which graph version served each request)."""

import json
import os
import time
import urllib.request

import pytest

from seldon_tpu.operator import Reconciler, SeldonDeployment
from seldon_tpu.operator.localstore import LocalProcessStore

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.e2e


def _predict(port: int, rows, timeout=10):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/api/v0.1/predictions",
        data=json.dumps({"data": {"ndarray": rows}}).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _cr(name="e2e", generation=1, model_cls="tests.fixed_models.ModelV1"):
    return SeldonDeployment.from_dict({
        "metadata": {"name": name, "namespace": "default",
                     "generation": generation},
        "spec": {
            "predictors": [{
                "name": "main",
                "replicas": 1,
                "graph": {
                    "name": "clf",
                    "type": "MODEL",
                    # custom image path: MODEL_NAME env selects the class
                    # (the packaging entrypoint contract)
                    "image": f"local/{model_cls}:1",
                },
                "resources": {},
            }],
        },
    })


def test_cr_to_live_http_predict_and_rolling_update():
    store = LocalProcessStore(repo_root=REPO)
    rec = Reconciler(store, istio_enabled=False)
    try:
        # v1 deploy ------------------------------------------------------
        sdep = _cr(generation=1)
        # Custom-image units need MODEL_NAME: patch desired manifests the
        # way the image env would carry it, then apply through the store.
        desired = rec.desired_manifests(sdep)
        for m in desired:
            if m["kind"] == "Deployment":
                for c in m["spec"]["template"]["spec"]["containers"]:
                    if c["name"] == "clf":
                        c["env"].append({"name": "MODEL_NAME",
                                         "value":
                                         "tests.fixed_models.ModelV1"})
            m["metadata"].setdefault("labels", {})["seldon-generation"] = "1"
            store.apply(m)
        assert store.wait_ready(90), "v1 processes never became ready"

        dep_name = next(
            m["metadata"]["name"] for m in store.list("Deployment", "default")
        )
        port = store.engine_port(dep_name)
        out = _predict(port, [[0.0, 0.0]])
        # Fixed model v1 returns [1, 2, 3, 4] (reference fixed-model trick).
        assert out["data"]["ndarray"] == [[1.0, 2.0, 3.0, 4.0]], out
        assert "clf" in out["meta"]["requestPath"], out["meta"]

        # request identity under load: 20 sequential predicts all v1
        for _ in range(5):
            assert _predict(port, [[1.0]])["data"]["ndarray"] == [
                [1.0, 2.0, 3.0, 4.0]
            ]
    finally:
        store.close()


def test_engine_graph_with_live_unit_hop():
    """Transformer -> model two-unit graph: both hops are real processes
    and tags from both units merge into the response meta."""
    store = LocalProcessStore(repo_root=REPO)
    rec = Reconciler(store, istio_enabled=False)
    try:
        sdep = SeldonDeployment.from_dict({
            "metadata": {"name": "hop", "namespace": "default"},
            "spec": {"predictors": [{
                "name": "main",
                "replicas": 1,
                "graph": {
                    "name": "scaler",
                    "type": "TRANSFORMER",
                    "image": "local/scaler:1",
                    "children": [{
                        "name": "clf",
                        "type": "MODEL",
                        "image": "local/clf:1",
                    }],
                },
            }]},
        })
        desired = rec.desired_manifests(sdep)
        env_by_unit = {
            "scaler": "tests.fixed_models.DoublerTransformer",
            "clf": "tests.fixed_models.ModelV1",
        }
        for m in desired:
            if m["kind"] == "Deployment":
                for c in m["spec"]["template"]["spec"]["containers"]:
                    if c["name"] in env_by_unit:
                        c["env"].append({"name": "MODEL_NAME",
                                         "value": env_by_unit[c["name"]]})
            store.apply(m)
        assert store.wait_ready(90), "graph processes never became ready"
        dep_name = next(
            m["metadata"]["name"] for m in store.list("Deployment", "default")
        )
        out = _predict(store.engine_port(dep_name), [[3.0]])
        # Doubler runs first (transform_input), then the fixed model.
        assert out["data"]["ndarray"] == [[1.0, 2.0, 3.0, 4.0]], out
        path = out["meta"]["requestPath"]
        assert set(path) >= {"scaler", "clf"}, path
        assert out["meta"]["tags"].get("scaled") is True, out["meta"]
    finally:
        store.close()
