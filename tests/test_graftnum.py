"""graftnum: the static numerics & buffer-lifetime certifier.

Claims under test, by pass:

 * **num-barrier**: an int8 quantize scale (``max(abs(x))`` in an
   int8-casting function) must read a barrier-pinned input, and an
   int8 dequant product (astype * astype with a scale reference) must
   pass through ``optimization_barrier`` before a materialization
   boundary (return / concatenate / scan carry).  The two hand-placed
   barrier idioms (``transformer._quantize_act`` pin-the-input,
   ``ragged_paged_attention._sparse_block`` wrap-the-product) certify;
   their barrier-free twins are findings.
 * **use-after-donate**: reads of a donated binding after the donating
   call are flagged on ANY path; the three safe shapes (same-statement
   rebind, tuple rebind, hand-off return) are clean; host-side
   container captures of a later-donated binding are flagged;
   the registry sees assigned jits, ``functools.partial`` decorators,
   dict-of-jits, and conditional aliases; ``.shape``/``.dtype`` reads
   survive donation; an early-``return`` branch's donation does not
   leak into the fall-through path.
 * **einsum-broadcast / mask-dtype**: a repeated einsum label binding
   a structural literal 1 against a real axis is flagged (the PR 16
   every-KV-head-summed-ALL-heads bug); the same symbol twice is
   clean; ``dot_general`` contracting dims get the same check; a
   masked softmax whose scores branch is cast to bf16 before the
   -1e30 fill is flagged.
 * **wiring**: all three rules waive via inline allow comments,
   fingerprints survive line drift, the CLI exits 1 on findings and 0
   clean, the ``--budget-s`` self-runtime gate trips, the graftnum
   headline prints, and the REAL tree (models/, ops/,
   servers/engine.py) is clean with a non-trivial certified count —
   the empty-baseline discipline, machine-checked.
"""

import os
import re
import subprocess
import sys
import textwrap
from pathlib import Path

from tools.graftlint import core, donate, einsumcheck, numbarrier

REPO = Path(__file__).resolve().parents[1]


def lint(tmp_path, src, passes, name="fixture.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(src))
    files = core.load_tree([p], tmp_path)
    ctx = core.Context(tmp_path)
    return core.run_passes(files, ctx, passes)


def lint_stats(tmp_path, src, passes, name="fixture.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(src))
    files = core.load_tree([p], tmp_path)
    ctx = core.Context(tmp_path)
    return core.run_passes(files, ctx, passes), ctx.stats


def rules(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# num-barrier: quantize-scale leg
# ---------------------------------------------------------------------------


SCALE_BAD = """
    import jax
    import jax.numpy as jnp

    def quantize(x):
        s = jnp.max(jnp.abs(x), axis=-1, keepdims=True) / 127.0
        q = jnp.round(x / s).astype(jnp.int8)
        return q, s
"""

SCALE_PINNED = """
    import jax
    import jax.numpy as jnp

    def quantize(x):
        x = jax.lax.optimization_barrier(x)
        s = jnp.max(jnp.abs(x), axis=-1, keepdims=True) / 127.0
        q = jnp.round(x / s).astype(jnp.int8)
        return q, s
"""

SCALE_WRAPPED = """
    import jax
    import jax.numpy as jnp

    def quantize(x):
        s = jnp.max(jnp.abs(jax.lax.optimization_barrier(x))) / 127.0
        q = jnp.round(x / s).astype(jnp.int8)
        return q, s
"""


def test_scale_without_barrier_flagged(tmp_path):
    findings = lint(tmp_path, SCALE_BAD, [numbarrier.run])
    assert rules(findings) == ["num-barrier"]
    assert "max(abs" in findings[0].message
    assert "fusion" in findings[0].message


def test_scale_with_barrier_pin_clean(tmp_path):
    assert lint(tmp_path, SCALE_PINNED, [numbarrier.run]) == []


def test_scale_with_inline_barrier_clean(tmp_path):
    assert lint(tmp_path, SCALE_WRAPPED, [numbarrier.run]) == []


def test_scale_in_float_only_function_clean(tmp_path):
    # max(abs(x)) without any int8 cast nearby is a norm, not a scale.
    src = SCALE_BAD.replace(".astype(jnp.int8)", ".astype(jnp.float32)")
    assert lint(tmp_path, src, [numbarrier.run]) == []


# ---------------------------------------------------------------------------
# num-barrier: dequant-product leg
# ---------------------------------------------------------------------------


DEQUANT_BAD = """
    import jax
    import jax.numpy as jnp

    def dequant_concat(w, w_scale, prior, sink, dt):
        full = w.astype(dt) * w_scale.astype(dt)
        sink["kv"] = jnp.concatenate([prior, full], axis=0)
"""

DEQUANT_BARRIERED = """
    import jax
    import jax.numpy as jnp

    def dequant_concat(w, w_scale, prior, dt):
        full = jax.lax.optimization_barrier(
            w.astype(dt) * w_scale.astype(dt))
        return jnp.concatenate([prior, full], axis=0)
"""

DEQUANT_INTERNAL = """
    import jax.numpy as jnp

    def attend(w, w_scale, q, dt):
        full = w.astype(dt) * w_scale.astype(dt)
        probs = jnp.exp(full - jnp.sum(full))
        del probs
        return q
"""


def test_dequant_into_concat_flagged(tmp_path):
    findings = lint(tmp_path, DEQUANT_BAD, [numbarrier.run])
    assert rules(findings) == ["num-barrier"]
    assert "concatenate() materialization" in findings[0].message


def test_dequant_barriered_clean_and_certified(tmp_path):
    findings, stats = lint_stats(
        tmp_path, DEQUANT_BARRIERED, [numbarrier.run])
    assert findings == []
    assert stats["numbarrier"]["certified"] == 1
    assert stats["numbarrier"]["dequant_sites"] == 1


def test_dequant_consumed_internally_clean(tmp_path):
    # The product never reaches a materialization boundary — every
    # consumer lives inside the same fusion, so there is no cross-leg
    # drift to certify against.
    assert lint(tmp_path, DEQUANT_INTERNAL, [numbarrier.run]) == []


def test_dequant_into_return_flagged(tmp_path):
    src = """
    import jax.numpy as jnp

    def dequant(w, w_scale, dt):
        return w.astype(dt) * w_scale.astype(dt)
    """
    findings = lint(tmp_path, src, [numbarrier.run])
    assert rules(findings) == ["num-barrier"]
    assert "jit return" in findings[0].message


def test_num_barrier_waivable(tmp_path):
    src = SCALE_BAD.replace(
        "s = jnp.max",
        "# graftlint: allow(num-barrier) host-side load-time quant\n"
        "        s = jnp.max")
    assert lint(tmp_path, src, [numbarrier.run]) == []


# ---------------------------------------------------------------------------
# use-after-donate
# ---------------------------------------------------------------------------


DONATE_BAD = """
    import jax

    step = jax.jit(lambda p, s: s, donate_argnums=(1,))

    def loop(params, state):
        new = step(params, state)
        stale = state["kv"]
        return new, stale
"""

DONATE_REBIND = """
    import jax

    step = jax.jit(lambda p, s: s, donate_argnums=(1,))

    def loop(params, state):
        state = step(params, state)
        state = step(params, state)
        return state
"""

DONATE_TUPLE = """
    import jax

    step = jax.jit(lambda p, s: (s, 0), donate_argnums=(1,))

    def loop(params, state):
        state, tok = step(params, state)
        return state, tok
"""

DONATE_CAPTURED = """
    import jax

    step = jax.jit(lambda p, s: s, donate_argnums=(1,))

    def loop(params, state, book):
        book["warm"] = state
        state = step(params, state)
        return state
"""

DONATE_DECORATOR = """
    import functools
    import jax

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(state, params):
        return state

    def loop(params, state):
        out = step(state, params)
        return state
"""

DONATE_DICT = """
    import jax

    class Engine:
        def __init__(self, fns):
            self._jit_chunks = {
                n: jax.jit(f, donate_argnums=(1,))
                for n, f in fns.items()
            }

        def run(self, n, params, state):
            out = self._jit_chunks[n](params, state)
            return state, out
"""

DONATE_BRANCH = """
    import jax

    step = jax.jit(lambda p, s: s, donate_argnums=(1,))

    def loop(params, state, fast):
        if fast:
            out = step(params, state)
        else:
            out = state
        return state
"""

DONATE_EARLY_RETURN = """
    import jax

    step = jax.jit(lambda p, s: s, donate_argnums=(1,))

    def loop(params, state, fast):
        if fast:
            return step(params, state)
        return state
"""

DONATE_METADATA = """
    import jax

    step = jax.jit(lambda p, s: s, donate_argnums=(1,))

    def loop(params, state):
        new = step(params, state)
        n = state.shape[0] + state.ndim
        return new, n
"""


def test_read_after_donate_flagged(tmp_path):
    findings = lint(tmp_path, DONATE_BAD, [donate.run])
    assert rules(findings) == ["use-after-donate"]
    assert "reads state after its buffer was donated" in \
        findings[0].message


def test_same_statement_rebind_clean(tmp_path):
    assert lint(tmp_path, DONATE_REBIND, [donate.run]) == []


def test_tuple_rebind_clean(tmp_path):
    assert lint(tmp_path, DONATE_TUPLE, [donate.run]) == []


def test_donate_while_captured_flagged(tmp_path):
    findings = lint(tmp_path, DONATE_CAPTURED, [donate.run])
    assert rules(findings) == ["use-after-donate"]
    assert "container still holds a reference" in findings[0].message


def test_decorator_partial_donate_flagged(tmp_path):
    findings = lint(tmp_path, DONATE_DECORATOR, [donate.run])
    assert rules(findings) == ["use-after-donate"]


def test_dict_of_jits_donate_flagged(tmp_path):
    findings = lint(tmp_path, DONATE_DICT, [donate.run])
    assert rules(findings) == ["use-after-donate"]


def test_donation_on_one_path_flags_fallthrough_read(tmp_path):
    # Union merge: donated on ANY path means the read after the join
    # is a hazard on that path.
    findings = lint(tmp_path, DONATE_BRANCH, [donate.run])
    assert rules(findings) == ["use-after-donate"]


def test_early_return_donation_does_not_leak(tmp_path):
    # The donating branch returns — its state must NOT merge back, so
    # the fall-through `return state` is the undonated path and clean.
    assert lint(tmp_path, DONATE_EARLY_RETURN, [donate.run]) == []


def test_metadata_reads_survive_donation(tmp_path):
    assert lint(tmp_path, DONATE_METADATA, [donate.run]) == []


def test_use_after_donate_waivable(tmp_path):
    src = DONATE_BAD.replace(
        "stale = state",
        "# graftlint: allow(use-after-donate) copy taken upstream\n"
        "        stale = state")
    assert lint(tmp_path, src, [donate.run]) == []


# ---------------------------------------------------------------------------
# einsum-broadcast / mask-dtype
# ---------------------------------------------------------------------------


EINSUM_BAD = """
    import jax.numpy as jnp

    def attend(q, kv):
        B, H, D = q.shape
        k = kv.reshape(B, 1, D)
        return jnp.einsum("bhd,bhd->bh", q, k)
"""

EINSUM_SAME_SYMBOL = """
    import jax.numpy as jnp

    def attend(q, kv):
        B, H, D = q.shape
        k = kv.reshape(B, H, D)
        return jnp.einsum("bhd,bhd->bh", q, k)
"""

DOT_GENERAL_BAD = """
    import jax
    import jax.numpy as jnp

    def contract():
        a = jnp.zeros((4, 1))
        b = jnp.zeros((4, 8))
        return jax.lax.dot_general(a, b, (((1,), (1,)), ((0,), (0,))))
"""

MASK_BAD = """
    import jax.numpy as jnp

    def masked(scores, mask):
        return jnp.where(mask, scores.astype(jnp.bfloat16), -1e30)
"""

MASK_F32 = """
    import jax.numpy as jnp

    def masked(scores, mask):
        return jnp.where(mask, scores.astype(jnp.float32), -1e30)
"""


def test_einsum_size1_broadcast_flagged(tmp_path):
    findings = lint(tmp_path, EINSUM_BAD, [einsumcheck.run])
    assert rules(findings) == ["einsum-broadcast"]
    assert "broadcasts silently" in findings[0].message


def test_einsum_same_symbol_clean(tmp_path):
    # Both operands bind 'h' to the SAME symbol H — a batch that may
    # be 1 at runtime is legitimate; the trap is a structural 1.
    assert lint(tmp_path, EINSUM_SAME_SYMBOL, [einsumcheck.run]) == []


def test_dot_general_size1_contraction_flagged(tmp_path):
    findings = lint(tmp_path, DOT_GENERAL_BAD, [einsumcheck.run])
    assert rules(findings) == ["einsum-broadcast"]
    assert "dot_general" in findings[0].message


def test_mask_low_precision_flagged(tmp_path):
    findings = lint(tmp_path, MASK_BAD, [einsumcheck.run])
    assert rules(findings) == ["mask-dtype"]


def test_mask_f32_clean(tmp_path):
    assert lint(tmp_path, MASK_F32, [einsumcheck.run]) == []


def test_einsum_broadcast_waivable(tmp_path):
    src = EINSUM_BAD.replace(
        "return jnp.einsum",
        "# graftlint: allow(einsum-broadcast) intended broadcast\n"
        "        return jnp.einsum")
    assert lint(tmp_path, src, [einsumcheck.run]) == []


# ---------------------------------------------------------------------------
# Fingerprint stability
# ---------------------------------------------------------------------------


def test_fingerprint_survives_line_drift(tmp_path):
    (f1,) = lint(tmp_path, SCALE_BAD, [numbarrier.run], name="a.py")
    drifted = SCALE_BAD.replace(
        "import jax\n", "import jax\n\n    # drift: unrelated comment\n")
    (f2,) = lint(tmp_path, drifted, [numbarrier.run], name="b.py")
    assert f1.line != f2.line  # the drift really moved the site
    # Same rule + qualname + normalized line -> same fingerprint tail;
    # only the path segment differs between the two fixture files.
    assert f1.fingerprint != f2.fingerprint  # path is in the print
    same = SCALE_BAD  # identical content, same file name now
    (f3,) = lint(tmp_path, same, [numbarrier.run], name="a.py")
    assert f3.fingerprint == f1.fingerprint


def test_fingerprint_stable_in_same_file_under_drift(tmp_path):
    (f1,) = lint(tmp_path, SCALE_BAD, [numbarrier.run], name="s.py")
    drifted = SCALE_BAD.replace(
        "import jax\n", "import jax\n\n    # drift: unrelated comment\n")
    (f2,) = lint(tmp_path, drifted, [numbarrier.run], name="s.py")
    assert f2.line == f1.line + 2
    assert f2.fingerprint == f1.fingerprint


# ---------------------------------------------------------------------------
# Real tree: the empty-baseline discipline, machine-checked
# ---------------------------------------------------------------------------


def test_real_tree_clean_with_nontrivial_certified_count():
    targets = [REPO / "seldon_tpu" / "models",
               REPO / "seldon_tpu" / "ops",
               REPO / "seldon_tpu" / "servers" / "engine.py"]
    files = core.load_tree(targets, REPO)
    ctx = core.Context(REPO)
    findings = core.run_passes(
        files, ctx, [numbarrier.run, donate.run, einsumcheck.run])
    assert findings == [], "\n".join(f.render() for f in findings)
    nb = ctx.stats["numbarrier"]
    # The hand-placed barriers are no longer folklore: the certifier
    # must SEE them. 2 scale pins (_quantize_act/_quantize_kv) + 2
    # _sparse_block products + 2 prefix-KV products at minimum.
    assert nb["certified"] >= 6, nb
    assert nb["scale_sites"] >= 2, nb
    dn = ctx.stats["donate"]
    assert dn["donating_jits"] >= 5, dn
    assert dn["donating_calls"] >= 10, dn
    es = ctx.stats["einsumcheck"]
    assert es["contraction_sites"] >= 20, es
    assert es["shape_traced"] >= 1, es


def test_baseline_has_no_graftnum_entries():
    baseline = core.load_baseline(core.Context(REPO).baseline_path)
    num_rules = {"num-barrier", "use-after-donate", "einsum-broadcast",
                 "mask-dtype"}
    offenders = {fp: e for fp, e in baseline.items()
                 if e.get("rule") in num_rules}
    assert not offenders, offenders


# ---------------------------------------------------------------------------
# CLI wiring: exit codes, headline, self-runtime budget
# ---------------------------------------------------------------------------


def _cli(*args, cwd=REPO):
    return subprocess.run(
        [sys.executable, "-m", "tools.graftlint", *args],
        cwd=cwd, capture_output=True, text=True,
        env={**os.environ, "PYTHONPATH": str(REPO)},
    )


def test_cli_exit_1_on_fixture_finding(tmp_path):
    p = tmp_path / "bad.py"
    p.write_text(textwrap.dedent(SCALE_BAD))
    r = _cli(str(p))
    assert r.returncode == 1, f"{r.stdout}\n{r.stderr}"
    assert "num-barrier" in r.stdout


def test_cli_exit_0_on_clean_fixture(tmp_path):
    p = tmp_path / "good.py"
    p.write_text(textwrap.dedent(SCALE_PINNED))
    r = _cli(str(p))
    assert r.returncode == 0, f"{r.stdout}\n{r.stderr}"


def test_cli_prints_graftnum_headline(tmp_path):
    p = tmp_path / "good.py"
    p.write_text(textwrap.dedent(DEQUANT_BARRIERED))
    r = _cli(str(p))
    assert r.returncode == 0, f"{r.stdout}\n{r.stderr}"
    m = re.search(
        r"graftnum: numbarrier (\d+) finding\(s\) "
        r"\((\d+) scale \+ (\d+) dequant site\(s\), "
        r"(\d+) barrier-certified\)", r.stdout)
    assert m, r.stdout
    assert m.group(1) == "0"
    assert m.group(4) == "1"
    assert "| donate 0 finding(s)" in r.stdout
    assert "einsumcheck 0 finding(s)" in r.stdout


def test_cli_budget_gate_trips(tmp_path):
    p = tmp_path / "good.py"
    p.write_text(textwrap.dedent(SCALE_PINNED))
    r = _cli(str(p), "--budget-s", "0.0001")
    assert r.returncode == 1, f"{r.stdout}\n{r.stderr}"
    assert "self-runtime budget exceeded" in r.stderr


def test_cli_budget_disabled_with_zero(tmp_path):
    p = tmp_path / "good.py"
    p.write_text(textwrap.dedent(SCALE_PINNED))
    r = _cli(str(p), "--budget-s", "0")
    assert r.returncode == 0, f"{r.stdout}\n{r.stderr}"
