"""Deployed-API fuzzer (runtime/tester.run_api_test) against a LIVE
engine — direct REST/gRPC and through a prefix-stripping mini-gateway
(the role Istio's rewrite plays in-cluster).

Reference parity: python/seldon_core/api_tester.py:1-140 (contract
fuzzing of a deployed SeldonDeployment endpoint, predict + feedback)."""

import asyncio
import json
import threading

import numpy as np
import pytest
from aiohttp import web

from seldon_tpu.orchestrator.server import EngineServer
from seldon_tpu.orchestrator.spec import PredictorSpec
from seldon_tpu.runtime.tester import run_api_test

CONTRACT = {
    "features": [
        {"name": "a", "dtype": "FLOAT", "ftype": "continuous",
         "range": [0.0, 1.0]},
        {"name": "b", "dtype": "FLOAT", "ftype": "continuous",
         "range": [0.0, 1.0]},
    ],
    "targets": [
        {"name": "proba", "dtype": "FLOAT", "ftype": "continuous",
         "range": [0.0, 1.0], "repeat": 3}
    ],
}


@pytest.fixture(scope="module")
def live_engine():
    """EngineServer + a mini ingress that strips /seldon/{ns}/{name}
    (what the Istio VirtualService rewrite does in-cluster)."""
    spec = PredictorSpec.from_dict({"name": "t", "graph": {
        "name": "simple", "type": "MODEL", "implementation": "SIMPLE_MODEL",
    }})
    holder = {}
    started = threading.Event()

    async def amain():
        es = EngineServer(spec=spec, http_port=0, grpc_port=0,
                          enable_batching=False)
        await es.start(host="127.0.0.1")

        async def gateway(request: web.Request) -> web.StreamResponse:
            # /seldon/{ns}/{name}/rest... -> engine /rest...
            rest = "/" + request.match_info["rest"]
            import aiohttp

            async with aiohttp.ClientSession() as s:
                async with s.post(
                    f"http://127.0.0.1:{es.http_port}{rest}",
                    data=await request.read(),
                    headers={"Content-Type":
                             request.headers.get("Content-Type", "")},
                ) as r:
                    return web.Response(status=r.status, body=await r.read(),
                                        content_type=r.content_type)

        gw = web.Application()
        gw.router.add_post("/seldon/{ns}/{name}/{rest:.*}", gateway)
        runner = web.AppRunner(gw)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        holder["engine"] = es
        holder["http"] = es.http_port
        holder["grpc"] = es.grpc_port
        holder["gateway"] = site._server.sockets[0].getsockname()[1]
        started.set()
        while not holder.get("stop"):
            await asyncio.sleep(0.05)
        await runner.cleanup()
        await es.stop()

    t = threading.Thread(target=lambda: asyncio.run(amain()), daemon=True)
    t.start()
    assert started.wait(30)
    yield holder
    holder["stop"] = True
    t.join(timeout=15)


def _write_contract(tmp_path):
    p = tmp_path / "contract.json"
    p.write_text(json.dumps(CONTRACT))
    return str(p)


def test_api_tester_rest_direct(live_engine, tmp_path):
    res = run_api_test(
        _write_contract(tmp_path), port=live_engine["http"],
        host="127.0.0.1", transport="rest", n_requests=5,
        with_feedback=True,
    )
    assert res["ok"], res["failures"]


def test_api_tester_grpc_direct(live_engine, tmp_path):
    res = run_api_test(
        _write_contract(tmp_path), host="127.0.0.1",
        grpc_port=live_engine["grpc"], transport="grpc", n_requests=5,
    )
    assert res["ok"], res["failures"]


def test_api_tester_through_gateway(live_engine, tmp_path):
    """deployment= routes REST through /seldon/{ns}/{name}/... — served
    here by the prefix-stripping gateway, proving the ingress path."""
    res = run_api_test(
        _write_contract(tmp_path), host="127.0.0.1",
        port=live_engine["gateway"], transport="rest", n_requests=5,
        deployment="t", namespace="default", with_feedback=True,
    )
    assert res["ok"], res["failures"]


def test_api_tester_detects_contract_violation(live_engine, tmp_path):
    """SIMPLE_MODEL emits 0.9/0.05/0.05 — a target range excluding 0.9
    must produce failures, proving validation actually bites."""
    bad = dict(CONTRACT)
    bad["targets"] = [{"name": "proba", "dtype": "FLOAT",
                       "ftype": "continuous", "range": [0.0, 0.5],
                       "repeat": 3}]
    p = tmp_path / "bad.json"
    p.write_text(json.dumps(bad))
    res = run_api_test(
        str(p), host="127.0.0.1", port=live_engine["http"],
        transport="rest", n_requests=2,
    )
    assert not res["ok"]
    assert any("out of range" in f for f in res["failures"])
