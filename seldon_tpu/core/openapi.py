"""OpenAPI 3 schema for the SeldonMessage REST surface.

Reference: `openapi/` (apife.oas3.json, engine.oas3.json) served by the
python wrapper at /seldon.json (wrapper.py:33-35). Generated rather than
vendored: the schema is derived from one source of truth here, so routes
and message shapes cannot drift from the servers that mount it.
"""

from __future__ import annotations

from typing import Dict, List

SELDON_MESSAGE_SCHEMA: Dict = {
    "type": "object",
    "properties": {
        "status": {
            "type": "object",
            "properties": {
                "code": {"type": "integer"},
                "info": {"type": "string"},
                "reason": {"type": "string"},
                "status": {"type": "integer"},
            },
        },
        "meta": {
            "type": "object",
            "properties": {
                "puid": {"type": "string"},
                "tags": {"type": "object", "additionalProperties": True},
                "routing": {
                    "type": "object",
                    "additionalProperties": {"type": "integer"},
                },
                "requestPath": {
                    "type": "object",
                    "additionalProperties": {"type": "string"},
                },
                "metrics": {
                    "type": "array",
                    "items": {
                        "type": "object",
                        "properties": {
                            "key": {"type": "string"},
                            "type": {
                                "type": "string",
                                "enum": ["COUNTER", "GAUGE", "TIMER"],
                            },
                            "value": {"type": "number"},
                        },
                    },
                },
            },
        },
        "data": {
            "type": "object",
            "properties": {
                "names": {"type": "array", "items": {"type": "string"}},
                "ndarray": {"type": "array", "items": {}},
                "tensor": {
                    "type": "object",
                    "properties": {
                        "shape": {
                            "type": "array", "items": {"type": "integer"},
                        },
                        "values": {
                            "type": "array", "items": {"type": "number"},
                        },
                    },
                },
                "dense": {
                    "type": "object",
                    "description": "bf16 packed tensor (base64 data)",
                    "properties": {
                        "shape": {
                            "type": "array", "items": {"type": "integer"},
                        },
                        "dtype": {"type": "string"},
                        "data": {"type": "string", "format": "byte"},
                    },
                },
            },
        },
        "binData": {"type": "string", "format": "byte"},
        "strData": {"type": "string"},
        "jsonData": {},
    },
}

FEEDBACK_SCHEMA: Dict = {
    "type": "object",
    "properties": {
        "request": SELDON_MESSAGE_SCHEMA,
        "response": SELDON_MESSAGE_SCHEMA,
        "reward": {"type": "number"},
        "truth": SELDON_MESSAGE_SCHEMA,
    },
}


def _msg_op(summary: str, request_schema: Dict) -> Dict:
    return {
        "summary": summary,
        "requestBody": {
            "required": True,
            "content": {
                "application/json": {"schema": request_schema},
                "application/x-protobuf": {
                    "schema": {"type": "string", "format": "binary"}
                },
            },
        },
        "responses": {
            "200": {
                "description": "SeldonMessage response",
                "content": {
                    "application/json": {"schema": SELDON_MESSAGE_SCHEMA}
                },
            },
            "400": {"description": "malformed request"},
            "500": {"description": "user code / graph failure"},
        },
    }


def unit_openapi(service_name: str = "seldon-tpu-microservice") -> Dict:
    """Spec for the per-unit microservice routes (wrapper.py)."""
    paths: Dict = {}
    for route, summary in [
        ("/predict", "Model prediction"),
        ("/transform-input", "Input transformation"),
        ("/transform-output", "Output transformation"),
        ("/route", "Router branch selection"),
        ("/aggregate", "Combiner aggregation"),
    ]:
        paths[route] = {"post": _msg_op(summary, SELDON_MESSAGE_SCHEMA)}
    paths["/send-feedback"] = {
        "post": _msg_op("Reward feedback", FEEDBACK_SCHEMA)
    }
    for route in list(paths):
        paths[f"/api/v0.1{route}"] = paths[route]
    paths["/generate"] = {
        "post": {
            "summary": "Text generation (jaxserver)",
            "requestBody": {
                "required": True,
                "content": {"application/json": {"schema": {
                    "type": "object",
                    "properties": {
                        "prompt": {"type": "string"},
                        "max_new_tokens": {"type": "integer"},
                        "temperature": {"type": "number"},
                        "top_k": {"type": "integer"},
                        "top_p": {"type": "number"},
                        "seed": {"type": "integer"},
                    },
                }}},
            },
            "responses": {"200": {"description": "generated text"}},
        }
    }
    paths["/live"] = {"get": {"summary": "liveness",
                              "responses": {"200": {"description": "ok"}}}}
    paths["/ready"] = {
        "get": {"summary": "readiness (incl. slice formation)",
                "responses": {"200": {"description": "ready"},
                              "503": {"description": "not ready"}}}
    }
    paths["/metadata"] = {
        "get": {"summary": "model metadata",
                "responses": {"200": {"description": "metadata JSON"}}}
    }
    for route in ("/metrics", "/prometheus"):
        paths[route] = {
            "get": {"summary": "prometheus exposition",
                    "responses": {"200": {"description": "metrics text"}}}
        }
    paths["/seldon.json"] = {
        "get": {"summary": "this schema",
                "responses": {"200": {"description": "OpenAPI document"}}}
    }
    return {
        "openapi": "3.0.3",
        "info": {"title": service_name, "version": "0.1.0"},
        "paths": paths,
    }


def _with_multipart(op: Dict) -> Dict:
    """Engine predictions also accept multipart/form-data: file parts map
    to binData/strData, plain fields parse as JSON subtrees
    (core/http.py:_merge_multipart; reference
    RestClientController.java:152-201)."""
    op = dict(op)
    op["requestBody"] = dict(op["requestBody"])
    content = dict(op["requestBody"]["content"])
    content["multipart/form-data"] = {
        "schema": {
            "type": "object",
            "properties": {
                "binData": {"type": "string", "format": "binary"},
                "strData": {"type": "string"},
                "data": {"type": "string",
                         "description": "JSON-encoded DefaultData"},
                "meta": {"type": "string", "description": "JSON-encoded Meta"},
            },
        }
    }
    op["requestBody"]["content"] = content
    return op


def engine_openapi(predictor: str = "predictor") -> Dict:
    """Spec for the engine's external API (orchestrator/server.py)."""
    return {
        "openapi": "3.0.3",
        "info": {"title": f"seldon-tpu engine ({predictor})",
                 "version": "0.1.0"},
        "paths": {
            "/api/v0.1/predictions": {
                "post": _with_multipart(
                    _msg_op("Graph prediction", SELDON_MESSAGE_SCHEMA)
                )
            },
            "/api/v0.1/feedback": {
                "post": _msg_op("Graph feedback (bandit reward routing)",
                                FEEDBACK_SCHEMA)
            },
            "/ready": {"get": {"summary": "whole-graph readiness",
                               "responses": {"200": {"description": "ready"},
                                             "503": {"description":
                                                     "not ready"}}}},
            "/live": {"get": {"summary": "liveness",
                              "responses": {"200": {"description": "ok"}}}},
            "/pause": {"post": {"summary": "drain traffic (preStop)",
                                "responses": {"200": {"description":
                                                      "paused"}}}},
            "/unpause": {"post": {"summary": "resume traffic",
                                  "responses": {"200": {"description":
                                                        "resumed"}}}},
            "/prometheus": {"get": {"summary": "prometheus exposition",
                                    "responses": {"200": {"description":
                                                          "metrics"}}}},
            "/metrics": {"get": {"summary": "prometheus exposition (alias)",
                                 "responses": {"200": {"description":
                                                       "metrics"}}}},
            "/seldon.json": {"get": {"summary": "this schema",
                                     "responses": {"200": {"description":
                                                           "OpenAPI doc"}}}},
        },
    }
