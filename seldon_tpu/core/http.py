"""Shared HTTP content negotiation for the proto/JSON dual REST surface.

One definition of the proto content type and the request-parse/response-
serialize logic, used by both the unit wrapper (runtime/wrapper.py) and the
engine server (orchestrator/server.py)."""

from __future__ import annotations

import json

from aiohttp import web

from seldon_tpu.core import payloads

PROTO_CONTENT_TYPE = "application/x-protobuf"
JSON_CONTENT_TYPE = "application/json"


def to_json_bytes(msg) -> bytes:
    """THE client-side JSON encoding of a proto message — one definition
    of the wire convention (field naming etc.), mirrored server-side by
    parse_message/reply. Used for foreign-language JSON units and the
    JSON client transports."""
    return json.dumps(payloads.message_to_dict(msg)).encode()


async def parse_message(request: web.Request, req_cls):
    """-> (proto message, encoding 'proto'|'json'). Accepts binary proto,
    JSON bodies, form `json=` fields, and GET `?json=` query params."""
    ctype = request.headers.get("Content-Type", "")
    if ctype.startswith(PROTO_CONTENT_TYPE):
        return req_cls.FromString(await request.read()), "proto"
    if request.method == "GET":
        raw = request.query.get("json")
        if raw is None:
            raise ValueError("empty json parameter in request")
        return payloads.dict_to_message(json.loads(raw), req_cls), "json"
    if ctype.startswith("application/json"):
        return payloads.dict_to_message(await request.json(), req_cls), "json"
    form = await request.post()
    raw = form.get("json")
    if raw is None:
        raise ValueError("no json payload in request")
    return payloads.dict_to_message(json.loads(raw), req_cls), "json"


def reply(msg, encoding: str) -> web.Response:
    if encoding == "proto":
        return web.Response(
            body=msg.SerializeToString(), content_type=PROTO_CONTENT_TYPE
        )
    return web.json_response(payloads.message_to_dict(msg))
