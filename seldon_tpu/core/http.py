"""Shared HTTP content negotiation for the proto/JSON dual REST surface.

One definition of the proto content type and the request-parse/response-
serialize logic, used by both the unit wrapper (runtime/wrapper.py) and the
engine server (orchestrator/server.py)."""

from __future__ import annotations

import json

from aiohttp import web

from seldon_tpu.core import payloads

PROTO_CONTENT_TYPE = "application/x-protobuf"
JSON_CONTENT_TYPE = "application/json"


def to_json_bytes(msg) -> bytes:
    """THE client-side JSON encoding of a proto message — one definition
    of the wire convention (field naming etc.), mirrored server-side by
    parse_message/reply. Used for foreign-language JSON units and the
    JSON client transports."""
    return json.dumps(payloads.message_to_dict(msg)).encode()


async def parse_message(request: web.Request, req_cls):
    """-> (proto message, encoding 'proto'|'json'). Accepts binary proto,
    JSON bodies, form `json=` fields, GET `?json=` query params, and
    `multipart/form-data` (file/field parts merged into one message)."""
    ctype = request.headers.get("Content-Type", "")
    if ctype.startswith(PROTO_CONTENT_TYPE):
        return req_cls.FromString(await request.read()), "proto"
    if request.method == "GET":
        raw = request.query.get("json")
        if raw is None:
            raise ValueError("empty json parameter in request")
        return payloads.dict_to_message(json.loads(raw), req_cls), "json"
    if ctype.startswith("application/json"):
        return payloads.dict_to_message(await request.json(), req_cls), "json"
    form = await request.post()
    if ctype.startswith("multipart/form-data"):
        return _merge_multipart(form, req_cls), "json"
    raw = form.get("json")
    if raw is None:
        raise ValueError("no json payload in request")
    return payloads.dict_to_message(json.loads(raw), req_cls), "json"


def _merge_multipart(form, req_cls):
    """Multipart prediction ingestion (reference engine
    RestClientController.java:152-201): every part key is a top-level
    SeldonMessage field; a part named `strData` (case-insensitive)
    contributes its content as text, file bytes under any other key are
    base64 (the proto-JSON encoding of `binData`), and plain fields are
    parsed as JSON subtrees (`data`, `jsonData`, `meta`, ...)."""
    import base64

    merged = {}
    for key, val in form.items():
        is_file = hasattr(val, "file")  # aiohttp FileField
        if key.lower() == "strdata":
            data = val.file.read() if is_file else val
            merged["strData"] = (
                data.decode() if isinstance(data, bytes) else data
            )
        elif is_file:
            raw = val.file.read()
            merged["binData" if key.lower() == "bindata" else key] = (
                base64.b64encode(raw).decode()
            )
        elif key.lower() == "bindata":
            merged["binData"] = val  # already base64 text
        else:
            merged[key] = json.loads(val)
    return payloads.dict_to_message(merged, req_cls)


def reply(msg, encoding: str) -> web.Response:
    if encoding == "proto":
        return web.Response(
            body=msg.SerializeToString(), content_type=PROTO_CONTENT_TYPE
        )
    return web.json_response(payloads.message_to_dict(msg))
