"""Distributed tracing: one trace spanning engine -> every unit -> model.

Reference: Jaeger via `TRACING=1` — engine `TracingProvider.java:1-37` +
REST/gRPC interceptors, python wrapper `microservice.py:115-150`. Neither
jaeger-client nor opentelemetry is in this image, so this is a small
OTel-modeled tracer of our own: W3C `traceparent` context propagation
(interoperable with any OTel collector at the wire level), contextvar
parenting (asyncio-safe — the reference's thread-local Jaeger scopes
can't follow an event loop), and pluggable exporters (in-memory for
tests, JSONL file for collection).

Enable with env `TRACING=1`. `TRACING_FILE` selects the JSONL sink
(default stderr). Spans carry: trace_id, span_id, parent_id, name,
service, start/end ns, attributes, status.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import json
import os
import secrets
import sys
import threading
import time
from typing import Any, Dict, List, Optional

_TRACEPARENT = "traceparent"  # W3C header/metadata key


@dataclasses.dataclass
class SpanContext:
    trace_id: str  # 32 hex chars
    span_id: str  # 16 hex chars

    def to_traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-01"

    @staticmethod
    def from_traceparent(value: str) -> Optional["SpanContext"]:
        parts = value.strip().split("-")
        if len(parts) < 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
            return None
        return SpanContext(trace_id=parts[1], span_id=parts[2])


@dataclasses.dataclass
class Span:
    name: str
    context: SpanContext
    parent_id: Optional[str]
    service: str
    start_ns: int
    end_ns: int = 0
    attributes: Dict[str, Any] = dataclasses.field(default_factory=dict)
    status: str = "OK"

    @property
    def trace_id(self) -> str:
        return self.context.trace_id

    @property
    def span_id(self) -> str:
        return self.context.span_id

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    def set_status(self, status: str) -> None:
        self.status = status

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "service": self.service,
            "start_ns": self.start_ns,
            "end_ns": self.end_ns,
            "duration_ms": round((self.end_ns - self.start_ns) / 1e6, 3),
            "attributes": self.attributes,
            "status": self.status,
        }


class InMemoryExporter:
    """Collects finished spans; the test exporter."""

    def __init__(self):
        self.spans: List[Span] = []
        self._lock = threading.Lock()

    def export(self, span: Span) -> None:
        with self._lock:
            self.spans.append(span)

    def by_trace(self) -> Dict[str, List[Span]]:
        with self._lock:
            out: Dict[str, List[Span]] = {}
            for s in self.spans:
                out.setdefault(s.trace_id, []).append(s)
            return out

    def clear(self) -> None:
        with self._lock:
            self.spans.clear()


class JsonlExporter:
    """One JSON object per finished span, appended to a file (or stderr)."""

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self._lock = threading.Lock()

    def export(self, span: Span) -> None:
        line = json.dumps(span.to_dict())
        with self._lock:
            if self.path:
                with open(self.path, "a") as f:
                    f.write(line + "\n")
            else:
                print(line, file=sys.stderr)


_current_span: contextvars.ContextVar[Optional[Span]] = contextvars.ContextVar(
    "seldon_tpu_current_span", default=None
)


class Tracer:
    def __init__(self, service: str, exporter=None, enabled: bool = True):
        self.service = service
        self.exporter = exporter or JsonlExporter(os.environ.get("TRACING_FILE"))
        self.enabled = enabled

    # -- span lifecycle ------------------------------------------------------

    def span(self, name: str, parent: Optional[SpanContext] = None,
             attributes: Optional[Dict[str, Any]] = None):
        """Context manager: opens a child of `parent`, else of the current
        contextvar span, else a new root. Disabled tracers return one
        shared nullcontext — a generator contextmanager per request is
        measurable overhead on the engine hot path."""
        if not self.enabled:
            return _NOOP_CM
        return self._span_cm(name, parent, attributes)

    @contextlib.contextmanager
    def _span_cm(self, name: str, parent: Optional[SpanContext],
                 attributes: Optional[Dict[str, Any]]):
        if parent is None:
            cur = _current_span.get()
            if cur is not None:
                parent = cur.context
        trace_id = parent.trace_id if parent else secrets.token_hex(16)
        span = Span(
            name=name,
            context=SpanContext(trace_id=trace_id, span_id=secrets.token_hex(8)),
            parent_id=parent.span_id if parent else None,
            service=self.service,
            start_ns=time.time_ns(),
            attributes=dict(attributes or {}),
        )
        token = _current_span.set(span)
        try:
            yield span
        except BaseException as e:
            span.set_status(f"ERROR: {type(e).__name__}")
            raise
        finally:
            _current_span.reset(token)
            span.end_ns = time.time_ns()
            try:
                self.exporter.export(span)
            except Exception:  # never let the sink break the request path
                pass

    # -- retro-emission ------------------------------------------------------

    def emit_span(
        self,
        name: str,
        start_ns: int,
        end_ns: int,
        parent: Optional[SpanContext] = None,
        context: Optional[SpanContext] = None,
        attributes: Optional[Dict[str, Any]] = None,
        status: str = "OK",
    ) -> Optional[SpanContext]:
        """Export a span after the fact, from recorded timestamps — no
        contextvars, no `with` scope. The engine scheduler uses this to
        reconstruct a request's lifecycle (queued/prefill/decode) at
        terminal time instead of holding open span objects on the hot
        path. Returns the span's context (for parenting children), or
        None when the tracer is disabled."""
        if not self.enabled:
            return None
        ctx = context or SpanContext(
            trace_id=parent.trace_id if parent else secrets.token_hex(16),
            span_id=secrets.token_hex(8),
        )
        span = Span(
            name=name,
            context=ctx,
            parent_id=parent.span_id if parent else None,
            service=self.service,
            start_ns=start_ns,
            end_ns=end_ns,
            attributes=dict(attributes or {}),
            status=status,
        )
        try:
            self.exporter.export(span)
        except Exception:  # never let the sink break the request path
            pass
        return ctx

    # -- propagation ---------------------------------------------------------

    def inject(self, carrier: Dict[str, str]) -> Dict[str, str]:
        """Write the current span's context into a header/metadata dict."""
        if self.enabled:
            cur = _current_span.get()
            if cur is not None:
                carrier[_TRACEPARENT] = cur.context.to_traceparent()
        return carrier

    @staticmethod
    def extract(carrier) -> Optional[SpanContext]:
        """Read a SpanContext from headers / gRPC metadata (any mapping or
        (key, value) iterable; keys case-insensitive)."""
        if carrier is None:
            return None
        items = carrier.items() if hasattr(carrier, "items") else carrier
        for k, v in items:
            if str(k).lower() == _TRACEPARENT:
                return SpanContext.from_traceparent(
                    v.decode() if isinstance(v, bytes) else str(v)
                )
        return None


def inject_current(carrier: Dict[str, str]) -> Dict[str, str]:
    """Module-level inject: writes the current span's traceparent into
    `carrier` if a span is open (no-op when tracing is off — the noop
    tracer never sets the contextvar)."""
    cur = _current_span.get()
    if cur is not None:
        carrier[_TRACEPARENT] = cur.context.to_traceparent()
    return carrier


class _NoopSpan:
    context = SpanContext(trace_id="0" * 32, span_id="0" * 16)
    parent_id = None

    def set_attribute(self, key, value):
        pass

    def set_status(self, status):
        pass


_NOOP_SPAN = _NoopSpan()
# nullcontext is stateless -> one shared instance serves every disabled
# span() call.
_NOOP_CM = contextlib.nullcontext(_NOOP_SPAN)
_NOOP_TRACER = Tracer("noop", enabled=False)


def tracing_enabled() -> bool:
    return os.environ.get("TRACING", "0") in ("1", "true", "True")


def get_tracer(service: str, exporter=None) -> Tracer:
    """Tracer for `service`; no-op unless TRACING=1 (or an explicit
    exporter is supplied, e.g. in tests)."""
    if exporter is not None:
        return Tracer(service, exporter=exporter, enabled=True)
    if not tracing_enabled():
        return _NOOP_TRACER
    return Tracer(service)


def current_span() -> Optional[Span]:
    return _current_span.get()


def new_traceparent() -> str:
    """A fresh W3C traceparent with random trace/span ids — for clients
    (loadtester) stamping requests so server-side spans can be pulled
    from the sink by trace id."""
    return SpanContext(
        trace_id=secrets.token_hex(16), span_id=secrets.token_hex(8)
    ).to_traceparent()
