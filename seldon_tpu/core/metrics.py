"""Custom-metrics helpers returned from user `metrics()` hooks.

Parity: /root/reference/python/seldon_core/metrics.py:1-89. Metric dicts are
propagated through `Meta.metrics` and aggregated by the orchestrator into
Prometheus counters/gauges/histograms.
"""

from __future__ import annotations

from typing import Dict, List, Optional

COUNTER = "COUNTER"
GAUGE = "GAUGE"
TIMER = "TIMER"

_TYPES = (COUNTER, GAUGE, TIMER)


def create_counter(key: str, value: float, tags: Optional[Dict[str, str]] = None) -> dict:
    return _metric(key, COUNTER, value, tags)


def create_gauge(key: str, value: float, tags: Optional[Dict[str, str]] = None) -> dict:
    return _metric(key, GAUGE, value, tags)


def create_timer(key: str, value: float, tags: Optional[Dict[str, str]] = None) -> dict:
    """value is milliseconds, matching the reference's TIMER convention."""
    return _metric(key, TIMER, value, tags)


def _metric(key: str, mtype: str, value: float, tags: Optional[Dict[str, str]]) -> dict:
    m = {"key": key, "type": mtype, "value": float(value)}
    if tags:
        m["tags"] = {str(k): str(v) for k, v in tags.items()}
    return m


def validate_metrics(metrics: List[dict]) -> bool:
    """Schema check mirroring reference `validate_metrics`
    (/root/reference/python/seldon_core/metrics.py:41-57)."""
    if not isinstance(metrics, (list, tuple)):
        return False
    for m in metrics:
        if not isinstance(m, dict):
            return False
        if "key" not in m or "value" not in m:
            return False
        if m.get("type", COUNTER) not in _TYPES:
            return False
        try:
            float(m["value"])
        except (TypeError, ValueError):
            return False
    return True
