from seldon_tpu.core import payloads
from seldon_tpu.core.metrics import create_counter, create_gauge, create_timer, validate_metrics

__all__ = ["payloads", "create_counter", "create_gauge", "create_timer", "validate_metrics"]
