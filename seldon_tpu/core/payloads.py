"""Payload codecs: numpy/JAX arrays <-> SeldonMessage protos <-> JSON.

Capability parity with the reference codec layer
(/root/reference/python/seldon_core/utils.py:17-566 — `array_to_grpc_datadef`,
`grpc_datadef_to_array`, `construct_response`, `extract_request_parts` and
their JSON duals), redesigned for TPU serving:

 * `DenseTensor` is the preferred wire type: dtype-tagged raw bytes (incl.
   bfloat16 via ml_dtypes) so device arrays cross process boundaries without
   float64 widening or JSON text. The reference's REST hot path re-encodes
   every tensor as JSON text at every graph hop (SURVEY.md §3.2); the 2.3x
   gRPC-vs-REST gap in its own benchmark is that tax.
 * Codecs accept jax.Array transparently (np.asarray pulls from device; the
   jaxserver hands back numpy views of committed host buffers).
"""

from __future__ import annotations

import base64
import json
from typing import Any, List, Optional, Sequence, Union

import numpy as np

try:  # ml_dtypes ships with jax; guard anyway so codecs work standalone.
    import ml_dtypes

    _BFLOAT16 = np.dtype(ml_dtypes.bfloat16)
except Exception:  # pragma: no cover
    ml_dtypes = None
    _BFLOAT16 = None

from google.protobuf import json_format
from google.protobuf.struct_pb2 import ListValue, Value

from seldon_tpu.proto import prediction_pb2 as pb

__all__ = [
    "array_to_dense",
    "dense_to_array",
    "array_to_tensor",
    "tensor_to_array",
    "array_to_listvalue",
    "listvalue_to_array",
    "array_to_data",
    "data_to_array",
    "get_data_from_message",
    "build_message",
    "construct_response",
    "extract_request_parts",
    "message_to_dict",
    "dict_to_message",
    "json_to_feedback",
    "feedback_to_dict",
]

# ---------------------------------------------------------------------------
# DenseTensor (TPU-native packed tensor)
# ---------------------------------------------------------------------------

_DT_TO_NP = {
    pb.DT_FLOAT32: np.dtype(np.float32),
    pb.DT_FLOAT64: np.dtype(np.float64),
    pb.DT_FLOAT16: np.dtype(np.float16),
    pb.DT_INT8: np.dtype(np.int8),
    pb.DT_INT16: np.dtype(np.int16),
    pb.DT_INT32: np.dtype(np.int32),
    pb.DT_INT64: np.dtype(np.int64),
    pb.DT_UINT8: np.dtype(np.uint8),
    pb.DT_UINT16: np.dtype(np.uint16),
    pb.DT_UINT32: np.dtype(np.uint32),
    pb.DT_UINT64: np.dtype(np.uint64),
    pb.DT_BOOL: np.dtype(np.bool_),
}
if _BFLOAT16 is not None:
    _DT_TO_NP[pb.DT_BFLOAT16] = _BFLOAT16

_NP_TO_DT = {v: k for k, v in _DT_TO_NP.items()}


def array_to_dense(arr: Any) -> pb.DenseTensor:
    arr = np.ascontiguousarray(np.asarray(arr))
    dt = _NP_TO_DT.get(arr.dtype)
    if dt is None:
        # Fall back to float32 for exotic dtypes rather than failing the wire.
        arr = arr.astype(np.float32)
        dt = pb.DT_FLOAT32
    return pb.DenseTensor(dtype=dt, shape=list(arr.shape), data=arr.tobytes())


def dense_to_array(dense: pb.DenseTensor, writable: bool = True) -> np.ndarray:
    """`writable=True` (default) copies out of the proto buffer so user hooks
    may mutate in place; internal fast paths that immediately hand the array
    to jnp.asarray pass writable=False to skip the copy."""
    np_dtype = _DT_TO_NP.get(dense.dtype)
    if np_dtype is None:
        raise ValueError(f"unsupported DenseTensor dtype {dense.dtype}")
    arr = np.frombuffer(dense.data, dtype=np_dtype).reshape(tuple(dense.shape))
    return arr.copy() if writable else arr


# ---------------------------------------------------------------------------
# Tensor / ndarray (reference-compatible forms)
# ---------------------------------------------------------------------------


def array_to_tensor(arr: Any) -> pb.Tensor:
    arr = np.asarray(arr, dtype=np.float64)
    return pb.Tensor(shape=list(arr.shape), values=arr.ravel().tolist())


def tensor_to_array(tensor: pb.Tensor) -> np.ndarray:
    arr = np.asarray(tensor.values, dtype=np.float64)
    if tensor.shape:
        arr = arr.reshape(tuple(tensor.shape))
    return arr


def array_to_listvalue(arr: Any) -> ListValue:
    lv = ListValue()
    lv.extend(np.asarray(arr).tolist())
    return lv


def listvalue_to_array(lv: ListValue) -> np.ndarray:
    return np.asarray(json_format.MessageToDict(lv))


# ---------------------------------------------------------------------------
# DefaultData
# ---------------------------------------------------------------------------

_DATA_KINDS = ("dense", "tensor", "ndarray")


def array_to_data(
    arr: Any, names: Optional[Sequence[str]] = None, kind: str = "dense"
) -> pb.DefaultData:
    data = pb.DefaultData()
    if names:
        data.names.extend([str(n) for n in names])
    if kind == "dense":
        data.dense.CopyFrom(array_to_dense(arr))
    elif kind == "tensor":
        data.tensor.CopyFrom(array_to_tensor(arr))
    elif kind == "ndarray":
        data.ndarray.CopyFrom(array_to_listvalue(arr))
    else:
        raise ValueError(f"unknown data kind {kind!r}; expected one of {_DATA_KINDS}")
    return data


def data_to_array(data: pb.DefaultData) -> np.ndarray:
    which = data.WhichOneof("data_oneof")
    if which == "dense":
        return dense_to_array(data.dense)
    if which == "tensor":
        return tensor_to_array(data.tensor)
    if which == "ndarray":
        return listvalue_to_array(data.ndarray)
    return np.array([])


def data_kind(msg: pb.SeldonMessage) -> str:
    """Which payload form a message carries ('dense'|'tensor'|'ndarray'|
    'binData'|'strData'|'jsonData'|'')."""
    which = msg.WhichOneof("data_oneof")
    if which == "data":
        return msg.data.WhichOneof("data_oneof") or ""
    return which or ""


def get_data_from_message(msg: pb.SeldonMessage) -> Any:
    """Extract the payload: ndarray for data, bytes/str/py-obj otherwise."""
    which = msg.WhichOneof("data_oneof")
    if which == "data":
        return data_to_array(msg.data)
    if which == "binData":
        return msg.binData
    if which == "strData":
        return msg.strData
    if which == "jsonData":
        return json_format.MessageToDict(msg.jsonData)
    return np.array([])


def build_message(
    payload: Any,
    names: Optional[Sequence[str]] = None,
    kind: str = "dense",
    meta: Optional[pb.Meta] = None,
) -> pb.SeldonMessage:
    """Build a SeldonMessage around `payload` (array/bytes/str/dict)."""
    msg = pb.SeldonMessage()
    if meta is not None:
        msg.meta.CopyFrom(meta)
    if isinstance(payload, bytes):
        msg.binData = payload
    elif isinstance(payload, str):
        msg.strData = payload
    elif isinstance(payload, (dict, list)) and kind == "jsonData":
        json_format.ParseDict(payload, msg.jsonData)
    else:
        msg.data.CopyFrom(array_to_data(payload, names, kind))
    return msg


# ---------------------------------------------------------------------------
# Request/response plumbing used by the method dispatch layer
# ---------------------------------------------------------------------------


def extract_request_parts(msg: pb.SeldonMessage):
    """-> (payload, meta, datadef, data_kind).

    Mirrors reference `extract_request_parts`
    (/root/reference/python/seldon_core/utils.py:527-566).
    """
    payload = get_data_from_message(msg)
    which = msg.WhichOneof("data_oneof")
    datadef = msg.data if which == "data" else None
    return payload, msg.meta, datadef, data_kind(msg)


def construct_response(
    user_model: Any,
    is_request: bool,
    client_request: pb.SeldonMessage,
    client_raw_response: Any,
    meta: Optional[pb.Meta] = None,
    tags: Optional[dict] = None,
    metrics: Optional[List[dict]] = None,
) -> pb.SeldonMessage:
    """Wrap a user function's raw output, mirroring the input payload form.

    Parity: reference `construct_response`
    (/root/reference/python/seldon_core/utils.py:410-471). The response uses
    the same wire form the request used (dense stays dense, tensor stays
    tensor, ...) so graph hops never silently widen dtypes.
    """
    if isinstance(client_raw_response, pb.SeldonMessage):
        return client_raw_response

    req_kind = data_kind(client_request)
    msg = pb.SeldonMessage()
    if meta is not None:
        msg.meta.CopyFrom(meta)
    if client_request.meta.puid:
        msg.meta.puid = client_request.meta.puid

    names: List[str] = []
    if user_model is not None:
        cn = getattr(user_model, "class_names", None)
        if callable(cn):
            try:
                names = list(cn() or [])
            except Exception:
                names = []
        elif isinstance(cn, (list, tuple)):
            names = list(cn)

    if isinstance(client_raw_response, bytes):
        msg.binData = client_raw_response
    elif isinstance(client_raw_response, str):
        msg.strData = client_raw_response
    elif isinstance(client_raw_response, dict) or (
        req_kind == "jsonData" and isinstance(client_raw_response, (dict, list))
    ):
        json_format.ParseDict(client_raw_response, msg.jsonData)
    else:
        kind = req_kind if req_kind in _DATA_KINDS else "dense"
        arr = np.asarray(client_raw_response)
        if arr.dtype.kind in "USO" and kind != "ndarray":
            # Non-numeric outputs (string labels, mixed objects) can't pack
            # into dense/tensor — fall back to the nested-list form, matching
            # reference behavior (utils.py:450-459).
            kind = "ndarray"
        msg.data.CopyFrom(array_to_data(arr, names, kind))

    if tags:
        for k, v in tags.items():
            if isinstance(v, (dict, list)):
                json_format.ParseDict(v, msg.meta.tags[k])
            else:
                _set_value(msg.meta.tags[k], v)
    if metrics:
        add_metric_dicts(msg.meta.metrics, metrics)
    return msg


def add_metric_dicts(repeated_metrics, dicts) -> None:
    """Append metric DICTS ({key,value,type,tags}) onto a repeated
    pb.Metric field — the one definition of the dict->Metric wire
    conversion (used by construct_response and the wrapper's generate
    metrics absorption)."""
    for m in dicts:
        metric = repeated_metrics.add()
        metric.key = m.get("key", "")
        metric.value = float(m.get("value", 0.0))
        metric.type = pb.Metric.MetricType.Value(m.get("type", "COUNTER"))
        for tk, tv in (m.get("tags") or {}).items():
            metric.tags[tk] = str(tv)


def _set_value(value: Value, py: Any) -> None:
    if isinstance(py, bool):
        value.bool_value = py
    elif isinstance(py, (int, float)):
        value.number_value = float(py)
    elif py is None:
        value.null_value = 0
    else:
        value.string_value = str(py)


# ---------------------------------------------------------------------------
# JSON <-> proto (REST path)
# ---------------------------------------------------------------------------


def message_to_dict(msg) -> dict:
    """Proto -> plain dict. binData is base64'd; DenseTensor data is base64'd
    with dtype/shape kept readable."""
    return json_format.MessageToDict(msg, preserving_proto_field_name=True)


def dict_to_message(d: Union[dict, str], cls=pb.SeldonMessage):
    if isinstance(d, str):
        d = json.loads(d)
    msg = cls()
    json_format.ParseDict(d, msg, ignore_unknown_fields=True)
    return msg


def json_to_feedback(d: Union[dict, str]) -> pb.Feedback:
    return dict_to_message(d, pb.Feedback)


def feedback_to_dict(fb: pb.Feedback) -> dict:
    return json_format.MessageToDict(fb, preserving_proto_field_name=True)


def ndarray_from_json_payload(payload: dict) -> np.ndarray:
    """Pull an ndarray out of a REST JSON body ({'data': {'tensor'|'ndarray'|
    'dense': ...}})."""
    return get_data_from_message(dict_to_message(payload))


def b64_bytes(data: bytes) -> str:
    return base64.b64encode(data).decode("ascii")
