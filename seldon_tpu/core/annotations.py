"""Downward-API annotation config (reference AnnotationsConfig.java:1-67).

K8s mounts pod annotations at /etc/podinfo/annotations in the downward
API format — one `key="value"` per line. The operator wires that volume
onto the engine container (reconciler.py) so runtime knobs set as CR
annotations (timeouts, retries, gRPC message caps) reach the process
without an image rebuild, exactly like the reference engine.

Known knobs (same names as the reference, ambassador.go:10-22 +
SeldonGrpcServer.java:40):
  seldon.io/rest-read-timeout        ms, engine->unit REST read timeout
  seldon.io/rest-connection-timeout  ms, connect timeout
  seldon.io/rest-connect-retries     engine->unit retry count
  seldon.io/grpc-read-timeout        ms, engine->unit gRPC deadline
  seldon.io/grpc-max-message-size    bytes, server + channel caps
"""

from __future__ import annotations

import logging
import os
from typing import Dict, Optional

logger = logging.getLogger(__name__)

PODINFO_PATH = "/etc/podinfo/annotations"

REST_READ_TIMEOUT = "seldon.io/rest-read-timeout"
REST_CONNECTION_TIMEOUT = "seldon.io/rest-connection-timeout"
REST_CONNECT_RETRIES = "seldon.io/rest-connect-retries"
GRPC_READ_TIMEOUT = "seldon.io/grpc-read-timeout"
GRPC_MAX_MSG_SIZE = "seldon.io/grpc-max-message-size"


def parse_downward_api(text: str) -> Dict[str, str]:
    """Parse the downward-API annotations format: `key="escaped value"`
    per line (the value is a Go-quoted string)."""
    out: Dict[str, str] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or "=" not in line:
            continue
        key, _, raw = line.partition("=")
        raw = raw.strip()
        if raw.startswith('"') and raw.endswith('"') and len(raw) >= 2:
            raw = raw[1:-1]
            # Unescape Go escapes in a SINGLE pass — sequential replaces
            # corrupt values like 'C:\\network' (the \\ pair must not be
            # re-read as the start of \n).
            import re as _re

            raw = _re.sub(
                r"\\(.)",
                lambda m: {"n": "\n", "t": "\t"}.get(m.group(1),
                                                     m.group(1)),
                raw,
            )
        out[key.strip()] = raw
    return out


class AnnotationsConfig:
    """Lazy view over the podinfo annotations file (missing file -> {})."""

    def __init__(self, path: Optional[str] = None):
        self.path = path or os.environ.get("PODINFO_ANNOTATIONS",
                                           PODINFO_PATH)
        self._annotations: Optional[Dict[str, str]] = None

    @property
    def annotations(self) -> Dict[str, str]:
        if self._annotations is None:
            try:
                with open(self.path) as f:
                    self._annotations = parse_downward_api(f.read())
                logger.info("loaded %d pod annotations from %s",
                            len(self._annotations), self.path)
            except FileNotFoundError:
                self._annotations = {}
        return self._annotations

    def get(self, key: str, default: str = "") -> str:
        return self.annotations.get(key, default)

    def get_int(self, key: str, default: int) -> int:
        raw = self.annotations.get(key)
        if raw is None:
            return default
        try:
            return int(raw)
        except ValueError:
            logger.warning("annotation %s=%r is not an int; using %d",
                           key, raw, default)
            return default

    # Typed accessors for the engine's knobs.

    def rest_timeout_s(self, default_ms: int = 5000) -> float:
        return self.get_int(REST_READ_TIMEOUT, default_ms) / 1000.0

    def connect_retries(self, default: int = 3) -> int:
        return self.get_int(REST_CONNECT_RETRIES, default)

    def grpc_timeout_s(self, default_ms: int = 5000) -> float:
        return self.get_int(GRPC_READ_TIMEOUT, default_ms) / 1000.0

    def grpc_max_msg_bytes(self, default: int = 512 * 1024 * 1024) -> int:
        return self.get_int(GRPC_MAX_MSG_SIZE, default)
