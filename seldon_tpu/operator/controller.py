"""Operator entrypoint: watch SeldonDeployments, reconcile, serve webhooks.

Reference: operator/main.go:54-97 (controller-runtime manager registering
the reconciler + admission webhooks). Redesign: a plain list+watch loop
over the KubeStore REST client — no informer cache machinery; the
reconciler is already idempotent, so at-least-once event delivery plus a
periodic full resync gives the same convergence guarantees with ~100
lines instead of a framework.

Run: `python -m seldon_tpu.operator.controller` (in-cluster), flags for
namespace / resync period / webhook port. The admission webhook server
implements AdmissionReview v1 over the SAME pure functions the CLI path
uses (webhook.py default_deployment/validate_deployment), so cluster and
library behavior can never drift.
"""

from __future__ import annotations

import base64
import copy
import json
import logging
import threading
import time
from typing import Dict, Optional

from seldon_tpu.operator import types as T
from seldon_tpu.operator.kubestore import KubeApiError, KubeStore
from seldon_tpu.operator.reconciler import Reconciler
from seldon_tpu.operator.webhook import default_deployment, validate_deployment

logger = logging.getLogger(__name__)


class ControllerLoop:
    """List+watch+reconcile until stopped."""

    def __init__(self, store: KubeStore, namespace: str = "default",
                 resync_s: float = 30.0, istio_enabled: bool = True):
        self.store = store
        self.namespace = namespace
        self.resync_s = resync_s
        self.reconciler = Reconciler(store, istio_enabled=istio_enabled)
        self._stop = threading.Event()
        self.reconcile_count = 0
        self._list_rv = ""

    def stop(self) -> None:
        self._stop.set()

    # -- one reconcile ------------------------------------------------------

    def reconcile_object(self, obj: Dict) -> Optional[T.DeploymentStatus]:
        try:
            sdep = T.SeldonDeployment.from_dict(obj)
        except Exception:
            logger.exception("unparseable SeldonDeployment: %s",
                             obj.get("metadata", {}).get("name"))
            return None
        status = self.reconciler.reconcile(sdep)
        self.reconcile_count += 1
        try:
            self.store.update_status(
                "SeldonDeployment", sdep.namespace, sdep.name,
                {"state": status.state, "description": status.description},
            )
        except KubeApiError as e:
            logger.warning("status update failed for %s: %s", sdep.name, e)
        return status

    def resync(self) -> int:
        """Full list + reconcile; returns number of objects handled.
        Remembers the list's resourceVersion so the following watch
        starts after it (no synthetic ADDED replay)."""
        lister = getattr(self.store, "list_with_version", None)
        if lister is not None:
            objs, self._list_rv = lister("SeldonDeployment", self.namespace)
        else:
            objs = self.store.list("SeldonDeployment", self.namespace)
            self._list_rv = ""
        for obj in objs:
            self.reconcile_object(obj)
        return len(objs)

    # -- the loop ------------------------------------------------------------

    def run(self) -> None:
        while not self._stop.is_set():
            try:
                self.resync()
                # timeout_s makes the SERVER close the watch at the resync
                # period, so quiet clusters still resync on schedule
                # instead of blocking in a long read.
                for event in self.store.watch(
                    "SeldonDeployment", self.namespace,
                    resource_version=self._list_rv,
                    timeout_s=self.resync_s,
                ):
                    if self._stop.is_set():
                        return
                    etype = event.get("type")
                    obj = event.get("object", {})
                    if etype in ("ADDED", "MODIFIED"):
                        self.reconcile_object(obj)
                    elif etype == "DELETED":
                        # ownerReferences cascade in-cluster; this explicit
                        # sweep covers stores without GC and pre-ownerRef
                        # resources.
                        meta = obj.get("metadata", {})
                        if meta.get("name"):
                            self.reconciler.delete_all(
                                meta["name"],
                                meta.get("namespace", self.namespace),
                            )
            except KubeApiError as e:
                logger.warning("watch/list failed (%s); retrying", e)
                self._stop.wait(2.0)
            except Exception:
                logger.exception("controller loop error; retrying")
                self._stop.wait(2.0)


# ---------------------------------------------------------------------------
# Admission webhooks (AdmissionReview v1)
# ---------------------------------------------------------------------------


def handle_admission_review(review: Dict, mutate: bool) -> Dict:
    """Pure AdmissionReview v1 handler shared by tests and the server.

    mutate=True -> defaulting webhook (JSONPatch response);
    mutate=False -> validating webhook (allowed true/false)."""
    req = review.get("request", {})
    uid = req.get("uid", "")
    obj = req.get("object", {}) or {}
    resp: Dict = {"uid": uid, "allowed": True}
    try:
        sdep = T.SeldonDeployment.from_dict(obj)
        if mutate:
            default_deployment(sdep)
            patched = sdep.to_dict()
            # Replace spec+metadata wholesale; k8s applies RFC-6902 patches.
            patch = [
                {"op": "replace", "path": "/spec", "value": patched["spec"]},
            ]
            resp["patchType"] = "JSONPatch"
            resp["patch"] = base64.b64encode(
                json.dumps(patch).encode()
            ).decode()
        else:
            default_deployment(sdep)  # validate what would actually deploy
            problems = validate_deployment(sdep)
            if problems:
                resp["allowed"] = False
                resp["status"] = {"message": "; ".join(problems)}
    except Exception as e:
        resp["allowed"] = False
        resp["status"] = {"message": f"malformed SeldonDeployment: {e}"}
    return {
        "apiVersion": "admission.k8s.io/v1",
        "kind": "AdmissionReview",
        "response": resp,
    }


def build_webhook_app():
    """aiohttp app serving /mutate and /validate."""
    from aiohttp import web

    async def mutate(request: web.Request) -> web.Response:
        return web.json_response(
            handle_admission_review(await request.json(), mutate=True)
        )

    async def validate(request: web.Request) -> web.Response:
        return web.json_response(
            handle_admission_review(await request.json(), mutate=False)
        )

    async def healthz(request: web.Request) -> web.Response:
        return web.Response(text="ok")

    app = web.Application()
    app.router.add_post("/mutate", mutate)
    app.router.add_post("/validate", validate)
    app.router.add_get("/healthz", healthz)
    return app


def main(argv=None) -> None:  # pragma: no cover - CLI entry
    import argparse

    parser = argparse.ArgumentParser(description="seldon-tpu operator")
    parser.add_argument("--namespace", default="default")
    parser.add_argument("--resync-seconds", type=float, default=30.0)
    parser.add_argument("--istio", type=int, default=1)
    parser.add_argument("--webhook-port", type=int, default=0,
                        help="serve admission webhooks when > 0")
    parser.add_argument("--api-server", default="",
                        help="override API server URL (tests)")
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    store = KubeStore(base_url=args.api_server or None)
    loop = ControllerLoop(store, namespace=args.namespace,
                          resync_s=args.resync_seconds,
                          istio_enabled=bool(args.istio))

    if args.webhook_port:
        import asyncio
        import os
        import ssl

        from aiohttp import web

        # The apiserver only calls webhooks over HTTPS; cert-manager (or
        # the operator chart) mounts the serving cert at WEBHOOK_CERT_DIR
        # (default: the conventional controller-runtime path).
        cert_dir = os.environ.get(
            "WEBHOOK_CERT_DIR", "/tmp/k8s-webhook-server/serving-certs"
        )
        crt = os.path.join(cert_dir, "tls.crt")
        key = os.path.join(cert_dir, "tls.key")
        ssl_ctx = None
        if os.path.exists(crt) and os.path.exists(key):
            ssl_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ssl_ctx.load_cert_chain(crt, key)
        else:
            logger.warning(
                "no webhook TLS cert at %s — serving PLAINTEXT "
                "(dev only; the apiserver requires HTTPS)", cert_dir,
            )

        def serve_webhooks():
            async def run():
                runner = web.AppRunner(build_webhook_app())
                await runner.setup()
                await web.TCPSite(
                    runner, "0.0.0.0", args.webhook_port, ssl_context=ssl_ctx
                ).start()
                while True:
                    await asyncio.sleep(3600)

            asyncio.run(run())

        threading.Thread(target=serve_webhooks, daemon=True).start()

    loop.run()


if __name__ == "__main__":  # pragma: no cover
    main()
