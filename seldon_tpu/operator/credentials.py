"""Storage credential injection for the model initializer.

Reference parity:
  operator/controllers/resources/credentials/service_account_credentials.go:1-113
  operator/controllers/resources/credentials/s3/s3_secret.go:1-156
  operator/controllers/resources/credentials/gcs/gcs_secret.go:1-49

The reference reads a `credentials` JSON blob from the `seldon-config`
ConfigMap, walks the predictor's ServiceAccount's secrets, and wires the
first matching S3 secret as env vars (secretKeyRef) and the first GCS
secret as a mounted volume + GOOGLE_APPLICATION_CREDENTIALS. This module
reproduces that contract against our raw-manifest Store (kubestore /
InMemoryStore / LocalProcessStore) so `gs://` and `s3://` model URIs work
for private buckets, with `servers/storage.py` consuming the standard
env/credential-file conventions on the other end.
"""

from __future__ import annotations

import dataclasses
import json
import logging
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)

# ConfigMap contract (the operator's own config object).
CONFIGMAP_NAME = "seldon-config"
CREDENTIAL_CONFIG_KEY = "credentials"

# S3 env contract (s3_secret.go:23-35).
AWS_ACCESS_KEY_ID = "AWS_ACCESS_KEY_ID"
AWS_SECRET_ACCESS_KEY = "AWS_SECRET_ACCESS_KEY"
AWS_ENDPOINT_URL = "AWS_ENDPOINT_URL"
AWS_REGION = "AWS_REGION"
S3_ENDPOINT = "S3_ENDPOINT"
S3_USE_HTTPS = "S3_USE_HTTPS"
S3_VERIFY_SSL = "S3_VERIFY_SSL"
# Secret DATA key names holding the credential material (overridable via
# the ConfigMap).
S3_ACCESS_KEY_ID_NAME = "awsAccessKeyID"
S3_SECRET_ACCESS_KEY_NAME = "awsSecretAccessKey"
# Secret ANNOTATION suffixes (s3_secret.go:45-50); both API-group
# prefixes are honored, ours first.
API_GROUP = "machinelearning.seldon.io"
FALLBACK_API_GROUP = "serving.kubeflow.org"
_ANN_ENDPOINT = "/s3-endpoint"
_ANN_REGION = "/s3-region"
_ANN_VERIFY_SSL = "/s3-verifyssl"
_ANN_USE_HTTPS = "/s3-usehttps"

# GCS contract (gcs_secret.go:23-28).
GCS_CREDENTIAL_FILE_NAME = "gcloud-application-credentials.json"
GCS_VOLUME_NAME = "user-gcp-sa"
GCS_MOUNT_PATH = "/var/secrets/"
GCS_CREDENTIAL_ENV = "GOOGLE_APPLICATION_CREDENTIALS"


@dataclasses.dataclass(frozen=True)
class S3Config:
    access_key_id_name: str = ""
    secret_access_key_name: str = ""
    endpoint: str = ""
    use_https: str = ""


@dataclasses.dataclass(frozen=True)
class GCSConfig:
    credential_file_name: str = ""


@dataclasses.dataclass(frozen=True)
class CredentialConfig:
    s3: S3Config = dataclasses.field(default_factory=S3Config)
    gcs: GCSConfig = dataclasses.field(default_factory=GCSConfig)

    @staticmethod
    def from_configmap(cm: Optional[Dict]) -> "CredentialConfig":
        """Parse the `credentials` key of a seldon-config ConfigMap
        manifest; malformed JSON is a config error worth failing loudly
        on (the reference panics — service_account_credentials.go:55)."""
        if not cm:
            return CredentialConfig()
        raw = (cm.get("data") or {}).get(CREDENTIAL_CONFIG_KEY)
        if not raw:
            return CredentialConfig()
        d = json.loads(raw)
        if not isinstance(d, dict):
            raise ValueError(
                f"credentials entry must be a JSON object, got {type(d).__name__}"
            )
        s3d, gcsd = d.get("s3", {}), d.get("gcs", {})
        return CredentialConfig(
            s3=S3Config(
                access_key_id_name=s3d.get("s3AccessKeyIDName", ""),
                secret_access_key_name=s3d.get("s3SecretAccessKeyName", ""),
                endpoint=s3d.get("s3Endpoint", ""),
                use_https=s3d.get("s3UseHttps", ""),
            ),
            gcs=GCSConfig(
                credential_file_name=gcsd.get("gcsCredentialFileName", ""),
            ),
        )


def _store_get(store, kind: str, namespace: str, name: str) -> Optional[Dict]:
    """Fetch one object by name: a Store exposing `get` (KubeStore —
    single apiserver GET) is preferred; otherwise fall back to list+filter
    (InMemoryStore / LocalProcessStore)."""
    getter = getattr(store, "get", None)
    if callable(getter):
        return getter(kind, namespace, name)
    for obj in store.list(kind, namespace):
        if obj["metadata"]["name"] == name:
            return obj
    return None


def _annotation(secret: Dict, suffix: str) -> Optional[str]:
    anns = secret.get("metadata", {}).get("annotations") or {}
    for group in (API_GROUP, FALLBACK_API_GROUP):
        if group + suffix in anns:
            return anns[group + suffix]
    return None


def build_s3_envs(secret: Dict, cfg: S3Config) -> List[Dict]:
    """S3 secret -> env var list (s3_secret.go:52-156): key material via
    secretKeyRef (values never enter the manifest), endpoint/region/ssl
    via secret annotations, falling back to the ConfigMap endpoint."""
    key_id_name = cfg.access_key_id_name or S3_ACCESS_KEY_ID_NAME
    secret_key_name = cfg.secret_access_key_name or S3_SECRET_ACCESS_KEY_NAME
    name = secret["metadata"]["name"]
    envs = [
        {"name": AWS_ACCESS_KEY_ID,
         "valueFrom": {"secretKeyRef": {"name": name, "key": key_id_name}}},
        {"name": AWS_SECRET_ACCESS_KEY,
         "valueFrom": {"secretKeyRef": {"name": name,
                                        "key": secret_key_name}}},
    ]
    endpoint = _annotation(secret, _ANN_ENDPOINT)
    use_https = _annotation(secret, _ANN_USE_HTTPS)
    if endpoint is None and cfg.endpoint:
        endpoint, use_https = cfg.endpoint, (cfg.use_https or None)
    if endpoint is not None:
        scheme = "http" if use_https == "0" else "https"
        if use_https is not None:
            envs.append({"name": S3_USE_HTTPS, "value": use_https})
        envs.append({"name": S3_ENDPOINT, "value": endpoint})
        envs.append(
            {"name": AWS_ENDPOINT_URL, "value": f"{scheme}://{endpoint}"}
        )
    region = _annotation(secret, _ANN_REGION)
    if region is not None:
        envs.append({"name": AWS_REGION, "value": region})
    verify = _annotation(secret, _ANN_VERIFY_SSL)
    if verify is not None:
        envs.append({"name": S3_VERIFY_SSL, "value": verify})
    return envs


def build_gcs_volume(secret: Dict, file_name: str):
    """GCS secret -> (volume, volumeMount, env) (gcs_secret.go:34-49)."""
    volume = {
        "name": GCS_VOLUME_NAME,
        "secret": {"secretName": secret["metadata"]["name"]},
    }
    mount = {"name": GCS_VOLUME_NAME, "mountPath": GCS_MOUNT_PATH,
             "readOnly": True}
    env = {"name": GCS_CREDENTIAL_ENV, "value": GCS_MOUNT_PATH + file_name}
    return volume, mount, env


class CredentialBuilder:
    """Walks a ServiceAccount's secrets and injects the first S3 match as
    envs and the first GCS match as a volume, onto the model-initializer
    container (service_account_credentials.go:64-113)."""

    def __init__(self, store, config: Optional[CredentialConfig] = None):
        self.store = store
        self.config = config or CredentialConfig()
        # Memo for SA/Secret reads: one builder instance lives for one
        # desired_manifests() pass, so a multi-unit graph hits the
        # apiserver once per object, not once per unit.
        self._cache: Dict[tuple, Optional[Dict]] = {}

    @staticmethod
    def from_store(store, namespaces=("seldon-system", "default")) -> (
            "CredentialBuilder"):
        """Locate the seldon-config ConfigMap in the usual namespaces.
        API errors (403 without the read RBAC, transient apiserver
        failures) degrade to no-credentials rather than wedging every
        reconcile — public-bucket deployments must keep working."""
        for ns in namespaces:
            try:
                cm = _store_get(store, "ConfigMap", ns, CONFIGMAP_NAME)
            except Exception as e:
                logger.warning("cannot read %s ConfigMap in %s: %s",
                               CONFIGMAP_NAME, ns, e)
                continue
            if cm is not None:
                try:
                    cfg = CredentialConfig.from_configmap(cm)
                except (ValueError, KeyError) as e:
                    raise ValueError(
                        f"seldon-config ConfigMap in {ns} has a "
                        f"malformed credentials entry: {e}"
                    ) from e
                return CredentialBuilder(store, cfg)
        return CredentialBuilder(store)

    def _get(self, kind: str, namespace: str, name: str) -> Optional[Dict]:
        key = (kind, namespace, name)
        if key in self._cache:
            return self._cache[key]
        try:
            obj = _store_get(self.store, kind, namespace, name)
        except Exception as e:
            logger.warning("cannot read %s %s/%s: %s", kind, namespace,
                           name, e)
            obj = None
        self._cache[key] = obj
        return obj

    def inject(self, namespace: str, service_account_name: str,
               container: Dict, volumes: List[Dict]) -> None:
        """Mutate `container` env/volumeMounts (+ pod `volumes`) with the
        credentials reachable from the ServiceAccount. Missing SA or
        secrets are logged and skipped, not fatal — matching the
        reference's lenient path so public-bucket deployments keep
        working without any RBAC on secrets."""
        sa_name = service_account_name or "default"
        sa = self._get("ServiceAccount", namespace, sa_name)
        if sa is None:
            if service_account_name:
                logger.warning("serviceAccount %s/%s not found",
                               namespace, sa_name)
            return
        s3_key = (self.config.s3.secret_access_key_name
                  or S3_SECRET_ACCESS_KEY_NAME)
        gcs_file = (self.config.gcs.credential_file_name
                    or GCS_CREDENTIAL_FILE_NAME)
        env = container.setdefault("env", [])
        mounts = container.setdefault("volumeMounts", [])
        # First S3 match and first GCS match win; later duplicates are
        # skipped (duplicate env names / identical mountPaths would fail
        # apiserver validation of the container).
        s3_done = gcs_done = False
        for ref in sa.get("secrets") or []:
            if not ref.get("name"):
                continue  # ObjectReference.name is optional in the API
            secret = self._get("Secret", namespace, ref["name"])
            if secret is None:
                logger.warning("secret %s/%s not found", namespace,
                               ref.get("name"))
                continue
            data = secret.get("data") or {}
            if s3_key in data and not s3_done:
                env.extend(build_s3_envs(secret, self.config.s3))
                s3_done = True
            elif gcs_file in data and not gcs_done:
                volume, mount, cred_env = build_gcs_volume(secret, gcs_file)
                # Pod volumes are shared across initContainers: two units
                # with the same SA must not duplicate the volume entry.
                if all(v["name"] != volume["name"] for v in volumes):
                    volumes.append(volume)
                mounts.append(mount)
                env.append(cred_env)
                gcs_done = True
            else:
                logger.debug("skipping secret %s",
                             secret["metadata"]["name"])
