"""Control plane: SeldonDeployment -> k8s manifests, TPU-aware.

Reference: the Go operator (/root/reference/operator/, SURVEY.md §2.2) —
CRD types + naming, mutating/validating webhooks, reconciler emitting
Deployments/Services/HPAs/Istio resources, engine + prepackaged-server +
model-initializer injection.

This build (no Go toolchain in the image) implements the same control
logic in Python: `kubectl apply` manifests come out of `reconciler.py`
as plain dicts (serializable to YAML), the defaulting/validation webhooks
are pure functions over the CR, and reconcile semantics (incl. the
zero-downtime stale-generation GC ordering) run against a pluggable
cluster-state store so they are fully testable without a cluster.

TPU-native extensions the reference never had: pods request
`google.com/tpu` with `cloud.google.com/gke-tpu-topology` /
`gke-tpu-accelerator` node selectors; multi-host slices get a headless
service + stable ordinals (StatefulSet) and slice-aware readiness.
"""

from seldon_tpu.operator.types import (
    SeldonDeployment,
    DeploymentStatus,
    machine_name,
)
from seldon_tpu.operator.webhook import (
    default_deployment,
    validate_deployment,
)
from seldon_tpu.operator.reconciler import Reconciler, InMemoryStore
from seldon_tpu.operator.kubestore import KubeStore

__all__ = [
    "SeldonDeployment",
    "DeploymentStatus",
    "machine_name",
    "default_deployment",
    "validate_deployment",
    "Reconciler",
    "InMemoryStore",
    "KubeStore",
]
