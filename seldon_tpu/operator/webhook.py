"""Defaulting + validating webhooks as pure functions.

Reference: operator/api/v1alpha2/seldondeployment_webhook.go —
DefaultSeldonDeployment (:137-351: port assignment from 9000+, endpoint
service hosts, prepackaged-server container materialization, type
defaulting) and ValidateCreate (:358-424: graph/container match, modelUri
required for prepack, unique predictor names, traffic sums to 100)."""

from __future__ import annotations

from typing import Dict, List

from seldon_tpu.operator import types as T
from seldon_tpu.orchestrator.spec import (
    Endpoint,
    EndpointType,
    PredictiveUnit,
    UnitImplementation,
    default_unit_types,
    validate_spec,
)

PREPACKAGED = {
    UnitImplementation.SKLEARN_SERVER,
    UnitImplementation.XGBOOST_SERVER,
    UnitImplementation.TENSORFLOW_SERVER,
    UnitImplementation.MLFLOW_SERVER,
    UnitImplementation.JAX_SERVER,
}

# Server class loaded by the microservice CLI per implementation
# (reference materializes docker images, operator/constants/constants.go:4-13;
# here one image + class selection via parameters).
PREPACKAGED_CLASSES = {
    UnitImplementation.SKLEARN_SERVER: "seldon_tpu.servers.sklearnserver.SKLearnServer",
    UnitImplementation.XGBOOST_SERVER: "seldon_tpu.servers.xgboostserver.XGBoostServer",
    UnitImplementation.MLFLOW_SERVER: "seldon_tpu.servers.mlflowserver.MLFlowServer",
    UnitImplementation.TENSORFLOW_SERVER: "seldon_tpu.servers.tfproxy.TFServingProxy",
    UnitImplementation.JAX_SERVER: "seldon_tpu.servers.jaxserver.JAXServer",
}


def default_deployment(sdep: T.SeldonDeployment) -> T.SeldonDeployment:
    """Fill defaults in place (and return it): traffic split, unit types,
    ports, service hosts, prepackaged images/classes."""
    _default_traffic(sdep)
    for pred in sdep.predictors:
        default_unit_types(pred.spec.graph)
        separate_engine = (
            sdep.annotations.get(T.ANNOTATION_SEPARATE_ENGINE, "false")
            == "true"
        )
        port = T.FIRST_UNIT_PORT
        for unit in pred.spec.graph.walk():
            if unit.implementation in PREPACKAGED and not unit.image:
                unit.image = T.DEFAULT_SERVER_IMAGE
                pred.component_images.setdefault(unit.name, unit.image)
            needs_endpoint = (
                unit.implementation
                not in (
                    UnitImplementation.SIMPLE_MODEL,
                    UnitImplementation.SIMPLE_ROUTER,
                    UnitImplementation.RANDOM_ABTEST,
                    UnitImplementation.AVERAGE_COMBINER,
                )
            )
            if not needs_endpoint:
                continue
            if unit.endpoint is None:
                unit.endpoint = Endpoint(type=EndpointType.GRPC)
            if unit.endpoint.service_port in (0, T.FIRST_UNIT_PORT) and (
                unit.endpoint.service_port != port
            ):
                unit.endpoint.service_port = port
            # Stride 2: seldon-tpu-native units serve the framed-proto
            # fast lane on service_port+1 (runtime/fastpath.py), so
            # consecutive allocation would collide with the next unit.
            port = max(port, unit.endpoint.service_port) + 2
            if not unit.endpoint.fast_port and _serves_fastpath(sdep, unit):
                unit.endpoint.fast_port = unit.endpoint.service_port + 1
            # Engine shares the pod with units unless separate-pod: then
            # units resolve via their container service DNS
            # (webhook.go:224-231).
            if not unit.endpoint.service_host or unit.endpoint.service_host == "localhost":
                if separate_engine:
                    unit.endpoint.service_host = (
                        f"{T.container_service_name(sdep, pred, unit)}."
                        f"{sdep.namespace}.svc.cluster.local."
                    )
                else:
                    unit.endpoint.service_host = "localhost"
    return sdep


def _serves_fastpath(sdep: T.SeldonDeployment, unit) -> bool:
    """Native images (our microservice runtime) serve the fast lane on
    gRPC-port+1; foreign images don't unless they opt in via the
    `seldon.io/fastpath: "true"` annotation ("false" opts native units
    out — e.g. when a NetworkPolicy only admits the gRPC port)."""
    override = sdep.annotations.get(T.ANNOTATION_FASTPATH, "")
    if override in ("true", "false"):
        return override == "true"
    image = unit.image or ""
    return (image.startswith("seldon-tpu/") or image.startswith("local/")
            or unit.implementation in PREPACKAGED)


def _default_traffic(sdep: T.SeldonDeployment) -> None:
    """Distribute unset (0) traffic: single predictor gets 100; with
    multiple, unset predictors split what the explicit ones left over."""
    preds = sdep.predictors
    if not preds:
        return
    unset = [p for p in preds if p.spec.traffic == 0]
    if not unset:
        return
    remainder = 100 - sum(p.spec.traffic for p in preds)
    if remainder <= 0:
        return  # explicit values already (over)claim; validation reports
    share, extra = divmod(remainder, len(unset))
    for i, p in enumerate(unset):
        p.spec.traffic = share + (1 if i < extra else 0)


def validate_deployment(sdep: T.SeldonDeployment) -> List[str]:
    problems: List[str] = []
    if not sdep.predictors:
        problems.append("deployment has no predictors")
    names = [p.spec.name for p in sdep.predictors]
    if len(set(names)) != len(names):
        problems.append(f"duplicate predictor names: {names}")
    traffic = sum(p.spec.traffic for p in sdep.predictors)
    if len(sdep.predictors) > 1 and traffic != 100:
        problems.append(
            f"traffic must sum to 100 across predictors, got {traffic}"
        )
    for pred in sdep.predictors:
        problems.extend(
            f"predictor {pred.spec.name!r}: {p}"
            for p in validate_spec(pred.spec)
        )
        if pred.tpu.chips:
            if pred.tpu.hosts < 1:
                problems.append(
                    f"predictor {pred.spec.name!r}: tpu.hosts must be >= 1"
                )
            if pred.tpu.hosts > 1 and not pred.tpu.topology:
                problems.append(
                    f"predictor {pred.spec.name!r}: multi-host tpu requires "
                    "an explicit topology"
                )
        if pred.hpa is not None and pred.tpu.hosts > 1:
            # An HPA scales pods one at a time, but a slice is only valid
            # in multiples of tpu.hosts — a partial slice never becomes
            # ready. Reject rather than flap.
            problems.append(
                f"predictor {pred.spec.name!r}: hpaSpec is not supported on "
                f"multi-host tpu predictors (slices scale in units of "
                f"{pred.tpu.hosts} hosts)"
            )
    return problems
