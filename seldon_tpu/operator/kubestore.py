"""KubeStore: the reconciler's Store protocol over the real k8s API.

Reference: the Go operator's controller-runtime client (operator/main.go:
54-97 manager + cached client). No kubernetes python package ships in
this image, and the operator needs only five verbs — so this speaks the
k8s REST API directly (requests + bearer token), which also keeps the
dependency surface at zero:

  apply  -> GET; 404 ? POST : PUT (resourceVersion carried over)
  delete -> DELETE
  list   -> GET ?labelSelector=
  is_ready -> GET status (readyReplicas >= replicas for workloads)
  watch  -> GET ?watch=true chunked JSON stream (controller loop)

Config resolution: in-cluster service account
(/var/run/secrets/kubernetes.io/serviceaccount) first, then
$KUBECONFIG/~/.kube/config (token / client-cert auth).
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, Dict, Iterator, List, Optional, Tuple

logger = logging.getLogger(__name__)

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

# kind -> (api prefix, plural). Everything the reconciler emits.
KIND_ROUTES: Dict[str, Tuple[str, str]] = {
    "Deployment": ("apis/apps/v1", "deployments"),
    "StatefulSet": ("apis/apps/v1", "statefulsets"),
    "Service": ("api/v1", "services"),
    "HorizontalPodAutoscaler": ("apis/autoscaling/v2",
                                "horizontalpodautoscalers"),
    "VirtualService": ("apis/networking.istio.io/v1beta1",
                       "virtualservices"),
    "DestinationRule": ("apis/networking.istio.io/v1beta1",
                        "destinationrules"),
    "SeldonDeployment": ("apis/machinelearning.seldon.io/v1alpha3",
                         "seldondeployments"),
    # Read-only kinds for credential injection (operator/credentials.py).
    "ConfigMap": ("api/v1", "configmaps"),
    "Secret": ("api/v1", "secrets"),
    "ServiceAccount": ("api/v1", "serviceaccounts"),
}


class KubeApiError(Exception):
    def __init__(self, status: int, body: str):
        super().__init__(f"k8s API {status}: {body[:200]}")
        self.status = status
        self.body = body


class KubeStore:
    """Store protocol (reconciler.py) against a live API server."""

    def __init__(self, base_url: Optional[str] = None,
                 token: Optional[str] = None,
                 verify: Any = None,
                 session=None):
        import requests

        self.session = session or requests.Session()
        cert = None
        if base_url is None:
            base_url, token, verify, cert = self._resolve_config(
                token, verify
            )
        self.base_url = base_url.rstrip("/")
        if token:
            self.session.headers["Authorization"] = f"Bearer {token}"
        if cert is not None:
            self.session.cert = cert
        if verify is not None:
            self.session.verify = verify

    @staticmethod
    def _resolve_config(token, verify):
        """In-cluster service account, else kubeconfig.
        Returns (base_url, token, verify, client_cert_pair)."""
        token_path = os.path.join(SA_DIR, "token")
        if os.path.exists(token_path):
            host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default.svc")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            with open(token_path) as f:
                token = token or f.read().strip()
            ca = os.path.join(SA_DIR, "ca.crt")
            return (f"https://{host}:{port}", token,
                    ca if os.path.exists(ca) else verify, None)
        import yaml

        path = os.environ.get("KUBECONFIG",
                              os.path.expanduser("~/.kube/config"))
        with open(path) as f:
            cfg = yaml.safe_load(f)
        ctx_name = cfg.get("current-context")
        ctx = next(c["context"] for c in cfg["contexts"]
                   if c["name"] == ctx_name)
        cluster = next(c["cluster"] for c in cfg["clusters"]
                       if c["name"] == ctx["cluster"])
        user = next(u["user"] for u in cfg["users"]
                    if u["name"] == ctx["user"])
        token = token or user.get("token")
        verify = cluster.get("certificate-authority",
                             not cluster.get("insecure-skip-tls-verify",
                                             False))
        cert = user.get("client-certificate")
        key = user.get("client-key")
        if not token and not (cert and key):
            raise RuntimeError(
                "kubeconfig user has neither a token nor client-certificate/"
                "client-key; embedded *-data credentials are not supported — "
                "use file paths or a token"
            )
        return cluster["server"], token, verify, (
            (cert, key) if cert and key else None
        )

    # -- plumbing ------------------------------------------------------------

    def _url(self, kind: str, namespace: str, name: str = "") -> str:
        prefix, plural = KIND_ROUTES[kind]
        url = f"{self.base_url}/{prefix}/namespaces/{namespace}/{plural}"
        return f"{url}/{name}" if name else url

    def _req(self, method: str, url: str, **kw):
        r = self.session.request(method, url, timeout=30, **kw)
        if r.status_code >= 400:
            raise KubeApiError(r.status_code, r.text)
        return r.json() if r.content else {}

    # -- Store protocol ------------------------------------------------------

    def apply(self, manifest: Dict) -> None:
        kind = manifest["kind"]
        meta = manifest["metadata"]
        ns = meta.get("namespace", "default")
        name = meta["name"]
        url = self._url(kind, ns, name)
        try:
            existing = self._req("GET", url)
        except KubeApiError as e:
            if e.status != 404:
                raise
            self._req("POST", self._url(kind, ns), json=manifest)
            return
        # Update: carry the live resourceVersion (k8s optimistic locking).
        manifest = dict(manifest)
        manifest["metadata"] = dict(meta)
        rv = existing.get("metadata", {}).get("resourceVersion")
        if rv:
            manifest["metadata"]["resourceVersion"] = rv
        # Workloads whose manifest omits spec.replicas (HPA owns scaling)
        # must keep the LIVE count: a PUT with nil replicas would let the
        # apiserver default it to 1, stomping the autoscaler every resync.
        if kind in ("Deployment", "StatefulSet"):
            spec = manifest.get("spec", {})
            live = existing.get("spec", {}).get("replicas")
            if "replicas" not in spec and live is not None:
                manifest["spec"] = dict(spec, replicas=live)
        self._req("PUT", url, json=manifest)

    def delete(self, kind: str, namespace: str, name: str) -> None:
        try:
            self._req("DELETE", self._url(kind, namespace, name))
        except KubeApiError as e:
            if e.status != 404:
                raise

    def get(self, kind: str, namespace: str, name: str) -> Optional[Dict]:
        """Single-object GET (None on 404) — credential injection reads
        ConfigMap/ServiceAccount/Secret by name without the O(namespace)
        payload of a LIST."""
        try:
            obj = self._req("GET", self._url(kind, namespace, name))
        except KubeApiError as e:
            if e.status == 404:
                return None
            raise
        obj.setdefault("kind", kind)
        return obj

    def list(self, kind: str, namespace: str,
             label_selector: Optional[Dict[str, str]] = None) -> List[Dict]:
        items, _ = self.list_with_version(kind, namespace, label_selector)
        return items

    def list_with_version(
        self, kind: str, namespace: str,
        label_selector: Optional[Dict[str, str]] = None,
    ) -> Tuple[List[Dict], str]:
        """(items, list resourceVersion) — feed the version into watch()
        so the stream starts AFTER this list instead of replaying ADDED
        for every existing object."""
        params = {}
        if label_selector:
            params["labelSelector"] = ",".join(
                f"{k}={v}" for k, v in sorted(label_selector.items())
            )
        out = self._req("GET", self._url(kind, namespace), params=params)
        items = out.get("items", [])
        for item in items:  # list items omit kind/apiVersion in k8s
            item.setdefault("kind", kind)
        return items, out.get("metadata", {}).get("resourceVersion", "")

    def is_ready(self, kind: str, namespace: str, name: str) -> bool:
        try:
            obj = self._req("GET", self._url(kind, namespace, name))
        except KubeApiError:
            return False
        if kind in ("Deployment", "StatefulSet"):
            spec_replicas = obj.get("spec", {}).get("replicas", 1)
            ready = obj.get("status", {}).get("readyReplicas", 0)
            return ready >= spec_replicas
        return True

    # -- CR access (controller loop) ----------------------------------------

    def get_status(self, kind: str, namespace: str, name: str) -> Dict:
        return self._req("GET", self._url(kind, namespace, name))

    def update_status(self, kind: str, namespace: str, name: str,
                      status: Dict) -> None:
        url = self._url(kind, namespace, name) + "/status"
        try:
            self._req(
                "PATCH", url, json={"status": status},
                headers={"Content-Type": "application/merge-patch+json"},
            )
        except KubeApiError as e:
            if e.status == 404:
                # CRD without a status subresource: patch the main object.
                self._req(
                    "PATCH", self._url(kind, namespace, name),
                    json={"status": status},
                    headers={"Content-Type": "application/merge-patch+json"},
                )
            else:
                raise

    def watch(self, kind: str, namespace: str,
              resource_version: str = "",
              timeout_s: float = 300.0) -> Iterator[Dict]:
        """Yield {type: ADDED|MODIFIED|DELETED, object: {...}} events from a
        chunked watch stream; returns when the server closes it (the
        controller loop re-lists and re-watches). `timeout_s` is sent as
        k8s `timeoutSeconds` so the SERVER ends the watch cleanly at the
        caller's resync period."""
        params: Dict[str, Any] = {
            "watch": "true",
            "timeoutSeconds": max(1, int(timeout_s)),
        }
        if resource_version:
            params["resourceVersion"] = resource_version
        r = self.session.get(
            self._url(kind, namespace), params=params, stream=True,
            timeout=(10, timeout_s + 10),
        )
        if r.status_code >= 400:
            raise KubeApiError(r.status_code, r.text)
        try:
            for line in r.iter_lines():
                if not line:
                    continue
                try:
                    yield json.loads(line)
                except json.JSONDecodeError:
                    logger.warning("unparseable watch line: %r", line[:200])
        finally:
            r.close()
