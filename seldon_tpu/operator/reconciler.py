"""Reconciler: SeldonDeployment -> k8s manifests -> cluster store.

Reference: operator/controllers/seldondeployment_controller.go —
createComponents (:253-391), createDeployments + stale-generation cleanup
(:855-1046, svc-orch deleted LAST so in-flight traffic drains through the
old engine until the new graph is ready — validated by
test_rolling_updates.py in the reference), Istio resources (:113-224);
engine injection (seldondeployment_engine.go:35-214); prepackaged servers
(seldondeployment_prepackaged_servers.go); model-initializer
(model_initializer_injector.go:65-228).

Manifests are plain dicts (yaml.safe_dump-able). The cluster is a
pluggable Store; InMemoryStore gives hermetic tests the same semantics
envtest gave the reference."""

from __future__ import annotations

import base64
import copy
import json
import logging
from typing import Any, Dict, List, Optional, Protocol, Tuple

from seldon_tpu.operator import types as T
from seldon_tpu.operator.webhook import (
    PREPACKAGED,
    PREPACKAGED_CLASSES,
    default_deployment,
    validate_deployment,
)
from seldon_tpu.orchestrator.spec import (
    HARDCODED_IMPLEMENTATIONS,
    PredictiveUnit,
    UnitImplementation,
)

logger = logging.getLogger(__name__)

GENERATION_LABEL = "seldon.io/generation"
ENGINE_LABEL = "seldon.io/svcorch"
DEPLOYMENT_LABEL = "seldon-deployment-id"


class Store(Protocol):  # pragma: no cover - interface
    def apply(self, manifest: Dict) -> None: ...

    def delete(self, kind: str, namespace: str, name: str) -> None: ...

    def list(self, kind: str, namespace: str,
             label_selector: Optional[Dict[str, str]] = None) -> List[Dict]: ...

    def is_ready(self, kind: str, namespace: str, name: str) -> bool: ...


class InMemoryStore:
    """Dict-backed store; everything applied is instantly 'ready' unless
    the test marks it otherwise."""

    def __init__(self):
        self.objects: Dict[Tuple[str, str, str], Dict] = {}
        self.not_ready: set = set()

    def _key(self, kind, ns, name):
        return (kind, ns, name)

    def apply(self, manifest: Dict) -> None:
        kind = manifest["kind"]
        ns = manifest["metadata"].get("namespace", "default")
        name = manifest["metadata"]["name"]
        self.objects[self._key(kind, ns, name)] = copy.deepcopy(manifest)

    def delete(self, kind: str, namespace: str, name: str) -> None:
        self.objects.pop(self._key(kind, namespace, name), None)

    def list(self, kind, namespace, label_selector=None) -> List[Dict]:
        out = []
        for (k, ns, _), obj in self.objects.items():
            if k != kind or ns != namespace:
                continue
            labels = obj["metadata"].get("labels", {})
            if label_selector and any(
                labels.get(lk) != lv for lk, lv in label_selector.items()
            ):
                continue
            out.append(obj)
        return out

    def is_ready(self, kind, namespace, name) -> bool:
        return (
            self._key(kind, namespace, name) in self.objects
            and self._key(kind, namespace, name) not in self.not_ready
        )


# ---------------------------------------------------------------------------
# Manifest builders
# ---------------------------------------------------------------------------


def _unit_container(sdep: T.SeldonDeployment, pred: T.PredictorExt,
                    unit: PredictiveUnit) -> Dict:
    params = [
        {"name": p.name, "value": p.value, "type": p.type}
        for p in unit.parameters
    ]
    if unit.implementation in PREPACKAGED:
        cls = PREPACKAGED_CLASSES[unit.implementation]
        if unit.model_uri:
            params.append(
                {"name": "model_uri", "value": "/mnt/models", "type": "STRING"}
            )
        command = ["python", "-m", "seldon_tpu.runtime.microservice", cls]
    else:
        command = None  # user image brings its own entrypoint
    port = unit.endpoint.service_port if unit.endpoint else T.FIRST_UNIT_PORT
    # The engine dials service_port with the endpoint's type, so the
    # container must bind THAT protocol on THAT port: without pinning
    # API_TYPE, the microservice defaults to REST,GRPC and puts gRPC on
    # port+1 while a GRPC-type endpoint dials port (latent mismatch).
    # The fast lane (fastPort = port+1, webhook stride 2) lands on
    # grpc_port+1 either way.
    api_type = (unit.endpoint.type.value if unit.endpoint else "GRPC")
    container: Dict[str, Any] = {
        "name": unit.name,
        "image": unit.image or T.DEFAULT_SERVER_IMAGE,
        "env": [
            {"name": T.ENV_PREDICTIVE_UNIT_SERVICE_PORT, "value": str(port)},
            {"name": "API_TYPE", "value": api_type},
            {"name": T.ENV_PREDICTIVE_UNIT_ID, "value": unit.name},
            {"name": T.ENV_PREDICTOR_ID, "value": pred.spec.name},
            {"name": T.ENV_SELDON_DEPLOYMENT_ID, "value": sdep.name},
            {"name": T.ENV_PREDICTIVE_UNIT_PARAMETERS,
             "value": json.dumps(params)},
        ],
        "ports": [{"containerPort": port, "name": "grpc", "protocol": "TCP"}],
        "readinessProbe": {
            "tcpSocket": {"port": port},
            "initialDelaySeconds": 5,
            "periodSeconds": 5,
            "failureThreshold": 3,
        },
        "livenessProbe": {
            "tcpSocket": {"port": port},
            "initialDelaySeconds": 10,
            "periodSeconds": 5,
        },
        "lifecycle": {
            "preStop": {"exec": {"command": ["/bin/sh", "-c", "sleep 10"]}}
        },
    }
    if command:
        container["command"] = command
    resources = dict(pred.resources.get(unit.name, {}))
    if pred.tpu.chips and unit.implementation == UnitImplementation.JAX_SERVER:
        resources.setdefault("limits", {})["google.com/tpu"] = pred.tpu.chips
        resources.setdefault("requests", {})["google.com/tpu"] = pred.tpu.chips
    if resources:
        container["resources"] = resources
    if unit.model_uri:
        # Per-unit volume: two prepackaged units in one graph must never
        # clobber each other's /mnt/models downloads.
        container["volumeMounts"] = [
            {"name": _model_volume_name(unit), "mountPath": "/mnt/models",
             "readOnly": True}
        ]
    return container


def _model_volume_name(unit: PredictiveUnit) -> str:
    return T.machine_name("model-volume", unit.name)


def _model_initializer(unit: PredictiveUnit) -> Dict:
    """initContainer downloading modelUri into the unit's volume
    (reference model_initializer_injector.go:65-228)."""
    return {
        "name": f"{unit.name}-model-initializer",
        "image": T.DEFAULT_SERVER_IMAGE,
        "command": ["python", "-m", "seldon_tpu.servers.storage"],
        "args": [unit.model_uri, "/mnt/models"],
        "volumeMounts": [
            {"name": _model_volume_name(unit), "mountPath": "/mnt/models"}
        ],
    }


def _engine_container(sdep: T.SeldonDeployment, pred: T.PredictorExt) -> Dict:
    predictor_json = json.dumps(pred.spec.to_dict()).encode()
    return {
        "name": "seldon-container-engine",
        "image": T.DEFAULT_ENGINE_IMAGE,
        "command": ["python", "-m", "seldon_tpu.orchestrator.server"],
        "env": [
            {"name": T.ENV_ENGINE_PREDICTOR,
             "value": base64.b64encode(predictor_json).decode()},
            {"name": T.ENV_PREDICTOR_ID, "value": pred.spec.name},
            {"name": T.ENV_SELDON_DEPLOYMENT_ID, "value": sdep.name},
        ],
        "ports": [
            {"containerPort": T.ENGINE_HTTP_PORT, "name": "rest"},
            {"containerPort": T.ENGINE_GRPC_PORT, "name": "grpc"},
        ],
        # Downward-API podinfo: CR annotations reach the engine at runtime
        # (timeouts/retries/grpc caps — core/annotations.py; reference
        # seldondeployment_controller.go:627-633 + AnnotationsConfig.java).
        "volumeMounts": [
            {"name": "podinfo", "mountPath": "/etc/podinfo", "readOnly": True}
        ],
        "readinessProbe": {
            "httpGet": {"path": "/ready", "port": T.ENGINE_HTTP_PORT},
            "initialDelaySeconds": 5,
            "periodSeconds": 5,
        },
        "livenessProbe": {
            "httpGet": {"path": "/live", "port": T.ENGINE_HTTP_PORT},
            "initialDelaySeconds": 10,
            "periodSeconds": 5,
        },
        "lifecycle": {
            "preStop": {
                "exec": {
                    "command": [
                        "/bin/sh", "-c",
                        f"curl -s localhost:{T.ENGINE_HTTP_PORT}/pause; sleep 10",
                    ]
                }
            }
        },
    }


def build_predictor_manifests(
    sdep: T.SeldonDeployment, pred: T.PredictorExt,
    credentials: Optional["CredentialBuilder"] = None,
) -> List[Dict]:
    """Deployment(+engine) + Services for one predictor. `credentials`
    (operator/credentials.py) injects storage secrets into the
    model-initializer initContainers for private gs://-/s3:// model URIs."""
    manifests: List[Dict] = []
    dep_name = T.predictor_deployment_name(sdep, pred)
    labels = {
        DEPLOYMENT_LABEL: sdep.name,
        "seldon-predictor": pred.spec.name,
        GENERATION_LABEL: str(sdep.generation),
    }
    separate_engine = (
        sdep.annotations.get(T.ANNOTATION_SEPARATE_ENGINE, "false") == "true"
    )

    containers = []
    init_containers = []
    volumes = []
    for unit in pred.spec.graph.walk():
        if unit.implementation in HARDCODED_IMPLEMENTATIONS:
            continue
        containers.append(_unit_container(sdep, pred, unit))
        if unit.model_uri:
            init = _model_initializer(unit)
            if credentials is not None:
                credentials.inject(
                    sdep.namespace, pred.service_account_name, init, volumes
                )
            init_containers.append(init)
            volumes.append(
                {"name": _model_volume_name(unit), "emptyDir": {}}
            )

    engine = _engine_container(sdep, pred)
    engine_labels = dict(labels)
    engine_labels[ENGINE_LABEL] = "true"

    podinfo_volume = {
        "name": "podinfo",
        "downwardAPI": {
            "items": [
                {"path": "annotations",
                 "fieldRef": {"fieldPath": "metadata.annotations"}}
            ]
        },
    }

    pod_spec: Dict[str, Any] = {"containers": list(containers)}
    if pred.service_account_name:
        # The pod runs AS this SA too — identity-based bucket access
        # (GKE Workload Identity) works without any key secrets; the
        # secret walk above only adds long-lived-key credentials when
        # the SA actually carries them.
        pod_spec["serviceAccountName"] = pred.service_account_name
    if init_containers:
        pod_spec["initContainers"] = init_containers
    if not separate_engine:
        volumes = volumes + [podinfo_volume]
    if volumes:
        pod_spec["volumes"] = volumes
    if pred.tpu.chips:
        selector = {}
        topology = pred.tpu.topology or sdep.annotations.get(
            T.ANNOTATION_TPU_TOPOLOGY, ""
        )
        accelerator = pred.tpu.accelerator or sdep.annotations.get(
            T.ANNOTATION_TPU_ACCELERATOR, "tpu-v5-lite-podslice"
        )
        if topology:
            selector["cloud.google.com/gke-tpu-topology"] = topology
        selector["cloud.google.com/gke-tpu-accelerator"] = accelerator
        pod_spec["nodeSelector"] = selector

    if not separate_engine:
        pod_spec["containers"].append(engine)
        pod_labels = engine_labels
    else:
        pod_labels = labels

    multi_host = pred.tpu.hosts > 1
    workload_kind = "StatefulSet" if multi_host else "Deployment"
    workload: Dict[str, Any] = {
        "apiVersion": "apps/v1",
        "kind": workload_kind,
        "metadata": {
            "name": dep_name,
            "namespace": sdep.namespace,
            "labels": pod_labels,
        },
        "spec": {
            "selector": {"matchLabels": {"app": dep_name}},
            "template": {
                "metadata": {
                    "labels": {"app": dep_name, **pod_labels},
                    # CR annotations ride the pod template so the downward
                    # API exposes them at /etc/podinfo/annotations for the
                    # engine's runtime knobs (core/annotations.py) — the
                    # reference copies deployment annotations the same way.
                    "annotations": {
                        **sdep.annotations,
                        "prometheus.io/scrape": "true",
                        "prometheus.io/path": "/prometheus",
                        "prometheus.io/port": str(T.ENGINE_HTTP_PORT),
                    },
                },
                "spec": pod_spec,
            },
        },
    }
    # When an HPA owns the replica count, omitting .spec.replicas stops
    # every reconcile PUT from resetting what the autoscaler set
    # (reference omits replicas when hpaSpec is present).
    if pred.hpa is None:
        workload["spec"]["replicas"] = (
            pred.spec.replicas * pred.tpu.hosts
            if multi_host
            else pred.spec.replicas
        )
    if multi_host:
        # Stable ordinals for jax.distributed: pod-0..pod-(hosts-1) form one
        # slice; headless service gives them DNS identity. The env goes on
        # the container(s) holding the TPU resources (all units if none do).
        headless_name = f"{dep_name}-hosts"
        workload["spec"]["serviceName"] = headless_name
        tpu_containers = [
            c for c in containers
            if "google.com/tpu" in c.get("resources", {}).get("limits", {})
        ] or containers
        for c in tpu_containers:
            c.setdefault("env", []).extend(
                [
                    {"name": "TPU_WORKER_HOSTNAMES_SVC",
                     "value": headless_name},
                    {"name": "TPU_WORKER_COUNT",
                     "value": str(pred.tpu.hosts)},
                ]
            )
        manifests.append(
            {
                "apiVersion": "v1",
                "kind": "Service",
                "metadata": {
                    "name": headless_name,
                    "namespace": sdep.namespace,
                    "labels": labels,
                },
                "spec": {
                    "clusterIP": "None",
                    "selector": {"app": dep_name},
                    "ports": [{"port": T.FIRST_UNIT_PORT, "name": "grpc"}],
                },
            }
        )
    else:
        workload["spec"]["strategy"] = {
            "type": "RollingUpdate",
            "rollingUpdate": {"maxUnavailable": "10%"},
        }
    manifests.append(workload)

    if separate_engine:
        engine_dep_name = machine_engine_name(sdep, pred)
        manifests.append(
            {
                "apiVersion": "apps/v1",
                "kind": "Deployment",
                "metadata": {
                    "name": engine_dep_name,
                    "namespace": sdep.namespace,
                    "labels": engine_labels,
                },
                "spec": {
                    "replicas": pred.spec.replicas,
                    "selector": {"matchLabels": {"app": engine_dep_name}},
                    "template": {
                        "metadata": {
                            "labels": {"app": engine_dep_name,
                                       **engine_labels},
                            "annotations": dict(sdep.annotations),
                        },
                        "spec": {"containers": [engine],
                                 "volumes": [podinfo_volume]},
                    },
                },
            }
        )
        # Per-unit container Services so the remote engine reaches them.
        for unit in pred.spec.graph.walk():
            if unit.implementation in HARDCODED_IMPLEMENTATIONS:
                continue
            svc = T.container_service_name(sdep, pred, unit)
            port = (
                unit.endpoint.service_port if unit.endpoint
                else T.FIRST_UNIT_PORT
            )
            manifests.append(
                {
                    "apiVersion": "v1",
                    "kind": "Service",
                    "metadata": {
                        "name": svc,
                        "namespace": sdep.namespace,
                        "labels": labels,
                    },
                    "spec": {
                        "selector": {"app": dep_name},
                        "ports": [{"port": port, "name": "grpc"}],
                    },
                }
            )

    # Predictor service fronting the engine.
    engine_app = (
        machine_engine_name(sdep, pred) if separate_engine else dep_name
    )
    manifests.append(
        {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {
                "name": T.predictor_service_name(sdep, pred),
                "namespace": sdep.namespace,
                "labels": labels,
            },
            "spec": {
                "selector": {"app": engine_app},
                "ports": [
                    {"port": T.ENGINE_HTTP_PORT, "name": "http"},
                    {"port": T.ENGINE_GRPC_PORT, "name": "grpc"},
                ],
            },
        }
    )
    return manifests


def machine_engine_name(sdep: T.SeldonDeployment, pred: T.PredictorExt) -> str:
    return T.machine_name(sdep.name, pred.spec.name, "svc-orch")


def build_hpa_manifest(sdep: T.SeldonDeployment,
                       pred: T.PredictorExt) -> Dict:
    """HorizontalPodAutoscaler targeting the predictor workload (reference
    createHpa, seldondeployment_controller.go:87-109). Defaults to a CPU
    utilization metric when the CR gives none — scale signals for a TPU
    serving pod come from the engine's req/s via custom metrics when
    configured."""
    dep_name = T.predictor_deployment_name(sdep, pred)
    hpa = pred.hpa
    assert hpa is not None, "build_hpa_manifest requires pred.hpa"
    metrics = hpa.metrics or [
        {
            "type": "Resource",
            "resource": {
                "name": "cpu",
                "target": {"type": "Utilization", "averageUtilization": 80},
            },
        }
    ]
    spec: Dict[str, Any] = {
        "scaleTargetRef": {
            "apiVersion": "apps/v1",
            # Multi-host slices deploy as StatefulSets of the same name.
            "kind": "StatefulSet" if pred.tpu.hosts > 1 else "Deployment",
            "name": dep_name,
        },
        "maxReplicas": hpa.max_replicas,
        "metrics": metrics,
    }
    if hpa.min_replicas is not None:
        spec["minReplicas"] = hpa.min_replicas
    return {
        "apiVersion": "autoscaling/v2",
        "kind": "HorizontalPodAutoscaler",
        "metadata": {
            "name": dep_name,
            "namespace": sdep.namespace,
            "labels": {DEPLOYMENT_LABEL: sdep.name},
        },
        "spec": spec,
    }


def build_explainer_manifests(sdep: T.SeldonDeployment,
                              pred: T.PredictorExt) -> List[Dict]:
    """Explainer Deployment + Service pointing back at the predictor
    (reference seldondeployment_explainers.go:33-194: separate deployment
    running the explainer against the predictor's endpoint, with its own
    `-explainer` ingress route)."""
    exp = pred.explainer
    if exp is None or not exp.type:
        return []
    dep_name = T.explainer_deployment_name(sdep, pred)
    pred_svc = T.predictor_service_name(sdep, pred)
    port_name = "grpc" if exp.endpoint_type.upper() == "GRPC" else "http"
    predictor_host = (
        f"{pred_svc}.{sdep.namespace}.svc.cluster.local:"
        + str(T.ENGINE_GRPC_PORT if port_name == "grpc"
              else T.ENGINE_HTTP_PORT)
    )
    args = [
        f"--model-name={sdep.name}",
        f"--predictor-host={predictor_host}",
        f"--protocol=seldon.{port_name}",
        f"--http-port={exp.service_port}",
        exp.type.lower(),
    ]
    container: Dict[str, Any] = {
        "name": dep_name,
        "image": exp.image or T.DEFAULT_EXPLAINER_IMAGE,
        "imagePullPolicy": "IfNotPresent",
        "args": args,
        "ports": [
            {"name": port_name, "containerPort": exp.service_port,
             "protocol": "TCP"},
        ],
        "livenessProbe": {
            "tcpSocket": {"port": port_name},
            "initialDelaySeconds": 60, "periodSeconds": 5,
            "failureThreshold": 5,
        },
        "readinessProbe": {
            "tcpSocket": {"port": port_name},
            "initialDelaySeconds": 20, "periodSeconds": 5,
            "failureThreshold": 7,
        },
        "lifecycle": {
            "preStop": {
                "exec": {"command": ["/bin/sh", "-c", "/bin/sleep 10"]}
            }
        },
    }
    volumes = []
    if exp.model_uri:
        container["args"].insert(-1, "--storage-uri=/mnt/models")
        vol = f"{dep_name}-model"
        container["volumeMounts"] = [
            {"name": vol, "mountPath": "/mnt/models", "readOnly": True}
        ]
        volumes.append({"name": vol, "emptyDir": {}})
    labels = {DEPLOYMENT_LABEL: sdep.name,
              "seldon-predictor": pred.spec.name}
    pod_spec: Dict[str, Any] = {"containers": [container]}
    if exp.model_uri:
        pod_spec["initContainers"] = [
            {
                "name": "model-initializer",
                "image": "seldon-tpu/storage-initializer:0.1.0",
                "args": [exp.model_uri, "/mnt/models"],
                "volumeMounts": container["volumeMounts"],
            }
        ]
        pod_spec["volumes"] = volumes
    deployment = {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {
            "name": dep_name,
            "namespace": sdep.namespace,
            "labels": labels,
        },
        "spec": {
            "replicas": 1,
            "selector": {"matchLabels": {"app": dep_name}},
            "template": {
                "metadata": {"labels": {"app": dep_name, **labels}},
                "spec": pod_spec,
            },
        },
    }
    service = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": dep_name,
            "namespace": sdep.namespace,
            "labels": labels,
        },
        "spec": {
            "selector": {"app": dep_name},
            "ports": [{"port": exp.service_port, "name": port_name}],
        },
    }
    return [deployment, service]


def build_istio_manifests(sdep: T.SeldonDeployment) -> List[Dict]:
    """VirtualService with per-predictor traffic weights + DestinationRules
    (reference seldondeployment_controller.go:113-224)."""
    http_routes = []
    drs = []
    for pred in sdep.predictors:
        svc = T.predictor_service_name(sdep, pred)
        host = f"{svc}.{sdep.namespace}.svc.cluster.local"
        http_routes.append(
            {
                "destination": {
                    "host": host,
                    "port": {"number": T.ENGINE_HTTP_PORT},
                },
                "weight": pred.spec.traffic,
            }
        )
        drs.append(
            {
                "apiVersion": "networking.istio.io/v1beta1",
                "kind": "DestinationRule",
                "metadata": {
                    "name": svc,
                    "namespace": sdep.namespace,
                    "labels": {DEPLOYMENT_LABEL: sdep.name},
                },
                "spec": {
                    "host": host,
                    "trafficPolicy": {"tls": {"mode": "ISTIO_MUTUAL"}},
                },
            }
        )
    http_blocks = [
        {
            "match": [
                {"uri": {"prefix": f"/seldon/{sdep.namespace}/{sdep.name}/"}}
            ],
            "rewrite": {"uri": "/"},
            "route": http_routes,
        }
    ]
    # Explainer routes: own `-explainer` prefix per predictor (reference
    # seldondeployment_explainers.go ingress wiring).
    for pred in sdep.predictors:
        if pred.explainer is None or not pred.explainer.type:
            continue
        exp_svc = T.explainer_deployment_name(sdep, pred)
        http_blocks.insert(0, {
            "match": [
                {"uri": {"prefix":
                         f"/seldon/{sdep.namespace}/{sdep.name}-explainer/"
                         f"{pred.spec.name}/"}}
            ],
            "rewrite": {"uri": "/"},
            "route": [
                {
                    "destination": {
                        "host": (f"{exp_svc}.{sdep.namespace}"
                                 ".svc.cluster.local"),
                        "port": {"number": pred.explainer.service_port},
                    },
                    "weight": 100,
                }
            ],
        })
    vs = {
        "apiVersion": "networking.istio.io/v1beta1",
        "kind": "VirtualService",
        "metadata": {
            "name": T.machine_name(sdep.name, "http"),
            "namespace": sdep.namespace,
            "labels": {DEPLOYMENT_LABEL: sdep.name},
        },
        "spec": {
            "hosts": ["*"],
            "gateways": ["seldon-gateway"],
            "http": http_blocks,
        },
    }
    return [vs] + drs


def _parse_header_annotation(value: str) -> Dict[str, str]:
    """'key1:val1:key2:val2' -> dict (reference ambassador.go:100-117).

    The wire format is inherently ambiguous when a VALUE contains ':'
    (a regex like 'x-ver:v[12]:x-env:prod.*' still parses, but
    'x-match:a:b' would mis-pair) — same limitation as the reference's
    strings.Split. Each key may appear once; a trailing unpaired token
    (odd part count) is dropped rather than silently becoming a key
    with the next pair's key as its value."""
    parts = value.split(":")
    out: Dict[str, str] = {}
    for i in range(0, len(parts) - 1, 2):
        out[parts[i].strip()] = parts[i + 1].strip()
    return out


def ambassador_annotations(sdep: T.SeldonDeployment) -> str:
    """Ambassador v1 Mapping YAML block (reference ambassador.go:50-263).

    Behavior knobs via deployment annotations:
      seldon.io/ambassador-config        — verbatim override of the config
      seldon.io/ambassador-shadow        — non-empty: predictors become
        SHADOW mappings (traffic mirrored to them, responses discarded —
        canary testing against production load, ambassador.go:119-133)
      seldon.io/ambassador-header        — 'k:v[:k2:v2]' exact-match header
        routing; the mapping only serves requests carrying the headers
      seldon.io/ambassador-regex-header  — same, regex match
      seldon.io/ambassador-service-name  — external path name override
      seldon.io/ambassador-id            — restrict to one ambassador
        instance (ambassador_id)
    """
    custom = sdep.annotations.get(T.ANNOTATION_AMBASSADOR_CUSTOM, "")
    if custom:
        return custom
    shadow = sdep.annotations.get(T.ANNOTATION_AMBASSADOR_SHADOW, "")
    svc_external = sdep.annotations.get(
        T.ANNOTATION_AMBASSADOR_SERVICE, sdep.name
    )
    header = _parse_header_annotation(
        sdep.annotations.get(T.ANNOTATION_AMBASSADOR_HEADER, "")
    )
    regex_header = _parse_header_annotation(
        sdep.annotations.get(T.ANNOTATION_AMBASSADOR_REGEX_HEADER, "")
    )
    instance_id = sdep.annotations.get(T.ANNOTATION_AMBASSADOR_ID, "")

    def header_yaml(tag: str, headers: Dict[str, str]) -> str:
        if not headers:
            return ""
        # json.dumps double-quotes values (valid YAML scalars), so regex
        # patterns with ':', '{', or leading specials can't malform the
        # emitted Mapping.
        lines = "".join(
            f"  {k}: {json.dumps(str(v))}\n" for k, v in headers.items()
        )
        return f"{tag}:\n{lines}"

    extras = ""
    if shadow:
        extras += "shadow: true\n"
    extras += header_yaml("headers", header)
    extras += header_yaml("regex_headers", regex_header)
    if instance_id:
        extras += f"ambassador_id: {instance_id}\n"

    blocks = []
    for pred in sdep.predictors:
        svc = T.predictor_service_name(sdep, pred)
        timeout = sdep.annotations.get(T.ANNOTATION_REST_READ_TIMEOUT, "3000")
        grpc_timeout = sdep.annotations.get(
            T.ANNOTATION_GRPC_READ_TIMEOUT, "3000"
        )
        weight = pred.spec.traffic if len(sdep.predictors) > 1 else 100
        blocks.append(
            "---\n"
            "apiVersion: ambassador/v1\n"
            "kind: Mapping\n"
            f"name: seldon_{sdep.namespace}_{sdep.name}_{pred.spec.name}_rest\n"
            f"prefix: /seldon/{sdep.namespace}/{svc_external}/\n"
            f"service: {svc}.{sdep.namespace}:{T.ENGINE_HTTP_PORT}\n"
            f"timeout_ms: {timeout}\n"
            f"weight: {weight}\n"
            "retry_policy:\n"
            "  retry_on: connect-failure\n"
            "  num_retries: 3\n"
            + extras
        )
        grpc_headers = {"seldon": svc_external, "namespace": sdep.namespace,
                        **header}
        blocks.append(
            "---\n"
            "apiVersion: ambassador/v1\n"
            "kind: Mapping\n"
            f"name: seldon_{sdep.namespace}_{sdep.name}_{pred.spec.name}_grpc\n"
            "grpc: true\n"
            f"prefix: /seldon.protos.Seldon/\n"
            + header_yaml("headers", grpc_headers)
            + f"service: {svc}.{sdep.namespace}:{T.ENGINE_GRPC_PORT}\n"
            f"timeout_ms: {grpc_timeout}\n"
            f"weight: {weight}\n"
            + ("shadow: true\n" if shadow else "")
            + header_yaml("regex_headers", regex_header)
            + (f"ambassador_id: {instance_id}\n" if instance_id else "")
        )
    return "".join(blocks)


# ---------------------------------------------------------------------------
# Reconciler
# ---------------------------------------------------------------------------


class Reconciler:
    def __init__(self, store: Store, istio_enabled: bool = True):
        self.store = store
        self.istio_enabled = istio_enabled

    def desired_manifests(self, sdep: T.SeldonDeployment) -> List[Dict]:
        from seldon_tpu.operator.credentials import CredentialBuilder

        credentials = CredentialBuilder.from_store(
            self.store, namespaces=("seldon-system", sdep.namespace)
        )
        manifests: List[Dict] = []
        for pred in sdep.predictors:
            manifests.extend(
                build_predictor_manifests(sdep, pred, credentials)
            )
            if pred.hpa is not None:
                manifests.append(build_hpa_manifest(sdep, pred))
            manifests.extend(build_explainer_manifests(sdep, pred))
        if self.istio_enabled:
            manifests.extend(build_istio_manifests(sdep))
        return manifests

    def reconcile(self, sdep: T.SeldonDeployment) -> T.DeploymentStatus:
        """Default, validate, apply desired state, GC stale generations
        (svc-orch LAST, only once the new generation is ready — reference
        :952-1044)."""
        default_deployment(sdep)
        problems = validate_deployment(sdep)
        if problems:
            sdep.status = T.DeploymentStatus(
                state="Failed", description="; ".join(problems)
            )
            return sdep.status

        desired = self.desired_manifests(sdep)
        for m in desired:
            m["metadata"].setdefault("labels", {})[GENERATION_LABEL] = str(
                sdep.generation
            )
            if sdep.uid:
                # In-cluster cascade GC: deleting the CR deletes everything
                # it owns (reference: controller refs, :1129-1198).
                m["metadata"]["ownerReferences"] = [
                    {
                        "apiVersion": "machinelearning.seldon.io/v1alpha3",
                        "kind": "SeldonDeployment",
                        "name": sdep.name,
                        "uid": sdep.uid,
                        "controller": True,
                        "blockOwnerDeletion": True,
                    }
                ]
            self.store.apply(m)

        all_ready = all(
            self.store.is_ready(
                m["kind"], m["metadata"].get("namespace", "default"),
                m["metadata"]["name"],
            )
            for m in desired
            if m["kind"] in ("Deployment", "StatefulSet")
        )

        if all_ready:
            self._gc_stale(sdep, desired)
            sdep.status = T.DeploymentStatus(state="Available")
        else:
            sdep.status = T.DeploymentStatus(
                state="Creating", description="waiting for workloads"
            )
        return sdep.status

    def delete_all(self, name: str, namespace: str) -> int:
        """Remove every resource labeled for `name` (CR deleted). With
        in-cluster ownerReferences this is redundant (cascade GC), but it
        is the only cleanup path for stores without GC and a belt-and-
        braces fallback when the CR predates ownerReference stamping."""
        kinds = ["Deployment", "StatefulSet", "Service",
                 "HorizontalPodAutoscaler"]
        if self.istio_enabled:
            kinds += ["VirtualService", "DestinationRule"]
        n = 0
        for kind in kinds:
            for obj in self.store.list(
                kind, namespace, {DEPLOYMENT_LABEL: name}
            ):
                self.store.delete(
                    obj.get("kind", kind),
                    obj["metadata"].get("namespace", namespace),
                    obj["metadata"]["name"],
                )
                n += 1
        return n

    def _gc_stale(self, sdep: T.SeldonDeployment, desired: List[Dict]) -> None:
        desired_names = {
            (m["kind"], m["metadata"]["name"]) for m in desired
        }
        stale: List[Dict] = []
        kinds = ["Deployment", "StatefulSet", "Service",
                 "HorizontalPodAutoscaler"]
        if self.istio_enabled:
            # Istio kinds only exist as API routes when Istio is installed;
            # listing them on a bare cluster would 404.
            kinds += ["VirtualService", "DestinationRule"]
        for kind in kinds:
            for obj in self.store.list(
                kind, sdep.namespace, {DEPLOYMENT_LABEL: sdep.name}
            ):
                name = obj["metadata"]["name"]
                gen = obj["metadata"].get("labels", {}).get(GENERATION_LABEL)
                if (kind, name) in desired_names:
                    continue
                if gen != str(sdep.generation):
                    stale.append(obj)
        # Non-engine resources first; the old svc-orch drains last so
        # in-flight requests finish (reference ordering :976-1043).
        stale.sort(
            key=lambda o: o["metadata"].get("labels", {}).get(ENGINE_LABEL)
            == "true"
        )
        for obj in stale:
            self.store.delete(
                obj["kind"], obj["metadata"].get("namespace", "default"),
                obj["metadata"]["name"],
            )
