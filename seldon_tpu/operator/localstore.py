"""LocalProcessStore: reconciler manifests become REAL local processes.

The reference's e2e tier runs a kind cluster and asserts HTTP responses
through the full control->data plane (SURVEY.md §4, testing/scripts/).
No kube binaries exist in this image, so this store gives the same
assurance one level down: `apply` of a Deployment manifest SPAWNS the
pod's containers as subprocesses (engine + unit microservices, the same
commands the images would run), `delete` terminates them, and readiness
means the processes' ports actually accept connections (the engine's
graph spec is rewritten to the units' live localhost ports — the job
kube DNS + Services do in-cluster).

The reconciler is unchanged — it emits identical manifests whether the
store is k8s, in-memory, or this. That's the point: the e2e test drives
`SeldonDeployment -> reconcile -> running processes -> HTTP predict`
with zero mocks in the data path.
"""

from __future__ import annotations

import base64
import json
import logging
import os
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _free_port_pair() -> int:
    """A port p where p+1 is ALSO free — unit processes serve gRPC on p
    and the framed-proto fast lane on p+1, and p+1 must not be handed to
    the next unit/engine by a later ephemeral allocation (the engine
    would then frame bytes at a foreign gRPC socket: connect succeeds,
    so the refused-connect fallback never fires)."""
    for _ in range(64):
        with socket.socket() as a:
            a.bind(("127.0.0.1", 0))
            p = a.getsockname()[1]
            with socket.socket() as b:
                try:
                    b.bind(("127.0.0.1", p + 1))
                except OSError:
                    continue
                return p
    return _free_port()  # degenerate host: fall back, fast lane may miss


def _proc_sink():
    """SELDON_TPU_LOCALSTORE_DEBUG=1 lets spawned pods inherit stdio
    (debugging a pod that never becomes ready); default devnull."""
    if os.environ.get("SELDON_TPU_LOCALSTORE_DEBUG") == "1":
        return None
    return subprocess.DEVNULL


def _port_open(port: int) -> bool:
    with socket.socket() as s:
        s.settimeout(0.2)
        return s.connect_ex(("127.0.0.1", port)) == 0


class _Pod:
    def __init__(self):
        self.procs: List[subprocess.Popen] = []
        self.ports: Dict[str, int] = {}  # container name -> host port

    def alive(self) -> bool:
        return all(p.poll() is None for p in self.procs)

    def terminate(self) -> None:
        for p in self.procs:
            if p.poll() is None:
                p.terminate()
        for p in self.procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:  # pragma: no cover
                p.kill()


class LocalProcessStore:
    """Store protocol over local subprocesses."""

    def __init__(self, repo_root: Optional[str] = None):
        self.repo_root = repo_root or os.getcwd()
        self.manifests: Dict[Tuple[str, str, str], Dict] = {}
        self.pods: Dict[str, _Pod] = {}  # workload name -> pod

    # -- Store protocol ------------------------------------------------------

    def apply(self, manifest: Dict) -> None:
        kind = manifest["kind"]
        meta = manifest["metadata"]
        key = (kind, meta.get("namespace", "default"), meta["name"])
        if kind in ("Deployment", "StatefulSet"):
            existing = self.pods.get(meta["name"])
            unchanged = (
                key in self.manifests
                and self.manifests[key]["spec"] == manifest["spec"]
            )
            if unchanged and existing is not None and existing.alive():
                self.manifests[key] = manifest
                return
            # Spec changed OR the pod is (even partially) dead: always
            # stop before relaunch so no old process survives unowned.
            self._stop_workload(meta["name"])
            self._launch_workload(manifest)
        self.manifests[key] = manifest

    def delete(self, kind: str, namespace: str, name: str) -> None:
        self.manifests.pop((kind, namespace, name), None)
        if kind in ("Deployment", "StatefulSet"):
            self._stop_workload(name)

    def list(self, kind: str, namespace: str,
             label_selector: Optional[Dict[str, str]] = None) -> List[Dict]:
        out = []
        for (k, ns, _), m in self.manifests.items():
            if k != kind or ns != namespace:
                continue
            labels = m["metadata"].get("labels", {})
            if label_selector and any(
                labels.get(a) != b for a, b in label_selector.items()
            ):
                continue
            out.append(m)
        return out

    def is_ready(self, kind: str, namespace: str, name: str) -> bool:
        if kind not in ("Deployment", "StatefulSet"):
            return True
        pod = self.pods.get(name)
        if pod is None or not pod.alive():
            return False
        return all(_port_open(p) for p in pod.ports.values())

    # -- process management --------------------------------------------------

    def _env_list_to_dict(self, env_list) -> Dict[str, str]:
        return {e["name"]: e.get("value", "") for e in (env_list or [])}

    def _launch_workload(self, manifest: Dict) -> None:
        name = manifest["metadata"]["name"]
        pod = _Pod()
        pod_spec = manifest["spec"]["template"]["spec"]
        containers = pod_spec["containers"]
        base_env = dict(os.environ)
        base_env["JAX_PLATFORMS"] = base_env.get("JAX_PLATFORMS", "cpu")
        base_env["PYTHONPATH"] = (
            self.repo_root + os.pathsep + base_env.get("PYTHONPATH", "")
        )

        # initContainers: the model-initializer downloads modelUri into the
        # shared volume; here each becomes a local dir the unit env is
        # rewritten to (file:// URIs resolve in place).
        from seldon_tpu.servers.storage import download

        model_dirs: Dict[str, str] = {}  # volume mount path stays /mnt/models
        for init in pod_spec.get("initContainers", []):
            uri, mount = init["args"][0], init["args"][1]
            vol = init["volumeMounts"][0]["name"]
            model_dirs[vol] = download(uri)

        def local_model_dir(c) -> Optional[str]:
            for vm in c.get("volumeMounts", []) or []:
                if vm["name"] in model_dirs:
                    return model_dirs[vm["name"]]
            return None

        # Units first: the engine's graph spec is rewritten to their ports
        # (the job kube DNS + Services do in-cluster).
        unit_ports: Dict[str, int] = {}
        engine_container = None
        for c in containers:
            if c["name"] == "seldon-container-engine":
                engine_container = c
                continue
            env = self._env_list_to_dict(c.get("env"))
            port = _free_port_pair()
            unit_ports[c["name"]] = port
            pod.ports[c["name"]] = port
            mdir = local_model_dir(c)
            if mdir and "PREDICTIVE_UNIT_PARAMETERS" in env:
                env["PREDICTIVE_UNIT_PARAMETERS"] = env[
                    "PREDICTIVE_UNIT_PARAMETERS"
                ].replace("/mnt/models", mdir)
            if c.get("command"):
                # The container's real entrypoint (prepackaged servers).
                cmd = list(c["command"]) + [
                    "--api-type", "GRPC",
                    "--grpc-port", str(port), "--http-port", "0",
                ]
            else:
                # Custom image: MODEL_NAME env names the user class (the
                # packaging entrypoint contract — always wins). Images
                # named `local/<module.Class>:<tag>` carry the class as a
                # fallback so manifests stay self-contained for this store.
                image = c.get("image", "")
                if env.get("MODEL_NAME"):
                    model = env["MODEL_NAME"]
                elif image.startswith("local/"):
                    model = image[len("local/"):].rsplit(":", 1)[0]
                else:
                    model = "seldon_tpu.orchestrator.units.SimpleModel"
                cmd = [
                    sys.executable, "-m", "seldon_tpu.runtime.microservice",
                    model, "--api-type", "GRPC",
                    "--grpc-port", str(port), "--http-port", "0",
                ]
            env["PREDICTIVE_UNIT_SERVICE_PORT"] = str(port)
            pod.procs.append(subprocess.Popen(
                cmd, env={**base_env, **env}, cwd=self.repo_root,
                stdout=_proc_sink(), stderr=_proc_sink(),
            ))

        if engine_container is not None:
            env = self._env_list_to_dict(engine_container.get("env"))
            http_port = _free_port()
            grpc_port = _free_port()
            pod.ports["engine-http"] = http_port
            pod.ports["engine-grpc"] = grpc_port
            raw = env.get("ENGINE_PREDICTOR", "")
            if raw:
                spec = json.loads(base64.b64decode(raw))

                def patch(unit: Dict) -> None:
                    if unit.get("name") in unit_ports:
                        uport = unit_ports[unit["name"]]
                        unit["endpoint"] = {
                            "service_host": "127.0.0.1",
                            "service_port": uport,
                            "type": "GRPC",
                            # The microservice serves the framed-proto
                            # fast lane on grpc_port+1 — same contract
                            # as the webhook's fastPort defaulting.
                            "fast_port": uport + 1,
                        }
                    for child in unit.get("children", []) or []:
                        patch(child)

                patch(spec.get("graph", {}))
                env["ENGINE_PREDICTOR"] = base64.b64encode(
                    json.dumps(spec).encode()
                ).decode()
            cmd = [
                sys.executable, "-m", "seldon_tpu.orchestrator.server",
                "--http-port", str(http_port), "--grpc-port", str(grpc_port),
            ]
            pod.procs.append(subprocess.Popen(
                cmd, env={**base_env, **env}, cwd=self.repo_root,
                stdout=_proc_sink(), stderr=_proc_sink(),
            ))
        self.pods[name] = pod
        logger.info("launched workload %s: ports=%s", name, pod.ports)

    def _stop_workload(self, name: str) -> None:
        pod = self.pods.pop(name, None)
        if pod is not None:
            pod.terminate()

    # -- e2e helpers ---------------------------------------------------------

    def engine_port(self, workload: str) -> Optional[int]:
        pod = self.pods.get(workload)
        return pod.ports.get("engine-http") if pod else None

    def wait_ready(self, timeout_s: float = 60.0) -> bool:
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            workloads = [
                m for (k, _, _), m in self.manifests.items()
                if k in ("Deployment", "StatefulSet")
            ]
            if workloads and all(
                self.is_ready(m["kind"], "default", m["metadata"]["name"])
                for m in workloads
            ):
                return True
            time.sleep(0.25)
        return False

    def close(self) -> None:
        for name in list(self.pods):
            self._stop_workload(name)
