"""SeldonDeployment CR types + k8s naming helpers.

Reference: operator/api/v1alpha2/seldondeployment_types.go:29-47 (env
consts), :75-133 (naming, md5 + 63-char truncation), :204-352 (types).
The CR JSON shape matches the reference CRD so existing SeldonDeployment
manifests parse unchanged; `tpu` fields are additive."""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Any, Dict, List, Optional

from seldon_tpu.orchestrator.spec import PredictorSpec, PredictiveUnit

# Env vars injected into unit containers (reference seldondeployment_types.go:29-47).
ENV_PREDICTIVE_UNIT_SERVICE_PORT = "PREDICTIVE_UNIT_SERVICE_PORT"
ENV_PREDICTIVE_UNIT_PARAMETERS = "PREDICTIVE_UNIT_PARAMETERS"
ENV_PREDICTIVE_UNIT_ID = "PREDICTIVE_UNIT_ID"
ENV_PREDICTOR_ID = "PREDICTOR_ID"
ENV_SELDON_DEPLOYMENT_ID = "SELDON_DEPLOYMENT_ID"
ENV_ENGINE_PREDICTOR = "ENGINE_PREDICTOR"

# Annotations (reference :43-47 + ambassador.go:10-22).
ANNOTATION_SEPARATE_ENGINE = "seldon.io/engine-separate-pod"
ANNOTATION_HEADLESS_SVC = "seldon.io/headless-svc"
ANNOTATION_REST_READ_TIMEOUT = "seldon.io/rest-read-timeout"
ANNOTATION_GRPC_READ_TIMEOUT = "seldon.io/grpc-read-timeout"
ANNOTATION_GRPC_MAX_MSG = "seldon.io/grpc-max-message-size"
# Ambassador behavior knobs (reference ambassador.go:13-18).
ANNOTATION_FASTPATH = "seldon.io/fastpath"
ANNOTATION_AMBASSADOR_CUSTOM = "seldon.io/ambassador-config"
ANNOTATION_AMBASSADOR_SHADOW = "seldon.io/ambassador-shadow"
ANNOTATION_AMBASSADOR_SERVICE = "seldon.io/ambassador-service-name"
ANNOTATION_AMBASSADOR_HEADER = "seldon.io/ambassador-header"
ANNOTATION_AMBASSADOR_REGEX_HEADER = "seldon.io/ambassador-regex-header"
ANNOTATION_AMBASSADOR_ID = "seldon.io/ambassador-id"
# TPU-native additions.
ANNOTATION_TPU_TOPOLOGY = "seldon.io/tpu-topology"
ANNOTATION_TPU_ACCELERATOR = "seldon.io/tpu-accelerator"

DEFAULT_ENGINE_IMAGE = "seldon-tpu/engine:0.1.0"
DEFAULT_SERVER_IMAGE = "seldon-tpu/microservice:0.1.0"
FIRST_UNIT_PORT = 9000
ENGINE_HTTP_PORT = 8000
ENGINE_GRPC_PORT = 5001
ENGINE_ADMIN_PORT = 8082


@dataclasses.dataclass
class TPUSpec:
    """TPU placement for a predictor (green-field vs reference)."""

    chips: int = 0  # google.com/tpu resource request per pod
    topology: str = ""  # e.g. "2x4" -> cloud.google.com/gke-tpu-topology
    accelerator: str = ""  # e.g. "tpu-v5-lite-podslice"
    hosts: int = 1  # multi-host slice size (pods per replica)

    @staticmethod
    def from_dict(d: Dict) -> "TPUSpec":
        return TPUSpec(
            chips=int(d.get("chips", 0)),
            topology=d.get("topology", ""),
            accelerator=d.get("accelerator", ""),
            hosts=int(d.get("hosts", 1)),
        )

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class HpaSpec:
    """Autoscaling knobs (reference SeldonPodSpec.HpaSpec, consumed by
    createHpa, seldondeployment_controller.go:87-109)."""

    max_replicas: int = 1
    min_replicas: Optional[int] = None
    metrics: List[Dict] = dataclasses.field(default_factory=list)

    @staticmethod
    def from_dict(d: Dict) -> "HpaSpec":
        return HpaSpec(
            max_replicas=int(d.get("maxReplicas", 1)),
            min_replicas=(
                int(d["minReplicas"]) if "minReplicas" in d else None
            ),
            metrics=list(d.get("metrics", [])),
        )

    def to_dict(self) -> Dict:
        out: Dict[str, Any] = {"maxReplicas": self.max_replicas}
        if self.min_replicas is not None:
            out["minReplicas"] = self.min_replicas
        if self.metrics:
            out["metrics"] = self.metrics
        return out


DEFAULT_EXPLAINER_IMAGE = "seldon-tpu/explainer:0.1.0"


@dataclasses.dataclass
class ExplainerSpec:
    """Explainer sidecar deployment (reference PredictorSpec.Explainer,
    seldondeployment_explainers.go:33-194)."""

    type: str = ""  # anchor_tabular | anchor_images | ...
    model_uri: str = ""
    image: str = ""
    endpoint_type: str = "GRPC"
    service_port: int = 9000
    config: Dict[str, str] = dataclasses.field(default_factory=dict)

    @staticmethod
    def from_dict(d: Dict) -> "ExplainerSpec":
        ep = d.get("endpoint") or {}  # tolerate explicit null
        return ExplainerSpec(
            type=d.get("type", ""),
            model_uri=d.get("modelUri", d.get("model_uri", "")),
            image=d.get("image", ""),
            endpoint_type=ep.get("type", "GRPC"),
            service_port=int(ep.get("servicePort", 9000)),
            config=dict(d.get("config") or {}),
        )

    def to_dict(self) -> Dict:
        out: Dict[str, Any] = {"type": self.type}
        if self.model_uri:
            out["modelUri"] = self.model_uri
        if self.image:
            out["image"] = self.image
        out["endpoint"] = {
            "type": self.endpoint_type, "servicePort": self.service_port,
        }
        if self.config:
            out["config"] = self.config
        return out


@dataclasses.dataclass
class PredictorExt:
    """PredictorSpec plus operator-level fields the orchestrator spec
    doesn't carry (componentSpecs images, tpu, hpa, explainer)."""

    spec: PredictorSpec
    tpu: TPUSpec = dataclasses.field(default_factory=TPUSpec)
    component_images: Dict[str, str] = dataclasses.field(default_factory=dict)
    # unit name -> container resources overrides
    resources: Dict[str, Dict] = dataclasses.field(default_factory=dict)
    hpa: Optional[HpaSpec] = None
    explainer: Optional[ExplainerSpec] = None
    # ServiceAccount whose secrets carry model-storage credentials
    # (operator/credentials.py; reference service_account_credentials.go).
    service_account_name: str = ""

    @staticmethod
    def from_dict(d: Dict) -> "PredictorExt":
        return PredictorExt(
            spec=PredictorSpec.from_dict(d),
            tpu=TPUSpec.from_dict(d.get("tpu", {})),
            component_images=dict(d.get("componentImages", {})),
            resources=dict(d.get("resources", {})),
            service_account_name=d.get("serviceAccountName", ""),
            hpa=(
                HpaSpec.from_dict(d["hpaSpec"]) if d.get("hpaSpec") else None
            ),
            explainer=(
                ExplainerSpec.from_dict(d["explainer"])
                if (d.get("explainer") or {}).get("type")
                else None
            ),
        )

    def to_dict(self) -> Dict:
        out = self.spec.to_dict()
        if self.tpu.chips:
            out["tpu"] = self.tpu.to_dict()
        if self.component_images:
            out["componentImages"] = self.component_images
        if self.resources:
            out["resources"] = self.resources
        if self.hpa is not None:
            out["hpaSpec"] = self.hpa.to_dict()
        if self.explainer is not None:
            out["explainer"] = self.explainer.to_dict()
        if self.service_account_name:
            out["serviceAccountName"] = self.service_account_name
        return out


@dataclasses.dataclass
class DeploymentStatus:
    state: str = "Creating"  # Creating | Available | Failed
    description: str = ""
    deployment_status: Dict[str, Dict] = dataclasses.field(default_factory=dict)
    service_status: Dict[str, Dict] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class SeldonDeployment:
    name: str
    namespace: str = "default"
    predictors: List[PredictorExt] = dataclasses.field(default_factory=list)
    annotations: Dict[str, str] = dataclasses.field(default_factory=dict)
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    generation: int = 1
    uid: str = ""  # cluster UID; enables ownerReference GC
    oauth_key: str = ""
    status: DeploymentStatus = dataclasses.field(default_factory=DeploymentStatus)

    @staticmethod
    def from_dict(d: Dict) -> "SeldonDeployment":
        meta = d.get("metadata", {})
        spec = d.get("spec", {})
        return SeldonDeployment(
            name=meta.get("name", spec.get("name", "seldon")),
            namespace=meta.get("namespace", "default"),
            predictors=[
                PredictorExt.from_dict(p) for p in spec.get("predictors", [])
            ],
            annotations=dict(meta.get("annotations") or {}),
            labels=dict(meta.get("labels") or {}),
            generation=int(meta.get("generation", 1)),
            uid=meta.get("uid", ""),
        )

    def to_dict(self) -> Dict:
        return {
            "apiVersion": "machinelearning.seldon.io/v1alpha3",
            "kind": "SeldonDeployment",
            "metadata": {
                "name": self.name,
                "namespace": self.namespace,
                "annotations": self.annotations,
                "labels": self.labels,
                "generation": self.generation,
            },
            "spec": {
                "name": self.name,
                "predictors": [p.to_dict() for p in self.predictors],
            },
        }


# ---------------------------------------------------------------------------
# Naming (reference seldondeployment_types.go:75-133)
# ---------------------------------------------------------------------------


def _hash_suffix(s: str) -> str:
    return hashlib.md5(s.encode()).hexdigest()[:8]


def machine_name(*parts: str, limit: int = 63) -> str:
    """Deterministic k8s-safe resource name: joined parts, md5-suffixed when
    truncation is needed (mirrors GetSeldonDeploymentName semantics)."""
    name = "-".join(p for p in parts if p).lower().replace("_", "-")
    if len(name) <= limit:
        return name
    return name[: limit - 9] + "-" + _hash_suffix(name)


def predictor_deployment_name(sdep: SeldonDeployment, pred: PredictorExt,
                              component_idx: int = 0) -> str:
    return machine_name(sdep.name, pred.spec.name, str(component_idx))


def predictor_service_name(sdep: SeldonDeployment, pred: PredictorExt) -> str:
    return machine_name(sdep.name, pred.spec.name)


def container_service_name(sdep: SeldonDeployment, pred: PredictorExt,
                           unit: PredictiveUnit) -> str:
    return machine_name(sdep.name, pred.spec.name, unit.name)


def explainer_deployment_name(sdep: SeldonDeployment,
                              pred: PredictorExt) -> str:
    """Reference GetExplainerDeploymentName semantics."""
    return machine_name(sdep.name, pred.spec.name, "explainer")
