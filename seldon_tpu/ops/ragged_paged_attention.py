"""graftkern — block-sparse ragged paged-attention partials.

Why: the ragged wave (models/ragged_attention.py) reads its resident
context through ``paged_prefix_view`` / ``paged_gather_kv`` at the FULL
table width — every row pays ``max_seq_len`` of gather + score traffic
and a ``-1e30`` mask throws the tail away. Bit-neutral, but the wave's
cost scales with capacity instead of occupancy (the documented 0.63x
BENCH_RAGGED loss regime). This module walks the per-slot block table
instead and touches only LIVE KV blocks — ``ceil(context / kv_block)``
blocks per row — with flash-style online softmax across blocks and the
int8 scales (rank-4 twins, models/transformer._quantize_kv) fused into
the block loop, never widening the 1-byte HBM read.

The op computes attention PARTIALS, not outputs: ``(m, l, acc)`` —
running max, exp-sum and unnormalized value accumulator of every query
row against the pool positions ``t < bound[b, s]``. Callers fold their
own fresh columns (prefill's causal suffix, decode's exact bf16 column,
verify's suffix + diagonal) into the partials with one more max/exp
combine, so one kernel serves all three wave legs. Layouts follow the
engine's attention einsums: q ``[B, Sq, Hkv, G, Dh]`` grouped, partials
``[B, Hkv, G, Sq, ...]`` f32.

Three legs, per the ops/ pattern (flash_attention.py):

 * :func:`partials_reference` — full-width gather + closed-form
   softmax partials. The masked engine arithmetic rearranged to the
   partials contract; the parity oracle for the walkers.
 * :func:`partials_sparse` — pure-jnp ``lax.fori_loop`` over block
   columns with a TRACED trip count ``ceil(max(bound) / block)``: the
   loop walks only as many columns as the wave's longest live row, so
   CPU cost scales with occupancy too (the leg tier-1 exercises and
   BENCH_RAGGED's ``kernel=sparse`` axis measures). Static shapes per
   iteration — the trip count is a traced scalar, never a shape — so
   the ragged compile lattice stays at ≤ 2 variants with zero live
   retraces.
 * :func:`partials_pallas` — the Pallas/Mosaic kernel: grid
   ``(B * Hkv, num_blocks)``, the block table rides as a
   scalar-prefetch operand and the K/V BlockSpec index maps read it
   (``pltpu.PrefetchScalarGridSpec``), so the DMA engine fetches
   exactly the addressed pool block per grid step — dead columns
   re-address the trash block (table tails are 0) and their compute is
   ``pl.when``-skipped. Runs under ``interpret=True`` off-TPU (CPU
   parity tests), compiled on TPU backends.

Numerics: the partials legs share one f32 accumulation formula
(scores bf16 x bf16 -> f32, scales factored OUT of the einsums exactly
like ``gqa_attention_decode``, value dot in f32), so they agree with
each other to f32 roundoff — but they are MORE accurate than the
masked kernels, which round softmax weights to the activation dtype
before the value dot, and that ~1e-3 drift flips near-tied greedy
argmaxes on flat-logit models. The ``sparse`` wave leg therefore uses
the masked-MATCHED two-pass walk (:func:`sparse_max_sum` +
:func:`sparse_weighted_value`, "Masked-matched" section below): the
masked kernels' exact term set, differing only in f32 summation order,
so greedy outputs stay token-identical to ``masked`` by construction
(pinned by tests/test_ragged_kernel.py) and raw logits agree within
:data:`RAGGED_LOGITS_ATOL`. The pallas leg keeps the fused one-pass
partials (atol contract only); ``masked`` stays the bit-exact leg.
"""

from __future__ import annotations

import functools
import logging
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

logger = logging.getLogger(__name__)

NEG_INF = -1e30

# Documented |logits_pallas - logits_masked| bound (f32 logits, tiny/CI
# geometries). The sparse leg needs no tolerance — its two-pass walk is
# bit-exact against the masked kernels — so this bounds only the pallas
# leg's fused one-pass f32 partials, whose online-softmax reassociation
# and f32-vs-bf16 value mix sit at ~3e-3 on the CI fixtures. Pinned by
# tests/test_ragged_kernel.py::test_prefill_logits_within_atol.
RAGGED_LOGITS_ATOL = 1e-2

MODES = ("reference", "sparse", "pallas")

Partials = Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]


def _grouped(q: jnp.ndarray, n_kv_heads: int) -> jnp.ndarray:
    """[B, Sq, H, Dh] -> [B, Sq, Hkv, G, Dh] (no copy)."""
    B, Sq, H, Dh = q.shape
    return q.reshape(B, Sq, n_kv_heads, H // n_kv_heads, Dh)


def _block_scores(qr, kb, k_scale_b, mask):
    """One block column's masked scores [B, Hkv, G, Sq, block] f32:
    int8 keys are exact in bf16 and the rank-4 scale twin multiplies
    the f32 scores AFTER the einsum (gqa_attention_decode's factoring
    — the HBM read stays 1 byte/element)."""
    Dh = qr.shape[-1]
    s = jnp.einsum(
        "bskgd,bktd->bkgst", qr, kb.astype(qr.dtype),
        preferred_element_type=jnp.float32,
    ) / (Dh**0.5)
    if k_scale_b is not None:
        s = s * k_scale_b[:, :, None, None, :]
    return jnp.where(mask[:, None, None, :, :], s, NEG_INF)


def _block_accumulate(carry: Partials, s, p_mask, vb, v_scale_b) -> Partials:
    """Online-softmax fold of one block column into (m, l, acc). The
    explicit ``where`` on p guards the all-masked prefix (m still at
    NEG_INF would make exp(s - m) == 1 on dead lanes)."""
    m, l, acc = carry
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.where(p_mask[:, None, None, :, :], jnp.exp(s - m_new), 0.0)
    alpha = jnp.exp(m - m_new)
    pw = p if v_scale_b is None else p * v_scale_b[:, :, None, None, :]
    acc = acc * alpha + jnp.einsum(
        "bkgst,bktd->bkgsd", pw, vb.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    return m_new, l, acc


def _init_partials(B, Hkv, G, Sq, Dh) -> Partials:
    return (
        jnp.full((B, Hkv, G, Sq, 1), NEG_INF, jnp.float32),
        jnp.zeros((B, Hkv, G, Sq, 1), jnp.float32),
        jnp.zeros((B, Hkv, G, Sq, Dh), jnp.float32),
    )


def combine_fresh(partials: Partials, s_fresh: jnp.ndarray,
                  v_fresh: jnp.ndarray,
                  p_mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Fold fresh score columns into pool partials and normalize.

    partials: (m, l, acc) from a walker below; s_fresh
    [B, Hkv, G, Sq, F] f32 scores of F fresh columns (already masked to
    NEG_INF where invisible; at least one column per row must be live —
    every wave leg guarantees its diagonal); v_fresh [B, Hkv, F, Dh]
    values in any dtype exact under f32. p_mask (same shape as s_fresh)
    re-zeroes masked fresh lanes explicitly when a row can have ALL
    fresh columns dead (verify row 0's empty suffix) — exp(NEG_INF - m)
    underflows to 0 for finite m, so it is only load-bearing when m
    itself sits at NEG_INF. Returns [B, Sq, Hkv*G*Dh] f32 un-cast."""
    m, l, acc = partials
    m_t = jnp.maximum(m, jnp.max(s_fresh, axis=-1, keepdims=True))
    alpha = jnp.exp(m - m_t)
    p_f = jnp.exp(s_fresh - m_t)
    if p_mask is not None:
        p_f = jnp.where(p_mask, p_f, 0.0)
    l_t = l * alpha + jnp.sum(p_f, axis=-1, keepdims=True)
    out = acc * alpha + jnp.einsum(
        "bkgsf,bkfd->bkgsd", p_f, v_fresh.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    out = out / jnp.maximum(l_t, 1e-30)
    B, Hkv, G, Sq, Dh = out.shape
    return out.transpose(0, 3, 1, 2, 4).reshape(B, Sq, Hkv * G * Dh)


# ---------------------------------------------------------------------------
# Reference (full-width gather) — the parity oracle
# ---------------------------------------------------------------------------


def partials_reference(q: jnp.ndarray, pool_layer: Dict[str, jnp.ndarray],
                       table: jnp.ndarray, bound: jnp.ndarray) -> Partials:
    """Full-width gather + closed-form partials — the masked engine
    gather (paged_gather_kv) with the softmax left unnormalized.

    q [B, Sq, Hkv, G, Dh]; pool_layer {"k","v"[,"k_scale","v_scale"]}
    [NB, Hkv, block, (Dh)]; table [B, nbs] int32; bound [B, Sq] int32 —
    query row s of slot b attends pool positions t < bound[b, s]."""
    B, Sq = bound.shape
    nbs = table.shape[1]
    block = pool_layer["k"].shape[2]

    def gather(key):
        g = pool_layer[key][table]          # [B, nbs, Hkv, block, (Dh)]
        g = jnp.moveaxis(g, 1, 2)           # [B, Hkv, nbs, block, (Dh)]
        return g.reshape(g.shape[0], g.shape[1],
                         g.shape[2] * g.shape[3], *g.shape[4:])

    ck, cv = gather("k"), gather("v")
    ks = gather("k_scale") if "k_scale" in pool_layer else None
    vs = gather("v_scale") if "v_scale" in pool_layer else None
    mask = jnp.arange(nbs * block)[None, None, :] < bound[:, :, None]
    s = _block_scores(q, ck, ks, mask)
    init = _init_partials(B, q.shape[2], q.shape[3], Sq, q.shape[4])
    return _block_accumulate(init, s, mask, cv, vs)


# ---------------------------------------------------------------------------
# Block-sparse jnp walker — the CPU leg
# ---------------------------------------------------------------------------


def partials_sparse(q: jnp.ndarray, pool_layer: Dict[str, jnp.ndarray],
                    table: jnp.ndarray, bound: jnp.ndarray) -> Partials:
    """Walk only live block columns: ``lax.fori_loop`` with the TRACED
    trip count ``ceil(max(bound) / block)`` — per-iteration shapes are
    static ([B] one table column, [B, Hkv, block, (Dh)] one gathered
    block), so the wave's compile key never sees the mix; XLA lowers
    the dynamic trip count to a while loop inside the one variant.
    Rows shorter than the longest one mask their dead tail lanes; rows
    past their own table prefix gather the trash block (table tails
    are 0) and mask it the same way."""
    B, Sq = bound.shape
    nbs = table.shape[1]
    block = pool_layer["k"].shape[2]
    quantized = "k_scale" in pool_layer
    offs = jnp.arange(block)

    def body(j, carry):
        bids = jax.lax.dynamic_index_in_dim(table, j, axis=1,
                                            keepdims=False)  # [B]
        kb = pool_layer["k"][bids]          # [B, Hkv, block, Dh]
        vb = pool_layer["v"][bids]
        ks = pool_layer["k_scale"][bids] if quantized else None
        vs = pool_layer["v_scale"][bids] if quantized else None
        t_abs = j * block + offs
        mask = t_abs[None, None, :] < bound[:, :, None]  # [B, Sq, block]
        s = _block_scores(q, kb, ks, mask)
        return _block_accumulate(carry, s, mask, vb, vs)

    n_live = jnp.clip(
        (jnp.max(bound) + block - 1) // block, 0, nbs
    ).astype(jnp.int32)
    init = _init_partials(B, q.shape[2], q.shape[3], Sq, q.shape[4])
    return jax.lax.fori_loop(0, n_live, body, init)


# ---------------------------------------------------------------------------
# Masked-matched two-pass walk — the greedy-parity leg
# ---------------------------------------------------------------------------
#
# The one-pass partials above keep the softmax weights in f32 end to
# end — strictly MORE accurate than the masked engine kernels, which
# round the normalized weights to the activation dtype before the value
# einsum (gqa_attention's ``w.astype(q.dtype)``, gqa_attention_decode's
# ``wc.astype(qr.dtype)``). More accurate is still DIFFERENT: on
# flat-logit models a ~1e-3 drift flips near-tied greedy argmaxes. The
# two-pass walk below reproduces the masked term set exactly — every
# weight is normalized in f32, scaled, then rounded to the query dtype
# before multiplying the same-dtype value block, accumulated in f32
# across blocks with ONE final cast — so sparse-vs-masked differences
# reduce to f32 summation order (~1 ulp), and greedy token identity
# becomes an engineering property instead of a margin bet. The sparse
# wave legs use this pair; ``partials_sparse`` remains for the pallas
# fallback and the oracle tests.
#
# ``dequant`` selects which masked kernel is being matched: False for
# the factored-scale decode/verify path (scores x k_scale in f32 after
# the einsum, weights x v_scale in f32 before the cast); True for the
# prefill path, which dequantizes int8 prefix KV into the activation
# dtype FIRST (_run_blocks_prefill_prefix's ``pk * k_scale``) and runs
# unscaled attention over it.


def _sparse_block(pool_layer, table, j, dtype, dequant):
    """Gather block column j: (kb, vb, k_scale, v_scale) with the
    dequant-vs-factored convention applied.

    The optimization_barrier pins the DEQUANTIZED block to its
    materialized (rounded) activation-dtype value — the same hazard
    class as models/transformer._quantize_kv: bf16 math inside an XLA
    fusion runs in f32 and only rounds at materialization boundaries.
    The masked twin (_run_blocks_prefill_prefix) rounds its dequant at
    the prefix‖fresh concat boundary; without the barrier the walker's
    dequant fuses straight into the score/value dots unrounded and the
    two legs' logits drift apart (greedy flips at ~2e-3 under int8)."""
    bids = jax.lax.dynamic_index_in_dim(table, j, axis=1, keepdims=False)
    kb = pool_layer["k"][bids]
    vb = pool_layer["v"][bids]
    ks = pool_layer["k_scale"][bids] if "k_scale" in pool_layer else None
    vs = pool_layer["v_scale"][bids] if "v_scale" in pool_layer else None
    if dequant and ks is not None:
        kb = jax.lax.optimization_barrier(
            kb.astype(dtype) * ks[..., None].astype(dtype))
        vb = jax.lax.optimization_barrier(
            vb.astype(dtype) * vs[..., None].astype(dtype))
        ks = vs = None
    return kb, vb, ks, vs


def sparse_max_sum(q: jnp.ndarray, pool_layer: Dict[str, jnp.ndarray],
                   table: jnp.ndarray, bound: jnp.ndarray,
                   dequant: bool = False) -> Tuple[jnp.ndarray,
                                                   jnp.ndarray]:
    """Pass 1 of the matched walk: running max ``m`` and exp-sum ``l``
    (relative to m) of the live pool scores — no value traffic. Shapes
    as in _init_partials; dead rows stay (NEG_INF, 0)."""
    B, Sq = bound.shape
    nbs = table.shape[1]
    block = pool_layer["k"].shape[2]
    offs = jnp.arange(block)

    def body(j, carry):
        m, l = carry
        kb, _, ks, _ = _sparse_block(pool_layer, table, j, q.dtype,
                                     dequant)
        t_abs = j * block + offs
        mask = t_abs[None, None, :] < bound[:, :, None]
        s = _block_scores(q, kb, ks, mask)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(mask[:, None, None, :, :], jnp.exp(s - m_new), 0.0)
        l = l * jnp.exp(m - m_new) + jnp.sum(p, axis=-1, keepdims=True)
        return m_new, l

    n_live = jnp.clip(
        (jnp.max(bound) + block - 1) // block, 0, nbs
    ).astype(jnp.int32)
    init = (
        jnp.full((B, q.shape[2], q.shape[3], Sq, 1), NEG_INF, jnp.float32),
        jnp.zeros((B, q.shape[2], q.shape[3], Sq, 1), jnp.float32),
    )
    return jax.lax.fori_loop(0, n_live, body, init)


def sparse_weighted_value(q: jnp.ndarray,
                          pool_layer: Dict[str, jnp.ndarray],
                          table: jnp.ndarray, bound: jnp.ndarray,
                          m_t: jnp.ndarray,
                          l_t: jnp.ndarray,
                          dequant: bool = False) -> jnp.ndarray:
    """Pass 2 of the matched walk: ``sum_t round(exp(s_t - m_t) / l_t
    [* v_scale]) . v_t`` over live pool columns, f32 accumulation
    across blocks. ``m_t``/``l_t`` are the GLOBAL max / exp-sum after
    the caller folded its fresh columns in, so each weight is the very
    number the masked kernel rounds to the query dtype. Returns
    [B, Hkv, G, Sq, Dh] f32 — cast once, by the caller, next to the
    masked leg's single einsum output cast."""
    B, Sq = bound.shape
    nbs = table.shape[1]
    block = pool_layer["k"].shape[2]
    offs = jnp.arange(block)
    l_safe = jnp.maximum(l_t, 1e-30)

    def body(j, acc):
        kb, vb, ks, vs = _sparse_block(pool_layer, table, j, q.dtype,
                                       dequant)
        t_abs = j * block + offs
        mask = t_abs[None, None, :] < bound[:, :, None]
        s = _block_scores(q, kb, ks, mask)
        # Mask BEFORE dividing: a fully-dead row has m_t finite only
        # via its fresh columns, but dead lanes at s = NEG_INF already
        # underflow; the where guards the bound = 0, m_t = NEG_INF case
        # where exp(s - m_t) would be exp(0) on every lane.
        w = jnp.where(mask[:, None, None, :, :],
                      jnp.exp(s - m_t), 0.0) / l_safe
        if vs is not None:
            w = w * vs[:, :, None, None, :]
        return acc + jnp.einsum(
            "bkgst,bktd->bkgsd", w.astype(q.dtype), vb.astype(q.dtype),
            preferred_element_type=jnp.float32,
        )

    n_live = jnp.clip(
        (jnp.max(bound) + block - 1) // block, 0, nbs
    ).astype(jnp.int32)
    init = jnp.zeros((B, q.shape[2], q.shape[3], Sq, q.shape[4]),
                     jnp.float32)
    return jax.lax.fori_loop(0, n_live, body, init)


# ---------------------------------------------------------------------------
# Pallas kernel — scalar-prefetched block tables, one DMA per live block
# ---------------------------------------------------------------------------


def _rpa_kernel(table_ref, bound_ref, q_ref, k_ref, v_ref, *rest,
                quantized, block, n_kv_heads, scale):
    """Grid (B * Hkv, nbs). Scalar-prefetch arg 0 is the block table —
    consumed by the K/V index maps, unused here. Scratch carries the
    (m, l, acc) accumulators across the block-column axis; dead columns
    (past every query row's bound) skip their FLOPs under pl.when while
    their index maps re-address the trash block, so neither DMA nor MXU
    pays for the padded tail."""
    from jax.experimental import pallas as pl

    if quantized:
        ks_ref, vs_ref, m_ref, l_ref, acc_ref, m_scr, l_scr, acc_scr = rest
    else:
        m_ref, l_ref, acc_ref, m_scr, l_scr, acc_scr = rest
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    bound = bound_ref[0]  # [R] int32
    live = j * block < jnp.max(bound)

    @pl.when(live)
    def _accumulate():
        q = q_ref[0]                        # [R, Dh]
        k = k_ref[0, 0]                     # [block, Dh] int8/bf16
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k.astype(q.dtype), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                           # [R, block]
        if quantized:
            s = s * ks_ref[0, 0][None, :].astype(jnp.float32)
        R = s.shape[0]
        cols = j * block + jax.lax.broadcasted_iota(
            jnp.int32, (R, block), 1
        )
        mask = cols < bound[:, None]
        s = jnp.where(mask, s, NEG_INF)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        if quantized:
            pw = p * vs_ref[0, 0][None, :].astype(jnp.float32)
        else:
            pw = p
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            pw, v.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        m_scr[:] = m_new

    @pl.when(j == pl.num_programs(1) - 1)
    def _finish():
        m_ref[0] = m_scr[:]
        l_ref[0] = l_scr[:]
        acc_ref[0] = acc_scr[:]


def partials_pallas(q: jnp.ndarray, pool_layer: Dict[str, jnp.ndarray],
                    table: jnp.ndarray, bound: jnp.ndarray,
                    interpret: Optional[bool] = None) -> Partials:
    """Pallas/Mosaic walker: same (m, l, acc) contract as the jnp legs.

    The block table rides as the scalar-prefetch operand so the K/V
    BlockSpec index maps address pool blocks DIRECTLY —
    ``(table[b, j], h, 0, 0)`` — one block-sized DMA per grid step,
    never a full-width gather. Off-TPU runs under ``interpret=True``
    (the CPU parity leg); pass ``interpret`` to force either mode."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, Sq, Hkv, G, Dh = q.shape
    nbs = table.shape[1]
    block = pool_layer["k"].shape[2]
    quantized = "k_scale" in pool_layer
    R = G * Sq
    if interpret is None:
        interpret = not _on_tpu()
    # Fold (G, Sq) onto one row axis; bound broadcasts per group.
    qf = q.transpose(0, 2, 3, 1, 4).reshape(B * Hkv, R, Dh)
    bound_r = jnp.broadcast_to(
        bound[:, None, :], (B, G, Sq)
    ).reshape(B, R).astype(jnp.int32)

    def kv_index(bh, j, tref):
        return (tref[bh // Hkv, j], bh % Hkv, 0, 0)

    def scale_index(bh, j, tref):
        return (tref[bh // Hkv, j], bh % Hkv, 0)

    in_specs = [
        pl.BlockSpec((1, R), lambda bh, j, tref: (bh // Hkv, 0)),
        pl.BlockSpec((1, R, Dh), lambda bh, j, tref: (bh, 0, 0)),
        pl.BlockSpec((1, 1, block, Dh), kv_index),
        pl.BlockSpec((1, 1, block, Dh), kv_index),
    ]
    args = [bound_r, qf, pool_layer["k"], pool_layer["v"]]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, 1, block), scale_index),
            pl.BlockSpec((1, 1, block), scale_index),
        ]
        args += [pool_layer["k_scale"], pool_layer["v_scale"]]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B * Hkv, nbs),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, R, 1), lambda bh, j, tref: (bh, 0, 0)),
            pl.BlockSpec((1, R, 1), lambda bh, j, tref: (bh, 0, 0)),
            pl.BlockSpec((1, R, Dh), lambda bh, j, tref: (bh, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((R, 1), jnp.float32),
            pltpu.VMEM((R, 1), jnp.float32),
            pltpu.VMEM((R, Dh), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _rpa_kernel,
        quantized=quantized,
        block=block,
        n_kv_heads=Hkv,
        scale=Dh**-0.5,
    )
    m, l, acc = pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct((B * Hkv, R, 1), jnp.float32),
            jax.ShapeDtypeStruct((B * Hkv, R, 1), jnp.float32),
            jax.ShapeDtypeStruct((B * Hkv, R, Dh), jnp.float32),
        ],
        grid_spec=grid_spec,
        interpret=interpret,
    )(table.astype(jnp.int32), *args)
    unfold = lambda t: t.reshape(B, Hkv, G, Sq, t.shape[-1])
    return unfold(m), unfold(l), unfold(acc)


# ---------------------------------------------------------------------------
# Dispatch
# ---------------------------------------------------------------------------


def _on_tpu() -> bool:
    try:
        platform = jax.devices()[0].platform
    except Exception:  # pragma: no cover
        return False
    return platform in ("tpu", "axon")


def ragged_paged_partials(
    q: jnp.ndarray,          # [B, Sq, Hkv, G, Dh] grouped queries
    pool_layer: Dict[str, jnp.ndarray],  # one layer's paged pool slice
    table: jnp.ndarray,      # [B, nbs] int32 block tables
    bound: jnp.ndarray,      # [B, Sq] int32 — attend pool t < bound
    mode: str = "sparse",
) -> Partials:
    """Per-backend dispatch of the block-sparse partials (m, l, acc).

    mode "sparse" — jnp fori_loop walker (the CPU winner);
    "pallas" — Mosaic kernel, interpret-mode off-TPU, falling back to
    the sparse walker on backend failure (flash_attention's fallback
    idiom); "reference" — full-width oracle."""
    if mode == "reference":
        return partials_reference(q, pool_layer, table, bound)
    if mode == "pallas":
        try:
            return partials_pallas(q, pool_layer, table, bound)
        except Exception:  # pragma: no cover - backend quirks
            logger.exception(
                "pallas ragged paged attention failed; falling back to "
                "the jnp block-sparse walker (q=%s table=%s)",
                q.shape, table.shape,
            )
            return partials_sparse(q, pool_layer, table, bound)
    if mode != "sparse":
        raise ValueError(f"unknown ragged kernel mode {mode!r}")
    return partials_sparse(q, pool_layer, table, bound)
