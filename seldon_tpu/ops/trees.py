"""Tree-ensemble inference on TPU — vectorized node traversal in JAX.

The reference serves xgboost/sklearn tree models on CPU via their native
libraries (servers/xgboostserver/XGBoostServer.py:10-26). Neither library
is in this image, and CPU traversal wouldn't use the chip anyway. Here an
ensemble is compiled to flat arrays — (feature, threshold, left, right,
value) per node — and traversal is `max_depth` rounds of vectorized
gathers over [batch, n_trees] node cursors: branchless, static-shaped,
XLA-fusable. Works for xgboost JSON dumps and any sklearn-style tree."""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Sequence

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class TreeEnsemble:
    """Flat ensemble: arrays [n_trees, max_nodes]."""

    feature: np.ndarray  # int32; -1 = leaf
    threshold: np.ndarray  # f32
    left: np.ndarray  # int32 child index (within tree)
    right: np.ndarray
    value: np.ndarray  # f32 leaf value (0 on internal nodes)
    missing: np.ndarray  # int32 child for NaN features (xgboost 'missing')
    max_depth: int
    base_score: float = 0.0

    @property
    def n_trees(self) -> int:
        return self.feature.shape[0]


def _pad_trees(trees: List[Dict[str, List]], max_depth_cap: int = 64):
    """trees: list of dicts with per-node parallel lists."""
    max_nodes = max(len(t["feature"]) for t in trees)
    n = len(trees)

    def arr(key, fill, dtype):
        out = np.full((n, max_nodes), fill, dtype=dtype)
        for i, t in enumerate(trees):
            out[i, : len(t[key])] = t[key]
        return out

    return (
        arr("feature", -1, np.int32),
        arr("threshold", 0.0, np.float32),
        arr("left", 0, np.int32),
        arr("right", 0, np.int32),
        arr("value", 0.0, np.float32),
        arr("missing", 0, np.int32),
    )


def from_xgboost_json(dump: Sequence[str] | str, base_score: float = 0.0
                      ) -> TreeEnsemble:
    """Build from `Booster.get_dump(dump_format='json')` (list of per-tree
    JSON strings) or a JSON array of trees."""
    if isinstance(dump, str):
        tree_objs = json.loads(dump)
    else:
        tree_objs = [json.loads(t) if isinstance(t, str) else t for t in dump]

    trees = []
    max_depth = 1
    for obj in tree_objs:
        nodes: Dict[int, Dict[str, Any]] = {}

        def walk(node, depth=0):
            nonlocal max_depth
            max_depth = max(max_depth, depth + 1)
            nid = node["nodeid"]
            if "leaf" in node:
                nodes[nid] = {"feature": -1, "threshold": 0.0, "left": nid,
                              "right": nid, "missing": nid,
                              "value": float(node["leaf"])}
                return
            feat = node["split"]
            fidx = int(feat[1:]) if isinstance(feat, str) and feat.startswith("f") else int(feat)
            nodes[nid] = {
                "feature": fidx,
                "threshold": float(node["split_condition"]),
                "left": int(node["yes"]),
                "right": int(node["no"]),
                # xgboost routes NaN to the learned missing-direction child
                # (defaults to 'yes' when the dump omits it).
                "missing": int(node.get("missing", node["yes"])),
                "value": 0.0,
            }
            for child in node.get("children", []):
                walk(child, depth + 1)

        walk(obj)
        # Re-index to dense 0..n-1 (xgboost node ids can be sparse).
        ids = sorted(nodes)
        remap = {old: new for new, old in enumerate(ids)}
        tree = {"feature": [], "threshold": [], "left": [], "right": [],
                "value": [], "missing": []}
        for old in ids:
            nd = nodes[old]
            tree["feature"].append(nd["feature"])
            tree["threshold"].append(nd["threshold"])
            tree["left"].append(remap[nd["left"]])
            tree["right"].append(remap[nd["right"]])
            tree["value"].append(nd["value"])
            tree["missing"].append(remap[nd["missing"]])
        trees.append(tree)

    f, t, l, r, v, m = _pad_trees(trees)
    return TreeEnsemble(f, t, l, r, v, m, max_depth=max_depth,
                        base_score=base_score)


def predict_margin(ensemble: TreeEnsemble, X: jnp.ndarray) -> jnp.ndarray:
    """X [B, F] -> summed leaf margins [B] (add sigmoid/softmax outside)."""
    feature = jnp.asarray(ensemble.feature)
    threshold = jnp.asarray(ensemble.threshold)
    left = jnp.asarray(ensemble.left)
    right = jnp.asarray(ensemble.right)
    value = jnp.asarray(ensemble.value)
    missing = jnp.asarray(ensemble.missing)
    B = X.shape[0]
    T = ensemble.n_trees
    node = jnp.zeros((B, T), jnp.int32)
    tree_idx = jnp.arange(T)[None, :]

    def step(_, node):
        feat = feature[tree_idx, node]  # [B, T]
        thr = threshold[tree_idx, node]
        is_leaf = feat < 0
        x = jnp.take_along_axis(X, jnp.maximum(feat, 0), axis=1)
        go_left = x < thr
        nxt = jnp.where(go_left, left[tree_idx, node], right[tree_idx, node])
        # NaN features take the learned missing-direction child (x < thr is
        # False for NaN, which would silently route 'no'/right otherwise).
        nxt = jnp.where(jnp.isnan(x), missing[tree_idx, node], nxt)
        return jnp.where(is_leaf, node, nxt)

    node = jax.lax.fori_loop(0, ensemble.max_depth, step, node)
    margins = value[tree_idx, node].sum(axis=1)
    return margins + ensemble.base_score


def predict(ensemble: TreeEnsemble, X, objective: str = "reg") -> jnp.ndarray:
    """objective: 'reg' (raw), 'binary' (sigmoid), 'binary:raw'."""
    m = predict_margin(ensemble, jnp.asarray(X, jnp.float32))
    if objective == "binary":
        return jax.nn.sigmoid(m)
    return m
