"""Decode attention (S=1 queries over the KV cache) — pallas TPU kernel.

Why: XLA lowers per-step cache attention to B*Hkv tiny matmuls
([G, Dh] x [Dh, T] with G = q heads per kv head, typically 2-8 rows) —
~1.6% MXU row utilization, and the dominant share of a decode step once
weights are amortized over enough slots. This kernel restructures both
matmuls so the MXU sees full tiles:

    scores^T [T_t, G] = K_tile [T_t, Dh] . q^T   (M = T_t = 128)
    acc      [Dh, G] += V_tile^T . p             (M = Dh = 128)

with the usual online-softmax accumulators per q-group, streaming the
cache through VMEM tile by tile. GQA is native (grid over B*Hkv, q
pre-grouped [B*Hkv, G, Dh]). int8 KV slots dequantize INSIDE the kernel
(per-(token, head) scales ride along as a second operand), so the HBM
read stays 1 byte/element.

Per-row `pos` bounds (continuous batching: every slot at a different
position) arrive via scalar-memory refs; tail tiles beyond the cache
window are masked by the same bound (pos < T always).
"""

from __future__ import annotations

import functools
import logging

import jax
import jax.numpy as jnp

logger = logging.getLogger(__name__)

DEFAULT_BLOCK_T = 128
NEG_INF = -1e30


# ---------------------------------------------------------------------------
# Reference (XLA) implementation — CPU fallback + numerics oracle
# ---------------------------------------------------------------------------


def decode_attention_reference(
    q: jnp.ndarray,  # [B, H, Dh]
    k: jnp.ndarray,  # [B, Hkv, T, Dh] head-major (already dequantized)
    v: jnp.ndarray,
    pos: jnp.ndarray,  # [B] attend to t <= pos
) -> jnp.ndarray:
    B, H, Dh = q.shape
    Hkv = k.shape[1]
    G = H // Hkv
    qg = q.reshape(B, Hkv, G, Dh)
    s = jnp.einsum("bkgd,bktd->bkgt", qg, k,
                   preferred_element_type=jnp.float32) * (Dh**-0.5)
    T = k.shape[2]
    mask = jnp.arange(T)[None, None, None, :] <= pos[:, None, None, None]
    s = jnp.where(mask, s, NEG_INF)
    w = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bkgt,bktd->bkgd", w.astype(v.dtype), v)
    return o.reshape(B, H, Dh)


# ---------------------------------------------------------------------------
# Pallas kernel
# ---------------------------------------------------------------------------


def _decode_kernel_bf16(pos_ref, q_ref, k_ref, v_ref, o_ref,
                        m_scr, l_scr, acc_scr, *, block_t, scale):
    _decode_kernel(pos_ref, q_ref, k_ref, v_ref, None, None, o_ref,
                   m_scr, l_scr, acc_scr, block_t=block_t, scale=scale,
                   quantized=False)


def _decode_kernel(pos_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref,
                   m_scr, l_scr, acc_scr, *, block_t, scale, quantized):
    from jax.experimental import pallas as pl

    tj = pl.program_id(1)

    @pl.when(tj == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Scalar-prefetched bound: the whole pos array sits in SMEM.
    bound = pos_ref[pl.program_id(0)]  # attend to t <= bound

    # Tiles wholly beyond the bound contribute nothing: skip their FLOPs.
    @pl.when(tj * block_t <= bound)
    def _accumulate():
        q = q_ref[0].astype(jnp.float32)  # [Hkv, G, Dh]
        k = k_ref[0].astype(jnp.float32)  # [Hkv, block_t, Dh]
        v = v_ref[0].astype(jnp.float32)
        if quantized:
            k = k * ks_ref[0][:, :, None].astype(jnp.float32)
            v = v * vs_ref[0][:, :, None].astype(jnp.float32)

        # Batched over kv heads; scores^T [Hkv, block_t, G] puts
        # M = block_t on the MXU.
        st = jax.lax.dot_general(
            k, q, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale
        t_global = tj * block_t + jax.lax.broadcasted_iota(
            jnp.int32, st.shape, 1
        )
        st = jnp.where(t_global <= bound, st, NEG_INF)
        # Zero v's masked rows: the tail tile reads past the cache window
        # (pallas pads with garbage, possibly NaN) and 0 * NaN would
        # poison the value matmul even though p is 0 there.
        t_rows = tj * block_t + jax.lax.broadcasted_iota(
            jnp.int32, v.shape, 1
        )
        v = jnp.where(t_rows <= bound, v, 0.0)

        m_prev = m_scr[:].reshape(st.shape[0], 1, st.shape[2])  # [Hkv,1,G]
        m_cur = jnp.max(st, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(st - m_new)  # [Hkv, block_t, G]
        alpha = jnp.exp(m_prev - m_new)  # [Hkv, 1, G]
        l_scr[:] = (alpha[:, 0] * l_scr[:] + jnp.sum(p, axis=1))
        # acc [Hkv, Dh, G]: M = Dh on the value matmul; alpha [Hkv,1,G]
        # broadcasts over Dh.
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            v, p, (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = m_new[:, 0]

    @pl.when(tj == pl.num_programs(1) - 1)
    def _finish():
        l = jnp.maximum(l_scr[:], 1e-30)[:, None, :]  # [Hkv, 1, G]
        out = acc_scr[:] / l  # [Hkv, Dh, G]
        o_ref[0] = out.transpose(0, 2, 1).astype(o_ref.dtype)  # [Hkv,G,Dh]


def _decode_pallas(q, k, v, pos, k_scale, v_scale, block_t, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, Dh = q.shape
    Hkv, T = k.shape[1], k.shape[2]
    G = H // Hkv
    quantized = k_scale is not None
    block_t = min(block_t, T)
    n_t = -(-T // block_t)  # ceil: tail tiles masked by the pos bound

    qg = q.reshape(B, Hkv, G, Dh)

    grid = (B, n_t)

    # index maps receive the prefetched scalar ref as a trailing arg.
    # DMA pruning: tiles past the row's bound clamp to the last live tile
    # index, so a short row re-fetches an already-resident block instead
    # of streaming the whole window — the compute skip (pl.when in the
    # kernel) alone would leave the bandwidth untouched.
    def kv_idx(b, t, pos_ref):
        t_live = jnp.minimum(t, pos_ref[b] // block_t)
        return (b, 0, t_live, 0)

    def scale_idx(b, t, pos_ref):
        t_live = jnp.minimum(t, pos_ref[b] // block_t)
        return (b, 0, t_live)

    kv_spec = pl.BlockSpec((1, Hkv, block_t, Dh), kv_idx)
    q_spec = pl.BlockSpec((1, Hkv, G, Dh), lambda b, t, pos_ref: (b, 0, 0, 0))
    if quantized:
        kernel = functools.partial(
            _decode_kernel, block_t=block_t, scale=Dh**-0.5, quantized=True,
        )
        scale_spec = pl.BlockSpec((1, Hkv, block_t), scale_idx)
        in_specs = [q_spec, kv_spec, kv_spec, scale_spec, scale_spec]
        args = (pos.astype(jnp.int32), qg, k, v, k_scale, v_scale)
    else:
        kernel = functools.partial(
            _decode_kernel_bf16, block_t=block_t, scale=Dh**-0.5,
        )
        in_specs = [q_spec, kv_spec, kv_spec]
        args = (pos.astype(jnp.int32), qg, k, v)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, Hkv, G, Dh), lambda b, t, pos_ref: (b, 0, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((Hkv, G), jnp.float32),
            pltpu.VMEM((Hkv, G), jnp.float32),
            pltpu.VMEM((Hkv, Dh, G), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B, Hkv, G, Dh), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(*args).reshape(B, H, Dh)


def _on_tpu() -> bool:
    try:
        platform = jax.devices()[0].platform
    except Exception:  # pragma: no cover
        return False
    return platform in ("tpu", "axon")


def decode_attention(
    q: jnp.ndarray,  # [B, H, Dh]
    k: jnp.ndarray,  # [B, Hkv, T, Dh] head-major — bf16, or int8 + scales
    v: jnp.ndarray,
    pos: jnp.ndarray,  # [B] int32: attend to t <= pos[b]
    k_scale: jnp.ndarray = None,  # [B, Hkv, T] when k/v are int8
    v_scale: jnp.ndarray = None,
    block_t: int = DEFAULT_BLOCK_T,
    force_reference: bool = False,
    interpret: bool = False,
) -> jnp.ndarray:
    """One-token-per-row attention over the cache; pallas on TPU (also
    under interpret=True for CPU tests), XLA reference elsewhere."""
    if force_reference or not (_on_tpu() or interpret):
        if k_scale is not None:
            k = k.astype(jnp.float32) * k_scale[..., None]
            v = v.astype(jnp.float32) * v_scale[..., None]
        return decode_attention_reference(
            q, k.astype(q.dtype), v.astype(q.dtype), pos
        )
    try:
        return _decode_pallas(q, k, v, pos, k_scale, v_scale, block_t,
                              interpret)
    except Exception:  # pragma: no cover - backend quirks
        logger.exception(
            "pallas decode attention failed; falling back to reference "
            "(q=%s k=%s)", q.shape, k.shape,
        )
        if k_scale is not None:
            k = k.astype(jnp.float32) * k_scale[..., None]
            v = v.astype(jnp.float32) * v_scale[..., None]
        return decode_attention_reference(
            q, k.astype(q.dtype), v.astype(q.dtype), pos
        )
