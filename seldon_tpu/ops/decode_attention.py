"""Decode attention (S=1 queries over the KV cache) — pallas TPU kernel.

Why: XLA lowers per-step cache attention to B*Hkv tiny matmuls
([G, Dh] x [Dh, T] with G = q heads per kv head, typically 2-8 rows) —
~1.6% MXU row utilization, and the dominant share of a decode step once
weights are amortized over enough slots. This kernel restructures both
matmuls so the MXU sees full tiles:

    scores^T [T_t, G] = K_tile [T_t, Dh] . q^T   (M = T_t = 128)
    acc      [Dh, G] += V_tile^T . p             (M = Dh = 128)

with the usual online-softmax accumulators per q-group, streaming the
cache through VMEM tile by tile. GQA is native (grid over B*Hkv, q
pre-grouped [B*Hkv, G, Dh]). int8 KV slots dequantize INSIDE the kernel
(per-(token, head) scales ride along as a second operand), so the HBM
read stays 1 byte/element.

Per-row `pos` bounds (continuous batching: every slot at a different
position) arrive via scalar-memory refs; tail tiles beyond the cache
window are masked by the same bound (pos < T always).
"""

from __future__ import annotations

import functools
import logging

import jax
import jax.numpy as jnp

logger = logging.getLogger(__name__)

DEFAULT_BLOCK_T = 128
NEG_INF = -1e30


# ---------------------------------------------------------------------------
# Reference (XLA) implementation — CPU fallback + numerics oracle
# ---------------------------------------------------------------------------


def decode_attention_reference(
    q: jnp.ndarray,  # [B, H, Dh]
    k: jnp.ndarray,  # [B, Hkv, T, Dh] head-major (already dequantized)
    v: jnp.ndarray,
    pos: jnp.ndarray,  # [B] attend to t <= pos
) -> jnp.ndarray:
    B, H, Dh = q.shape
    Hkv = k.shape[1]
    G = H // Hkv
    qg = q.reshape(B, Hkv, G, Dh)
    s = jnp.einsum("bkgd,bktd->bkgt", qg, k,
                   preferred_element_type=jnp.float32) * (Dh**-0.5)
    T = k.shape[2]
    mask = jnp.arange(T)[None, None, None, :] <= pos[:, None, None, None]
    s = jnp.where(mask, s, NEG_INF)
    w = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bkgt,bktd->bkgd", w.astype(v.dtype), v)
    return o.reshape(B, H, Dh)


# ---------------------------------------------------------------------------
# Pallas kernel
# ---------------------------------------------------------------------------


def _decode_kernel_bf16(pos_ref, q_ref, k_ref, v_ref, o_ref,
                        m_scr, l_scr, acc_scr, *, block_t, scale):
    _decode_kernel(pos_ref, q_ref, k_ref, v_ref, None, None, o_ref,
                   m_scr, l_scr, acc_scr, block_t=block_t, scale=scale,
                   quantized=False)


def _decode_kernel(pos_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref,
                   m_scr, l_scr, acc_scr, *, block_t, scale, quantized):
    from jax.experimental import pallas as pl

    tj = pl.program_id(1)

    @pl.when(tj == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Scalar-prefetched bound: the whole pos array sits in SMEM.
    bound = pos_ref[pl.program_id(0)]  # attend to t <= bound

    # Tiles wholly beyond the bound contribute nothing: skip their FLOPs.
    @pl.when(tj * block_t <= bound)
    def _accumulate():
        q = q_ref[0].astype(jnp.float32)  # [Hkv, G, Dh]
        k = k_ref[0].astype(jnp.float32)  # [Hkv, block_t, Dh]
        v = v_ref[0].astype(jnp.float32)
        if quantized:
            k = k * ks_ref[0][:, :, None].astype(jnp.float32)
            v = v * vs_ref[0][:, :, None].astype(jnp.float32)

        # Batched over kv heads; scores^T [Hkv, block_t, G] puts
        # M = block_t on the MXU.
        st = jax.lax.dot_general(
            k, q, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale
        t_global = tj * block_t + jax.lax.broadcasted_iota(
            jnp.int32, st.shape, 1
        )
        st = jnp.where(t_global <= bound, st, NEG_INF)
        # Zero v's masked rows: the tail tile reads past the cache window
        # (pallas pads with garbage, possibly NaN) and 0 * NaN would
        # poison the value matmul even though p is 0 there.
        t_rows = tj * block_t + jax.lax.broadcasted_iota(
            jnp.int32, v.shape, 1
        )
        v = jnp.where(t_rows <= bound, v, 0.0)

        m_prev = m_scr[:].reshape(st.shape[0], 1, st.shape[2])  # [Hkv,1,G]
        m_cur = jnp.max(st, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(st - m_new)  # [Hkv, block_t, G]
        alpha = jnp.exp(m_prev - m_new)  # [Hkv, 1, G]
        l_scr[:] = (alpha[:, 0] * l_scr[:] + jnp.sum(p, axis=1))
        # acc [Hkv, Dh, G]: M = Dh on the value matmul; alpha [Hkv,1,G]
        # broadcasts over Dh.
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            v, p, (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = m_new[:, 0]

    @pl.when(tj == pl.num_programs(1) - 1)
    def _finish():
        l = jnp.maximum(l_scr[:], 1e-30)[:, None, :]  # [Hkv, 1, G]
        out = acc_scr[:] / l  # [Hkv, Dh, G]
        o_ref[0] = out.transpose(0, 2, 1).astype(o_ref.dtype)  # [Hkv,G,Dh]


def _decode_pallas(q, k, v, pos, k_scale, v_scale, block_t, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, Dh = q.shape
    Hkv, T = k.shape[1], k.shape[2]
    G = H // Hkv
    quantized = k_scale is not None
    block_t = min(block_t, T)
    n_t = -(-T // block_t)  # ceil: tail tiles masked by the pos bound

    qg = q.reshape(B, Hkv, G, Dh)

    grid = (B, n_t)

    # index maps receive the prefetched scalar ref as a trailing arg.
    # DMA pruning: tiles past the row's bound clamp to the last live tile
    # index, so a short row re-fetches an already-resident block instead
    # of streaming the whole window — the compute skip (pl.when in the
    # kernel) alone would leave the bandwidth untouched.
    def kv_idx(b, t, pos_ref):
        t_live = jnp.minimum(t, pos_ref[b] // block_t)
        return (b, 0, t_live, 0)

    def scale_idx(b, t, pos_ref):
        t_live = jnp.minimum(t, pos_ref[b] // block_t)
        return (b, 0, t_live)

    kv_spec = pl.BlockSpec((1, Hkv, block_t, Dh), kv_idx)
    q_spec = pl.BlockSpec((1, Hkv, G, Dh), lambda b, t, pos_ref: (b, 0, 0, 0))
    if quantized:
        kernel = functools.partial(
            _decode_kernel, block_t=block_t, scale=Dh**-0.5, quantized=True,
        )
        scale_spec = pl.BlockSpec((1, Hkv, block_t), scale_idx)
        in_specs = [q_spec, kv_spec, kv_spec, scale_spec, scale_spec]
        args = (pos.astype(jnp.int32), qg, k, v, k_scale, v_scale)
    else:
        kernel = functools.partial(
            _decode_kernel_bf16, block_t=block_t, scale=Dh**-0.5,
        )
        in_specs = [q_spec, kv_spec, kv_spec]
        args = (pos.astype(jnp.int32), qg, k, v)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, Hkv, G, Dh), lambda b, t, pos_ref: (b, 0, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((Hkv, G), jnp.float32),
            pltpu.VMEM((Hkv, G), jnp.float32),
            pltpu.VMEM((Hkv, Dh, G), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B, Hkv, G, Dh), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(*args).reshape(B, H, Dh)


def _cached_kernel(li_ref, pos_ref, bmax_ref, q_ref, kf_ref, vf_ref, k_ref,
                   v_ref, ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr,
                   *, block_b, block_t, scale, quantized):
    """Layer-indexed decode attention over the PRE-write cache with the
    fresh token folded into the flash-init:

        m0 = s_fresh = (q . k_fresh) * scale,  l0 = 1,  acc0 = v_fresh

    which IS the softmax state after processing exactly one (the fresh)
    column — the cache tiles then stream through the standard online-
    softmax update with a STRICT t < pos bound (slot pos is stale; the
    engine scatters this step's k/v after the layer scan).

    Each grid cell covers BLOCK_B batch rows x one T tile: per-cell fixed
    cost measured ~4 us on v5e, so one-row cells (B x n_t grid) burn more
    time in overhead than in the 84 MB cache read. Work is kept in the
    flat [block_b*Hkv, ...] form and per-row bounds apply as UNROLLED
    scalar masks (Mosaic cannot broadcast an SMEM-built vector over major
    dims — vector<16> -> vector<16x1x1x1> shape casts are rejected)."""
    from jax.experimental import pallas as pl

    bi = pl.program_id(0)
    tj = pl.program_id(1)
    bb = q_ref.shape[0]
    Hkv, G, Dh = q_ref.shape[1:]
    # Per-row bounds: scalar loads (SMEM serves scalars only).
    bounds = [pos_ref[bi * block_b + i] for i in range(block_b)]
    block_max = bmax_ref[bi]

    @pl.when(tj == 0)
    def _init():
        qf = q_ref[...].astype(jnp.float32).reshape(bb * Hkv, G, Dh)
        kf = kf_ref[...].astype(jnp.float32).reshape(bb * Hkv, 1, Dh)
        vf = vf_ref[...].astype(jnp.float32).reshape(bb * Hkv, 1, Dh)
        s_f = jnp.sum(qf * kf, axis=-1) * scale  # [bb*Hkv, G]
        m_scr[:] = s_f
        l_scr[:] = jnp.ones_like(l_scr)
        # acc [bb*Hkv, Dh, G] = v_fresh per (row, d), replicated over G.
        acc_scr[:] = jnp.broadcast_to(
            vf.transpose(0, 2, 1), acc_scr.shape
        ).astype(jnp.float32)

    def _mask_rows(x, t0, fill):
        """x [bb*Hkv, block_t, last]: per-row scalar bound, unrolled."""
        ti = t0 + jax.lax.broadcasted_iota(
            jnp.int32, (Hkv,) + x.shape[1:], 1
        )
        rows = [
            jnp.where(ti < bounds[i], x[i * Hkv:(i + 1) * Hkv], fill)
            for i in range(block_b)
        ]
        return jnp.concatenate(rows, axis=0)

    # Skip tiles wholly past every row's bound in this block.
    @pl.when(tj * block_t < block_max)
    def _accumulate():
        q = q_ref[...].astype(jnp.float32).reshape(bb * Hkv, G, Dh)
        k = k_ref[0].astype(jnp.float32)  # [bb, Hkv, block_t, Dh]
        v = v_ref[0].astype(jnp.float32)
        if quantized:
            k = k * ks_ref[0][..., None].astype(jnp.float32)
            v = v * vs_ref[0][..., None].astype(jnp.float32)
        k = k.reshape(bb * Hkv, block_t, Dh)
        v = v.reshape(bb * Hkv, block_t, Dh)
        st = jax.lax.dot_general(
            k, q, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale  # [bb*Hkv, block_t, G]
        st = _mask_rows(st, tj * block_t, NEG_INF)
        # Zero v's masked rows: tail tiles read past the window (pallas
        # pads with garbage, possibly NaN) and 0 * NaN would poison the
        # value matmul even though p is 0 there.
        v = _mask_rows(v, tj * block_t, 0.0)

        m_prev = m_scr[:].reshape(bb * Hkv, 1, G)
        m_new = jnp.maximum(m_prev, jnp.max(st, axis=1, keepdims=True))
        p = jnp.exp(st - m_new)  # [bb*Hkv, block_t, G]
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:] = alpha[:, 0] * l_scr[:] + jnp.sum(p, axis=1)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            v, p, (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = m_new[:, 0]

    @pl.when(tj == pl.num_programs(1) - 1)
    def _finish():
        l = jnp.maximum(l_scr[:], 1e-30)[:, None, :]  # [bb*Hkv, 1, G]
        out = acc_scr[:] / l  # [bb*Hkv, Dh, G]
        o_ref[...] = (
            out.transpose(0, 2, 1).reshape(bb, Hkv, G, Dh).astype(o_ref.dtype)
        )


def _cached_kernel_bf16(li_ref, pos_ref, bmax_ref, q_ref, kf_ref, vf_ref,
                        k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr,
                        *, block_b, block_t, scale):
    _cached_kernel(li_ref, pos_ref, bmax_ref, q_ref, kf_ref, vf_ref, k_ref,
                   v_ref, None, None, o_ref, m_scr, l_scr, acc_scr,
                   block_b=block_b, block_t=block_t, scale=scale,
                   quantized=False)


def decode_attention_cached(
    q: jnp.ndarray,  # [B, H, Dh] this layer's rope'd queries
    k_fresh: jnp.ndarray,  # [B, Hkv, 1, Dh] exact bf16 fresh k (rope'd)
    v_fresh: jnp.ndarray,  # [B, Hkv, 1, Dh]
    cache_k: jnp.ndarray,  # [L, B, Hkv, T, Dh] FULL stacked cache
    cache_v: jnp.ndarray,
    li: jnp.ndarray,  # [] int32 layer index (traced)
    pos: jnp.ndarray,  # [B] int32: attend to t < pos[b] plus the fresh col
    k_scale: jnp.ndarray = None,  # [L, B, Hkv, T] when cache is int8
    v_scale: jnp.ndarray = None,
    block_b: int = 8,
    block_t: int = DEFAULT_BLOCK_T,
    interpret: bool = False,
) -> jnp.ndarray:
    """Pre-write decode attention with the cache consumed IN PLACE.

    The whole stacked [L, ...] cache is the pallas operand and the layer
    index rides scalar prefetch into the BlockSpec index maps, so calling
    this inside the layer scan streams exactly layer li's tiles HBM->VMEM
    — no per-layer dynamic-slice materialization (the cost that killed
    both the XLA post-write path and the earlier per-layer kernel).
    Returns [B, H, Dh]. B must be a multiple of block_b (the engine's
    slot counts are; block_b is shrunk to B when B is smaller)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, Dh = q.shape
    L, _, Hkv, T, _ = cache_k.shape
    G = H // Hkv
    quantized = k_scale is not None
    block_t = min(block_t, T)
    n_t = -(-T // block_t)
    while B % block_b:
        block_b //= 2
    qg = q.reshape(B, Hkv, G, Dh)

    grid = (B // block_b, n_t)

    # Tiles at or past every block row's bound clamp to the block's last
    # live tile (DMA pruning); bound == 0 (no past) still maps a tile,
    # but compute is skipped by the pl.when gate. The per-block max bound
    # is precomputed host-side and scalar-prefetched (index maps run on
    # the scalar core — no vector reductions there).
    def kv_idx(b, t, li_ref, pos_ref, bmax_ref):
        t_live = jnp.minimum(
            t, jnp.maximum(bmax_ref[b] - 1, 0) // block_t
        )
        return (li_ref[0], b, 0, t_live, 0)

    def scale_idx(b, t, li_ref, pos_ref, bmax_ref):
        t_live = jnp.minimum(
            t, jnp.maximum(bmax_ref[b] - 1, 0) // block_t
        )
        return (li_ref[0], b, 0, t_live)

    def row_idx(b, t, li_ref, pos_ref, bmax_ref):
        return (b, 0, 0, 0)

    q_spec = pl.BlockSpec((block_b, Hkv, G, Dh), row_idx)
    fresh_spec = pl.BlockSpec((block_b, Hkv, 1, Dh), row_idx)
    kv_spec = pl.BlockSpec((1, block_b, Hkv, block_t, Dh), kv_idx)
    li_arr = jnp.reshape(li, (1,)).astype(jnp.int32)
    pos32 = pos.astype(jnp.int32)
    block_max = jnp.max(pos32.reshape(B // block_b, block_b), axis=1)
    if quantized:
        kernel = functools.partial(
            _cached_kernel, block_b=block_b, block_t=block_t,
            scale=Dh**-0.5, quantized=True,
        )
        scale_spec = pl.BlockSpec((1, block_b, Hkv, block_t), scale_idx)
        in_specs = [q_spec, fresh_spec, fresh_spec, kv_spec, kv_spec,
                    scale_spec, scale_spec]
        args = (li_arr, pos32, block_max, qg, k_fresh, v_fresh,
                cache_k, cache_v, k_scale, v_scale)
    else:
        kernel = functools.partial(
            _cached_kernel_bf16, block_b=block_b, block_t=block_t,
            scale=Dh**-0.5,
        )
        in_specs = [q_spec, fresh_spec, fresh_spec, kv_spec, kv_spec]
        args = (li_arr, pos32, block_max, qg, k_fresh, v_fresh,
                cache_k, cache_v)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((block_b, Hkv, G, Dh), row_idx),
        scratch_shapes=[
            pltpu.VMEM((block_b * Hkv, G), jnp.float32),
            pltpu.VMEM((block_b * Hkv, G), jnp.float32),
            pltpu.VMEM((block_b * Hkv, Dh, G), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B, Hkv, G, Dh), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(*args).reshape(B, H, Dh)


def _on_tpu() -> bool:
    try:
        platform = jax.devices()[0].platform
    except Exception:  # pragma: no cover
        return False
    return platform in ("tpu", "axon")


def decode_attention(
    q: jnp.ndarray,  # [B, H, Dh]
    k: jnp.ndarray,  # [B, Hkv, T, Dh] head-major — bf16, or int8 + scales
    v: jnp.ndarray,
    pos: jnp.ndarray,  # [B] int32: attend to t <= pos[b]
    k_scale: jnp.ndarray = None,  # [B, Hkv, T] when k/v are int8
    v_scale: jnp.ndarray = None,
    block_t: int = DEFAULT_BLOCK_T,
    force_reference: bool = False,
    interpret: bool = False,
) -> jnp.ndarray:
    """One-token-per-row attention over the cache; pallas on TPU (also
    under interpret=True for CPU tests), XLA reference elsewhere."""
    if force_reference or not (_on_tpu() or interpret):
        if k_scale is not None:
            k = k.astype(jnp.float32) * k_scale[..., None]
            v = v.astype(jnp.float32) * v_scale[..., None]
        return decode_attention_reference(
            q, k.astype(q.dtype), v.astype(q.dtype), pos
        )
    try:
        return _decode_pallas(q, k, v, pos, k_scale, v_scale, block_t,
                              interpret)
    except Exception:  # pragma: no cover - backend quirks
        logger.exception(
            "pallas decode attention failed; falling back to reference "
            "(q=%s k=%s)", q.shape, k.shape,
        )
        if k_scale is not None:
            k = k.astype(jnp.float32) * k_scale[..., None]
            v = v.astype(jnp.float32) * v_scale[..., None]
        return decode_attention_reference(
            q, k.astype(q.dtype), v.astype(q.dtype), pos
        )
