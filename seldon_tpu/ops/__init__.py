"""Hand-written TPU kernels (pallas) with XLA fallbacks.

The reference has no kernel layer at all (CPU serving only). Here the hot
ops get pallas implementations tuned to the TPU memory hierarchy
(HBM->VMEM->MXU, /opt/skills/guides/pallas_guide.md), each with a pure-jnp
fallback so the same code runs on the CPU test mesh.
"""

from seldon_tpu.ops.flash_attention import flash_attention

__all__ = ["flash_attention"]
