"""Flash attention (blockwise online-softmax) — pallas TPU kernel.

Why: XLA materializes the [B, H, S, S] score tensor for naive attention;
at S=8192 that's 2 GB per head-batch in f32 — HBM-bound and cache-hostile.
The flash kernel streams K/V blocks through VMEM with running max/sum
accumulators, never materializing scores, trading HBM traffic for VMEM
reuse (the standard FlashAttention-2 schedule laid onto the MXU).

Layout: q [BH, Sq, Dh], k/v [BH, Skv, Dh] — callers fold batch x heads
(GQA callers expand kv heads to q heads first; the repeat is free under
XLA's gather fusion and keeps the kernel simple). `causal=True` masks with
the global positions q_offset + i >= j.

`flash_attention` dispatches: pallas on TPU backends, jnp reference
elsewhere (CPU tests). Both paths are numerically compared in
tests/test_ops.py.
"""

from __future__ import annotations

import functools
import logging

import jax
import jax.numpy as jnp

logger = logging.getLogger(__name__)

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30


# ---------------------------------------------------------------------------
# Reference (XLA) implementation — also the CPU fallback
# ---------------------------------------------------------------------------


def attention_reference(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, causal: bool = True,
    q_offset: int = 0,
) -> jnp.ndarray:
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum(
        "bqd,bkd->bqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        Sq, Sk = q.shape[1], k.shape[1]
        qi = jnp.arange(Sq)[:, None] + q_offset
        kj = jnp.arange(Sk)[None, :]
        scores = jnp.where(qi >= kj, scores, NEG_INF)
    w = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bqk,bkd->bqd", w, v)


# ---------------------------------------------------------------------------
# Pallas kernel
# ---------------------------------------------------------------------------


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
                  causal, block_q, block_k, scale, q_offset):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)  # query block index
    kj = pl.program_id(2)  # kv block index

    @pl.when(kj == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Causal: a kv block strictly above the diagonal is fully masked — skip
    # its FLOPs entirely (≈2x saving over the full grid).
    if causal:
        visible = kj * block_k <= qi * block_q + (block_q - 1) + q_offset
    else:
        visible = True

    @pl.when(visible)
    def _accumulate():
        q = q_ref[0]  # [block_q, Dh]
        k = k_ref[0]  # [block_k, Dh]
        v = v_ref[0]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [block_q, block_k]

        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            ) + q_offset
            cols = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(rows >= cols, s, NEG_INF)

        m_prev = m_scr[:]  # [block_q, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)  # [block_q, block_k]
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_scr[:] + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = m_new
        l_scr[:] = l_new

    @pl.when(kj == pl.num_programs(2) - 1)
    def _finish():
        o_ref[0] = (acc_scr[:] / jnp.maximum(l_scr[:], 1e-30)).astype(
            o_ref.dtype
        )


def _flash_pallas(q, k, v, causal, q_offset, block_q, block_k, q_per_kv=1):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BH, Sq, Dh = q.shape
    Skv = k.shape[1]
    if k.shape[0] * q_per_kv != BH:
        raise ValueError(
            f"kv rows {k.shape[0]} x group {q_per_kv} != q rows {BH}"
        )
    block_q = min(block_q, Sq)
    block_k = min(block_k, Skv)
    if Sq % block_q or Skv % block_k:
        # A truncated grid would silently drop attention over the tail.
        raise ValueError(
            f"flash kernel needs divisible blocks: Sq={Sq}%{block_q}, "
            f"Skv={Skv}%{block_k}"
        )
    scale = Dh**-0.5

    grid = (BH, Sq // block_q, Skv // block_k)
    kernel = functools.partial(
        _flash_kernel,
        causal=causal,
        block_q=block_q,
        block_k=block_k,
        scale=scale,
        q_offset=q_offset,
    )
    # GQA: kv stays [B*Hkv, S, Dh]; the index_map folds each group of
    # q_per_kv query heads onto its shared kv row — no jnp.repeat, no
    # HBM duplication of K/V.
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (1, block_q, Dh), lambda b, i, j: (b, i, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, block_k, Dh),
                lambda b, i, j: (b // q_per_kv, j, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, block_k, Dh),
                lambda b, i, j: (b // q_per_kv, j, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, block_q, Dh), lambda b, i, j: (b, i, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, Dh), jnp.float32),
        ],
    )(q, k, v)


def _on_tpu() -> bool:
    try:
        platform = jax.devices()[0].platform
    except Exception:  # pragma: no cover
        return False
    return platform in ("tpu", "axon")


def _expand_kv(x: jnp.ndarray, q_per_kv: int) -> jnp.ndarray:
    if q_per_kv == 1:
        return x
    BHkv, S, Dh = x.shape
    return jnp.repeat(x, q_per_kv, axis=0)


def flash_attention(
    q: jnp.ndarray,  # [B*H, Sq, Dh]
    k: jnp.ndarray,  # [B*Hkv, Skv, Dh] (Hkv == H / q_per_kv)
    v: jnp.ndarray,
    causal: bool = True,
    q_offset: int = 0,
    q_per_kv: int = 1,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    force_pallas: bool = False,
    force_reference: bool = False,
) -> jnp.ndarray:
    """Blockwise attention; pallas on TPU, jnp reference elsewhere. GQA is
    native in the kernel (kv block index_map); only the reference fallback
    pays a repeat."""
    if force_reference:
        return attention_reference(
            q, _expand_kv(k, q_per_kv), _expand_kv(v, q_per_kv), causal,
            q_offset,
        )
    use_pallas = force_pallas or _on_tpu()
    divisible = (
        q.shape[1] % min(block_q, q.shape[1]) == 0
        and k.shape[1] % min(block_k, k.shape[1]) == 0
    )
    if use_pallas and not divisible:
        if force_pallas:
            raise ValueError(
                f"flash kernel needs divisible blocks: Sq={q.shape[1]}, "
                f"Skv={k.shape[1]}, blocks=({block_q},{block_k})"
            )
        logger.warning(
            "flash attention bypassed: Sq=%d/Skv=%d not divisible by blocks "
            "(%d,%d); running O(S^2) reference attention",
            q.shape[1], k.shape[1], block_q, block_k,
        )
    if use_pallas and divisible:
        try:
            return _flash_pallas(q, k, v, causal, q_offset, block_q, block_k,
                                 q_per_kv)
        except Exception:  # pragma: no cover - backend quirks
            if force_pallas:
                raise
            logger.exception(
                "pallas flash attention failed; falling back to the O(S^2) "
                "reference path (shapes q=%s k=%s)", q.shape, k.shape,
            )
    return attention_reference(
        q, _expand_kv(k, q_per_kv), _expand_kv(v, q_per_kv), causal, q_offset
    )
