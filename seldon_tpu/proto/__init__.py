"""Wire protocol for seldon_tpu.

`prediction_pb2` is generated from `prediction.proto` by `protoc --python_out`
(regenerate with `make proto` at the repo root). The gRPC service layer is
hand-written in `prediction_grpc.py` because the runtime image ships grpcio but
not grpcio-tools; it is also clearer than generated stubs.
"""

from seldon_tpu.proto import prediction_pb2

__all__ = ["prediction_pb2"]
