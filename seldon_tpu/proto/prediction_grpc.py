"""Hand-written gRPC bindings for the seldon_tpu prediction protocol.

Parity: the seven per-unit-type services of the reference protocol
(/root/reference/proto/prediction.proto:94-128 — Generic, Model, Router,
Transformer, OutputTransformer, Combiner, Seldon) plus a TPU-native `TextGen`
service for LLM serving (unary + server-streaming token generation).

Written against grpcio's generic-handler API instead of grpc_tools codegen.
Each service is described once in `_SERVICES`; client stub classes and server
registration helpers are derived from that table.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import grpc

from seldon_tpu.proto import prediction_pb2 as pb

_PKG = "seldon_tpu.protos"

_SM = pb.SeldonMessage
_FB = pb.Feedback
_SML = pb.SeldonMessageList
_GRQ = pb.GenerateRequest
_GRS = pb.GenerateResponse

# service -> method -> (request_cls, response_cls, arity)
# arity: "unary" or "stream" (server-streaming response).
_SERVICES: Dict[str, Dict[str, Tuple[Any, Any, str]]] = {
    "Generic": {
        "TransformInput": (_SM, _SM, "unary"),
        "TransformOutput": (_SM, _SM, "unary"),
        "Route": (_SM, _SM, "unary"),
        "Aggregate": (_SML, _SM, "unary"),
        "SendFeedback": (_FB, _SM, "unary"),
    },
    "Model": {
        "Predict": (_SM, _SM, "unary"),
        "SendFeedback": (_FB, _SM, "unary"),
    },
    "Router": {
        "Route": (_SM, _SM, "unary"),
        "SendFeedback": (_FB, _SM, "unary"),
    },
    "Transformer": {
        "TransformInput": (_SM, _SM, "unary"),
    },
    "OutputTransformer": {
        "TransformOutput": (_SM, _SM, "unary"),
    },
    "Combiner": {
        "Aggregate": (_SML, _SM, "unary"),
    },
    # External-facing orchestrator API.
    "Seldon": {
        "Predict": (_SM, _SM, "unary"),
        "SendFeedback": (_FB, _SM, "unary"),
    },
    # TPU-native LLM serving API (no reference equivalent; SURVEY.md §5.7).
    "TextGen": {
        "Generate": (_GRQ, _GRS, "unary"),
        "GenerateStream": (_GRQ, _GRS, "stream"),
    },
}


def method_path(service: str, method: str) -> str:
    return f"/{_PKG}.{service}/{method}"


# ---------------------------------------------------------------------------
# Server side
# ---------------------------------------------------------------------------


def generic_handler(service: str, impl: Any) -> grpc.GenericRpcHandler:
    """Build a GenericRpcHandler for `service` backed by `impl`.

    `impl` provides a method per RPC (e.g. `Predict(request, context)`); only
    the methods it actually defines are registered.
    """
    methods = _SERVICES[service]
    handlers: Dict[str, grpc.RpcMethodHandler] = {}
    for name, (req_cls, resp_cls, arity) in methods.items():
        fn = getattr(impl, name, None)
        if fn is None:
            continue
        if arity == "unary":
            handlers[name] = grpc.unary_unary_rpc_method_handler(
                fn,
                request_deserializer=req_cls.FromString,
                response_serializer=lambda m: m.SerializeToString(),
            )
        else:
            handlers[name] = grpc.unary_stream_rpc_method_handler(
                fn,
                request_deserializer=req_cls.FromString,
                response_serializer=lambda m: m.SerializeToString(),
            )
    return grpc.method_handlers_generic_handler(f"{_PKG}.{service}", handlers)


def add_servicer(server: grpc.Server, service: str, impl: Any) -> None:
    server.add_generic_rpc_handlers((generic_handler(service, impl),))


# ---------------------------------------------------------------------------
# Client side
# ---------------------------------------------------------------------------


class _Stub:
    """Base for derived stub classes: one callable per RPC method."""

    _service: str = ""

    def __init__(self, channel: grpc.Channel):
        for name, (req_cls, resp_cls, arity) in _SERVICES[self._service].items():
            path = method_path(self._service, name)
            if arity == "unary":
                rpc = channel.unary_unary(
                    path,
                    request_serializer=lambda m: m.SerializeToString(),
                    response_deserializer=resp_cls.FromString,
                )
            else:
                rpc = channel.unary_stream(
                    path,
                    request_serializer=lambda m: m.SerializeToString(),
                    response_deserializer=resp_cls.FromString,
                )
            setattr(self, name, rpc)


def _make_stub(service: str) -> type:
    return type(f"{service}Stub", (_Stub,), {"_service": service})


GenericStub = _make_stub("Generic")
ModelStub = _make_stub("Model")
RouterStub = _make_stub("Router")
TransformerStub = _make_stub("Transformer")
OutputTransformerStub = _make_stub("OutputTransformer")
CombinerStub = _make_stub("Combiner")
SeldonStub = _make_stub("Seldon")
TextGenStub = _make_stub("TextGen")

STUBS: Dict[str, Callable[[grpc.Channel], Any]] = {
    "Generic": GenericStub,
    "Model": ModelStub,
    "Router": RouterStub,
    "Transformer": TransformerStub,
    "OutputTransformer": OutputTransformerStub,
    "Combiner": CombinerStub,
    "Seldon": SeldonStub,
    "TextGen": TextGenStub,
}
