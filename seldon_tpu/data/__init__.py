"""Training data pipeline: token shards + native prefetching loader."""

from seldon_tpu.data.loader import (
    TokenDataLoader,
    write_token_shard,
)

__all__ = ["TokenDataLoader", "write_token_shard"]
