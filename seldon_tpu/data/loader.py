"""Token-shard data loader — native prefetch with a bit-identical fallback.

The reference has no training path; this build's sharded train step
(models/train.py) consumes [B, S+1] next-token windows. The native
loader (native/dataloader.cc) mmaps raw little-endian uint32 shards and
prefetches batches on a background C++ thread so the host never stalls a
TPU step on slicing; the numpy fallback implements the SAME splitmix64
window sampling, so streams are bit-identical across backends (tested)
and a run can move between machines with/without the native lib without
changing its data order.
"""

from __future__ import annotations

import ctypes
import logging
import os
from typing import Iterator, List, Optional, Sequence

import numpy as np

from seldon_tpu.native import load_native_lib

logger = logging.getLogger(__name__)

_lib: Optional[ctypes.CDLL] = None
_load_attempted = False


def _splitmix64(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 (must match dataloader.cc exactly)."""
    x = (x + np.uint64(0x9E3779B97F4A7C15)).astype(np.uint64)
    x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return x ^ (x >> np.uint64(31))


def _native() -> Optional[ctypes.CDLL]:
    global _lib, _load_attempted
    if _load_attempted:
        return _lib
    _load_attempted = True
    lib = load_native_lib("libseldon_dataloader.so")
    if lib is None:
        return None
    lib.seldon_loader_create.restype = ctypes.c_void_p
    lib.seldon_loader_create.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_uint64, ctypes.c_int64,
    ]
    lib.seldon_loader_next.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32),
    ]
    lib.seldon_loader_total_tokens.restype = ctypes.c_int64
    lib.seldon_loader_total_tokens.argtypes = [ctypes.c_void_p]
    lib.seldon_loader_destroy.argtypes = [ctypes.c_void_p]
    _lib = lib
    return _lib


def write_token_shard(path: str, tokens: Sequence[int]) -> str:
    """Raw little-endian uint32 token file — the shard format."""
    arr = np.asarray(tokens, dtype="<u4")
    arr.tofile(path)
    return path


class TokenDataLoader:
    """Iterator of [batch, seq_len+1] int32 windows over token shards.

    Sampling: row r of batch i starts at
    `splitmix64(seed ^ (i*B + r)) % (n_tokens - seq_len - 1)` —
    deterministic, backend-independent, and random-access (no epoch
    state to checkpoint; resume = remember the batch counter).
    """

    def __init__(self, paths: Sequence[str], batch_size: int, seq_len: int,
                 seed: int = 0, prefetch: int = 4,
                 force_fallback: bool = False):
        self.paths = [os.path.abspath(p) for p in paths]
        self.batch_size = int(batch_size)
        self.seq_len = int(seq_len)
        self.seed = np.uint64(seed)
        self._i = 0
        self._handle = None
        self._tokens: Optional[np.ndarray] = None

        lib = None if force_fallback else _native()
        if lib is not None:
            blob = b"".join(
                p.encode() + b"\x00" for p in self.paths
            ) + b"\x00"
            handle = lib.seldon_loader_create(
                blob, self.batch_size, self.seq_len,
                ctypes.c_uint64(seed), prefetch,
            )
            if handle:
                self._handle = ctypes.c_void_p(handle)
                self._lib = lib
                self.total_tokens = int(
                    lib.seldon_loader_total_tokens(self._handle)
                )
                return
            logger.warning("native loader rejected shards; numpy fallback")
        # Fallback: concatenate shards in memory (fine for tests/small
        # corpora; the native path is the production one).
        parts = [np.fromfile(p, dtype="<u4") for p in self.paths]
        self._tokens = np.concatenate(parts) if parts else np.zeros(0, "<u4")
        self.total_tokens = int(self._tokens.size)
        if self.total_tokens < self.seq_len + 2:
            raise ValueError(
                f"corpus of {self.total_tokens} tokens is smaller than one "
                f"window ({self.seq_len + 1})"
            )

    @property
    def native(self) -> bool:
        return self._handle is not None

    def __iter__(self) -> Iterator[np.ndarray]:
        return self

    def __next__(self) -> np.ndarray:
        out = np.empty((self.batch_size, self.seq_len + 1), np.int32)
        if self._handle is not None:
            self._lib.seldon_loader_next(
                self._handle,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            )
        else:
            B, S1 = self.batch_size, self.seq_len + 1
            idx = np.arange(B, dtype=np.uint64) + np.uint64(self._i * B)
            offs = _splitmix64(self.seed ^ idx) % np.uint64(
                self.total_tokens - S1
            )
            for r, off in enumerate(offs):
                out[r] = self._tokens[int(off): int(off) + S1]
        self._i += 1
        return out

    def close(self) -> None:
        if self._handle is not None:
            self._lib.seldon_loader_destroy(self._handle)
            self._handle = None

    def __del__(self):  # pragma: no cover - gc path
        try:
            self.close()
        except Exception:
            pass
