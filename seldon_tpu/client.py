"""SeldonClient — user-facing SDK.

Parity: reference SeldonClient (/root/reference/python/seldon_core/
seldon_client.py:111-592): predict / feedback / explain / microservice
calls over REST or gRPC against a deployed predictor (gateway) or a bare
microservice. TPU-native additions: `generate` / `generate_stream` for the
TextGen surface, binary-proto REST fast path, no oauth gateway (the
reference's seldon-oauth route is dead in modern deployments)."""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Iterator, Optional, Sequence

import numpy as np

from seldon_tpu.core import payloads
from seldon_tpu.proto import prediction_grpc
from seldon_tpu.proto import prediction_pb2 as pb

from seldon_tpu.core.http import PROTO_CONTENT_TYPE  # noqa: F401 (shared constant)


@dataclasses.dataclass
class ClientResponse:
    success: bool
    msg: Optional[pb.SeldonMessage] = None
    response: Optional[dict] = None
    error: str = ""

    @property
    def data(self):
        if self.msg is None:
            return None
        return payloads.get_data_from_message(self.msg)


class SeldonClient:
    def __init__(
        self,
        host: str = "localhost",
        port: int = 8000,
        grpc_port: int = 5001,
        transport: str = "grpc",  # "grpc" | "rest" | "rest-proto"
        timeout_s: float = 30.0,
        deployment: str = "",
        namespace: str = "default",
    ):
        self.host = host
        self.port = port
        self.grpc_port = grpc_port
        self.transport = transport
        self.timeout_s = timeout_s
        # Gateway routing identity: gRPC ingresses (ambassador Mapping,
        # reconciler.ambassador_annotations) route Seldon RPCs on the
        # `seldon`/`namespace` metadata — sent on every gRPC call when
        # `deployment` is set. REST uses gateway_prefix() paths instead.
        self.deployment = deployment
        self.namespace = namespace
        self._channel = None

    # --- plumbing -----------------------------------------------------------

    def _grpc_channel(self):
        import grpc

        if self._channel is None:
            self._channel = grpc.insecure_channel(
                f"{self.host}:{self.grpc_port}",
                options=[
                    ("grpc.max_send_message_length", 512 * 1024 * 1024),
                    ("grpc.max_receive_message_length", 512 * 1024 * 1024),
                ],
            )
        return self._channel

    def _rest(self, path: str, message, response_cls) -> ClientResponse:
        import urllib.error
        import urllib.request

        url = f"http://{self.host}:{self.port}{path}"
        if self.transport == "rest-proto":
            body = message.SerializeToString()
            headers = {"Content-Type": PROTO_CONTENT_TYPE}
        else:
            body = json.dumps(payloads.message_to_dict(message)).encode()
            headers = {"Content-Type": "application/json"}
        req = urllib.request.Request(url, data=body, headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                raw = resp.read()
                ctype = resp.headers.get("Content-Type", "")
        except urllib.error.HTTPError as e:
            return ClientResponse(False, error=f"{e.code}: {e.read().decode('utf-8', 'replace')}")
        except OSError as e:
            return ClientResponse(False, error=str(e))
        if ctype.startswith(PROTO_CONTENT_TYPE):
            msg = response_cls.FromString(raw)
            return ClientResponse(True, msg=msg)
        d = json.loads(raw)
        return ClientResponse(
            True, msg=payloads.dict_to_message(d, response_cls), response=d
        )

    def _grpc_call(self, service: str, method: str, message,
                   response_cls) -> ClientResponse:
        import grpc

        stub = prediction_grpc.STUBS[service](self._grpc_channel())
        metadata = (
            [("seldon", self.deployment), ("namespace", self.namespace)]
            if self.deployment else None
        )
        try:
            out = getattr(stub, method)(
                message, timeout=self.timeout_s, metadata=metadata
            )
        except grpc.RpcError as e:
            return ClientResponse(False, error=f"{e.code().name}: {e.details()}")
        return ClientResponse(True, msg=out)

    @staticmethod
    def _build_request(
        data: Any = None,
        payload_kind: str = "dense",
        names: Optional[Sequence[str]] = None,
        msg: Optional[pb.SeldonMessage] = None,
    ) -> pb.SeldonMessage:
        if msg is not None:
            return msg
        return payloads.build_message(np.asarray(data), names=names,
                                      kind=payload_kind)

    # --- API ----------------------------------------------------------------

    @staticmethod
    def gateway_prefix(namespace: str, deployment: str) -> str:
        """Ingress route prefix for a deployed SeldonDeployment — the path
        Ambassador/Istio rewrite onto the engine
        (reconciler.ambassador_annotations / build_istio_manifests;
        reference seldon_client gateway='ambassador')."""
        return f"/seldon/{namespace}/{deployment}"

    def predict(self, data=None, names=None, payload_kind="dense",
                msg=None, gateway_prefix: str = "") -> ClientResponse:
        """Predict via the engine's external API (Seldon.Predict /
        /api/v0.1/predictions). `gateway_prefix` routes through an
        ingress instead of a bare engine (REST only — gRPC ingresses
        route on the seldon/namespace metadata headers, which
        _grpc_call already sends)."""
        request = self._build_request(data, payload_kind, names, msg)
        if self.transport.startswith("rest"):
            path = f"{gateway_prefix.rstrip('/')}/api/v0.1/predictions"
            return self._rest(path, request, pb.SeldonMessage)
        return self._grpc_call("Seldon", "Predict", request, pb.SeldonMessage)

    def explain(self, data=None, names=None, payload_kind="dense",
                msg=None, explainer_host: str = "",
                gateway_prefix: str = "") -> ClientResponse:
        """Attributions from the predictor's `-explainer` deployment
        (reference seldon_client.explain). Address the explainer one of
        two ways: `explainer_host` (direct host:port of the explainer
        service) or `gateway_prefix` (ingress prefix, e.g.
        `/seldon/ns/name-explainer/pred` — the istio route rewrites it
        onto the explainer's /predict)."""
        request = self._build_request(data, payload_kind, names, msg)
        if explainer_host:
            import requests as _rq

            r = _rq.post(
                f"http://{explainer_host}/predict",
                json=payloads.message_to_dict(request),
                timeout=self.timeout_s,
            )
            r.raise_for_status()
            return ClientResponse(
                True, payloads.dict_to_message(r.json()), r.json()
            )
        if not gateway_prefix:
            raise ValueError(
                "explain() needs explainer_host (direct) or gateway_prefix "
                "(ingress route) — the engine itself serves no /explain"
            )
        return self._rest(
            f"{gateway_prefix.rstrip('/')}/predict", request,
            pb.SeldonMessage,
        )

    def feedback(self, request_msg=None, response_msg=None, reward=0.0,
                 truth=None, gateway_prefix: str = "") -> ClientResponse:
        fb = pb.Feedback(reward=float(reward))
        if request_msg is not None:
            fb.request.CopyFrom(request_msg)
        if response_msg is not None:
            fb.response.CopyFrom(response_msg)
        if truth is not None:
            fb.truth.CopyFrom(
                truth if isinstance(truth, pb.SeldonMessage)
                else payloads.build_message(np.asarray(truth))
            )
        if self.transport.startswith("rest"):
            path = f"{gateway_prefix.rstrip('/')}/api/v0.1/feedback"
            return self._rest(path, fb, pb.SeldonMessage)
        return self._grpc_call("Seldon", "SendFeedback", fb, pb.SeldonMessage)

    _MICROSERVICE_METHODS = {
        "predict": ("Model", "Predict"),
        "transform_input": ("Generic", "TransformInput"),
        "transform_output": ("Generic", "TransformOutput"),
        "route": ("Router", "Route"),
        "aggregate": ("Combiner", "Aggregate"),
        "send_feedback": ("Generic", "SendFeedback"),
    }

    def microservice(self, data=None, method="predict", names=None,
                     payload_kind="dense", msg=None,
                     msgs=None) -> ClientResponse:
        """Call a bare unit microservice (reference `microservice` gateway).

        `aggregate` takes `msgs` (list of SeldonMessage, or list of arrays);
        `send_feedback` takes `msg` as a pb.Feedback."""
        if method not in self._MICROSERVICE_METHODS:
            return ClientResponse(
                False,
                error=f"unknown method {method!r}; expected one of "
                f"{sorted(self._MICROSERVICE_METHODS)}",
            )
        if method == "aggregate":
            if msgs is None and data is not None:
                # predict-style convenience: data = list of per-child arrays.
                msgs = list(data)
            if not msgs:
                return ClientResponse(
                    False,
                    error="aggregate requires msgs=[SeldonMessage|array, ...]",
                )
            request = pb.SeldonMessageList()
            for m in msgs:
                if isinstance(m, pb.SeldonMessage):
                    request.seldonMessages.append(m)
                else:
                    request.seldonMessages.append(
                        payloads.build_message(np.asarray(m), kind=payload_kind)
                    )
        elif method == "send_feedback":
            if not isinstance(msg, pb.Feedback):
                return ClientResponse(
                    False, error="send_feedback requires msg=pb.Feedback"
                )
            request = msg
        else:
            request = self._build_request(data, payload_kind, names, msg)
        if self.transport.startswith("rest"):
            path = "/" + method.replace("_", "-")
            return self._rest(path, request, pb.SeldonMessage)
        return self._grpc_call(
            *self._MICROSERVICE_METHODS[method], request, pb.SeldonMessage
        )

    def generate(self, prompt: str = "", prompt_token_ids=None,
                 max_new_tokens: int = 16, temperature: float = 0.7,
                 top_k: int = 0, top_p: float = 1.0,
                 seed: int = 0) -> Dict[str, Any]:
        req = pb.GenerateRequest(
            prompt=prompt,
            prompt_token_ids=list(prompt_token_ids or []),
            max_new_tokens=max_new_tokens,
            temperature=temperature,
            top_k=top_k,
            top_p=top_p,
            seed=seed,
        )
        if self.transport.startswith("rest"):
            r = self._rest("/generate", req, pb.GenerateResponse)
            if not r.success:
                raise RuntimeError(r.error)
            out = r.msg
        else:
            import grpc

            stub = prediction_grpc.TextGenStub(self._grpc_channel())
            out = stub.Generate(req, timeout=self.timeout_s)
        return {
            "text": out.text,
            "token_ids": list(out.token_ids),
            "ttft_ms": out.ttft_ms,
            "total_ms": out.total_ms,
        }

    def generate_stream(self, prompt: str = "", max_new_tokens: int = 16,
                        **kw) -> Iterator[Dict[str, Any]]:
        req = pb.GenerateRequest(
            prompt=prompt, max_new_tokens=max_new_tokens,
            temperature=float(kw.get("temperature", 0.7)),
            top_k=int(kw.get("top_k", 0)), top_p=float(kw.get("top_p", 1.0)),
            seed=int(kw.get("seed", 0)),
        )
        stub = prediction_grpc.TextGenStub(self._grpc_channel())
        for chunk in stub.GenerateStream(req, timeout=self.timeout_s):
            yield {"text": chunk.text, "token_ids": list(chunk.token_ids),
                   "ttft_ms": chunk.ttft_ms}

    def close(self):
        if self._channel is not None:
            self._channel.close()
            self._channel = None
