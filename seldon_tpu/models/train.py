"""Sharded training step (fine-tuning path + the driver's multichip dryrun).

The reference is serving-only (SURVEY.md §2.9) — this is green-field
TPU-native capability: a pjit'd next-token cross-entropy step with optax,
params/grads/optimizer-state all sharded by the same GSPMD specs as
inference (dp batch, sp sequence, tp weights, ep experts), rematerialized
blocks (`jax.checkpoint`) to trade FLOPs for HBM. The init fn is jitted
with explicit out-shardings so full-size params materialize directly
sharded — they never exist whole on one host.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.tree_util import tree_flatten_with_path, tree_unflatten

from seldon_tpu.models import transformer
from seldon_tpu.models.config import ModelConfig
from seldon_tpu.parallel import sharding as shd


class TrainState(NamedTuple):
    step: jnp.ndarray
    params: Any
    opt_state: Any


def _decay_mask(params):
    """Decay matrices only — norm gains are [L, D] in the layer-stacked
    layout, so an ndim test would wrongly decay them; go by name."""
    leaves, treedef = tree_flatten_with_path(params)
    out = [
        leaf.ndim >= 2 and not any("norm" in str(k) for k in path)
        for path, leaf in leaves
    ]
    return tree_unflatten(treedef, out)


def make_optimizer(lr: float = 3e-4, weight_decay: float = 0.1,
                   warmup: int = 100, total_steps: int = 10000):
    schedule = optax.warmup_cosine_decay_schedule(
        0.0, lr, warmup, max(total_steps, warmup + 1), end_value=lr * 0.1
    )
    return optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.adamw(schedule, b1=0.9, b2=0.95, weight_decay=weight_decay,
                    mask=_decay_mask),
    )


MOE_AUX_WEIGHT = 0.01


def loss_fn(params, tokens, loss_mask, cfg: ModelConfig, act_spec=None,
            forward_fn=None, ring_mesh=None):
    """Next-token CE (+ router load-balance aux for MoE configs).
    tokens [B,S]; loss_mask [B,S] (0 on pad/prompt).
    forward_fn overrides the dense forward (pipeline-parallel path);
    ring_mesh activates ring attention (attn_impl == "ring")."""
    if forward_fn is not None:
        logits, aux = forward_fn(params, tokens)
    else:
        logits, aux = transformer.forward(params, tokens, cfg,
                                          act_spec=act_spec,
                                          remat=True, return_aux=True,
                                          ring_mesh=ring_mesh)
    targets = tokens[:, 1:]
    lp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(lp, targets[..., None], axis=-1)[..., 0]
    mask = loss_mask[:, 1:].astype(jnp.float32)
    denom = jnp.maximum(mask.sum(), 1.0)
    ce = (nll * mask).sum() / denom
    if cfg.n_experts:
        ce = ce + MOE_AUX_WEIGHT * aux["moe_lb_loss"]
    return ce


def _shardings_like(shape_tree, params_ns_tree, repl: NamedSharding):
    """Sharding tree for an arbitrary state pytree: any leaf whose key-path
    SUFFIX matches a param leaf (optax moments mirror the param tree
    structure) inherits that param's sharding; everything else replicates."""
    pleaves, _ = tree_flatten_with_path(
        params_ns_tree, is_leaf=lambda x: isinstance(x, NamedSharding)
    )
    pmap = {tuple(str(k) for k in path): ns for path, ns in pleaves}

    leaves, treedef = tree_flatten_with_path(shape_tree)
    out = []
    for path, leaf in leaves:
        keys = tuple(str(k) for k in path)
        ns = repl
        for i in range(len(keys)):
            hit = pmap.get(keys[i:])
            if hit is not None:
                ns = hit
                break
        out.append(ns)
    return tree_unflatten(treedef, out)


def make_sharded_train_step(mesh: Mesh, cfg: ModelConfig, optimizer,
                            seq_sharded: bool = True,
                            n_microbatches: int = 4):
    """Returns (init_fn, step_fn).

    init_fn(key) -> TrainState, materialized sharded on `mesh`.
    step_fn(state, tokens, loss_mask) -> (state, metrics); donates state.

    If the mesh has a pp axis > 1, the layer stack is pipeline-parallel:
    weights shard their layer axis over 'pp' and the forward runs the
    GPipe microbatch schedule (parallel/pipeline.py); dp/sp/tp/ep compose
    unchanged.
    """
    cfg = cfg.validate()
    pp = mesh.shape.get("pp", 1)
    forward_fn = None
    if pp > 1:
        from seldon_tpu.parallel import pipeline

        forward_fn = pipeline.make_pipeline_forward(
            mesh, cfg, n_microbatches=n_microbatches, remat=True
        )
        param_specs = pipeline.pp_param_pspecs(cfg)
    else:
        param_specs = shd.param_pspecs(cfg)
    act_spec = NamedSharding(mesh, shd.activation_pspec(seq_sharded))
    params_ns = shd.named_shardings(mesh, param_specs)
    repl = NamedSharding(mesh, P())
    batch_ns = NamedSharding(mesh, shd.batch_pspec(seq_sharded))

    def _init(key):
        params = transformer.init_params(cfg, key)
        return TrainState(
            jnp.zeros((), jnp.int32), params, optimizer.init(params)
        )

    state_shape = jax.eval_shape(_init, jax.random.key(0))
    state_ns = _shardings_like(state_shape, params_ns, repl)

    init_fn = jax.jit(_init, out_shardings=state_ns)

    ring_mesh = (
        mesh if (cfg.attn_impl == "ring" and mesh.shape.get("sp", 1) > 1)
        else None
    )

    def _step(state: TrainState, tokens, loss_mask):
        loss, grads = jax.value_and_grad(loss_fn)(
            state.params, tokens, loss_mask, cfg,
            None if forward_fn is not None else act_spec, forward_fn,
            ring_mesh,
        )
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params
        )
        params = optax.apply_updates(state.params, updates)
        metrics = {"loss": loss, "grad_norm": optax.global_norm(grads)}
        return TrainState(state.step + 1, params, opt_state), metrics

    step_fn = jax.jit(
        _step,
        in_shardings=(state_ns, batch_ns, batch_ns),
        out_shardings=(state_ns, repl),
        donate_argnums=(0,),
    )
    return init_fn, step_fn
