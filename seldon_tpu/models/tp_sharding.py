"""graftmesh sharding tables: EXACT tensor parallelism over the 'tp' axis.

The serving engine's contract is bit-identical greedy output in every
configuration pair it ships (paged vs dense, spec on/off, ragged vs
bucketed) — so the TP scheme must be exact too, not Megatron-exact-ish.
Classic Megatron TP partitions the CONTRACTION dimension of the second
matmul in each pair (wo, w_down) and psums partial products; float
addition is not associative, so the reduction order differs from tp=1
and a greedy argmax can flip on near-ties. That would break
`make mesh-audit`'s parity gate, the bench BENCH_MESH assert, and the
whole bit-exact testing discipline the repo leans on.

Instead, graftmesh shards only OUTPUT dimensions and never a
contraction:

 * ``wq`` / ``wk`` / ``wv`` are partitioned on their head output axis
   ('tp' on the last dim): every device computes the FULL ``d_model``
   contraction for its own disjoint slice of heads — K-reduction order
   per output element is identical to tp=1.
 * attention runs per-KV-head with heads sharded on 'tp' (GQA groups
   stay device-local since tp | n_kv_heads); softmax reduces over the
   TOKEN axis, which is never sharded.
 * the attention output is ALL-GATHERED (a pure data movement — exact
   in any dtype) and ``wo`` is kept REPLICATED: the wo matmul runs
   redundantly on every device, bit-identically to tp=1.
 * ``w_gate`` / ``w_up`` shard on the ``d_ff`` output axis; the SwiGLU
   hidden is all-gathered and ``w_down`` (the contraction over d_ff)
   is replicated-redundant, same argument.
 * embeddings / lm_head / norms are replicated; logits, samples and
   every host-visible output are therefore replicated and identical
   across the TP group by construction.
 * the KV cache (dense slab or paged pool) shards on its ``Hkv`` axis;
   block tables stay host-side int32 and replicated.

W8A8 stays exact for the same reason: the per-token activation scale is
a max over the (unsharded) feature axis, int8 x int8 -> int32
accumulation is exact integer math, and the sharded weights' per-output
-channel scales ride with their output slice.

The price is redundant wo/w_down/lm_head compute and their full weight
replica per device — the Nitsum-style tradeoff for small TP groups,
where the sharded 2/3 of the matmul stack (qkv + gate/up) dominates.
The cost model prices exactly this split (cost_model.py, tp= params).

MoE blocks are deliberately NOT sharded on 'tp' (their expert_out
matmul contracts d_ff, which would need a psum): expert weights stay
replicated and MoE configs serve tp>1 with attention-only sharding.

Divisibility contract (``validate``): tp | n_kv_heads (and hence
tp | n_heads via GQA) and tp | d_ff.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from seldon_tpu.parallel.mesh import AXES

TP_AXIS = AXES[-1]  # "tp" — the innermost axis of the mesh vocabulary

# Block weights whose OUTPUT dim shards on 'tp' (dense MLP only; MoE
# weights replicate — see module docstring).
_SHARDED_BLOCK_WEIGHTS = ("wq", "wk", "wv", "w_gate", "w_up")


def validate(cfg, tp: int) -> None:
    """Raise ValueError unless the config admits an exact tp-way split."""
    tp = int(tp)
    if tp <= 1:
        return
    if cfg.n_kv_heads % tp:
        raise ValueError(
            f"tp={tp} must divide n_kv_heads={cfg.n_kv_heads} "
            "(KV heads shard on 'tp')")
    if cfg.n_heads % tp:
        raise ValueError(
            f"tp={tp} must divide n_heads={cfg.n_heads} "
            "(query heads shard on 'tp')")
    if cfg.d_ff % tp:
        raise ValueError(
            f"tp={tp} must divide d_ff={cfg.d_ff} "
            "(the SwiGLU hidden shards on 'tp')")


def mesh_tp(mesh: Optional[Mesh]) -> int:
    """Size of the mesh's 'tp' axis (1 when absent/None)."""
    if mesh is None:
        return 1
    return int(dict(zip(mesh.axis_names, mesh.devices.shape)).get(TP_AXIS, 1))


# -- partition-spec tables ---------------------------------------------------


def _block_spec(name: str, ndim: int, moe: bool) -> P:
    """Spec for one entry of params["blocks"]. Quantization scales
    (``<w>_scale``, shaped like the weight with the contraction dim
    collapsed to 1) shard exactly like their weight: the sharded dim is
    the LAST dim for weight and scale alike."""
    base = name[:-6] if name.endswith("_scale") else name
    if not moe and base in _SHARDED_BLOCK_WEIGHTS:
        return P(*([None] * (ndim - 1) + [TP_AXIS]))
    return P()


def param_pspecs(cfg, params: Dict[str, Any]) -> Dict[str, Any]:
    """Exact-TP PartitionSpec tree matching ``params``' structure.

    Everything outside the blocks (embed, final_norm, lm_head, their
    scales) replicates; inside the blocks only the qkv / gate / up
    projections (and their scales) shard, on their output dim.
    """
    moe = bool(getattr(cfg, "n_experts", 0))
    out: Dict[str, Any] = {}
    for name, leaf in params.items():
        if name == "blocks":
            out[name] = {
                bn: _block_spec(bn, np.ndim(bl), moe)
                for bn, bl in leaf.items()
            }
        else:
            out[name] = P()
    return out


def state_leaf_spec(leaf) -> P:
    """Spec for one engine-state leaf, by rank: 5D KV slabs/pools
    [L, B|NB, Hkv, T|block, Dh] shard Hkv on 'tp'; their 4D int8 scale
    twins [L, B|NB, Hkv, T|block] likewise; everything else (the [B]
    per-slot scalars) replicates."""
    nd = np.ndim(leaf)
    if nd == 5:
        return P(None, None, TP_AXIS, None, None)
    if nd == 4:
        return P(None, None, TP_AXIS, None)
    return P()


def state_pspecs(state) -> Any:
    return jax.tree_util.tree_map(state_leaf_spec, state)


def shard_params(mesh: Mesh, cfg, params: Dict[str, Any]) -> Dict[str, Any]:
    """Commit a params tree onto the mesh under the exact-TP table."""
    specs = param_pspecs(cfg, params)
    return jax.device_put(
        params,
        jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), specs,
            is_leaf=lambda x: isinstance(x, P)),
    )


def shard_state(mesh: Mesh, state) -> Any:
    """Commit an engine state tree (cache + per-slot scalars) onto the
    mesh: KV leaves shard on Hkv, scalars replicate."""
    return jax.device_put(
        state,
        jax.tree_util.tree_map(
            lambda leaf: NamedSharding(mesh, state_leaf_spec(leaf)), state),
    )


# -- in-jit constraint hints -------------------------------------------------


@dataclass(frozen=True)
class TpHints:
    """Sharding-constraint helper threaded through the transformer's
    serving paths (``tp=`` kwarg). Carries the mesh so constraints can
    be NamedSharding-pinned from inside jit without global mesh context.

    The constraint points are the whole exactness argument in four
    verbs: ``heads``/``flat`` keep the sharded two-thirds of each block
    sharded (so GSPMD cannot back-propagate replication into the qkv /
    gate / up matmuls), ``gather`` inserts the exact bf16 all-gather in
    front of the replicated wo / w_down contractions, and
    ``constrain_state`` pins the donated cache's output sharding so the
    jit cache key never drifts (a drifted donation sharding would
    retrace on the next dispatch — the compile ledger's zero-live-
    retrace gate would catch it, loudly).
    """

    mesh: Mesh
    tp: int

    def _pin(self, x, spec: P):
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(self.mesh, spec))

    def heads(self, x):
        """[B, S, H|Hkv, Dh] with the head axis sharded."""
        return self._pin(x, P(None, None, TP_AXIS, None))

    def flat(self, x):
        """[B, S, H*Dh] or [B, S, F]: head-major flattened / hidden
        features sharded contiguously on the last axis."""
        return self._pin(x, P(None, None, TP_AXIS))

    def gather(self, x):
        """Exact all-gather to replicated — pure data movement, placed
        immediately before a replicated-weight contraction."""
        return self._pin(x, P())

    def constrain_state(self, state):
        """Pin every state leaf to its committed sharding (rank rule of
        state_leaf_spec) at the end of a donating impl."""
        return jax.tree_util.tree_map(
            lambda leaf: self._pin(leaf, state_leaf_spec(leaf)), state)


def hints(mesh: Optional[Mesh], tp: int) -> Optional[TpHints]:
    """TpHints iff tp > 1 (the EngineConfig.tp gate); None otherwise —
    callers keep a None attribute and the unconstrained trace, so the
    tp=1 path stays byte-identical to a build without graftmesh."""
    tp = int(tp)
    if tp <= 1:
        return None
    if mesh is None:
        raise ValueError("EngineConfig.tp > 1 requires a mesh with a "
                         "'tp' axis (servers/mesh_engine.build_tp_mesh)")
    have = mesh_tp(mesh)
    if have != tp:
        raise ValueError(
            f"EngineConfig.tp={tp} but the mesh carries a {have}-way "
            f"'{TP_AXIS}' axis")
    return TpHints(mesh=mesh, tp=tp)
