"""Token sampling — temperature / top-k / top-p, fully jittable.

All branching is value-level (jnp.where), never Python-level, so one
compiled sampler serves every request config; per-request knobs arrive as
arrays and sampling stays inside the jitted decode loop (no host sync per
token — the reference has no generation path at all, SURVEY.md §5.7).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Host-side request knobs; converted to per-row arrays by the server."""

    temperature: float = 0.7
    top_k: int = 0  # 0 = disabled
    top_p: float = 1.0  # 1.0 = disabled
    max_new_tokens: int = 128
    seed: int = 0
    # Request TTL in milliseconds, measured from submit. 0 = no per-
    # request deadline (EngineConfig.default_deadline_ms still applies).
    # Expired requests are shed from the queue or finalized early at the
    # next scheduler boundary (servers/engine.py request lifecycle).
    deadline_ms: int = 0
    # W3C traceparent adopting the caller's trace: engine lifecycle spans
    # parent under it so one trace id covers orchestrator -> engine ->
    # streamed tokens. "" = no incoming context (the engine roots its own
    # trace when tracing is on). Rides meta.tags["traceparent"] over the
    # proto transports, same route as deadline_ms.
    traceparent: str = ""


def _mask_top_k_top_p(
    scaled: jnp.ndarray,  # [B, V] temperature-scaled logits
    top_k: jnp.ndarray,  # [B] int32; 0 => off
    top_p: jnp.ndarray,  # [B] f32; 1.0 => off
) -> jnp.ndarray:
    """Apply top-k + top-p (nucleus) masks. O(V log V) per row (one sort) —
    callers skip this entirely via lax.cond when every row has both off."""
    B, V = scaled.shape
    # top-k: mask everything below the k-th largest logit per row.
    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]
    k = jnp.clip(jnp.where(top_k <= 0, V, top_k), 1, V)
    kth = jnp.take_along_axis(sorted_desc, (k - 1)[:, None], axis=-1)
    masked = jnp.where(scaled < kth, -jnp.inf, scaled)

    # top-p: keep the smallest prefix of the sorted distribution whose
    # cumulative probability covers p; always keep the argmax (so top_p<=0
    # degrades to greedy rather than an all-masked row). The post-top-k
    # sorted view is the first sort with ranks >= k masked — no second
    # O(V log V) sort.
    sorted_logits = jnp.where(
        jnp.arange(V)[None, :] >= k[:, None], -jnp.inf, sorted_desc
    )
    probs_sorted = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs_sorted, axis=-1)
    inside = cum - probs_sorted < jnp.maximum(top_p, 1e-9)[:, None]
    cut = jnp.where(inside, sorted_logits, jnp.inf)
    min_keep = jnp.min(cut, axis=-1, keepdims=True)
    return jnp.where(masked < min_keep, -jnp.inf, masked)


def sample_per_row(
    logits: jnp.ndarray,  # [B, V]
    keys: jax.Array,  # [B] PRNG keys (one per row)
    temperature: jnp.ndarray,  # [B] f32; 0 => greedy
    top_k: jnp.ndarray,  # [B] int32; 0 => off
    top_p: jnp.ndarray,  # [B] f32; 1.0 => off
) -> jnp.ndarray:
    """Row-independent sampling: each row draws from its own key, so a
    request's tokens are reproducible from (seed, position) no matter
    what other requests share the batch (continuous-batching
    requirement). The top-k/top-p sort is behind a batch-level lax.cond
    and costs nothing when no active row uses them (the decode-loop
    common case).

    Gumbel-argmax over inverse-CDF: argmax(logits/T + g) IS a categorical
    sample, in ONE pass over the logits — the CDF route (softmax + cumsum
    + compare) is 4+ passes over the [B, V] f32 tensor and measured ~0.5
    ms/step at [160, 32k] on v5e vs ~0.15 ms for the Gumbel ALU. Masked
    entries stay -inf through the addition, so the same argmax serves the
    top-k/top-p branch."""
    B, V = logits.shape
    greedy = jnp.argmax(logits, axis=-1)

    temp = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = logits / temp
    need_mask = jnp.any(top_k > 0) | jnp.any(top_p < 1.0)
    scaled = jax.lax.cond(
        need_mask,
        lambda s: _mask_top_k_top_p(s, top_k, top_p),
        lambda s: s,
        scaled,
    )

    gumbel = jax.vmap(
        lambda k: jax.random.gumbel(k, (V,), dtype=jnp.float32)
    )(keys)
    sampled = jnp.argmax(scaled + gumbel, axis=-1)
    return jnp.where(temperature <= 0, greedy, sampled).astype(jnp.int32)


def sample(
    logits: jnp.ndarray,  # [B, V] f32
    key: jax.Array,
    temperature: jnp.ndarray,  # [B] f32; 0 => greedy
    top_k: jnp.ndarray,  # [B] int32; 0 => off
    top_p: jnp.ndarray,  # [B] f32; 1.0 => off
) -> jnp.ndarray:
    """Batch sampling from one key (whole-batch generate path)."""
    keys = jax.random.split(key, logits.shape[0])
    return sample_per_row(keys=keys, logits=logits, temperature=temperature,
                          top_k=top_k, top_p=top_p)
