"""Token sampling — temperature / top-k / top-p, fully jittable.

All branching is value-level (jnp.where), never Python-level, so one
compiled sampler serves every request config; per-request knobs arrive as
arrays and sampling stays inside the jitted decode loop (no host sync per
token — the reference has no generation path at all, SURVEY.md §5.7).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Host-side request knobs; converted to per-row arrays by the server."""

    temperature: float = 0.7
    top_k: int = 0  # 0 = disabled
    top_p: float = 1.0  # 1.0 = disabled
    max_new_tokens: int = 128
    seed: int = 0


def sample(
    logits: jnp.ndarray,  # [B, V] f32
    key: jax.Array,
    temperature: jnp.ndarray,  # [B] f32; 0 => greedy
    top_k: jnp.ndarray,  # [B] int32; 0 => off
    top_p: jnp.ndarray,  # [B] f32; 1.0 => off
) -> jnp.ndarray:
    """Returns sampled token ids [B]."""
    B, V = logits.shape
    greedy = jnp.argmax(logits, axis=-1)

    temp = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = logits / temp

    # top-k: mask everything below the k-th largest logit per row.
    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]
    k = jnp.clip(jnp.where(top_k <= 0, V, top_k), 1, V)
    kth = jnp.take_along_axis(sorted_desc, (k - 1)[:, None], axis=-1)
    scaled = jnp.where(scaled < kth, -jnp.inf, scaled)

    # top-p (nucleus): keep the smallest prefix of the sorted distribution
    # whose cumulative probability covers p; always keep the argmax (so
    # top_p<=0 degrades to greedy rather than an all-masked row).
    # The post-top-k sorted view is the first sort with ranks >= k masked —
    # no second O(V log V) sort in the per-token hot loop.
    sorted_logits = jnp.where(
        jnp.arange(V)[None, :] >= k[:, None], -jnp.inf, sorted_desc
    )
    probs_sorted = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs_sorted, axis=-1)
    inside = cum - probs_sorted < jnp.maximum(top_p, 1e-9)[:, None]
    cut = jnp.where(inside, sorted_logits, jnp.inf)
    min_keep = jnp.min(cut, axis=-1, keepdims=True)
    scaled = jnp.where(scaled < min_keep, -jnp.inf, scaled)

    sampled = jax.random.categorical(key, scaled, axis=-1)
    return jnp.where(temperature <= 0, greedy, sampled).astype(jnp.int32)


def sample_per_row(
    logits: jnp.ndarray,  # [B, V]
    keys: jax.Array,  # [B] PRNG keys (one per row)
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
) -> jnp.ndarray:
    """Row-independent sampling: each row draws from its own key, so a
    request's tokens are reproducible from (seed, position) no matter what
    other requests share the batch (continuous-batching requirement)."""

    def one(l, k, t, tk, tp):
        return sample(l[None], k, t[None], tk[None], tp[None])[0]

    return jax.vmap(one)(logits, keys, temperature, top_k, top_p)
