"""Weight-only int8 quantization for serving.

Why: decode is HBM-bound on weight reads once enough slots amortize the
cache; int8 weights halve that traffic (bench-1b: 2.2 GB -> 1.1 GB per
step) and halve the footprint, which is what lets llama3-8b-class models
(16 GB bf16) serve from one 16 GB v5e chip at all.

Scheme: symmetric per-OUTPUT-CHANNEL scales (the einsum's last axis), so
`w ≈ w_q.astype(bf16) * scale[None, :]`. XLA fuses the convert+multiply
into the matmul's operand read — the HBM side stays 1 byte/element; no
custom kernel needed. Activations, norms, router logits stay bf16/f32.

The transformer consumes quantized leaves transparently: for each
quantized weight `name`, the params tree carries `name` (int8) plus
`name_scale` (f32, broadcastable), and `models.transformer._w` dequants
at use. `quantize_params` works on any already-built tree (random init,
orbax, HF loader), so one code path covers every loader.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

# Block leaves quantized per output channel (last axis). Norm gains and
# the MoE router stay full precision.
_BLOCK_WEIGHTS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def _quantize_leaf(w: jnp.ndarray):
    """-> (int8 w_q, f32 scale broadcastable against w)."""
    wf = w.astype(jnp.float32)
    scale = jnp.max(jnp.abs(wf), axis=-2, keepdims=True) / 127.0
    scale = jnp.maximum(scale, 1e-12)
    w_q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return w_q, scale


def quantize_params(params: Dict[str, Any]) -> Dict[str, Any]:
    """int8-quantize the matmul weights of a transformer param tree
    (blocks + embed + lm_head); returns a NEW tree with `*_scale` leaves
    alongside each quantized weight. Idempotent: re-quantizing an int8
    tree would compute scale=max(|int8|)/127~=1 and DROP the real
    per-channel scales — silently garbage weights."""
    if is_quantized(params):
        return params
    out: Dict[str, Any] = {}
    blocks = dict(params["blocks"])
    for name in _BLOCK_WEIGHTS:
        if name not in blocks:
            continue
        w_q, scale = _quantize_leaf(blocks[name])
        blocks[name] = w_q
        blocks[f"{name}_scale"] = scale
    out["blocks"] = blocks

    # Embed rows are gathered then matmul'd (tied logits): per-COLUMN
    # scale over d_model keeps both uses a plain broadcast multiply.
    embed_q, embed_scale = _quantize_leaf(params["embed"])
    out["embed"] = embed_q
    out["embed_scale"] = embed_scale
    out["final_norm"] = params["final_norm"]
    if "lm_head" in params:
        lm_q, lm_scale = _quantize_leaf(params["lm_head"])
        out["lm_head"] = lm_q
        out["lm_head_scale"] = lm_scale
    return out


def is_quantized(params: Dict[str, Any]) -> bool:
    return "embed_scale" in params


def dequant(w: jnp.ndarray, scale, dtype) -> jnp.ndarray:
    """Dequantize at use; fuses into the consuming matmul under XLA."""
    if scale is None:
        return w if w.dtype == dtype else w.astype(dtype)
    return w.astype(dtype) * scale.astype(dtype)
