"""Weight-only int8 quantization for serving.

Why: decode is HBM-bound on weight reads once enough slots amortize the
cache; int8 weights halve that traffic (bench-1b: 2.2 GB -> 1.1 GB per
step) and halve the footprint, which is what lets llama3-8b-class models
(16 GB bf16) serve from one 16 GB v5e chip at all.

Scheme: symmetric per-OUTPUT-CHANNEL scales (the einsum's last axis), so
`w ≈ w_q.astype(bf16) * scale[None, :]`. XLA fuses the convert+multiply
into the matmul's operand read — the HBM side stays 1 byte/element; no
custom kernel needed. Activations, norms, router logits stay bf16/f32.

The transformer consumes quantized leaves transparently: for each
quantized weight `name`, the params tree carries `name` (int8) plus
`name_scale` (f32, broadcastable), and `models.transformer._w` dequants
at use. `quantize_params` works on any already-built tree (random init,
orbax, HF loader), so one code path covers every loader.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

# Block leaves quantized per output channel (last axis). Norm gains and
# the MoE router stay full precision.
_BLOCK_WEIGHTS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def _quantize_leaf(w: jnp.ndarray):
    """-> (int8 w_q, f32 scale broadcastable against w)."""
    wf = w.astype(jnp.float32)
    # graftlint: allow(num-barrier) load-time weight quantization: runs
    # once on the host outside every serving jit, so there is no second
    # compilation for the scale to diverge against; the SERVING-side
    # scales (_quantize_act/_quantize_kv) carry the barrier.
    scale = jnp.max(jnp.abs(wf), axis=-2, keepdims=True) / 127.0
    scale = jnp.maximum(scale, 1e-12)
    w_q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return w_q, scale


def quantize_params(params: Dict[str, Any]) -> Dict[str, Any]:
    """int8-quantize the matmul weights of a transformer param tree
    (blocks + embed + lm_head); returns a NEW tree with `*_scale` leaves
    alongside each quantized weight. Idempotent: re-quantizing an int8
    tree would compute scale=max(|int8|)/127~=1 and DROP the real
    per-channel scales — silently garbage weights."""
    if is_quantized(params):
        return params
    out: Dict[str, Any] = {}
    blocks = dict(params["blocks"])
    for name in _BLOCK_WEIGHTS:
        if name not in blocks:
            continue
        w_q, scale = _quantize_leaf(blocks[name])
        blocks[name] = w_q
        blocks[f"{name}_scale"] = scale
    out["blocks"] = blocks

    # Embed rows are gathered then matmul'd (tied logits): per-COLUMN
    # scale over d_model keeps both uses a plain broadcast multiply.
    embed_q, embed_scale = _quantize_leaf(params["embed"])
    out["embed"] = embed_q
    out["embed_scale"] = embed_scale
    out["final_norm"] = params["final_norm"]
    if "lm_head" in params:
        lm_q, lm_scale = _quantize_leaf(params["lm_head"])
        out["lm_head"] = lm_q
        out["lm_head_scale"] = lm_scale
    return out


def is_quantized(params: Dict[str, Any]) -> bool:
    return "embed_scale" in params


def init_params_int8(cfg, key: "jax.Array") -> Dict[str, Any]:
    """Random-init an ALREADY-int8 param tree without ever materializing
    the bf16 model: each stacked block leaf is filled layer-slice by
    layer-slice with a jitted generate+quantize into donated buffers, so
    peak HBM is the int8 tree plus ONE layer's f32 slice. An 8 GB-int8
    llama3-8b geometry (16 GB as bf16) inits on one 16 GB chip this way;
    `init_params(cfg) -> quantize_params` needs ~24 GB transient.

    Same quantization scheme as quantize_params (symmetric per-output-
    channel); the random draw differs from init_params' (different key
    walk) — irrelevant for random-init benches/tests, and real serving
    loads checkpoints through hf_loader/orbax anyway."""
    import functools

    cfg = cfg.validate()
    L, D, F, V = cfg.n_layers, cfg.d_model, cfg.d_ff, cfg.vocab_size
    H, Hkv, Dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    out_scale = 0.02 / (2 * L) ** 0.5

    @functools.partial(jax.jit, static_argnums=(2,), donate_argnums=(3, 4))
    def fill_layer(key, li, scale_shape, wq, wsc):
        """Generate one layer's slice f32 -> quantize -> write in place.
        scale_shape: (shape, init_scale) static tuple.

        Donation contract: wq/wsc are donated IN and rebound in the
        same statement at every call site (the idiomatic donation
        chain), so the buffers update in place instead of doubling the
        tree's peak HBM. Certified by graftlint's use-after-donate
        pass — any later read of the old binding is a lint finding."""
        shape, sc = scale_shape
        w = jax.random.normal(key, shape, jnp.float32) * sc
        q, s = _quantize_leaf(w)
        return wq.at[li].set(q), wsc.at[li].set(s)

    def make_stacked(key, shape, sc):
        wq = jnp.zeros((L,) + shape, jnp.int8)
        # Per-layer scale shape mirrors _quantize_leaf's keepdims on the
        # -2 axis: (D,F)->(1,F); MoE (E,D,F)->(E,1,F).
        wsc = jnp.zeros((L,) + shape[:-2] + (1, shape[-1]), jnp.float32)
        for li in range(L):
            key, sub = jax.random.split(key)
            wq, wsc = fill_layer(sub, li, (shape, sc), wq, wsc)
        return key, wq, wsc

    def norm(*shape):
        return jnp.ones(shape, dtype=jnp.float32)

    blocks: Dict[str, Any] = {
        "attn_norm": norm(L, D),
        "mlp_norm": norm(L, D),
    }
    leaf_shapes = [
        ("wq", (D, H * Dh), 0.02),
        ("wk", (D, Hkv * Dh), 0.02),
        ("wv", (D, Hkv * Dh), 0.02),
        ("wo", (H * Dh, D), out_scale),
        ("w_gate", (D, F), 0.02),
        ("w_up", (D, F), 0.02),
        ("w_down", (F, D), out_scale),
    ]
    if cfg.n_experts:
        E = cfg.n_experts
        key, kr = jax.random.split(key)
        blocks["router"] = (
            jax.random.normal(kr, (L, D, E), jnp.float32) * 0.02
        )
        leaf_shapes = leaf_shapes[:4] + [
            ("w_gate", (E, D, F), 0.02),
            ("w_up", (E, D, F), 0.02),
            ("w_down", (E, F, D), out_scale),
        ]
    for name, shape, sc in leaf_shapes:
        key, wq, wsc = make_stacked(key, shape, sc)
        blocks[name] = wq
        blocks[f"{name}_scale"] = wsc

    @functools.partial(jax.jit, static_argnums=(1, 2))
    def make_flat(key, shape, sc):
        w = jax.random.normal(key, shape, jnp.float32) * sc
        return _quantize_leaf(w)

    key, k1, k2 = jax.random.split(key, 3)
    embed_q, embed_scale = make_flat(k1, (V, D), 0.02)
    params: Dict[str, Any] = {
        "embed": embed_q,
        "embed_scale": embed_scale,
        "blocks": blocks,
        "final_norm": norm(D),
    }
    if not cfg.tie_embeddings:
        lm_q, lm_scale = make_flat(k2, (D, V), 0.02)
        params["lm_head"] = lm_q
        params["lm_head_scale"] = lm_scale
    return params


def dequant(w: jnp.ndarray, scale, dtype) -> jnp.ndarray:
    """Dequantize at use; fuses into the consuming matmul under XLA."""
    if scale is None:
        return w if w.dtype == dtype else w.astype(dtype)
    # graftlint: allow(num-barrier) fusing into the consumer is the
    # POINT here: weights are constants, so every compilation sees the
    # same int8 bits and the same product — there is no cross-leg
    # materialization to diverge from (unlike activation/KV dequant).
    return w.astype(dtype) * scale.astype(dtype)
