"""TPU-native model zoo for seldon_tpu.

The reference serves models as black-box CPU microservices (sklearn joblib,
xgboost boosters, tfserving sidecars — /root/reference/servers/). Here the
flagship leaf is a JAX transformer family (Llama-style dense + MoE) designed
for the MXU: bf16 matmuls, scanned layers, static shapes, pjit/GSPMD
sharding over a device mesh.
"""

from seldon_tpu.models.config import ModelConfig, PRESETS, get_config
from seldon_tpu.models.transformer import (
    init_params,
    forward,
    prefill,
    decode_step,
    init_cache,
)

__all__ = [
    "ModelConfig",
    "PRESETS",
    "get_config",
    "init_params",
    "forward",
    "prefill",
    "decode_step",
    "init_cache",
]
