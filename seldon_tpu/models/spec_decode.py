"""graftspec — speculative decoding device kernels: draft + verify.

Speculative decoding converts draft-model throughput into target-model
throughput: a cheap drafter proposes ``k`` tokens per live slot and the
target model scores all ``k + 1`` positions in ONE wide dispatch
(``verify_wave``) instead of ``k + 1`` sequential decode steps. Because
this engine's sampling is deterministic-per-row — every emitted token
is keyed ``fold_in(key(seed), pos + 1)`` — verification is EXACT, not
probabilistic: the wave samples the target's own token at each
position with the sequential keys and accepts drafts only while they
match, so the emitted stream is bit-identical to the spec-off engine
for ANY temperature, not just greedy. The draft only ever decides how
many sequential steps are skipped, never what is emitted.

Numerics: sequential decode computes position ``p`` by attending
positions ``t < p`` from the CACHE (int8 caches round-trip through
quantize/dequantize) plus its OWN column as one exact bf16 fresh
column (``gqa_attention_decode``). The wide pass reproduces that
per query row: the per-layer block-table gather
(``paged_gather_kv``) yields the same dense cache view decode reads,
the wave's own suffix k/v are scattered INTO that view in cache dtype
(so query row ``i`` sees rows ``j < i`` exactly as the cache decode
step ``i`` would — already round-tripped), and
``gqa_attention_verify`` is ``gqa_attention_decode`` generalized to
``Sq`` query rows with a per-row strict mask and a DIAGONAL fresh
column. Stale pool values at positions >= a row's rewound ``pos``
(rejected drafts from an earlier wave) are always shadowed by that
in-layer view scatter before any mask exposes them, which is what
makes host-side rollback a pure block-table trim.

The commit scatter writes all ``Sq`` suffix positions through the
block tables unconditionally (non-wave rows route to the trash
block): positions past the accepted prefix are dead — every future
reader either rewrites them first (view scatter above) or masks them
(strict ``t < pos``) — so acceptance never syncs the host mid-wave.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from seldon_tpu.models import transformer
from seldon_tpu.models.config import ModelConfig
from seldon_tpu.models.sampling import sample_per_row
from seldon_tpu.ops import ragged_paged_attention as rpa

Cache = Dict[str, jnp.ndarray]
State = Dict[str, Any]


def gqa_attention_verify(
    q: jnp.ndarray,  # [B, Sq, H, Dh]
    ck: jnp.ndarray,  # [B, Hkv, T, Dh] cache view (int8 if scales)
    cv: jnp.ndarray,  # [B, Hkv, T, Dh]
    k_fresh: jnp.ndarray,  # [B, Sq, Hkv, Dh] bf16 (exact, own column)
    v_fresh: jnp.ndarray,  # [B, Sq, Hkv, Dh]
    mask_lt: jnp.ndarray,  # [B, Sq, T] True where t < row position (strict)
    k_scale: Optional[jnp.ndarray] = None,  # [B, Hkv, T] (int8 cache)
    v_scale: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """``gqa_attention_decode`` generalized to Sq query rows.

    Each query row attends the cache view under its OWN strict mask
    plus a DIAGONAL fresh column (row i's exact bf16 k/v — never the
    other rows', whose cache-dtype values live in the view). Scales
    stay factored out of the einsums and the fresh column rides the
    same flash-style max/exp combine, so row i's arithmetic is the
    decode kernel's arithmetic at the same T width — the wave is a
    batch of decode steps, not an approximation of one."""
    B, S, H, Dh = q.shape
    Hkv = ck.shape[1]
    G = H // Hkv
    qr = q.reshape(B, S, Hkv, G, Dh)
    scores = jnp.einsum(
        "bskgd,bktd->bkgst", qr, ck.astype(qr.dtype),
        preferred_element_type=jnp.float32,
    ) / (Dh**0.5)
    if k_scale is not None:
        scores = scores * k_scale[:, :, None, None, :]
    # Diagonal fresh column: row i against ITS OWN k only ("bskgd,bskd"
    # contracts d and keeps s paired — the decode kernel's [s, u=1]
    # outer product collapsed onto s == u).
    s_fresh = jnp.einsum(
        "bskgd,bskd->bkgs", qr, k_fresh.astype(qr.dtype),
        preferred_element_type=jnp.float32,
    )[..., None] / (Dh**0.5)
    scores = jnp.where(mask_lt[:, None, None, :, :], scores, -1e30)
    m = jnp.maximum(jnp.max(scores, axis=-1, keepdims=True), s_fresh)
    p = jnp.exp(scores - m)
    p_f = jnp.exp(s_fresh - m)  # [B,k,g,S,1]
    l = jnp.sum(p, axis=-1, keepdims=True) + p_f
    wc = p / l
    if v_scale is not None:
        wc = wc * v_scale[:, :, None, None, :]
    out = jnp.einsum(
        "bkgst,bktd->bskgd", wc.astype(qr.dtype), cv.astype(qr.dtype)
    ) + jnp.einsum(
        "bkgs,bskd->bskgd", (p_f / l)[..., 0].astype(qr.dtype),
        v_fresh.astype(qr.dtype),
    )
    return out.reshape(B, S, H * Dh)


def _run_blocks_verify(params, x, cfg, positions, inv_freq, mask_lt, pool,
                       table, tp=None):
    """Layer scan for the VERIFY wave: per layer, gather the dense
    cache view through the block tables, scatter this wave's own
    suffix k/v into it in CACHE DTYPE (int8 round-trip — the very
    arrays committed to the pool after the scan), and run the widened
    decode attention. The ephemeral view scatter is what lets query
    row i read rows j < i exactly as sequential decode would read them
    back from the cache."""
    quantized = cfg.kv_cache_dtype == "int8"
    B, Sq = positions.shape
    rows = jnp.arange(B)[:, None]

    def body(carry, xs):
        bp, pl = xs
        h = transformer.rms_norm(carry, bp["attn_norm"], cfg.rms_norm_eps)
        q, k, v = transformer._qkv(h, bp, cfg, positions, inv_freq,
                                   tp=tp)
        if quantized:
            kq, ksc = transformer._quantize_kv(k)  # [B,Sq,Hkv,(Dh)]
            vq, vsc = transformer._quantize_kv(v)
            view = {"k": kq, "v": vq, "k_scale": ksc, "v_scale": vsc}
        else:
            dt = pool["k"].dtype
            view = {"k": k.astype(dt), "v": v.astype(dt)}
        cl = transformer.paged_gather_kv(pl, table)  # [B,Hkv,Smax,(Dh)]
        # Advanced indices (rows, positions) broadcast to [B, Sq] and
        # land in front, so the update operand keeps the [B,Sq,Hkv,...]
        # layout; OOB rows (pos past the window) drop.
        cl = {
            key: cl[key].at[rows, :, positions].set(
                view[key], mode="drop"
            )
            for key in cl
        }
        attn = gqa_attention_verify(
            q, cl["k"], cl["v"], k, v, mask_lt,
            k_scale=cl.get("k_scale"), v_scale=cl.get("v_scale"),
        )
        if tp is not None:
            attn = tp.gather(tp.flat(attn))
        x = carry + transformer._qdot(attn, bp, "wo", cfg)
        x, aux = transformer._mlp_res(x, bp, cfg, None, tp=tp)
        # ys in paged_scatter_tokens layout: [B, Hkv, Sq, (Dh)].
        fresh = {key: jnp.swapaxes(view[key], 1, 2) for key in view}
        return x, (fresh, aux)

    x, (fresh, aux) = jax.lax.scan(body, x, (params["blocks"], pool))
    return x, fresh, jnp.mean(aux)


def _run_blocks_verify_sparse(params, x, cfg, positions, inv_freq, pool,
                              table, bound, tp=None, mode="sparse"):
    """Block-sparse twin of _run_blocks_verify (graftkern): the pool
    contribution comes from the walker's online-softmax partials over
    live blocks bounded at each row's pre-wave ``pos`` — NOT pos + i:
    pool positions >= pos hold stale rejected drafts that the masked
    path shadows with its in-view scatter — and the wave's own suffix
    columns join the combine directly from the cache-dtype ``view``
    arrays (query row s sees suffix rows u < s exactly as the masked
    path reads them back out of the scattered view; the diagonal stays
    the exact bf16 fresh column). The combine is manual because the
    diagonal's value rows are query-row-dependent, which
    rpa.combine_fresh's shared-value contract cannot express."""
    quantized = cfg.kv_cache_dtype == "int8"
    B, Sq = positions.shape
    Hkv, Dh = cfg.n_kv_heads, cfg.head_dim
    Smax = table.shape[1] * pool["k"].shape[3]
    bound2 = jnp.broadcast_to(bound[:, None], (B, Sq)).astype(jnp.int32)
    offs = jnp.arange(Sq)
    # Row s sees suffix column u strictly before it (u < s; u == s is
    # the exact diagonal) and only in-window columns — the masked
    # path's view scatter drops OOB writes (mode="drop").
    suf_mask = (offs[None, :] < offs[:, None])[None] \
        & (positions[:, None, :] < Smax)  # [B, s, u]
    sm5 = suf_mask[:, None, None, :, :]

    def body(carry, xs):
        bp, pl = xs
        h = transformer.rms_norm(carry, bp["attn_norm"], cfg.rms_norm_eps)
        q, k, v = transformer._qkv(h, bp, cfg, positions, inv_freq,
                                   tp=tp)
        if quantized:
            kq, ksc = transformer._quantize_kv(k)  # [B,Sq,Hkv,(Dh)]
            vq, vsc = transformer._quantize_kv(v)
            view = {"k": kq, "v": vq, "k_scale": ksc, "v_scale": vsc}
        else:
            dt = pool["k"].dtype
            view = {"k": k.astype(dt), "v": v.astype(dt)}
        qr = q.reshape(B, Sq, Hkv, -1, Dh)
        s_suf = jnp.einsum(
            "bskgd,bukd->bkgsu", qr, view["k"].astype(qr.dtype),
            preferred_element_type=jnp.float32,
        ) / (Dh**0.5)
        if quantized:
            # graftlint: allow(num-barrier) factored-scale scores stay
            # f32 end to end (preferred_element_type above, softmax
            # below) — there is no low-precision rounding boundary for
            # fusion placement to move.
            s_suf = s_suf \
                * view["k_scale"].transpose(0, 2, 1)[:, :, None, None, :]
        s_suf = jnp.where(sm5, s_suf, rpa.NEG_INF)
        s_diag = jnp.einsum(
            "bskgd,bskd->bkgs", qr, k.astype(qr.dtype),
            preferred_element_type=jnp.float32,
        )[..., None] / (Dh**0.5)
        vd = v.transpose(0, 2, 1, 3)[:, :, None, :, :]  # [B,Hkv,1,Sq,Dh]
        if mode == "sparse":
            # Masked-MATCHED two-pass (ops/ragged_paged_attention):
            # pool + suffix weights normalized in f32, x v_scale,
            # rounded to the query dtype, f32-accumulated with one
            # cast; the exact bf16 diagonal rides
            # gqa_attention_verify's second-einsum convention.
            m_p, l_p = rpa.sparse_max_sum(qr, pl, table, bound2)
            m_t = jnp.maximum(
                jnp.maximum(m_p, jnp.max(s_suf, axis=-1, keepdims=True)),
                s_diag,
            )
            pw = jnp.where(sm5, jnp.exp(s_suf - m_t), 0.0)
            p_d = jnp.exp(s_diag - m_t)
            l_t = l_p * jnp.exp(m_p - m_t) \
                + jnp.sum(pw, axis=-1, keepdims=True) + p_d
            acc = rpa.sparse_weighted_value(qr, pl, table, bound2,
                                            m_t, l_t)
            w_suf = pw / l_t
            if quantized:
                w_suf = w_suf \
                    * view["v_scale"].transpose(0, 2, 1)[:, :, None,
                                                         None, :]
            acc = acc + jnp.einsum(
                "bkgsu,bukd->bkgsd", w_suf.astype(qr.dtype),
                view["v"].astype(qr.dtype),
                preferred_element_type=jnp.float32,
            )
            out = acc.astype(qr.dtype) \
                + (p_d / l_t).astype(qr.dtype) * vd.astype(qr.dtype)
            attn = out.transpose(0, 3, 1, 2, 4).reshape(B, Sq, -1)
            attn = attn.astype(carry.dtype)
        else:
            m_p, l_p, acc = rpa.ragged_paged_partials(
                qr, pl, table, bound2, mode=mode)
            # Manual flash-style combine of (pool partials, suffix
            # columns, diagonal); the diagonal is always live, so m_t
            # is finite.
            m_t = jnp.maximum(
                jnp.maximum(m_p, jnp.max(s_suf, axis=-1, keepdims=True)),
                s_diag,
            )
            alpha = jnp.exp(m_p - m_t)
            pw = jnp.where(sm5, jnp.exp(s_suf - m_t), 0.0)
            p_d = jnp.exp(s_diag - m_t)
            l_t = l_p * alpha + jnp.sum(pw, axis=-1, keepdims=True) + p_d
            if quantized:
                pw = pw \
                    * view["v_scale"].transpose(0, 2, 1)[:, :, None,
                                                         None, :]
            out = (
                acc * alpha
                + jnp.einsum("bkgsu,bukd->bkgsd", pw,
                             view["v"].astype(jnp.float32))
                + p_d * vd.astype(jnp.float32)
            ) / jnp.maximum(l_t, 1e-30)
            attn = out.transpose(0, 3, 1, 2, 4).reshape(B, Sq, -1)
            attn = attn.astype(carry.dtype)
        if tp is not None:
            attn = tp.gather(tp.flat(attn))
        x = carry + transformer._qdot(attn, bp, "wo", cfg)
        x, aux = transformer._mlp_res(x, bp, cfg, None, tp=tp)
        fresh = {key: jnp.swapaxes(view[key], 1, 2) for key in view}
        return x, (fresh, aux)

    x, (fresh, aux) = jax.lax.scan(body, x, (params["blocks"], pool))
    return x, fresh, jnp.mean(aux)


def verify_wave(
    params: Any,
    state: State,
    table: jnp.ndarray,  # [B, NBs] int32 block tables
    drafts: jnp.ndarray,  # [B, k] int32 proposed tokens
    wave: jnp.ndarray,  # [B] bool — row participates in this wave
    cfg: ModelConfig,
    tp=None,
    kernel: str = "masked",
    block_budget: int = 0,
) -> Tuple[State, jnp.ndarray, jnp.ndarray]:
    """One speculative verify wave over all B slots.

    Inputs per wave row are ``[last_tok, d_1 .. d_k]`` at positions
    ``pos .. pos + k``; the target's token at each position is sampled
    with the sequential key ``fold_in(key(seed), pos_i + 1)`` and
    drafts are accepted while they MATCH — so every row emits between
    1 (first draft rejected: plain decode) and k + 1 (full acceptance
    + the bonus token) tokens, all bit-identical to sequential decode.
    The per-step accept chain is unrolled host-side (k is static);
    termination (EOS / budget / window) uses the decode chunk's exact
    value-level rule, so a row finishing mid-prefix truncates its
    acceptance chain the same way a finished row freezes a chunk.

    Returns (state, toks [k+1, B], valid [k+1, B]) — valid columns are
    True-prefixes, the _process_chunk contract.

    ``kernel`` != "masked" swaps the layer scan for the block-sparse
    twin (_run_blocks_verify_sparse). The docstring's ANY-temperature
    bit-identity guarantee is the MASKED leg's: sparse/pallas pin
    greedy token parity + the RAGGED_LOGITS_ATOL logits band
    (tests/test_ragged_kernel.py), so spec exactness audits run the
    masked leg."""
    k = drafts.shape[1]
    Sq = k + 1
    pool = state["cache"]
    block = pool["k"].shape[3]
    Smax = table.shape[1] * block
    pos0 = state["pos"]
    inputs = jnp.concatenate(
        [state["last_tok"][:, None], drafts], axis=1
    )  # [B, Sq]
    positions = pos0[:, None] + jnp.arange(Sq)[None, :]  # [B, Sq]
    # Strict per-row mask: query row i sees t < pos + i — the decode
    # step's t < pos at each unrolled position.
    mask_lt = (
        jnp.arange(Smax)[None, None, :] < positions[:, :, None]
    )  # [B, Sq, Smax]
    x = transformer._embed_rows(params, inputs, transformer._dtype(cfg))
    inv_freq = transformer.rope_frequencies(cfg)

    def masked_body():
        return _run_blocks_verify(
            params, x, cfg, positions, inv_freq, mask_lt, pool, table,
            tp=tp,
        )

    if kernel == "masked":
        x, fresh, _ = masked_body()
    else:
        bound = jnp.where(wave, pos0, 0).astype(jnp.int32)

        def sparse_body():
            return _run_blocks_verify_sparse(
                params, x, cfg, positions, inv_freq, pool, table, bound,
                tp=tp, mode=kernel,
            )

        if block_budget > 0:
            n_live = (jnp.max(bound) + block - 1) // block
            x, fresh, _ = jax.lax.cond(
                n_live <= block_budget, sparse_body, masked_body
            )
        else:
            x, fresh, _ = sparse_body()
    # All Sq positions project to logits: Sq = k + 1 stays small, and
    # the acceptance chain below needs every row's candidate.
    logits = transformer._logits(params, x, cfg)  # [B, Sq, V] f32
    # Commit every suffix position through the tables; non-wave rows
    # route to the trash block. Rejected-tail positions are dead by the
    # shadowing argument in the module docstring.
    spos = jnp.where(wave[:, None], positions, Smax)
    new_pool = transformer.paged_scatter_tokens(pool, fresh, table, spos)

    # Unrolled acceptance chain — each iteration IS the decode chunk's
    # step body (same keys, same masking, same termination), with the
    # chain broken at the first draft mismatch or finished row.
    run = wave & state["active"]
    pos = pos0
    remaining = state["remaining"]
    active = state["active"]
    last = state["last_tok"]
    toks_list = []
    valid_list = []
    for i in range(Sq):
        keys = jax.vmap(
            lambda s, p: jax.random.fold_in(jax.random.key(s), p + 1)
        )(state["seeds"], pos)
        tok = sample_per_row(
            logits[:, i],
            keys,
            state["temp"],
            jnp.where(run, state["top_k"], 0),
            jnp.where(run, state["top_p"], 1.0),
        )
        tok = jnp.where(run, tok, cfg.pad_token_id)
        pos = pos + run.astype(jnp.int32)
        remaining = remaining - run.astype(jnp.int32)
        done = run & (
            (tok == cfg.eos_token_id)
            | (remaining <= 0)
            | (pos >= Smax - 1)
        )
        last = jnp.where(run, tok, last)
        active = active & ~done
        toks_list.append(tok)
        valid_list.append(run)
        if i < k:
            run = run & ~done & (tok == drafts[:, i])
    new_state = {
        **state,
        "cache": new_pool,
        "last_tok": last,
        "pos": pos,
        "active": active,
        "remaining": remaining,
    }
    return new_state, jnp.stack(toks_list), jnp.stack(valid_list)


def draft_tokens(
    params: Any,
    window: jnp.ndarray,  # [B, W] int32 right-padded history windows
    wlens: jnp.ndarray,  # [B] true window lengths (>= 1)
    cfg: ModelConfig,
    k: int,
) -> jnp.ndarray:
    """Model drafter: k greedy continuations of each row's sliding
    history window, in ONE dispatch (prefill + a k-1 step scan over a
    scratch dense cache). Stateless by design — the draft model keeps
    no KV between waves, so rollback needs no draft-side bookkeeping
    and the draft cache costs W + k tokens of scratch HBM, not a
    second resident pool. Greedy always: drafts are proposals; only
    determinism matters, acceptance is decided by the target.
    Returns drafts [B, k] int32."""
    B, W = window.shape
    cache = transformer.init_cache(cfg, B, W + k)
    logits, cache = transformer.prefill(params, window, wlens, cache, cfg)
    tok0 = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if k == 1:
        return tok0[:, None]

    def step(carry, _):
        tok, pos, cache = carry
        logits, cache = transformer.decode_step(
            params, tok, pos, cache, cfg
        )
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return (nxt, pos + 1, cache), nxt

    (_, _, _), rest = jax.lax.scan(
        step, (tok0, wlens, cache), None, length=k - 1
    )
    return jnp.concatenate([tok0[:, None], rest.T], axis=1)
