"""graftragged — shape-stable ragged unified-batch attention wave.

One kernel, one compiled dispatch, no bucket lattice. Every scheduler
wave runs this single fused function over ALL slots: mixed cold
prefills, chunked prefill continuations, prefix-warm resumes and decode
steps ride the same dispatch, so the engine compiles exactly ONE
variant — key ``("ragged", chunk)`` — instead of one per
(prefix bucket, suffix bucket, pow2 group) cell (the Ragged Paged
Attention design, PAPERS.md).

Wave layout (all shapes are config constants — nothing about the live
mix appears in any array shape):

 * ``tokens``: the flat ``[max_tokens]`` token buffer with
   ``max_tokens = max_slots * chunk`` — slot ``s`` owns the fixed
   segment ``[s * chunk, (s + 1) * chunk)`` (fixed stride keeps the
   buffer shape-stable AND makes the per-slot view a free reshape; a
   packed variable-stride buffer would need a gather keyed on the mix).
 * per-slot descriptors, each ``[max_slots]``: ``starts`` (tokens of
   the request already KV-resident — prior chunks plus any zero-copy
   prefix-trie hit; this wave's segment lands at absolute positions
   ``start + i``), ``plens`` (full prompt length, so
   ``kv_len = min(plens, starts + chunk)`` after the wave), sampling
   knobs (seed/temp/top_k/top_p/max_new), ``finals`` (this wave
   completes the row's prompt: sample its first token), and
   ``is_prefill`` (the occupancy mask — rows NOT prefilling this wave
   keep their state bit-for-bit and their KV writes route to the
   trash block).
 * ``table``: the ``[max_slots, max_seq_len // kv_block]`` paged block
   tables — block tables are the wave's only KV currency, which is why
   ragged requires the paged engine.

The math is deliberately the engine's proven paged kernels composed
into one trace: the prefill phase is ``_paged_admit_chunk_impl`` with
the resident-prefix width pinned to the FULL table (masking, not
shape, hides the tail — f32 softmax with the -1e30 mask makes wider
padding bit-neutral) and per-row occupancy masking; the decode phase
is ``_paged_chunk_impl`` with one step. Sampling keys stay
``fold_in(key(seed), plen)`` / ``fold_in(key(seed), pos + 1)``, int8
KV scales ride along unchanged, so greedy outputs are bit-identical to
the ragged-off engine — the migration gate tests/test_ragged.py pins.

Capacity is NOT padding: a wave's unused token-slots cost the real
ragged TPU kernel nothing (it walks per-request token counts, the
whole point), so the sched ledger accounts a wave as
``useful == packed tokens`` with zero bucket/group pad — see
docs/benchmarking.md "Ragged dispatch" for the sizing formula and the
tiny-batch crossover where the dense path still wins.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from seldon_tpu.models import transformer
from seldon_tpu.models.config import ModelConfig
from seldon_tpu.models.sampling import sample_per_row

Cache = Dict[str, jnp.ndarray]
State = Dict[str, Any]


def token_buffer_size(max_slots: int, chunk: int) -> int:
    """The wave's fixed token capacity: ``max_slots * chunk``. Sizing
    formula (docs/benchmarking.md): chunk bounds per-wave prefill
    progress per slot, so TTFT under load ~ ceil(prompt / chunk) waves;
    HBM workspace and host-array traffic scale with the product."""
    return max_slots * chunk


def _mask_state(old: State, new: State, mask: jnp.ndarray) -> State:
    """Merge per-slot state writes under the occupancy mask: masked-out
    rows keep every field bit-for-bit (``where`` on the [B] leaves; the
    KV pool is excluded — its writes are trash-routed by position, not
    masked here)."""
    out = dict(old)
    for key in ("last_tok", "pos", "active", "temp", "top_k", "top_p",
                "seeds", "remaining"):
        out[key] = jnp.where(mask, new[key], old[key])
    out["cache"] = new["cache"]
    return out


def ragged_prefill_phase(
    params: Any,
    state: State,
    table: jnp.ndarray,   # [B, NBs] int32 block tables
    tokens: jnp.ndarray,  # [B * chunk] flat token buffer
    plens: jnp.ndarray,   # [B] full prompt lengths
    starts: jnp.ndarray,  # [B] KV-resident tokens (chunk start)
    seeds: jnp.ndarray,
    temps: jnp.ndarray,
    top_ks: jnp.ndarray,
    top_ps: jnp.ndarray,
    max_news: jnp.ndarray,
    finals: jnp.ndarray,      # [B] bool — last chunk: sample + arm
    is_prefill: jnp.ndarray,  # [B] bool occupancy mask
    cfg: ModelConfig,
    tp=None,
) -> Tuple[State, jnp.ndarray, jnp.ndarray]:
    """The wave's prefill leg: run every occupied segment of the token
    buffer through prefill_with_prefix against the FULL block-table
    gather (resident width = the whole window; the t < start mask hides
    the tail, so one static width serves every mix), scatter fresh KV
    through the tables, sample first tokens on final rows. Exactly
    ``_paged_admit_chunk_impl`` with the group axis pinned to all slots
    and non-prefill rows masked out (their descriptors trash-route the
    scatter: start = Smax puts every write past the table)."""
    pool = state["cache"]
    block = pool["k"].shape[3]
    nbs = table.shape[1]
    Smax = nbs * block
    B = table.shape[0]
    Sc = tokens.shape[0] // B
    toks = tokens.reshape(B, Sc)
    prefix_kv = transformer.paged_prefix_view(pool, table, nbs)
    logits, kv = transformer.prefill_with_prefix(
        params, toks, plens, prefix_kv, starts, cfg, tp=tp
    )
    keys = jax.vmap(
        lambda s, p: jax.random.fold_in(jax.random.key(s), p)
    )(seeds, plens)
    first = sample_per_row(logits, keys, temps, top_ks, top_ps)
    first_done = (
        (first == cfg.eos_token_id)
        | (max_news <= 1)
        | (plens + 1 >= Smax)
    )
    new_pos = jnp.minimum(plens, starts + Sc)
    if cfg.kv_cache_dtype == "int8":
        kq, ks = transformer._quantize_kv(kv["k"])
        vq, vs = transformer._quantize_kv(kv["v"])
        writes = {"k": kq, "v": vq, "k_scale": ks, "v_scale": vs}
    else:
        dt = pool["k"].dtype
        writes = {"k": kv["k"].astype(dt), "v": kv["v"].astype(dt)}
    spos = starts[:, None] + jnp.arange(Sc)[None, :]
    new_pool = transformer.paged_scatter_tokens(pool, writes, table,
                                                spos)
    new_state = _mask_state(
        state,
        {
            "cache": new_pool,
            "last_tok": first,
            "pos": new_pos,
            "active": finals & ~first_done,
            "temp": temps,
            "top_k": top_ks,
            "top_p": top_ps,
            "seeds": seeds,
            "remaining": max_news - 1,
        },
        is_prefill,
    )
    return new_state, first, first_done


def ragged_decode_phase(
    params: Any,
    state: State,
    table: jnp.ndarray,
    cfg: ModelConfig,
    tp=None,
) -> Tuple[State, jnp.ndarray, jnp.ndarray]:
    """The wave's decode leg: ONE decode step over every slot, reading
    and writing KV through the block tables — ``_paged_chunk_impl``
    with n_steps = 1 (the same lax.scan wrapper, so the primitive
    sequence — and therefore greedy argmax — matches the ragged-off
    engine exactly). Rows armed by this wave's prefill leg decode
    immediately, mirroring the off path where the decode chunk follows
    the admissions inside one scheduler wave."""
    block = state["cache"]["k"].shape[3]
    Smax = table.shape[1] * block

    def step(carry, _):
        run = carry["active"]
        logits, pool = transformer.paged_decode_step(
            params, carry["last_tok"], carry["pos"], carry["cache"],
            table, cfg, tp=tp,
        )
        keys = jax.vmap(
            lambda s, p: jax.random.fold_in(jax.random.key(s), p + 1)
        )(carry["seeds"], carry["pos"])
        tok = sample_per_row(
            logits,
            keys,
            carry["temp"],
            jnp.where(run, carry["top_k"], 0),
            jnp.where(run, carry["top_p"], 1.0),
        )
        tok = jnp.where(run, tok, cfg.pad_token_id)
        pos = carry["pos"] + run.astype(jnp.int32)
        remaining = carry["remaining"] - run.astype(jnp.int32)
        done = run & (
            (tok == cfg.eos_token_id)
            | (remaining <= 0)
            | (pos >= Smax - 1)
        )
        new_carry = {
            **carry,
            "cache": pool,
            "last_tok": jnp.where(run, tok, carry["last_tok"]),
            "pos": pos,
            "active": carry["active"] & ~done,
            "remaining": remaining,
        }
        return new_carry, (tok, run)

    state, (toks, valid) = jax.lax.scan(step, state, None, length=1)
    return state, toks, valid


def ragged_wave(
    params: Any,
    state: State,
    table: jnp.ndarray,
    tokens: jnp.ndarray,
    plens: jnp.ndarray,
    starts: jnp.ndarray,
    seeds: jnp.ndarray,
    temps: jnp.ndarray,
    top_ks: jnp.ndarray,
    top_ps: jnp.ndarray,
    max_news: jnp.ndarray,
    finals: jnp.ndarray,
    is_prefill: jnp.ndarray,
    cfg: ModelConfig,
    tp=None,
) -> Tuple[State, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One full unified wave: prefill leg then decode leg in a single
    trace (ONE dispatch, ONE compiled variant). Returns
    ``(state, first [B], first_done [B], toks [1, B], valid [1, B])``
    — first/first_done are slot-indexed (the caller reads row
    ``req.slot``), toks/valid flow through the engine's chunk-boundary
    processing unchanged."""
    state, first, first_done = ragged_prefill_phase(
        params, state, table, tokens, plens, starts, seeds, temps,
        top_ks, top_ps, max_news, finals, is_prefill, cfg, tp=tp,
    )
    state, toks, valid = ragged_decode_phase(params, state, table, cfg,
                                             tp=tp)
    return state, first, first_done, toks, valid
