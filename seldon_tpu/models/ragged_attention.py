"""graftragged — shape-stable ragged unified-batch attention wave.

One kernel, one compiled dispatch, no bucket lattice. Every scheduler
wave runs this single fused function over ALL slots: mixed cold
prefills, chunked prefill continuations, prefix-warm resumes and decode
steps ride the same dispatch, so the engine compiles exactly ONE
variant — key ``("ragged", chunk)`` — instead of one per
(prefix bucket, suffix bucket, pow2 group) cell (the Ragged Paged
Attention design, PAPERS.md).

Wave layout (all shapes are config constants — nothing about the live
mix appears in any array shape):

 * ``tokens``: the flat ``[max_tokens]`` token buffer with
   ``max_tokens = max_slots * chunk`` — slot ``s`` owns the fixed
   segment ``[s * chunk, (s + 1) * chunk)`` (fixed stride keeps the
   buffer shape-stable AND makes the per-slot view a free reshape; a
   packed variable-stride buffer would need a gather keyed on the mix).
 * per-slot descriptors, each ``[max_slots]``: ``starts`` (tokens of
   the request already KV-resident — prior chunks plus any zero-copy
   prefix-trie hit; this wave's segment lands at absolute positions
   ``start + i``), ``plens`` (full prompt length, so
   ``kv_len = min(plens, starts + chunk)`` after the wave), sampling
   knobs (seed/temp/top_k/top_p/max_new), ``finals`` (this wave
   completes the row's prompt: sample its first token), and
   ``is_prefill`` (the occupancy mask — rows NOT prefilling this wave
   keep their state bit-for-bit and their KV writes route to the
   trash block).
 * ``table``: the ``[max_slots, max_seq_len // kv_block]`` paged block
   tables — block tables are the wave's only KV currency, which is why
   ragged requires the paged engine.

The math is deliberately the engine's proven paged kernels composed
into one trace: the prefill phase is ``_paged_admit_chunk_impl`` with
the resident-prefix width pinned to the FULL table (masking, not
shape, hides the tail — f32 softmax with the -1e30 mask makes wider
padding bit-neutral) and per-row occupancy masking; the decode phase
is ``_paged_chunk_impl`` with one step. Sampling keys stay
``fold_in(key(seed), plen)`` / ``fold_in(key(seed), pos + 1)``, int8
KV scales ride along unchanged, so greedy outputs are bit-identical to
the ragged-off engine — the migration gate tests/test_ragged.py pins.

Kernel legs (``RAGGED_KERNEL`` / EngineConfig.ragged_kernel —
graftkern): the paragraph above describes ``kernel="masked"``, the
bit-exact baseline. ``"sparse"`` / ``"pallas"`` swap the full-width
reads for the block-sparse walkers in ops/ragged_paged_attention.py —
per row only ``ceil(context / kv_block)`` live pool blocks are
touched, with online softmax across blocks and int8 dequant fused into
the walk — and additionally skip the ENTIRE prefill leg under a traced
``lax.cond(any(is_prefill))`` on decode-only waves (the dominant CPU
cost of the masked wave was a dead full-width prefill on ~5 of every 6
waves). Both stay inside the single ``("ragged", C)`` variant: the
kernel choice is a config constant closed over at jit time, the cond
predicates are traced scalars, and the walkers' per-iteration shapes
are static — zero new variants, zero live retraces (compile-audit runs
the RAGGED leg once per kernel). Numerics: the sparse leg runs the
masked-MATCHED two-pass walk (ops/ragged_paged_attention
"Masked-matched") — the masked kernels' exact term set, softmax
weights rounded to the activation dtype before the value dot, so
sparse-vs-masked differences reduce to f32 summation order and greedy
outputs stay token-identical (the contract
tests/test_ragged_kernel.py pins; raw logits within
ops/ragged_paged_attention.RAGGED_LOGITS_ATOL). The pallas leg keeps
the fused one-pass f32 partials (atol contract only). Non-greedy
sampling may diverge in ulps, so ``masked`` remains the
any-temperature exactness leg. A wave
whose longest live row exceeds ``block_budget`` blocks (> 0) falls
back to the masked leg IN-TRACE via ``lax.cond`` — never truncates,
never retraces.

Capacity is NOT padding: a wave's unused token-slots cost the real
ragged TPU kernel nothing (it walks per-request token counts, the
whole point), so the sched ledger accounts a wave as
``useful == packed tokens`` with zero bucket/group pad — see
docs/benchmarking.md "Ragged dispatch" for the sizing formula and the
tiny-batch crossover where the dense path still wins.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from seldon_tpu.models import transformer
from seldon_tpu.models.config import ModelConfig
from seldon_tpu.models.sampling import sample_per_row
from seldon_tpu.ops import ragged_paged_attention as rpa

Cache = Dict[str, jnp.ndarray]
State = Dict[str, Any]

RAGGED_KERNELS = ("masked", "sparse", "pallas")


def token_buffer_size(max_slots: int, chunk: int) -> int:
    """The wave's fixed token capacity: ``max_slots * chunk``. Sizing
    formula (docs/benchmarking.md): chunk bounds per-wave prefill
    progress per slot, so TTFT under load ~ ceil(prompt / chunk) waves;
    HBM workspace and host-array traffic scale with the product."""
    return max_slots * chunk


def _mask_state(old: State, new: State, mask: jnp.ndarray) -> State:
    """Merge per-slot state writes under the occupancy mask: masked-out
    rows keep every field bit-for-bit (``where`` on the [B] leaves; the
    KV pool is excluded — its writes are trash-routed by position, not
    masked here)."""
    out = dict(old)
    for key in ("last_tok", "pos", "active", "temp", "top_k", "top_p",
                "seeds", "remaining"):
        out[key] = jnp.where(mask, new[key], old[key])
    out["cache"] = new["cache"]
    return out


def _prefill_logits_sparse(
    params: Any,
    toks: jnp.ndarray,    # [B, Sc] this wave's suffix segments
    plens: jnp.ndarray,
    starts: jnp.ndarray,  # [B] raw descriptor starts (idle = Smax)
    bound: jnp.ndarray,   # [B] pool visibility (idle rows clamped to 0)
    pool: Cache,
    table: jnp.ndarray,
    cfg: ModelConfig,
    mode: str,
    tp=None,
) -> Tuple[jnp.ndarray, Cache]:
    """Block-sparse twin of paged_prefix_view + prefill_with_prefix:
    per layer, the walker covers only the LIVE pool blocks combined
    with the causal fresh suffix — no full-width gather, no
    [B, Sc, Smax] score slab. Same (logits, fresh-KV ys) contract as
    prefill_with_prefix; idle rows' pool walk is clamped to zero
    blocks via `bound` (their outputs are discarded by _mask_state, so
    only live rows pin parity). mode "sparse" runs the masked-MATCHED
    two-pass walk in gqa_attention's convention — int8 pool KV
    dequantized into the query dtype first, softmax weights rounded to
    the query dtype over pool AND suffix alike, one f32 accumulation
    with one output cast — so the term set is prefill_with_prefix's
    exactly; "pallas" keeps the fused one-pass partials."""
    B, Sc = toks.shape
    Hkv, Dh = cfg.n_kv_heads, cfg.head_dim
    x = transformer._embed_rows(params, toks, transformer._dtype(cfg))
    positions = starts[:, None] + jnp.arange(Sc)[None, :]
    inv_freq = transformer.rope_frequencies(cfg)
    bound2 = jnp.broadcast_to(bound[:, None], (B, Sc)).astype(jnp.int32)
    smask = jnp.broadcast_to(
        jnp.tril(jnp.ones((Sc, Sc), dtype=bool))[None], (B, Sc, Sc)
    )

    def body(carry, xs):
        bp, pl = xs
        h = transformer.rms_norm(carry, bp["attn_norm"], cfg.rms_norm_eps)
        q, k, v = transformer._qkv(h, bp, cfg, positions, inv_freq,
                                   tp=tp)
        qr = q.reshape(B, Sc, Hkv, -1, Dh)
        # Fresh causal suffix: the diagonal is always visible, so the
        # combine's total max is finite on every row.
        s_f = jnp.einsum(
            "bskgd,btkd->bkgst", qr, k,
            preferred_element_type=jnp.float32,
        ) / (Dh**0.5)
        s_f = jnp.where(smask[:, None, None, :, :], s_f, rpa.NEG_INF)
        if mode == "sparse":
            m_p, l_p = rpa.sparse_max_sum(qr, pl, table, bound2,
                                          dequant=True)
            m_t = jnp.maximum(m_p, jnp.max(s_f, axis=-1, keepdims=True))
            p_f = jnp.exp(s_f - m_t)
            l_t = l_p * jnp.exp(m_p - m_t) \
                + jnp.sum(p_f, axis=-1, keepdims=True)
            acc = rpa.sparse_weighted_value(qr, pl, table, bound2,
                                            m_t, l_t, dequant=True)
            acc = acc + jnp.einsum(
                "bkgst,bktd->bkgsd",
                (p_f / l_t).astype(qr.dtype),
                v.transpose(0, 2, 1, 3).astype(qr.dtype),
                preferred_element_type=jnp.float32,
            )
            attn = acc.transpose(0, 3, 1, 2, 4).reshape(B, Sc, -1)
        else:
            parts = rpa.ragged_paged_partials(qr, pl, table, bound2,
                                              mode=mode)
            attn = rpa.combine_fresh(parts, s_f, v.transpose(0, 2, 1, 3))
        attn = attn.astype(carry.dtype)
        if tp is not None:
            attn = tp.gather(tp.flat(attn))
        x = carry + transformer._qdot(attn, bp, "wo", cfg)
        x, aux = transformer._mlp_res(x, bp, cfg, None, tp=tp)
        return x, (k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3), aux)

    x, (ks, vs, _) = jax.lax.scan(body, x, (params["blocks"], pool))
    last = jnp.clip(plens - starts - 1, 0, Sc - 1)
    x_last = jnp.take_along_axis(x, last[:, None, None], axis=1)
    return transformer._logits(params, x_last, cfg)[:, 0], {
        "k": ks, "v": vs,
    }


def _decode_step_sparse(
    params: Any,
    token: jnp.ndarray,  # [B] int32 current tokens
    pos: jnp.ndarray,    # [B] int32 positions to write at
    bound: jnp.ndarray,  # [B] pool visibility (inactive rows = 0)
    pool: Cache,
    table: jnp.ndarray,
    cfg: ModelConfig,
    mode: str,
    tp=None,
) -> Tuple[jnp.ndarray, Cache]:
    """Block-sparse twin of paged_decode_step: per layer, the walker
    covers the live pool blocks and combines with the one
    always-visible fresh column — no full-width paged_gather_kv.
    mode "sparse" runs the masked-MATCHED two-pass walk
    (ops/ragged_paged_attention "Masked-matched"): weights normalized
    in f32, scaled, rounded to the query dtype before the value dot —
    gqa_attention_decode's exact term set, so greedy argmax survives
    the block reassociation. mode "pallas" keeps the fused one-pass
    f32 partials (the TPU leg). Fresh KV lands after the scan in the
    SAME batched trash-routed scatter as _run_blocks_decode_paged
    (inactive rows write block 0)."""
    B = token.shape[0]
    Hkv, Dh = cfg.n_kv_heads, cfg.head_dim
    quantized = cfg.kv_cache_dtype == "int8"
    block = pool["k"].shape[3]
    x = transformer._embed_rows(params, token,
                                transformer._dtype(cfg))[:, None, :]
    positions = pos[:, None]
    inv_freq = transformer.rope_frequencies(cfg)
    bound2 = bound[:, None].astype(jnp.int32)

    def body(carry, xs):
        bp, pl = xs
        h = transformer.rms_norm(carry, bp["attn_norm"], cfg.rms_norm_eps)
        q, k, v = transformer._qkv(h, bp, cfg, positions, inv_freq,
                                   tp=tp)
        qr = q.reshape(B, 1, Hkv, -1, Dh)
        s_f = jnp.einsum(
            "bskgd,bukd->bkgsu", qr, k,
            preferred_element_type=jnp.float32,
        ) / (Dh**0.5)
        if mode == "sparse":
            m_p, l_p = rpa.sparse_max_sum(qr, pl, table, bound2)
            m_t = jnp.maximum(m_p, s_f)
            p_f = jnp.exp(s_f - m_t)
            l_t = l_p * jnp.exp(m_p - m_t) + p_f
            acc = rpa.sparse_weighted_value(qr, pl, table, bound2,
                                            m_t, l_t)
            # gqa_attention_decode's two-einsum tail: pool contribution
            # cast once, fresh column in query dtype, added in it.
            out = acc.astype(qr.dtype) + jnp.einsum(
                "bkgsu,bukd->bkgsd",
                (p_f / l_t).astype(qr.dtype),
                v.astype(qr.dtype),
            )
            attn = out.transpose(0, 3, 1, 2, 4).reshape(B, 1, -1)
        else:
            parts = rpa.ragged_paged_partials(qr, pl, table, bound2,
                                              mode=mode)
            attn = rpa.combine_fresh(parts, s_f,
                                     v.transpose(0, 2, 1, 3))
        attn = attn.astype(carry.dtype)
        if tp is not None:
            attn = tp.gather(tp.flat(attn))
        x = carry + transformer._qdot(attn, bp, "wo", cfg)
        x, aux = transformer._mlp_res(x, bp, cfg, None, tp=tp)
        if quantized:
            kq, ksc = transformer._quantize_kv(k[:, 0])
            vq, vsc = transformer._quantize_kv(v[:, 0])
            fresh = {"k": kq, "v": vq, "k_scale": ksc, "v_scale": vsc}
        else:
            dt = pool["k"].dtype
            fresh = {"k": k[:, 0].astype(dt), "v": v[:, 0].astype(dt)}
        return x, (fresh, aux)

    x, (fresh, _) = jax.lax.scan(body, x, (params["blocks"], pool))
    rows = jnp.arange(B)
    idx = pos // block
    # Same OOB trash-routing as _run_blocks_decode_paged: pos at Smax
    # must not clamp into the row's last (possibly shared) block.
    bid = jnp.where(
        idx < table.shape[1],
        table[rows, jnp.minimum(idx, table.shape[1] - 1)],
        0,
    )
    off = pos % block
    new_pool = {
        key: pool[key].at[:, bid, :, off].set(
            jnp.swapaxes(fresh[key], 0, 1)
        )
        for key in pool
    }
    return transformer._logits(params, x, cfg)[:, 0], new_pool


def ragged_prefill_phase(
    params: Any,
    state: State,
    table: jnp.ndarray,   # [B, NBs] int32 block tables
    tokens: jnp.ndarray,  # [B * chunk] flat token buffer
    plens: jnp.ndarray,   # [B] full prompt lengths
    starts: jnp.ndarray,  # [B] KV-resident tokens (chunk start)
    seeds: jnp.ndarray,
    temps: jnp.ndarray,
    top_ks: jnp.ndarray,
    top_ps: jnp.ndarray,
    max_news: jnp.ndarray,
    finals: jnp.ndarray,      # [B] bool — last chunk: sample + arm
    is_prefill: jnp.ndarray,  # [B] bool occupancy mask
    cfg: ModelConfig,
    tp=None,
    kernel: str = "masked",
    block_budget: int = 0,
) -> Tuple[State, jnp.ndarray, jnp.ndarray]:
    """The wave's prefill leg: run every occupied segment of the token
    buffer through prefill_with_prefix against the FULL block-table
    gather (resident width = the whole window; the t < start mask hides
    the tail, so one static width serves every mix), scatter fresh KV
    through the tables, sample first tokens on final rows. Exactly
    ``_paged_admit_chunk_impl`` with the group axis pinned to all slots
    and non-prefill rows masked out (their descriptors trash-route the
    scatter: start = Smax puts every write past the table).

    ``kernel`` swaps the attention head for the block-sparse walkers
    (module docstring "Kernel legs"); sampling, scatter and state
    masking below are shared verbatim across legs. ``block_budget`` > 0
    bounds the sparse walk: a wave whose longest live row needs more
    blocks falls back to the masked head in-trace (lax.cond — one
    variant either way)."""
    pool = state["cache"]
    block = pool["k"].shape[3]
    nbs = table.shape[1]
    Smax = nbs * block
    B = table.shape[0]
    Sc = tokens.shape[0] // B
    toks = tokens.reshape(B, Sc)

    def masked_head():
        prefix_kv = transformer.paged_prefix_view(pool, table, nbs)
        return transformer.prefill_with_prefix(
            params, toks, plens, prefix_kv, starts, cfg, tp=tp
        )

    if kernel == "masked":
        logits, kv = masked_head()
    else:
        bound = jnp.where(is_prefill, starts, 0).astype(jnp.int32)

        def sparse_head():
            return _prefill_logits_sparse(
                params, toks, plens, starts, bound, pool, table, cfg,
                kernel, tp=tp,
            )

        if block_budget > 0:
            n_live = (jnp.max(bound) + block - 1) // block
            logits, kv = jax.lax.cond(
                n_live <= block_budget, sparse_head, masked_head
            )
        else:
            logits, kv = sparse_head()
    keys = jax.vmap(
        lambda s, p: jax.random.fold_in(jax.random.key(s), p)
    )(seeds, plens)
    first = sample_per_row(logits, keys, temps, top_ks, top_ps)
    first_done = (
        (first == cfg.eos_token_id)
        | (max_news <= 1)
        | (plens + 1 >= Smax)
    )
    new_pos = jnp.minimum(plens, starts + Sc)
    if cfg.kv_cache_dtype == "int8":
        kq, ks = transformer._quantize_kv(kv["k"])
        vq, vs = transformer._quantize_kv(kv["v"])
        writes = {"k": kq, "v": vq, "k_scale": ks, "v_scale": vs}
    else:
        dt = pool["k"].dtype
        writes = {"k": kv["k"].astype(dt), "v": kv["v"].astype(dt)}
    spos = starts[:, None] + jnp.arange(Sc)[None, :]
    new_pool = transformer.paged_scatter_tokens(pool, writes, table,
                                                spos)
    new_state = _mask_state(
        state,
        {
            "cache": new_pool,
            "last_tok": first,
            "pos": new_pos,
            "active": finals & ~first_done,
            "temp": temps,
            "top_k": top_ks,
            "top_p": top_ps,
            "seeds": seeds,
            "remaining": max_news - 1,
        },
        is_prefill,
    )
    return new_state, first, first_done


def ragged_decode_phase(
    params: Any,
    state: State,
    table: jnp.ndarray,
    cfg: ModelConfig,
    tp=None,
    kernel: str = "masked",
    block_budget: int = 0,
) -> Tuple[State, jnp.ndarray, jnp.ndarray]:
    """The wave's decode leg: ONE decode step over every slot, reading
    and writing KV through the block tables — ``_paged_chunk_impl``
    with n_steps = 1 (the same lax.scan wrapper, so the primitive
    sequence — and therefore greedy argmax — matches the ragged-off
    engine exactly). Rows armed by this wave's prefill leg decode
    immediately, mirroring the off path where the decode chunk follows
    the admissions inside one scheduler wave.

    ``kernel`` != "masked" swaps paged_decode_step for the block-sparse
    step (inactive rows' pool walk clamps to zero blocks — their
    outputs and KV writes are already dead by the ``run`` mask and
    trash routing); sampling and state updates are shared verbatim."""
    block = state["cache"]["k"].shape[3]
    Smax = table.shape[1] * block

    def step(carry, _):
        run = carry["active"]

        def masked_step():
            return transformer.paged_decode_step(
                params, carry["last_tok"], carry["pos"], carry["cache"],
                table, cfg, tp=tp,
            )

        if kernel == "masked":
            logits, pool = masked_step()
        else:
            bound = jnp.where(run, carry["pos"], 0).astype(jnp.int32)

            def sparse_step():
                return _decode_step_sparse(
                    params, carry["last_tok"], carry["pos"], bound,
                    carry["cache"], table, cfg, kernel, tp=tp,
                )

            if block_budget > 0:
                n_live = (jnp.max(bound) + block - 1) // block
                logits, pool = jax.lax.cond(
                    n_live <= block_budget, sparse_step, masked_step
                )
            else:
                logits, pool = sparse_step()
        keys = jax.vmap(
            lambda s, p: jax.random.fold_in(jax.random.key(s), p + 1)
        )(carry["seeds"], carry["pos"])
        tok = sample_per_row(
            logits,
            keys,
            carry["temp"],
            jnp.where(run, carry["top_k"], 0),
            jnp.where(run, carry["top_p"], 1.0),
        )
        tok = jnp.where(run, tok, cfg.pad_token_id)
        pos = carry["pos"] + run.astype(jnp.int32)
        remaining = carry["remaining"] - run.astype(jnp.int32)
        done = run & (
            (tok == cfg.eos_token_id)
            | (remaining <= 0)
            | (pos >= Smax - 1)
        )
        new_carry = {
            **carry,
            "cache": pool,
            "last_tok": jnp.where(run, tok, carry["last_tok"]),
            "pos": pos,
            "active": carry["active"] & ~done,
            "remaining": remaining,
        }
        return new_carry, (tok, run)

    state, (toks, valid) = jax.lax.scan(step, state, None, length=1)
    return state, toks, valid


def ragged_wave(
    params: Any,
    state: State,
    table: jnp.ndarray,
    tokens: jnp.ndarray,
    plens: jnp.ndarray,
    starts: jnp.ndarray,
    seeds: jnp.ndarray,
    temps: jnp.ndarray,
    top_ks: jnp.ndarray,
    top_ps: jnp.ndarray,
    max_news: jnp.ndarray,
    finals: jnp.ndarray,
    is_prefill: jnp.ndarray,
    cfg: ModelConfig,
    tp=None,
    kernel: str = "masked",
    block_budget: int = 0,
) -> Tuple[State, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One full unified wave: prefill leg then decode leg in a single
    trace (ONE dispatch, ONE compiled variant). Returns
    ``(state, first [B], first_done [B], toks [1, B], valid [1, B])``
    — first/first_done are slot-indexed (the caller reads row
    ``req.slot``), toks/valid flow through the engine's chunk-boundary
    processing unchanged.

    Sparse/pallas kernels additionally skip the WHOLE prefill leg on
    decode-only waves via a traced ``lax.cond`` — the dominant masked-
    wave CPU cost was a dead full-width prefill on every decode-only
    wave. XLA's Conditional executes only the live branch, and the cond
    is inside the one ("ragged", C) variant, so the lattice and retrace
    counts are untouched. The masked leg keeps its original cond-free
    trace: it is the bit-exactness baseline and must not change."""
    if kernel == "masked":
        state, first, first_done = ragged_prefill_phase(
            params, state, table, tokens, plens, starts, seeds, temps,
            top_ks, top_ps, max_news, finals, is_prefill, cfg, tp=tp,
        )
    else:
        B = table.shape[0]

        def run_prefill(st):
            return ragged_prefill_phase(
                params, st, table, tokens, plens, starts, seeds, temps,
                top_ks, top_ps, max_news, finals, is_prefill, cfg,
                tp=tp, kernel=kernel, block_budget=block_budget,
            )

        def skip_prefill(st):
            return (st, jnp.zeros((B,), jnp.int32),
                    jnp.zeros((B,), bool))

        state, first, first_done = jax.lax.cond(
            jnp.any(is_prefill), run_prefill, skip_prefill, state
        )
    state, toks, valid = ragged_decode_phase(
        params, state, table, cfg, tp=tp, kernel=kernel,
        block_budget=block_budget,
    )
    return state, first, first_done, toks, valid
