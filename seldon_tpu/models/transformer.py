"""Llama-family transformer, functional JAX, TPU-first.

Design (vs the reference's black-box CPU model servers, SURVEY.md §2.5):
 * Params are a plain pytree with layers STACKED on a leading [L, ...] axis
   and the forward pass is a `lax.scan` over layers — one traced block, so
   compile time is O(1) in depth and XLA fuses each block aggressively.
 * bf16 params/compute, f32 for norms/softmax/logits (MXU-friendly).
 * Static shapes everywhere; decode is a fixed-size KV cache with per-row
   write positions, so the whole generate loop jits once per bucket.
 * GQA + RoPE (half-split convention, HF-compatible) + SwiGLU; optional
   MoE blocks (top-k routing, experts sharded over 'ep').
 * Sharding is supplied externally (parallel/sharding.py) via GSPMD specs;
   this file only places `with_sharding_constraint` hints on activations.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from seldon_tpu.models.config import ModelConfig
from seldon_tpu.models.quantize import dequant

Params = Dict[str, Any]


def _w(container: Dict[str, Any], name: str, dtype) -> jnp.ndarray:
    """Weight fetch with transparent int8 dequant (models/quantize.py):
    `name_scale` present -> int8 * per-output-channel scale, which XLA
    fuses into the consuming matmul's operand read."""
    return dequant(container[name], container.get(name + "_scale"), dtype)


def _quantize_act(x: jnp.ndarray):
    """Dynamic per-token symmetric int8 for W8A8 matmul inputs:
    x [..., D] -> (int8 [..., D], f32 scale [..., 1]).

    The optimization_barrier pins the quantization input to the
    MATERIALIZED activation: without it XLA may fuse this max into the
    producer and reduce over unrounded f32 intermediates, making the
    scale — and hence the int8 bits — a function of fusion choices.
    Fusion differs between the single-chip and the SPMD-partitioned
    (graftmesh tp>1) compilations of the same model, so an unpinned
    scale breaks the engine's bit-exact-across-configs contract on
    near-ties (observed: tp=2 vs tp=1 greedy divergence at the 128
    bucket). The barrier costs one activation materialization the
    int8 dot was about to force anyway. Machine-certified: graftlint's
    num-barrier pass proves every int8 scale in the tree reads a
    barrier-pinned input (make lint)."""
    x = jax.lax.optimization_barrier(x)
    s = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True) / 127.0
    s = jnp.maximum(s, 1e-8)
    q = jnp.clip(
        jnp.round(x.astype(jnp.float32) / s), -127, 127
    ).astype(jnp.int8)
    return q, s


def _w8a8_applies(container: Dict[str, Any], name: str,
                  cfg: ModelConfig) -> bool:
    return (cfg.act_dtype == "int8"
            and container[name].dtype == jnp.int8
            and container.get(name + "_scale") is not None)


def _qdot(x: jnp.ndarray, container: Dict[str, Any], name: str,
          cfg: ModelConfig, act_q=None) -> jnp.ndarray:
    """x [..., D] @ W [D, F] with optional W8A8.

    When cfg.act_dtype == "int8" and the weight is int8-quantized:
    dynamic per-token A8 feeds an s8 x s8 -> s32 dot — the v5e MXU runs
    int8 at double rate, and the round-5 profile shows decode is
    COMPUTE-bound past the slot knee, so this halves the binding
    resource (probe: tools/probe_w8a8.py, 2.2x on the MLP stack).
    Scales apply to the f32 output; exact algebra since weight scales
    are per-output-channel ([1, F]). Otherwise falls back to the
    dequant-in-fusion bf16-math path (identical contraction to the
    einsums it replaces). `act_q` shares one _quantize_act(x) across
    the projections that consume the same input (XLA CSE would dedupe
    anyway under jit; sharing keeps eager/debug runs cheap too)."""
    w = container[name]
    wscale = container.get(name + "_scale")
    if not _w8a8_applies(container, name, cfg):
        return jnp.einsum("...d,df->...f", x, dequant(w, wscale, x.dtype))
    xq, xs = act_q if act_q is not None else _quantize_act(x)
    y = jax.lax.dot_general(
        xq, w, (((xq.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    # graftlint: allow(num-barrier) the s32->f32 epilogue is exact
    # algebra (per-channel scales commute with the dot); both inputs to
    # the product are already-materialized jit values, so fusion cannot
    # change the bits — the hazard lives in the SCALES, which are
    # barrier-pinned inside _quantize_act.
    return (y.astype(jnp.float32) * xs
            * wscale.astype(jnp.float32)).astype(x.dtype)


def _embed_rows(params: Params, tokens: jnp.ndarray, dtype) -> jnp.ndarray:
    """Embedding gather with transparent dequant (scale is per-column,
    so it broadcasts over gathered rows)."""
    rows = jnp.take(params["embed"], tokens, axis=0)
    scale = params.get("embed_scale")
    if scale is None:
        return rows
    # graftlint: allow(num-barrier) weight dequant of constant embed
    # rows: the int8 bits and per-column scale are load-time constants
    # identical in every compilation, so the product is too.
    return rows.astype(dtype) * scale.astype(dtype)[0]
Cache = Dict[str, jnp.ndarray]


def _dtype(cfg: ModelConfig):
    return jnp.dtype(cfg.dtype)


# ---------------------------------------------------------------------------
# Initialization
# ---------------------------------------------------------------------------


def init_params(cfg: ModelConfig, key: jax.Array) -> Params:
    cfg = cfg.validate()
    dt = _dtype(cfg)
    L, D, F, V = cfg.n_layers, cfg.d_model, cfg.d_ff, cfg.vocab_size
    H, Hkv, Dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    k = iter(jax.random.split(key, 16))

    def norm(*shape):
        return jnp.ones(shape, dtype=jnp.float32)

    def dense(key, *shape, scale=0.02):
        return (jax.random.normal(key, shape, dtype=jnp.float32) * scale).astype(dt)

    out_scale = 0.02 / (2 * L) ** 0.5  # residual-stream init damping
    blocks = {
        "attn_norm": norm(L, D),
        "wq": dense(next(k), L, D, H * Dh),
        "wk": dense(next(k), L, D, Hkv * Dh),
        "wv": dense(next(k), L, D, Hkv * Dh),
        "wo": dense(next(k), L, H * Dh, D, scale=out_scale),
        "mlp_norm": norm(L, D),
    }
    if cfg.n_experts:
        E = cfg.n_experts
        blocks.update(
            {
                "router": dense(next(k), L, D, E).astype(jnp.float32),
                "w_gate": dense(next(k), L, E, D, F),
                "w_up": dense(next(k), L, E, D, F),
                "w_down": dense(next(k), L, E, F, D, scale=out_scale),
            }
        )
    else:
        blocks.update(
            {
                "w_gate": dense(next(k), L, D, F),
                "w_up": dense(next(k), L, D, F),
                "w_down": dense(next(k), L, F, D, scale=out_scale),
            }
        )
    params: Params = {
        "embed": dense(next(k), V, D),
        "blocks": blocks,
        "final_norm": norm(D),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = dense(next(k), D, V)
    return params


# ---------------------------------------------------------------------------
# Primitive layers
# ---------------------------------------------------------------------------


def rms_norm(x: jnp.ndarray, w: jnp.ndarray, eps: float) -> jnp.ndarray:
    xf = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * scale * w).astype(x.dtype)


def rope_frequencies(cfg: ModelConfig) -> jnp.ndarray:
    half = cfg.head_dim // 2
    inv_freq = 1.0 / (
        cfg.rope_theta ** (jnp.arange(half, dtype=jnp.float32) / half)
    )
    if cfg.rope_scaling_type == "linear":
        return inv_freq / cfg.rope_scaling_factor
    if cfg.rope_scaling_type == "llama3":
        # HF transformers' _compute_llama3_parameters: frequencies whose
        # wavelength exceeds the ORIGINAL context window are slowed by
        # `factor`; those well inside it are untouched; a smooth ramp
        # (parameterized by the low/high frequency knees) interpolates.
        factor = cfg.rope_scaling_factor
        lo_f = cfg.rope_scaling_low_freq_factor
        hi_f = cfg.rope_scaling_high_freq_factor
        old_ctx = cfg.rope_scaling_original_max_position
        wavelen = 2.0 * jnp.pi / inv_freq
        low_wavelen = old_ctx / lo_f
        high_wavelen = old_ctx / hi_f
        smooth = (old_ctx / wavelen - lo_f) / (hi_f - lo_f)
        scaled = jnp.where(
            wavelen > low_wavelen,
            inv_freq / factor,
            jnp.where(
                wavelen < high_wavelen,
                inv_freq,
                (1.0 - smooth) * inv_freq / factor + smooth * inv_freq,
            ),
        )
        return scaled
    return inv_freq


def apply_rope(x: jnp.ndarray, positions: jnp.ndarray, inv_freq: jnp.ndarray):
    """x: [B, S, H, Dh], positions: [B, S] -> rotated x (half-split pairing)."""
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [B,S,half]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def gqa_attention(
    q: jnp.ndarray,  # [B, Sq, H, Dh]
    k: jnp.ndarray,  # [B, Skv, Hkv, Dh]
    v: jnp.ndarray,  # [B, Skv, Hkv, Dh]
    mask: jnp.ndarray,  # [B, Sq, Skv] bool (True = attend)
) -> jnp.ndarray:
    """Grouped-query attention, f32 softmax. Returns [B, Sq, H*Dh]."""
    B, Sq, H, Dh = q.shape
    Hkv = k.shape[2]
    G = H // Hkv
    q = q.reshape(B, Sq, Hkv, G, Dh)
    scores = jnp.einsum(
        "bskgd,btkd->bkgst", q, k, preferred_element_type=jnp.float32
    ) / (Dh**0.5)
    scores = jnp.where(mask[:, None, None, :, :], scores, -1e30)
    w = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    out = jnp.einsum("bkgst,btkd->bskgd", w, v)
    return out.reshape(B, Sq, H * Dh)


def gqa_attention_decode(
    q: jnp.ndarray,  # [B, 1, H, Dh]
    ck: jnp.ndarray,  # [B, Hkv, T, Dh] OLD cache (pre-write; int8 if scales)
    cv: jnp.ndarray,  # [B, Hkv, T, Dh]
    k_fresh: jnp.ndarray,  # [B, 1, Hkv, Dh] bf16 (exact, this token)
    v_fresh: jnp.ndarray,  # [B, 1, Hkv, Dh]
    mask_lt: jnp.ndarray,  # [B, 1, T] True where t < pos (strict)
    k_scale: Optional[jnp.ndarray] = None,  # [B, Hkv, T] f32 (int8 cache)
    v_scale: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Decode attention over the PRE-write head-major cache plus a
    fresh-token column.

    Why pre-write: scattering this step's k/v into the carried cache and
    slice-reading it back defeats XLA's operand fusion — the read-after-
    write materializes a copy of the whole [B,*,T,Dh] layer (measured 2x
    attention cost at [160, 257] on v5e). Reading the OLD cache (no data
    dependency on the write) fuses; the current token rides as one exact
    bf16 column appended to the score matrix, and cache writes happen
    OUTSIDE the layer scan in one batched scatter.

    Why head-major [B,Hkv,T,Dh]: it is the layout the attention einsums
    want; storing token-major made XLA insert a per-layer transpose copy
    of every slice (seen in HLO as bf16[1,B,T,Hkv,Dh]{4,2,3,1,0} copies).

    For int8 caches the per-(token, head) scales are factored OUT of the
    einsums — scores = (q . k_q) * k_scale, out = (w * v_scale) . v_q —
    so the HBM read stays 1 byte/element (dequantizing first re-widens
    the operand: measured int8 bought only 3% that way). int8 values are
    exact in bf16 and scales apply in f32, so rounding is strictly
    tighter than dequantize-then-multiply. The fresh column is exact
    bf16 — requantization noise only enters through PAST tokens."""
    B, S, H, Dh = q.shape
    Hkv = ck.shape[1]
    G = H // Hkv
    qr = q.reshape(B, S, Hkv, G, Dh)
    scores = jnp.einsum(
        "bskgd,bktd->bkgst", qr, ck.astype(qr.dtype),
        preferred_element_type=jnp.float32,
    ) / (Dh**0.5)
    if k_scale is not None:
        scores = scores * k_scale[:, :, None, None, :]
    s_fresh = jnp.einsum(
        "bskgd,bukd->bkgsu", qr, k_fresh.astype(qr.dtype),
        preferred_element_type=jnp.float32,
    ) / (Dh**0.5)
    scores = jnp.where(mask_lt[:, None, None, :, :], scores, -1e30)
    # Flash-style combine of the fresh column — concatenating it as a
    # T+1th score column forces XLA to relayout the whole (lane-padded)
    # score tensor; explicit max/exp algebra touches only what it must.
    m = jnp.maximum(
        jnp.max(scores, axis=-1, keepdims=True), s_fresh
    )  # [B,k,g,1,1]
    p = jnp.exp(scores - m)
    p_f = jnp.exp(s_fresh - m)  # [B,k,g,1,1]
    l = jnp.sum(p, axis=-1, keepdims=True) + p_f
    wc = p / l
    if v_scale is not None:
        wc = wc * v_scale[:, :, None, None, :]
    out = jnp.einsum(
        "bkgst,bktd->bskgd", wc.astype(qr.dtype), cv.astype(qr.dtype)
    ) + jnp.einsum(
        "bkgsu,bukd->bskgd", (p_f / l).astype(qr.dtype),
        v_fresh.astype(qr.dtype),
    )
    return out.reshape(B, S, H * Dh)


def moe_block(x: jnp.ndarray, bp: Dict[str, jnp.ndarray], cfg: ModelConfig):
    """Top-k MoE. Dense-mixing formulation: every expert runs on every token
    and results are combined with the (sparsified) router weights. This is
    compute-inflated by E/k but fully static-shaped and shards cleanly over
    'ep'; the dropless all_to_all dispatch path is ops/moe_dispatch.py's job
    once capacity-based routing lands.
    """
    B, S, D = x.shape
    E, K = cfg.n_experts, cfg.n_experts_per_token
    logits = jnp.einsum("bsd,de->bse", x.astype(jnp.float32), bp["router"])
    probs_full = jax.nn.softmax(logits, axis=-1)  # [B,S,E] f32
    top_vals, top_idx = jax.lax.top_k(logits, K)  # [B,S,K]
    gates = jax.nn.softmax(top_vals, axis=-1)
    # Scatter the top-k gates back into a dense [B,S,E] mixing matrix.
    onehot = jax.nn.one_hot(top_idx, E, dtype=jnp.float32)  # [B,S,K,E]
    mix = jnp.einsum("bske,bsk->bse", onehot, gates)
    # Switch-style load-balance aux: E * Σ_e frac_routed(e) · mean_prob(e);
    # minimized (→1) by a uniform router, grows as experts collapse.
    frac = onehot.sum(axis=2).mean(axis=(0, 1)) / K  # [E]
    lb_loss = E * jnp.sum(frac * probs_full.mean(axis=(0, 1)))
    hidden = jax.nn.silu(
        jnp.einsum("bsd,edf->besf", x, _w(bp, "w_gate", x.dtype))
    ) * jnp.einsum("bsd,edf->besf", x, _w(bp, "w_up", x.dtype))
    expert_out = jnp.einsum(
        "besf,efd->besd", hidden, _w(bp, "w_down", x.dtype)
    )
    return jnp.einsum("besd,bse->bsd", expert_out, mix.astype(x.dtype)), lb_loss


# ---------------------------------------------------------------------------
# Transformer block via lax.scan
# ---------------------------------------------------------------------------


def _quantize_kv(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-(token, head) symmetric int8: x [..., Dh] -> (int8 [..., Dh],
    scale [...]). Halves KV-cache HBM traffic — the decode-step
    bottleneck once weights are amortized over enough slots.

    Scales are stored bf16: their relative error (2^-8 ~ 0.4%) sits
    below the int8 quantization noise itself, and f32 scales measurably
    hurt — they double the scale read AND the full-array relayout copy
    XLA inserts for the scale buffers each decode step.

    The optimization_barrier pins the scale to the MATERIALIZED k/v
    (same hazard as _quantize_act: a max fused into the rope/projection
    producer reads unrounded f32 and its value drifts across the
    single-chip vs SPMD-partitioned compilations of the same model)."""
    x = jax.lax.optimization_barrier(x)
    scale = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1) / 127.0
    scale = jnp.maximum(scale, 1e-8)
    q = jnp.clip(
        jnp.round(x.astype(jnp.float32) / scale[..., None]), -127, 127
    ).astype(jnp.int8)
    return q, scale.astype(jnp.bfloat16)


def _block(
    x: jnp.ndarray,
    bp: Dict[str, jnp.ndarray],
    cfg: ModelConfig,
    positions: jnp.ndarray,
    inv_freq: jnp.ndarray,
    mask: jnp.ndarray,
    act_spec: Optional[P] = None,
    ring_mesh=None,
):
    """One CACHE-FREE transformer block (training / scoring / ring).
    Serving paths live in _run_blocks_prefill / _run_blocks_decode."""
    B, S, _ = x.shape
    Dh = cfg.head_dim
    h = rms_norm(x, bp["attn_norm"], cfg.rms_norm_eps)
    q, k, v = _qkv(h, bp, cfg, positions, inv_freq)

    use_flash = cfg.attn_impl == "flash" and S > 1
    # Ring attention: long-context full-sequence path with the sequence
    # axis sharded over 'sp' — exact attention, k/v blocks rotate over ICI
    # (parallel/ring_attention.py).
    use_ring = cfg.attn_impl == "ring" and ring_mesh is not None and S > 1

    if use_ring:
        from seldon_tpu.parallel.ring_attention import ring_attention

        # GQA is native in the ring: only the Hkv-head k/v blocks rotate
        # over ICI (q_per_kv x less traffic than pre-expanding to H).
        out = ring_attention(q, k, v, ring_mesh, axis="sp", causal=True)
        attn = out.reshape(B, S, cfg.n_heads * Dh)
    elif use_flash:
        # Full-sequence causal path through the pallas flash kernel
        # (ops/flash_attention.py). GQA is native in the kernel: kv stays
        # at Hkv heads and the q-head grid maps onto shared kv rows.
        from seldon_tpu.ops.flash_attention import flash_attention

        def fold(t):
            n = t.shape[2]
            return t.transpose(0, 2, 1, 3).reshape(B * n, S, Dh)

        out = flash_attention(fold(q), fold(k), fold(v), causal=True,
                              q_per_kv=cfg.q_per_kv)
        attn = (
            out.reshape(B, cfg.n_heads, S, Dh)
            .transpose(0, 2, 1, 3)
            .reshape(B, S, cfg.n_heads * Dh)
        )
    else:
        attn = gqa_attention(q, k, v, mask)

    x = x + _qdot(attn, bp, "wo", cfg)
    if act_spec is not None:
        x = jax.lax.with_sharding_constraint(x, act_spec)
    x, aux = _mlp_res(x, bp, cfg, act_spec)
    return x, aux


def _run_blocks(params, x, cfg, positions, inv_freq, mask,
                act_spec=None, remat=False, ring_mesh=None):
    """Cache-free lax.scan over the stacked layer axis."""

    def body(carry, bp):
        out, aux = _block(carry, bp, cfg, positions, inv_freq, mask,
                          act_spec=act_spec, ring_mesh=ring_mesh)
        return out, aux

    if remat:
        body = jax.checkpoint(body)
    x, aux = jax.lax.scan(body, x, params["blocks"])
    return x, None, jnp.mean(aux)


def _qkv(h, bp, cfg, positions, inv_freq, tp=None):
    """`tp` (models/tp_sharding.TpHints, EngineConfig.tp > 1 only) pins
    the projected heads sharded on 'tp': each device computes the FULL
    d_model contraction for its own disjoint head slice, so per-element
    reduction order — and hence the bits — match tp=1 exactly."""
    B, S, _ = h.shape
    Hkv, Dh = cfg.n_kv_heads, cfg.head_dim
    hq = _quantize_act(h) if _w8a8_applies(bp, "wq", cfg) else None
    q = _qdot(h, bp, "wq", cfg, act_q=hq).reshape(B, S, cfg.n_heads, Dh)
    k = _qdot(h, bp, "wk", cfg, act_q=hq).reshape(B, S, Hkv, Dh)
    v = _qdot(h, bp, "wv", cfg, act_q=hq).reshape(B, S, Hkv, Dh)
    q = apply_rope(q, positions, inv_freq)
    k = apply_rope(k, positions, inv_freq)
    if tp is not None:
        q, k, v = tp.heads(q), tp.heads(k), tp.heads(v)
    return q, k, v


def _mlp_res(x, bp, cfg, act_spec, tp=None):
    """Post-attention half of a block: residual + (SwiGLU | MoE).

    Under `tp` the gate/up projections run output-sharded on d_ff and
    the hidden is ALL-GATHERED (exact data movement) before the
    REPLICATED w_down contraction — no partial-sum reduction ever forms,
    keeping outputs bit-identical to tp=1 (tp_sharding module doc). MoE
    weights replicate, so that branch needs no hints."""
    h = rms_norm(x, bp["mlp_norm"], cfg.rms_norm_eps)
    aux = jnp.zeros((), jnp.float32)
    if cfg.n_experts:
        mlp_out, aux = moe_block(h, bp, cfg)
        x = x + mlp_out
    else:
        hq = _quantize_act(h) if _w8a8_applies(bp, "w_gate", cfg) else None
        hidden = jax.nn.silu(_qdot(h, bp, "w_gate", cfg, act_q=hq)) \
            * _qdot(h, bp, "w_up", cfg, act_q=hq)
        if tp is not None:
            hidden = tp.gather(tp.flat(hidden))
        x = x + _qdot(hidden, bp, "w_down", cfg)
    if act_spec is not None:
        x = jax.lax.with_sharding_constraint(x, act_spec)
    return x, aux


def _run_blocks_prefill(params, x, cfg, positions, inv_freq, mask,
                        act_spec=None, ring_mesh=None, tp=None):
    """Layer scan for PREFILL: attention runs over the fresh k/v only
    (every serving prefill starts at position 0, so the fresh tokens ARE
    the whole visible window — the cache is never read) and each layer's
    rope'd k/v come back as scan ys, stacked [L, B, Hkv, S, Dh], exactly
    the head-major cache layout. The caller builds/updates the cache from
    them in ONE operation — no per-layer cache traffic at all.

    `ring_mesh` (with cfg.attn_impl == "ring") runs the attention as
    CONTEXT-PARALLEL ring attention over the 'sp' mesh axis — long
    prompts prefill with the sequence sharded across devices, k/v blocks
    rotating over ICI (parallel/ring_attention.py). The returned k/v ys
    are full arrays; GSPMD gathers the sp shards when the caller
    scatters them into the (T-unsharded) decode cache. Returns
    (x, {"k","v"} stacked bf16, aux)."""

    def body(carry, bp):
        h = rms_norm(carry, bp["attn_norm"], cfg.rms_norm_eps)
        q, k, v = _qkv(h, bp, cfg, positions, inv_freq, tp=tp)
        B, S = q.shape[0], q.shape[1]
        if ring_mesh is not None and cfg.attn_impl == "ring" and S > 1:
            from seldon_tpu.parallel.ring_attention import ring_attention

            # Hkv-head k/v rotate directly (GQA native in the ring).
            out = ring_attention(q, k, v, ring_mesh, axis="sp", causal=True)
            attn = out.reshape(B, S, cfg.n_heads * cfg.head_dim)
        elif cfg.attn_impl == "flash" and S > 1:
            from seldon_tpu.ops.flash_attention import flash_attention

            Dh = cfg.head_dim

            def fold(t):
                n = t.shape[2]
                return t.transpose(0, 2, 1, 3).reshape(B * n, S, Dh)

            out = flash_attention(fold(q), fold(k), fold(v), causal=True,
                                  q_per_kv=cfg.q_per_kv)
            attn = (out.reshape(B, cfg.n_heads, S, Dh)
                    .transpose(0, 2, 1, 3).reshape(B, S, -1))
        else:
            attn = gqa_attention(q, k, v, mask)
        if tp is not None:
            # Exact all-gather of the head-sharded attention before the
            # REPLICATED wo contraction (tp_sharding module doc).
            attn = tp.gather(tp.flat(attn))
        x = carry + _qdot(attn, bp, "wo", cfg)
        if act_spec is not None:
            x = jax.lax.with_sharding_constraint(x, act_spec)
        x, aux = _mlp_res(x, bp, cfg, act_spec, tp=tp)
        # ys in cache layout: [B, Hkv, S, Dh] per layer.
        return x, (k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3), aux)

    x, (ks, vs, aux) = jax.lax.scan(body, x, params["blocks"])
    return x, {"k": ks, "v": vs}, jnp.mean(aux)


def _run_blocks_prefill_prefix(params, x, cfg, positions, inv_freq, mask,
                               prefix_kv, tp=None):
    """Layer scan for SUFFIX prefill (prefix-cache admissions): attention
    runs over reused prefix KV plus the fresh suffix k/v. `prefix_kv` is
    {"k","v"[,"k_scale","v_scale"]} stacked [L, B, Hkv, Pb, (Dh)] in
    cache storage dtype — it rides the scan as xs next to the blocks, so
    each layer reads exactly its own [B, Hkv, Pb, Dh] slice (int8 caches
    dequantize per layer; the scales' relative error already sits below
    the int8 noise, see _quantize_kv). Fresh suffix k/v come back as ys
    in cache layout, same contract as _run_blocks_prefill."""
    quantized = "k_scale" in prefix_kv

    def body(carry, xs):
        bp, pl = xs
        h = rms_norm(carry, bp["attn_norm"], cfg.rms_norm_eps)
        q, k, v = _qkv(h, bp, cfg, positions, inv_freq, tp=tp)
        pk = pl["k"].astype(q.dtype)
        pv = pl["v"].astype(q.dtype)
        if quantized:
            # Barrier-pinned like ops/ragged_paged_attention._sparse_block:
            # the dequanted prefix must materialize to ONE value before
            # the concat so every consumer fusion reads the same bits
            # (certified by graftlint's num-barrier pass).
            pk = jax.lax.optimization_barrier(
                pk * pl["k_scale"][..., None].astype(q.dtype))
            pv = jax.lax.optimization_barrier(
                pv * pl["v_scale"][..., None].astype(q.dtype))
        # Prefix is head-major [B, Hkv, Pb, Dh]; attention wants
        # token-major columns in front of the fresh suffix.
        k_all = jnp.concatenate([pk.transpose(0, 2, 1, 3), k], axis=1)
        v_all = jnp.concatenate([pv.transpose(0, 2, 1, 3), v], axis=1)
        if tp is not None:
            k_all, v_all = tp.heads(k_all), tp.heads(v_all)
        attn = gqa_attention(q, k_all, v_all, mask)
        if tp is not None:
            attn = tp.gather(tp.flat(attn))
        x = carry + _qdot(attn, bp, "wo", cfg)
        x, aux = _mlp_res(x, bp, cfg, None, tp=tp)
        return x, (k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3), aux)

    x, (ks, vs, aux) = jax.lax.scan(body, x, (params["blocks"], prefix_kv))
    return x, {"k": ks, "v": vs}, jnp.mean(aux)


def _run_blocks_decode(params, x, cfg, positions, inv_freq, pos, cache,
                       act_spec=None, tp=None):
    """Layer scan for DECODE: the cache is read PRE-write (attention
    handles the current token via an exact fresh column) and all L
    layers' fresh k/v are written back AFTER the scan in one batched
    scatter. The cache rides the scan as xs — read-only per-layer slices
    fuse into the attention einsums (GSPMD-shardable), unlike
    slice-reads of a just-scattered carry. (A pallas decode-attention
    kernel was built and measured here in rounds 3-4: 16.3 vs 8.1
    ms/step against this XLA path at 160-slot serving shapes — the
    einsum path rides XLA's fusions to ~80% of HBM roofline, so the
    kernel was removed. See git history for the implementation.)

    Returns (x, new_cache, aux)."""
    quantized = cfg.kv_cache_dtype == "int8"
    Smax = cache["k"].shape[3]
    mask_lt = jnp.arange(Smax)[None, None, :] < pos[:, None, None]

    def attend(q, k, v, cl):
        return gqa_attention_decode(
            q, cl["k"], cl["v"], k, v, mask_lt,
            k_scale=cl.get("k_scale"), v_scale=cl.get("v_scale"),
        )

    def body(carry, xs):
        bp, cl = xs
        h = rms_norm(carry, bp["attn_norm"], cfg.rms_norm_eps)
        q, k, v = _qkv(h, bp, cfg, positions, inv_freq, tp=tp)
        attn = attend(q, k, v, cl)
        if tp is not None:
            attn = tp.gather(tp.flat(attn))
        x = carry + _qdot(attn, bp, "wo", cfg)
        if act_spec is not None:
            x = jax.lax.with_sharding_constraint(x, act_spec)
        x, aux = _mlp_res(x, bp, cfg, act_spec, tp=tp)
        if quantized:
            kq, ksc = _quantize_kv(k[:, 0])
            vq, vsc = _quantize_kv(v[:, 0])
            fresh = {"k": kq, "v": vq, "k_scale": ksc, "v_scale": vsc}
        else:
            dt = cache["k"].dtype
            fresh = {"k": k[:, 0].astype(dt), "v": v[:, 0].astype(dt)}
        return x, (fresh, aux)

    x, (fresh, aux) = jax.lax.scan(body, x, (params["blocks"], cache))
    rows = jnp.arange(pos.shape[0])
    # One scatter covers all layers. k/v are [L,B,Hkv,T,Dh]; advanced
    # indices (rows on dim 1, pos on dim 3) land in front, so the update
    # operand is fresh[key] [L,B,Hkv,(Dh)] transposed to [B,L,Hkv,(Dh)].
    new_cache = {
        key: cache[key].at[:, rows, :, pos].set(
            jnp.swapaxes(fresh[key], 0, 1), unique_indices=True
        )
        for key in cache
    }
    return x, new_cache, jnp.mean(aux)


def _logits(params, x, cfg):
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    if "lm_head" not in params:
        # Tied embeddings: contract against embed's OWN layout ("vd") —
        # materializing embed.T would move the whole vocab matrix per
        # decode step (measured 2.3ms/step for a 131MB bf16 table on v5e).
        return jnp.einsum(
            "bsd,vd->bsv", x, _w(params, "embed", x.dtype),
            preferred_element_type=jnp.float32,
        )
    return jnp.einsum(
        "bsd,dv->bsv", x, _w(params, "lm_head", x.dtype),
        preferred_element_type=jnp.float32,
    )


# ---------------------------------------------------------------------------
# Public entry points
# ---------------------------------------------------------------------------


def forward(
    params: Params,
    tokens: jnp.ndarray,  # [B, S] int32
    cfg: ModelConfig,
    act_spec: Optional[P] = None,
    remat: bool = False,
    return_aux: bool = False,
    ring_mesh=None,
):
    """Full-sequence teacher-forced logits [B, S, V] (training / scoring).
    With return_aux=True also returns {"moe_lb_loss": scalar} (zero for
    dense configs). `ring_mesh` activates ring attention over 'sp' when
    cfg.attn_impl == "ring" (long-context path)."""
    B, S = tokens.shape
    x = _embed_rows(params, tokens, _dtype(cfg))
    if act_spec is not None:
        x = jax.lax.with_sharding_constraint(x, act_spec)
    positions = jnp.broadcast_to(jnp.arange(S), (B, S))
    inv_freq = rope_frequencies(cfg)
    mask = jnp.tril(jnp.ones((S, S), dtype=bool))[None].repeat(B, 0)
    x, _, aux = _run_blocks(params, x, cfg, positions, inv_freq, mask,
                            act_spec=act_spec, remat=remat,
                            ring_mesh=ring_mesh)
    logits = _logits(params, x, cfg)
    if return_aux:
        return logits, {"moe_lb_loss": aux}
    return logits


def init_cache(cfg: ModelConfig, batch: int, max_len: int, dtype=None) -> Cache:
    """KV cache, HEAD-major [L, B, Hkv, T, Dh] (scales [L, B, Hkv, T]).

    Head-major is the layout the decode attention einsums consume; stored
    token-major, XLA inserted a per-layer transpose copy of every slice
    (~2x attention cost at [160 slots, 257 window] on v5e). The write
    side no longer cares about layout: since the cache is read pre-write
    (gqa_attention_decode), all L layers' fresh k/v land in ONE batched
    scatter per step (_run_blocks_decode), not L per-layer scatters."""
    shape = (cfg.n_layers, batch, cfg.n_kv_heads, max_len, cfg.head_dim)
    if cfg.kv_cache_dtype == "int8":
        assert dtype is None, (
            "dtype override is meaningless for an int8 cache (slots are "
            "int8 + f32 scales by construction)"
        )
        sshape = shape[:-1]  # [L, B, Hkv, T]
        return {
            "k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            # Scales min-clamped at init so a read of a never-written slot
            # dequantizes to exact zeros (0 * 1e-8), like the bf16 cache.
            # bf16 storage: see _quantize_kv.
            "k_scale": jnp.full(sshape, 1e-8, jnp.bfloat16),
            "v_scale": jnp.full(sshape, 1e-8, jnp.bfloat16),
        }
    dt = dtype or _dtype(cfg)
    return {"k": jnp.zeros(shape, dtype=dt), "v": jnp.zeros(shape, dtype=dt)}


def init_paged_cache(cfg: ModelConfig, num_blocks: int, block: int) -> Cache:
    """Paged KV pool: HEAD-major [L, NB, Hkv, block, Dh] (scales
    [L, NB, Hkv, block]) — the dense slab's [B, T] plane cut into NB
    fixed-size blocks of `block` tokens, addressed through per-slot
    int32 block tables instead of a contiguous slice. Layout inside a
    block is identical to the slab, so a gather through the table
    reproduces the dense cache bit-for-bit (paged_gather_kv) and the
    attention math is shared with the dense path."""
    shape = (cfg.n_layers, num_blocks, cfg.n_kv_heads, block, cfg.head_dim)
    if cfg.kv_cache_dtype == "int8":
        sshape = shape[:-1]
        return {
            "k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            # Same min-clamp as init_cache: unwritten slots dequantize to
            # exact zeros, keeping garbage finite (the hard t < pos mask
            # zeroes its weight either way).
            "k_scale": jnp.full(sshape, 1e-8, jnp.bfloat16),
            "v_scale": jnp.full(sshape, 1e-8, jnp.bfloat16),
        }
    dt = _dtype(cfg)
    return {"k": jnp.zeros(shape, dtype=dt), "v": jnp.zeros(shape, dtype=dt)}


def paged_gather_kv(pool_layer: Cache, table: jnp.ndarray) -> Cache:
    """Gather ONE layer's K/V dense view through block tables.

    pool_layer: {"k","v"[,scales]} [NB, Hkv, block, (Dh)];
    table: [B, T // block] int32 block ids. Returns [B, Hkv, T, (Dh)]
    arrays elementwise IDENTICAL to the dense slab's layer slice at
    every written position — a pure gather, no arithmetic — so the
    shared attention kernels produce bit-identical outputs (unwritten
    positions differ only where the strict t < pos mask already forces
    exactly-zero weight)."""
    out = {}
    for key, arr in pool_layer.items():
        g = arr[table]  # [B, nb, Hkv, block, (Dh)]
        g = jnp.moveaxis(g, 1, 2)  # [B, Hkv, nb, block, (Dh)]
        shape = g.shape
        out[key] = g.reshape(
            shape[0], shape[1], shape[2] * shape[3], *shape[4:]
        )
    return out


def paged_prefix_view(pool: Cache, table: jnp.ndarray, nb: int) -> Cache:
    """Stacked-layer dense view of the first `nb` table blocks:
    pool [L, NB, Hkv, block, (Dh)] + table [B, >=nb] ->
    {key: [L, B, Hkv, nb*block, (Dh)]} — the paged stand-in for the
    dense engine's resident-prefix slice cache[:, slots, :, :W]."""
    tb = table[:, :nb]
    out = {}
    for key, arr in pool.items():
        g = arr[:, tb]  # [L, B, nb, Hkv, block, (Dh)]
        g = jnp.moveaxis(g, 2, 3)  # [L, B, Hkv, nb, block, (Dh)]
        shape = g.shape
        out[key] = g.reshape(
            shape[0], shape[1], shape[2], shape[3] * shape[4], *shape[5:]
        )
    return out


def paged_scatter_tokens(
    pool: Cache, writes: Cache, table: jnp.ndarray, spos: jnp.ndarray
) -> Cache:
    """Scatter per-token KV writes through block tables.

    writes: {key: [L, B, Hkv, S, (Dh)]} landing at absolute positions
    spos [B, S]; table [B, NBs]. The flat position decomposes into
    (block id via the table, offset inside the block); advanced indices
    on dims 1 and 3 land in front exactly like the dense engine's
    cache[:, slots[:, None], :, spos] scatter, so the update operand is
    the same moveaxis. Rows whose table entry is 0 (unallocated tail of
    a padded bucket) write into the reserved trash block — same
    harmless-garbage discipline as the dense slab's pad writes, hence
    no unique_indices claim (trash collisions are fine). Positions past
    the table's window are routed to the trash block explicitly: the
    dense scatter DROPS out-of-bounds rows, but take_along_axis CLAMPS,
    which would silently corrupt the row's last real block."""
    block = pool["k"].shape[3]
    idx = spos // block  # [B, S]
    bids = jnp.where(
        idx < table.shape[1],
        jnp.take_along_axis(
            table, jnp.minimum(idx, table.shape[1] - 1), axis=1
        ),
        0,
    )
    offs = spos % block
    return {
        key: pool[key].at[:, bids, :, offs].set(
            jnp.moveaxis(writes[key], (1, 3), (0, 1)).astype(pool[key].dtype)
        )
        for key in pool
    }


def _run_blocks_decode_paged(params, x, cfg, positions, inv_freq, pos,
                             pool, table, tp=None):
    """Paged twin of _run_blocks_decode: per layer, K/V are GATHERED
    through the block table into the dense head-major view and fed to
    the SAME gqa_attention_decode — a pure relayout, so greedy decode is
    bit-identical to the slab path. The pool rides the scan as xs (read-
    only per-layer slices, like the dense cache) and all L layers' fresh
    k/v land after the scan in one batched scatter at the flat
    (table[pos // block], pos % block) address."""
    quantized = cfg.kv_cache_dtype == "int8"
    block = pool["k"].shape[3]
    Smax = table.shape[1] * block
    mask_lt = jnp.arange(Smax)[None, None, :] < pos[:, None, None]

    def body(carry, xs):
        bp, pl = xs
        h = rms_norm(carry, bp["attn_norm"], cfg.rms_norm_eps)
        q, k, v = _qkv(h, bp, cfg, positions, inv_freq, tp=tp)
        cl = paged_gather_kv(pl, table)
        attn = gqa_attention_decode(
            q, cl["k"], cl["v"], k, v, mask_lt,
            k_scale=cl.get("k_scale"), v_scale=cl.get("v_scale"),
        )
        if tp is not None:
            attn = tp.gather(tp.flat(attn))
        x = carry + _qdot(attn, bp, "wo", cfg)
        x, aux = _mlp_res(x, bp, cfg, None, tp=tp)
        if quantized:
            kq, ksc = _quantize_kv(k[:, 0])
            vq, vsc = _quantize_kv(v[:, 0])
            fresh = {"k": kq, "v": vq, "k_scale": ksc, "v_scale": vsc}
        else:
            dt = pool["k"].dtype
            fresh = {"k": k[:, 0].astype(dt), "v": v[:, 0].astype(dt)}
        return x, (fresh, aux)

    x, (fresh, aux) = jax.lax.scan(body, x, (params["blocks"], pool))
    rows = jnp.arange(pos.shape[0])
    idx = pos // block
    # pos can sit AT Smax for rows admitted with a full-window prompt
    # (first_done, frozen): the dense scatter drops that OOB write, so
    # the paged one must route it to trash — plain indexing would clamp
    # into the row's last (possibly trie-shared) block.
    bid = jnp.where(
        idx < table.shape[1],
        table[rows, jnp.minimum(idx, table.shape[1] - 1)],
        0,
    )
    off = pos % block
    # Same one-scatter-for-all-layers shape as the dense write: advanced
    # indices (bid on dim 1, off on dim 3) land in front, update operand
    # is fresh[key] [L, B, Hkv, (Dh)] with B swapped forward. Inactive
    # rows write through table entry 0 (trash) — collisions allowed.
    new_pool = {
        key: pool[key].at[:, bid, :, off].set(
            jnp.swapaxes(fresh[key], 0, 1)
        )
        for key in pool
    }
    return x, new_pool, jnp.mean(aux)


def paged_decode_step(
    params: Params,
    token: jnp.ndarray,  # [B] int32 current tokens
    pos: jnp.ndarray,  # [B] int32 positions to write at
    pool: Cache,  # [L, NB, Hkv, block, (Dh)] global block pool
    table: jnp.ndarray,  # [B, Smax // block] int32 block tables
    cfg: ModelConfig,
    tp=None,
) -> Tuple[jnp.ndarray, Cache]:
    """One autoregressive step over the paged pool. Returns
    (logits [B, V], updated pool) — the block-table twin of decode_step,
    bit-identical for greedy outputs. `tp` (tp_sharding.TpHints) runs
    the step SPMD over the 'tp' mesh axis, still bit-identical."""
    x = _embed_rows(params, token, _dtype(cfg))[:, None, :]
    positions = pos[:, None]
    inv_freq = rope_frequencies(cfg)
    x, pool, _ = _run_blocks_decode_paged(params, x, cfg, positions,
                                          inv_freq, pos, pool, table,
                                          tp=tp)
    return _logits(params, x, cfg)[:, 0], pool


def prefill(
    params: Params,
    tokens: jnp.ndarray,  # [B, S] right-padded prompts
    prompt_lens: jnp.ndarray,  # [B] true lengths
    cache: Cache,
    cfg: ModelConfig,
    ring_mesh=None,
    tp=None,
) -> Tuple[jnp.ndarray, Cache]:
    """Run prompts through the model, filling cache slots [0, S).
    Returns (next-token logits [B, V] taken at each row's last real token,
    updated cache). `ring_mesh` + cfg.attn_impl=="ring": context-parallel
    prefill — the prompt's sequence axis shards over 'sp' and attention
    runs as a ring (long-prompt admissions scale across the slice; the
    decode cache stays T-unsharded, GSPMD gathers the shards at the
    cache write)."""
    B, S = tokens.shape
    x = _embed_rows(params, tokens, _dtype(cfg))
    use_ring = ring_mesh is not None and cfg.attn_impl == "ring" and S > 1
    if use_ring:
        # Pin the activation sequence axis to 'sp' so the per-layer qkv
        # projections and MLP also run sequence-sharded, not just the
        # ring attention itself.
        x = jax.lax.with_sharding_constraint(
            x, jax.sharding.NamedSharding(
                ring_mesh, P(None, "sp", None)
            )
        )
    positions = jnp.broadcast_to(jnp.arange(S), (B, S))
    inv_freq = rope_frequencies(cfg)
    mask = jnp.tril(jnp.ones((S, S), dtype=bool))[None].repeat(B, 0)
    Smax = cache["k"].shape[3]
    # Attention never reads `cache` — prefill starts at position 0, so the
    # fresh tokens are the entire visible window (_run_blocks_prefill).
    # The stacked ys land in the cache in one update per array.
    x, kv, _ = _run_blocks_prefill(params, x, cfg, positions, inv_freq, mask,
                                   ring_mesh=ring_mesh if use_ring else None,
                                   tp=tp)
    if cfg.kv_cache_dtype == "int8":
        kq, ks = _quantize_kv(kv["k"])
        vq, vs = _quantize_kv(kv["v"])
        writes = {"k": kq, "v": vq, "k_scale": ks, "v_scale": vs}
    else:
        dt = cache["k"].dtype
        writes = {"k": kv["k"].astype(dt), "v": kv["v"].astype(dt)}
    if S == Smax:
        cache = writes
    else:
        # T is dim 3 of k/v and the trailing dim of the scales, so one
        # indexing expression covers every cache array.
        cache = {
            key: cache[key].at[:, :, :, :S].set(writes[key]) for key in cache
        }
    # Gather each row's last real hidden state BEFORE the vocab projection:
    # projecting all S positions would materialize [B,S,V] f32 (~4 GB for an
    # 8k-prompt llama3-8b bucket) only to keep one row.
    last = jnp.clip(prompt_lens - 1, 0, S - 1)
    x_last = jnp.take_along_axis(x, last[:, None, None], axis=1)  # [B,1,D]
    return _logits(params, x_last, cfg)[:, 0], cache


def prefill_with_prefix(
    params: Params,
    tokens: jnp.ndarray,  # [B, Sq] right-padded SUFFIX tokens
    prompt_lens: jnp.ndarray,  # [B] FULL prompt lengths
    prefix_kv: Cache,  # [L, B, Hkv, Pb, (Dh)] reused prefix, cache dtype
    prefix_lens: jnp.ndarray,  # [B] true prefix lengths (<= Pb)
    cfg: ModelConfig,
    tp=None,
) -> Tuple[jnp.ndarray, Cache]:
    """Prefill that RESUMES at a position offset: runs only the uncached
    suffix of each prompt, attending to already-computed prefix KV
    (prefix-cache admissions, servers/engine.py).

    RoPE is position-absolute, so suffix q/k rotate at their true
    positions (prefix_len + i) and the reused prefix KV — rotated at its
    own absolute positions when first computed — lines up exactly with a
    cold full prefill. The mask exposes prefix columns t < prefix_len
    plus the causal triangle over the suffix; padded prefix/suffix
    columns are masked or land past each row's real tokens, where the
    decode-side strict t < pos mask guarantees write-before-read.

    Returns (next-token logits [B, V] at each row's last real suffix
    token, fresh suffix KV {"k","v"} stacked [L, B, Hkv, Sq, Dh] bf16 —
    the caller scatters prefix and suffix into the slot cache)."""
    B, Sq = tokens.shape
    Pb = prefix_kv["k"].shape[3]
    x = _embed_rows(params, tokens, _dtype(cfg))
    positions = prefix_lens[:, None] + jnp.arange(Sq)[None, :]
    inv_freq = rope_frequencies(cfg)
    pmask = jnp.broadcast_to(
        jnp.arange(Pb)[None, None, :] < prefix_lens[:, None, None],
        (B, Sq, Pb),
    )
    smask = jnp.broadcast_to(
        jnp.tril(jnp.ones((Sq, Sq), dtype=bool))[None], (B, Sq, Sq)
    )
    mask = jnp.concatenate([pmask, smask], axis=2)
    x, kv, _ = _run_blocks_prefill_prefix(
        params, x, cfg, positions, inv_freq, mask, prefix_kv, tp=tp
    )
    # Last real token of the SUFFIX (admissions cap the reused prefix at
    # prompt_len - 1, so there is always at least one suffix token).
    last = jnp.clip(prompt_lens - prefix_lens - 1, 0, Sq - 1)
    x_last = jnp.take_along_axis(x, last[:, None, None], axis=1)
    return _logits(params, x_last, cfg)[:, 0], kv


def decode_step(
    params: Params,
    token: jnp.ndarray,  # [B] int32 current tokens
    pos: jnp.ndarray,  # [B] int32 positions to write at
    cache: Cache,
    cfg: ModelConfig,
    tp=None,
) -> Tuple[jnp.ndarray, Cache]:
    """One autoregressive step. Returns (logits [B, V], updated cache)."""
    x = _embed_rows(params, token, _dtype(cfg))[:, None, :]  # [B,1,D]
    positions = pos[:, None]
    inv_freq = rope_frequencies(cfg)
    x, cache, _ = _run_blocks_decode(params, x, cfg, positions, inv_freq,
                                     pos, cache, tp=tp)
    return _logits(params, x, cfg)[:, 0], cache
