"""Model configuration for the transformer family.

Configs are static dataclasses so every shape is known at trace time —
XLA requirement (no dynamic shapes under jit). Presets cover the bench
ladder: `tiny` (CPU tests), `bench-1b` (fits one v5e chip in bf16),
`llama3-8b` (the BASELINE.json north-star target, TP over a v5e-8 slice).
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    vocab_size: int = 128256
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 14336
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    rms_norm_eps: float = 1e-5
    tie_embeddings: bool = False
    dtype: str = "bfloat16"
    # MoE (0 experts = dense). Expert-parallel ('ep') only engages when >0.
    n_experts: int = 0
    n_experts_per_token: int = 2
    eos_token_id: int = 128001
    pad_token_id: int = 0
    # "xla" = einsum attention (GSPMD-shardable, default); "flash" = pallas
    # blockwise kernel on the full-sequence path (single-device / tp=1 —
    # pallas ops don't auto-partition under GSPMD); "ring" = exact
    # sequence-parallel attention over the 'sp' mesh axis (long context).
    attn_impl: str = "xla"
    # "bf16" (compute dtype) or "int8": per-(token, head) symmetric
    # quantization of KV slots — halves the cache read per decode step,
    # the serving bottleneck at high slot counts.
    kv_cache_dtype: str = "bf16"
    # "bf16" or "int8": weight-only quantization (per-output-channel
    # scales, models/quantize.py) — halves weight HBM reads and the
    # footprint (llama3-8b on one 16GB v5e chip needs this). Applied by
    # loaders via quantize_params; compute stays bf16.
    weight_dtype: str = "bf16"
    # "bf16" or "int8": MATMUL ACTIVATION dtype (W8A8). With int8 weights,
    # dynamic per-token activation quantization feeds s8 x s8 -> s32
    # matmuls — the v5e MXU runs those at double rate, which matters
    # because decode is COMPUTE-bound past the slot knee (round-5
    # profile, docs/benchmarking.md). Applies to the dense projections
    # (qkv/o, SwiGLU); lm_head/embeddings stay bf16 for logit quality.
    # No-op unless weight_dtype is int8.
    act_dtype: str = "bf16"
    # RoPE frequency scaling (long-context checkpoints). Flat scalar
    # fields rather than a dict so the frozen config stays hashable.
    # rope_scaling_type: None (no scaling), "linear" (inv_freq / factor),
    # or "llama3" (HF _compute_llama3_parameters: wavelengths past the
    # original context window are divided by `factor`, with a smooth
    # ramp between the low/high frequency knees). Llama-3.1/3.2
    # checkpoints declare rope_type=llama3 — ignoring it would produce
    # subtly wrong logits at every position.
    rope_scaling_type: Optional[str] = None
    rope_scaling_factor: float = 1.0
    rope_scaling_low_freq_factor: float = 1.0
    rope_scaling_high_freq_factor: float = 4.0
    rope_scaling_original_max_position: int = 8192

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def q_per_kv(self) -> int:
        return self.n_heads // self.n_kv_heads

    def validate(self) -> "ModelConfig":
        assert self.d_model % self.n_heads == 0, "d_model must divide by n_heads"
        assert self.n_heads % self.n_kv_heads == 0, "n_heads must divide by n_kv_heads"
        assert self.attn_impl in ("xla", "flash", "ring"), (
            f"unknown attn_impl {self.attn_impl!r}"
        )
        assert self.kv_cache_dtype in ("bf16", "int8"), (
            f"unknown kv_cache_dtype {self.kv_cache_dtype!r}"
        )
        assert self.weight_dtype in ("bf16", "int8"), (
            f"unknown weight_dtype {self.weight_dtype!r}"
        )
        assert self.act_dtype in ("bf16", "int8"), (
            f"unknown act_dtype {self.act_dtype!r}"
        )
        assert self.rope_scaling_type in (None, "linear", "llama3"), (
            f"unknown rope_scaling_type {self.rope_scaling_type!r}"
        )
        if self.n_experts:
            assert self.n_experts_per_token <= self.n_experts
        return self


PRESETS = {
    # CPU-testable config: every dim divides an 8-way mesh.
    "tiny": ModelConfig(
        vocab_size=256,
        d_model=64,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        d_ff=128,
        max_seq_len=128,
        rope_theta=10000.0,
        eos_token_id=1,
    ),
    "tiny-moe": ModelConfig(
        vocab_size=256,
        d_model=64,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        d_ff=128,
        max_seq_len=128,
        rope_theta=10000.0,
        eos_token_id=1,
        n_experts=4,
        n_experts_per_token=2,
    ),
    # ~1.1B params: single v5e chip (16 GB HBM) with room for KV cache.
    "bench-1b": ModelConfig(
        vocab_size=32000,
        d_model=2048,
        n_layers=16,
        n_heads=16,
        n_kv_heads=8,
        d_ff=5632,
        max_seq_len=2048,
        rope_theta=10000.0,
        eos_token_id=2,
    ),
    # The north-star serving target (BASELINE.json): Llama-3-8B geometry.
    "llama3-8b": ModelConfig(),
    "llama3-70b": ModelConfig(
        d_model=8192,
        n_layers=80,
        n_heads=64,
        n_kv_heads=8,
        d_ff=28672,
    ),
}


def get_config(name_or_cfg, **overrides) -> ModelConfig:
    if isinstance(name_or_cfg, ModelConfig):
        cfg = name_or_cfg
    else:
        cfg = PRESETS[name_or_cfg]
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)
    return cfg.validate()
