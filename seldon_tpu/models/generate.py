"""Batched autoregressive generation: prefill + lax.scan decode.

The whole generate path is a single jitted function per (batch, prompt-len,
max-new-tokens) bucket: prefill fills the KV cache, a `lax.scan` of
`decode_step` produces tokens with per-row sampling knobs, EOS rows freeze
via value-level masking (no dynamic shapes, no host round-trip per token).
Serving-side bucketing keeps the number of compiled variants small
(servers/jaxserver.py).
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from seldon_tpu.models import transformer
from seldon_tpu.models.config import ModelConfig
from seldon_tpu.models.sampling import sample


@functools.partial(
    jax.jit, static_argnames=("cfg", "max_new_tokens")
)
def generate(
    params,
    tokens: jnp.ndarray,  # [B, S] right-padded prompts
    prompt_lens: jnp.ndarray,  # [B]
    key: jax.Array,
    temperature: jnp.ndarray,  # [B]
    top_k: jnp.ndarray,  # [B]
    top_p: jnp.ndarray,  # [B]
    cfg: ModelConfig,
    max_new_tokens: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (out_tokens [B, max_new_tokens], out_lens [B]).

    Rows stop at cfg.eos_token_id; positions past EOS hold pad_token_id.
    """
    B, S = tokens.shape
    cache = transformer.init_cache(cfg, B, S + max_new_tokens)
    logits, cache = transformer.prefill(params, tokens, prompt_lens, cache, cfg)

    def step(carry, step_key):
        logits, cache, pos, done = carry
        tok = sample(logits, step_key, temperature, top_k, top_p)
        tok = jnp.where(done, cfg.pad_token_id, tok)
        new_done = done | (tok == cfg.eos_token_id)
        logits, cache = transformer.decode_step(params, tok, pos, cache, cfg)
        return (logits, cache, pos + 1, new_done), tok

    done0 = jnp.zeros((B,), dtype=bool)
    keys = jax.random.split(key, max_new_tokens)
    (_, _, _, done), toks = jax.lax.scan(
        step, (logits, cache, prompt_lens, done0), keys
    )
    out = jnp.swapaxes(toks, 0, 1)  # [B, T]
    # Length = tokens up to and including EOS (or T if never finished).
    is_eos = out == cfg.eos_token_id
    first_eos = jnp.argmax(is_eos, axis=-1)
    has_eos = jnp.any(is_eos, axis=-1)
    out_lens = jnp.where(has_eos, first_eos + 1, max_new_tokens)
    return out, out_lens
