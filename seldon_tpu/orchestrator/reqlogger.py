"""Request/response logging: CloudEvents pairs POSTed to a logging sink.

Reference: the engine optionally (a) dumps raw request/response JSON to
stdout (`SELDON_LOG_REQUESTS/RESPONSES`, application.properties:20-23)
and (b) POSTs CloudEvents-style message pairs to
`SELDON_MESSAGE_LOGGING_SERVICE` with `CE-*` headers
(PredictionService.java:169-203), consumed by
seldon-request-logger/app/app.py.

TPU-native redesign: logging must NEVER stall the serving hot loop — a
bounded asyncio queue with a single drainer task; events are dropped
(and counted) when the sink can't keep up, instead of backpressuring
prediction latency. Payloads ship as SeldonMessage JSON, one event for
the request and one for the response, correlated by puid.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time
from typing import Optional

from seldon_tpu.core import payloads
from seldon_tpu.proto import prediction_pb2 as pb

logger = logging.getLogger(__name__)

ENV_SINK = "SELDON_MESSAGE_LOGGING_SERVICE"
ENV_LOG_REQUESTS = "SELDON_LOG_REQUESTS"
ENV_LOG_RESPONSES = "SELDON_LOG_RESPONSES"

CE_TYPE_REQUEST = "io.seldon.serving.inference.request"
CE_TYPE_RESPONSE = "io.seldon.serving.inference.response"


class RequestLogger:
    """Fire-and-forget CloudEvents shipper + optional stdout raw logs."""

    def __init__(
        self,
        sink_url: Optional[str] = None,
        log_requests: Optional[bool] = None,
        log_responses: Optional[bool] = None,
        deployment: str = "",
        predictor: str = "",
        max_queue: int = 1024,
    ):
        def env_flag(name):
            return os.environ.get(name, "false").lower() in ("1", "true")

        self.sink_url = sink_url if sink_url is not None else os.environ.get(ENV_SINK, "")
        self.log_requests = (
            log_requests if log_requests is not None else env_flag(ENV_LOG_REQUESTS)
        )
        self.log_responses = (
            log_responses if log_responses is not None else env_flag(ENV_LOG_RESPONSES)
        )
        self.deployment = deployment
        self.predictor = predictor
        self.max_queue = max_queue
        self.dropped = 0
        self.sent = 0
        self._queue: Optional[asyncio.Queue] = None
        self._drainer: Optional[asyncio.Task] = None
        self._session = None

    @property
    def enabled(self) -> bool:
        return bool(self.sink_url) or self.log_requests or self.log_responses

    # --- hot-path entry (sync, never blocks) --------------------------------

    def log_pair(self, request: pb.SeldonMessage, response: pb.SeldonMessage,
                 puid: str) -> None:
        """Called from the serving path after each prediction."""
        if not self.enabled:
            return
        if self.log_requests:
            print("Request: "
                  + json.dumps(payloads.message_to_dict(request)), flush=True)
        if self.log_responses:
            print("Response: "
                  + json.dumps(payloads.message_to_dict(response)), flush=True)
        if not self.sink_url:
            return
        if self._queue is None:
            self._queue = asyncio.Queue(maxsize=self.max_queue)
            self._drainer = asyncio.get_running_loop().create_task(
                self._drain()
            )
        for ce_type, msg in (
            (CE_TYPE_REQUEST, request),
            (CE_TYPE_RESPONSE, response),
        ):
            try:
                # Serialize with the proto C++ fast path only; the O(payload)
                # python dict conversion happens in the drainer, off the
                # serving hot loop.
                self._queue.put_nowait(
                    (ce_type, msg.SerializeToString(), puid)
                )
            except asyncio.QueueFull:
                self.dropped += 1

    # --- drainer ------------------------------------------------------------

    async def _drain(self) -> None:
        try:
            import aiohttp

            self._session = aiohttp.ClientSession()
        except Exception:
            logger.exception("request-logger drainer failed to start; "
                             "events will be dropped")
            while True:  # keep consuming so close() can flush
                await self._queue.get()
                self.dropped += 1
        while True:
            ce_type, raw, puid = await self._queue.get()
            body = payloads.message_to_dict(pb.SeldonMessage.FromString(raw))
            # CloudEvents ids must be unique per (source, id): dedup-capable
            # sinks drop one of a same-id pair, losing half the record. The
            # request/response correlation rides Ce-Requestid (= puid),
            # matching the reference logger's scheme.
            kind = "request" if ce_type == CE_TYPE_REQUEST else "response"
            headers = {
                "Content-Type": "application/json",
                "CE-SpecVersion": "0.2",
                "CE-Type": ce_type,
                "CE-Source": "seldon-tpu-engine",
                "CE-Id": f"{puid}-{kind}",
                "CE-Time": time.strftime(
                    "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
                ),
                "Ce-Requestid": puid,
                "Ce-Deploymentname": self.deployment,
                "Ce-Predictorname": self.predictor,
            }
            try:
                async with self._session.post(
                    self.sink_url, json=body, headers=headers, timeout=2
                ) as resp:
                    await resp.read()
                    if resp.status < 400:
                        self.sent += 1
                    else:
                        self.dropped += 1
            except Exception as e:
                self.dropped += 1
                logger.debug("request-logger sink unreachable: %s", e)

    async def close(self, flush_timeout_s: float = 2.0) -> None:
        if self._drainer is not None:
            # Best-effort flush with a deadline: never let a dead drainer
            # or a slow sink hold up server shutdown.
            deadline = time.monotonic() + flush_timeout_s
            while (
                self._queue is not None
                and not self._queue.empty()
                and not self._drainer.done()
                and time.monotonic() < deadline
            ):
                await asyncio.sleep(0.01)
            self._drainer.cancel()
            try:
                await self._drainer
            except asyncio.CancelledError:
                pass
            self._drainer = None
        if self._session is not None:
            await self._session.close()
            self._session = None


def build_sink_app(store=None, echo: bool = False):
    """The logging SINK: an aiohttp app accepting the engine's CloudEvents
    and flattening tensor payloads into per-row JSON docs (reference
    seldon-request-logger/app/app.py:15-117 flattens for fluentd/ELK).

    `store`: optional list to collect flattened docs (tests / in-process
    pipelines); docs also print to stdout when echo=True.
    """
    from aiohttp import web

    docs = store if store is not None else []

    async def handle(request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except Exception:
            return web.json_response({"error": "bad json"}, status=400)
        ce_type = request.headers.get("CE-Type", "")
        puid = request.headers.get("Ce-Requestid",
                                   request.headers.get("CE-Id", ""))
        flat = _flatten(body, ce_type, puid, dict(request.headers))
        for doc in flat:
            docs.append(doc)
            if echo:
                print(json.dumps(doc), flush=True)
        return web.json_response({"ingested": len(flat)})

    async def dump(request: web.Request) -> web.Response:
        return web.json_response(list(docs)[-1000:])

    async def healthz(request: web.Request) -> web.Response:
        return web.json_response({"docs": len(docs)})

    app = web.Application()
    app.router.add_post("/", handle)
    app.router.add_get("/dump", dump)
    app.router.add_get("/healthz", healthz)
    app["docs"] = docs
    return app


def _flatten(body: dict, ce_type: str, puid: str, headers: dict):
    """SeldonMessage JSON -> one doc per batch row (tensor/ndarray data);
    non-tensor payloads pass through as a single doc."""
    base = {
        "ce_type": ce_type,
        "request_id": puid,
        "deployment": headers.get("Ce-Deploymentname", ""),
        "predictor": headers.get("Ce-Predictorname", ""),
        "kind": "request" if ce_type.endswith(".request") else "response",
    }
    data = body.get("data")
    if not isinstance(data, dict):
        out = dict(base)
        out["payload"] = {
            k: v for k, v in body.items() if k not in ("meta", "status")
        }
        return [out]
    names = data.get("names") or []
    rows = None
    if "ndarray" in data:
        rows = data["ndarray"]
    elif "tensor" in data:
        shape = data["tensor"].get("shape", [])
        values = data["tensor"].get("values", [])
        if len(shape) == 2:
            rows = [
                values[i * shape[1]: (i + 1) * shape[1]]
                for i in range(shape[0])
            ]
    elif "dense" in data:
        # bf16 dense payloads arrive base64-packed; keep shape info only
        # (the sink is a CPU text pipeline — decoding bf16 here would
        # just re-encode it as text anyway).
        out = dict(base)
        out["dense_shape"] = data["dense"].get("shape", [])
        return [out]
    if rows is None:
        out = dict(base)
        out["data"] = data
        return [out]
    docs = []
    for i, row in enumerate(rows):
        doc = dict(base)
        doc["batch_index"] = i
        if isinstance(row, list) and names and len(names) == len(row):
            doc.update({str(n): v for n, v in zip(names, row)})
        else:
            doc["row"] = row
        docs.append(doc)
    return docs


def main(argv=None) -> None:  # pragma: no cover - CLI entry
    """Run the flattening sink standalone (reference
    seldon-request-logger container): engines POST CloudEvents here.
    The durable output is the stdout echo (fluentd/ELK pick it up); the
    in-memory store is a BOUNDED ring so a long-lived pod can't OOM."""
    import argparse
    import collections

    from aiohttp import web

    parser = argparse.ArgumentParser(description="seldon-tpu request logger")
    parser.add_argument("--port", type=int,
                        default=int(os.environ.get("PORT", "8080")))
    parser.add_argument("--quiet", action="store_true",
                        help="don't echo flattened docs to stdout")
    parser.add_argument("--keep", type=int, default=1000,
                        help="docs retained for /dump")
    args = parser.parse_args(argv)

    async def run():
        store = collections.deque(maxlen=args.keep)
        runner = web.AppRunner(
            build_sink_app(store=store, echo=not args.quiet)
        )
        await runner.setup()
        await web.TCPSite(runner, "0.0.0.0", args.port).start()
        logger.info("request-logger sink on :%d", args.port)
        while True:
            await asyncio.sleep(3600)

    logging.basicConfig(level=logging.INFO)
    asyncio.run(run())


if __name__ == "__main__":  # pragma: no cover
    main()
