"""Graph orchestrator ("engine") — the request-path hot loop.

Reference: the Java svc-orch (/root/reference/engine/src/main/java/io/seldon/
engine/, SURVEY.md §2.3): a per-predictor process that walks the inference
graph at request time, calling each predictive unit over gRPC/REST, merging
Meta (tags / routing / requestPath / metrics) at every hop, with feedback
routed back down the recorded path.

TPU-native redesign:
 * asyncio single-process event loop instead of Spring @Async thread pools —
   fan-out over graph branches is `asyncio.gather`, unit calls are
   grpc.aio / aiohttp with cached channels.
 * Dynamic micro-batching at MODEL leaves (batcher.py): many in-flight
   requests fuse into one leaf call (BatchIndex framing) so the TPU sees
   MXU-sized batches. The reference has no batching at all.
 * DenseTensor protobuf end-to-end — no per-hop JSON codec tax.
"""

from seldon_tpu.orchestrator.spec import (
    PredictiveUnit,
    PredictorSpec,
    UnitType,
    load_predictor_spec,
)
from seldon_tpu.orchestrator.walker import PredictorEngine

__all__ = [
    "PredictiveUnit",
    "PredictorSpec",
    "UnitType",
    "load_predictor_spec",
    "PredictorEngine",
]
