"""Engine external API: REST + gRPC + admin surface.

Reference: RestClientController.java (/api/v0.1/predictions, /feedback,
/ping, /ready, /live, /pause, /unpause) + SeldonGrpcServer/SeldonService
(gRPC Seldon.Predict/SendFeedback) + Micrometer /prometheus
(SURVEY.md §2.3). One asyncio process serves all of it."""

from __future__ import annotations

import asyncio
import logging
import os
import time
from typing import Optional

import grpc
import grpc.aio
from aiohttp import web

from seldon_tpu.core import payloads, tracing
from seldon_tpu.core.annotations import AnnotationsConfig
from seldon_tpu.core.http import PROTO_CONTENT_TYPE, parse_message, reply
from seldon_tpu.orchestrator.batcher import MicroBatcher
from seldon_tpu.orchestrator.client import (
    InternalClient,
    SyncInternalClient,
    UnitCallError,
)
from seldon_tpu.orchestrator.reqlogger import RequestLogger
from seldon_tpu.orchestrator.spec import (
    HARDCODED_IMPLEMENTATIONS,
    PredictorSpec,
    load_predictor_spec,
)
from seldon_tpu.orchestrator.walker import PredictorEngine
from seldon_tpu.proto import prediction_grpc
from seldon_tpu.proto import prediction_pb2 as pb
from seldon_tpu.runtime.metrics_server import ServerMetrics, get_default_metrics

logger = logging.getLogger(__name__)

class GraphReadyChecker:
    """Recursive TCP ping of every microservice endpoint (reference
    SeldonGraphReadyChecker.java:40-80: 3 attempts x 500ms)."""

    def __init__(self, spec: PredictorSpec, attempts: int = 3,
                 timeout_s: float = 0.5):
        self.endpoints = [
            (u.endpoint.service_host, u.endpoint.service_port)
            for u in spec.graph.walk()
            if u.endpoint is not None
            and u.implementation not in HARDCODED_IMPLEMENTATIONS
        ]
        self.attempts = attempts
        self.timeout_s = timeout_s

    async def ready(self) -> bool:
        for host, port in self.endpoints:
            ok = False
            for _ in range(self.attempts):
                try:
                    _, writer = await asyncio.wait_for(
                        asyncio.open_connection(host, port), self.timeout_s
                    )
                    writer.close()
                    ok = True
                    break
                except (OSError, asyncio.TimeoutError):
                    await asyncio.sleep(0.05)
            if not ok:
                return False
        return True


class EngineServer:
    """The per-predictor orchestrator process."""

    def __init__(
        self,
        spec: Optional[PredictorSpec] = None,
        http_port: int = 8000,
        grpc_port: int = 5001,
        enable_batching: bool = True,
        metrics: Optional[ServerMetrics] = None,
    ):
        self.spec = spec or load_predictor_spec()
        self.http_port = http_port
        self.grpc_port = grpc_port
        self.metrics = metrics or get_default_metrics()
        self.reqlogger = RequestLogger(predictor=self.spec.name)
        self.batcher = MicroBatcher() if enable_batching else None
        # Runtime knobs from CR annotations via the downward-API podinfo
        # mount (reference AnnotationsConfig.java; no-op outside a pod).
        self.annotations = AnnotationsConfig()
        self.grpc_max_msg = self.annotations.grpc_max_msg_bytes()
        self.engine = PredictorEngine(
            self.spec,
            client=InternalClient(
                timeout_s=self.annotations.rest_timeout_s(30000),
                retries=self.annotations.connect_retries(3),
                max_message_bytes=self.grpc_max_msg,
            ),
            batcher=self.batcher,
            metrics_hook=self._on_custom_metric,
            reward_hook=self._on_reward,
        )
        # A second engine over a BLOCKING gRPC client backs the sync
        # thread-pool gRPC lane whenever the graph allows it (linear or
        # router graphs with gRPC-endpoint units, unbatched) — the lane
        # used to require a fully in-process graph; now every deployed
        # gRPC-unit graph rides C completion queues instead of asyncio.
        # The asyncio engine still serves REST and any non-eligible graph.
        self.engine_sync: Optional[PredictorEngine] = None
        if PredictorEngine.sync_drivable(self.spec, self.batcher):
            self.engine_sync = PredictorEngine(
                self.spec,
                client=SyncInternalClient(
                    timeout_s=self.annotations.rest_timeout_s(30000),
                    retries=self.annotations.connect_retries(3),
                    max_message_bytes=self.grpc_max_msg,
                ),
                batcher=None,
                metrics_hook=self._on_custom_metric,
                reward_hook=self._on_reward,
            )
        self.ready_checker = GraphReadyChecker(self.spec)
        self.paused = False  # /pause drains traffic before pod kill
        self._grpc_server: Optional[grpc.aio.Server] = None
        self._runner: Optional[web.AppRunner] = None

    def _on_custom_metric(self, metric: pb.Metric, unit) -> None:
        self.metrics.record_custom([metric])

    def _on_reward(self, unit, reward: float) -> None:
        self.metrics.record_reward(unit.name, reward)

    # --- REST ---------------------------------------------------------------

    def build_app(self) -> web.Application:
        app = web.Application(client_max_size=1024**3)
        parse = parse_message  # shared proto/JSON negotiation (core/http.py)

        async def predictions(request: web.Request) -> web.Response:
            if self.paused:
                return web.json_response({"error": "paused"}, status=503)
            t0 = time.perf_counter()
            try:
                msg, enc = await parse(request, pb.SeldonMessage)
            except web.HTTPBadRequest:
                raise
            except Exception as e:
                return web.json_response({"error": str(e)}, status=400)
            try:
                out = await self.engine.predict(
                    msg, trace_parent=tracing.Tracer.extract(request.headers)
                )
            except UnitCallError as e:
                return web.json_response(
                    {"status": {"status": 1, "info": str(e), "code": -1,
                                "reason": "ENGINE_UNIT_FAILURE"}},
                    status=500,
                )
            self.metrics.observe("predictions", "rest",
                                 time.perf_counter() - t0, out)
            self.reqlogger.log_pair(msg, out, out.meta.puid)
            return reply(out, enc)

        async def feedback(request: web.Request) -> web.Response:
            if self.paused:
                return web.json_response({"error": "paused"}, status=503)
            t0 = time.perf_counter()
            try:
                fb, enc = await parse(request, pb.Feedback)
            except Exception as e:
                return web.json_response({"error": str(e)}, status=400)
            out = await self.engine.send_feedback(fb)
            self.metrics.observe("feedback", "rest",
                                 time.perf_counter() - t0, out)
            return reply(out, enc)

        async def ready(request: web.Request) -> web.Response:
            if self.paused:
                return web.Response(status=503, text="paused")
            is_ready = await self.ready_checker.ready()
            self.metrics.set_graph_ready(is_ready)  # seldon_graph_ready gauge
            if is_ready:
                return web.Response(text="ready")
            return web.Response(status=503, text="graph not ready")

        async def live(request: web.Request) -> web.Response:
            return web.Response(text="live")

        async def pause(request: web.Request) -> web.Response:
            self.paused = True
            return web.Response(text="paused")

        async def unpause(request: web.Request) -> web.Response:
            self.paused = False
            return web.Response(text="unpaused")

        async def metrics_handler(request: web.Request) -> web.Response:
            body, ctype = self.metrics.export()
            return web.Response(body=body, content_type=ctype.split(";")[0])

        app.router.add_post("/api/v0.1/predictions", predictions)
        app.router.add_post("/api/v1.0/predictions", predictions)
        app.router.add_post("/predict", predictions)
        app.router.add_post("/api/v0.1/feedback", feedback)
        app.router.add_post("/api/v1.0/feedback", feedback)
        app.router.add_get("/ping", live)
        app.router.add_get("/live", live)
        app.router.add_get("/ready", ready)
        app.router.add_get("/pause", pause)
        app.router.add_post("/pause", pause)
        app.router.add_get("/unpause", unpause)
        app.router.add_post("/unpause", unpause)
        app.router.add_get("/prometheus", metrics_handler)
        app.router.add_get("/metrics", metrics_handler)

        async def openapi_handler(request: web.Request) -> web.Response:
            from seldon_tpu.core.openapi import engine_openapi

            return web.json_response(engine_openapi(self.spec.name))

        app.router.add_get("/seldon.json", openapi_handler)
        return app

    # --- gRPC ---------------------------------------------------------------

    class _SeldonServicer:
        def __init__(self, outer: "EngineServer"):
            self.outer = outer

        async def Predict(self, request, context):
            if self.outer.paused:
                await context.abort(grpc.StatusCode.UNAVAILABLE, "paused")
            t0 = time.perf_counter()
            try:
                out = await self.outer.engine.predict(
                    request,
                    trace_parent=tracing.Tracer.extract(
                        context.invocation_metadata()
                    ),
                )
            except UnitCallError as e:
                await context.abort(grpc.StatusCode.INTERNAL, str(e))
                return
            self.outer.metrics.observe(
                "predictions", "grpc", time.perf_counter() - t0, out
            )
            self.outer.reqlogger.log_pair(request, out, out.meta.puid)
            return out

        async def SendFeedback(self, request, context):
            if self.outer.paused:
                await context.abort(grpc.StatusCode.UNAVAILABLE, "paused")
            t0 = time.perf_counter()
            out = await self.outer.engine.send_feedback(request)
            self.outer.metrics.observe(
                "feedback", "grpc", time.perf_counter() - t0, out
            )
            return out

    class _SeldonServicerSync:
        """Thread-pool servicer for fully in-process graphs.

        grpc.aio's per-call task/future machinery costs more CPU than the
        entire graph walk when no unit leaves the process; the sync
        server's C completion queues + worker threads drive the (never-
        suspending) walker coroutine directly (PredictorEngine.drive_sync)
        — measured ~2x requests per server-core on the dense-payload
        Predict path. Network graphs keep the asyncio servicer: their
        fan-out parallelism needs the loop."""

        def __init__(self, outer: "EngineServer", loop):
            self.outer = outer
            self._loop = loop  # for thread-safe reqlogger handoff

        def Predict(self, request, context):
            outer = self.outer
            if outer.paused:
                context.abort(grpc.StatusCode.UNAVAILABLE, "paused")
            t0 = time.perf_counter()
            try:
                out = outer.engine_sync.predict_sync(
                    request,
                    trace_parent=(
                        tracing.Tracer.extract(context.invocation_metadata())
                        if outer.engine_sync.tracer.enabled else None
                    ),
                )
            except UnitCallError as e:
                context.abort(grpc.StatusCode.INTERNAL, str(e))
                return
            outer.metrics.observe(
                "predictions", "grpc", time.perf_counter() - t0, out
            )
            if outer.reqlogger.enabled:
                # log_pair touches the asyncio sink queue — marshal onto
                # the loop; no-op cost when logging is off.
                self._loop.call_soon_threadsafe(
                    outer.reqlogger.log_pair, request, out, out.meta.puid
                )
            return out

        def SendFeedback(self, request, context):
            # Mirrors the async servicer exactly: pause semantics and the
            # feedback counter must not depend on which lane a graph rides.
            if self.outer.paused:
                context.abort(grpc.StatusCode.UNAVAILABLE, "paused")
            t0 = time.perf_counter()
            out = self.outer.engine_sync.drive_sync(
                self.outer.engine_sync.send_feedback(request)
            )
            self.outer.metrics.observe(
                "feedback", "grpc", time.perf_counter() - t0, out
            )
            return out

    async def start(self, host: str = "0.0.0.0", reuse_port: bool = False):
        app = self.build_app()
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, self.http_port,
                           reuse_port=reuse_port or None)
        await site.start()
        self.http_port = site._server.sockets[0].getsockname()[1]

        grpc_options = [
            ("grpc.max_send_message_length", self.grpc_max_msg),
            ("grpc.max_receive_message_length", self.grpc_max_msg),
            # Worker processes share the port (kernel load-balanced).
            ("grpc.so_reuseport", 1 if reuse_port else 0),
        ]
        if self.engine_sync is not None:
            from concurrent import futures

            self._grpc_server = grpc.server(
                futures.ThreadPoolExecutor(
                    # 8 measured best on the netunit bench once the solo
                    # fast walk shrank per-handler python time (15 s
                    # windows: 8 -> 2.6x, 12 -> 2.1x per engine core);
                    # more workers just convoy on the GIL. Blocking unit
                    # hops release the GIL, so 8 still overlaps plenty of
                    # in-flight requests.
                    max_workers=int(
                        os.environ.get("SELDON_TPU_GRPC_WORKERS", "8")
                    )
                ),
                options=grpc_options,
            )
            prediction_grpc.add_servicer(
                self._grpc_server, "Seldon",
                self._SeldonServicerSync(self, asyncio.get_running_loop()),
            )
            self.grpc_port = self._grpc_server.add_insecure_port(
                f"{host}:{self.grpc_port}"
            )
            self._grpc_server.start()
        else:
            self._grpc_server = grpc.aio.server(options=grpc_options)
            prediction_grpc.add_servicer(
                self._grpc_server, "Seldon", self._SeldonServicer(self)
            )
            self.grpc_port = self._grpc_server.add_insecure_port(
                f"{host}:{self.grpc_port}"
            )
            await self._grpc_server.start()
        logger.info(
            "engine up: http=%d grpc=%d graph=%s",
            self.http_port, self.grpc_port, self.spec.graph.name,
        )

    async def stop(self):
        if self._grpc_server is not None:
            stopping = self._grpc_server.stop(grace=1.0)
            if asyncio.iscoroutine(stopping):
                await stopping  # aio server
            else:
                # Sync server returns a threading.Event; waiting inline
                # would block the loop (and /ready answers) during drain.
                await asyncio.get_running_loop().run_in_executor(
                    None, stopping.wait, 5
                )
        if self._runner is not None:
            await self._runner.cleanup()
        await self.reqlogger.close()
        await self.engine.close()
        if self.engine_sync is not None:
            await self.engine_sync.close()


def _worker_main(http_port: int, grpc_port: int, enable_batching: bool,
                 reuse_port: bool) -> None:
    logging.basicConfig(level=logging.INFO)
    server = EngineServer(
        http_port=http_port, grpc_port=grpc_port,
        enable_batching=enable_batching,
    )

    async def run():
        await server.start(reuse_port=reuse_port)
        while True:
            await asyncio.sleep(3600)

    asyncio.run(run())


def main():  # pragma: no cover - CLI entry
    import argparse
    import os

    parser = argparse.ArgumentParser(description="seldon-tpu engine")
    parser.add_argument("--http-port", type=int, default=8000)
    parser.add_argument("--grpc-port", type=int, default=5001)
    parser.add_argument("--no-batching", action="store_true")
    parser.add_argument(
        "--workers", type=int,
        default=int(os.environ.get("ENGINE_WORKERS", "1")),
        help="event-loop processes sharing the ports via SO_REUSEPORT "
             "(the asyncio engine is single-core; the reference's Java "
             "engine used every core of its n1-standard-16)",
    )
    args = parser.parse_args()

    if args.workers > 1:
        import multiprocessing as mp
        import signal

        procs = [
            mp.Process(
                target=_worker_main,
                args=(args.http_port, args.grpc_port,
                      not args.no_batching, True),
                daemon=False,
            )
            for _ in range(args.workers)
        ]
        for p in procs:
            p.start()

        def shutdown(signum, frame):
            # Propagate termination: otherwise SIGTERM (k8s pod stop)
            # kills only the supervisor and orphans bound workers.
            for p in procs:
                if p.is_alive():
                    p.terminate()

        signal.signal(signal.SIGTERM, shutdown)
        signal.signal(signal.SIGINT, shutdown)
        try:
            for p in procs:
                p.join()
        finally:
            shutdown(None, None)
            for p in procs:
                p.join(timeout=5)
    else:
        _worker_main(args.http_port, args.grpc_port,
                     not args.no_batching, False)


if __name__ == "__main__":  # pragma: no cover
    main()
