"""Built-in (hardcoded) units the engine runs in-process.

Reference: engine/src/main/java/io/seldon/engine/predictors/
{SimpleModelUnit,SimpleRouterUnit,RandomABTestUnit,AverageCombinerUnit}.java —
these let a graph run with zero microservices (used heavily by the reference
engine tests, SURVEY.md §4)."""

from __future__ import annotations

import hashlib
from typing import List, Optional

import numpy as np

from seldon_tpu.core import payloads
from seldon_tpu.proto import prediction_pb2 as pb


class SimpleModelUnit:
    """Fixed 3-class scores (reference SimpleModelUnit.java:29-79)."""

    values = np.array([[0.9, 0.05, 0.05]])
    class_names = ["proba0", "proba1", "proba2"]

    def transform_input(self, msg: pb.SeldonMessage) -> pb.SeldonMessage:
        kind = payloads.data_kind(msg)
        out = payloads.build_message(
            self.values, names=self.class_names,
            kind=kind if kind in ("dense", "tensor", "ndarray") else "dense",
        )
        out.meta.CopyFrom(msg.meta)
        return out

    def send_feedback(self, feedback: pb.Feedback) -> None:
        return None


class SimpleRouterUnit:
    """Always routes to branch 0 (reference SimpleRouterUnit.java:36)."""

    def route(self, msg: pb.SeldonMessage, n_children: int) -> int:
        return 0

    def send_feedback(self, feedback: pb.Feedback) -> None:
        return None


class RandomABTestUnit:
    """Deterministic pseudo-random 50/50 A/B split.

    Reference RandomABTestUnit.java:105-112 uses a seeded Random per unit;
    here the branch is a hash of the request puid, so the choice is
    reproducible per request (and across engine replicas — better than the
    reference, whose per-process RNG diverges between replicas)."""

    def __init__(self, ratio_a: float = 0.5, seed: int = 1337):
        self.ratio_a = ratio_a
        self.seed = seed

    def route(self, msg: pb.SeldonMessage, n_children: int) -> int:
        h = hashlib.sha256(
            f"{self.seed}:{msg.meta.puid}".encode()
        ).digest()
        u = int.from_bytes(h[:8], "little") / 2**64
        return 0 if u < self.ratio_a else min(1, n_children - 1)

    def send_feedback(self, feedback: pb.Feedback) -> None:
        return None


class AverageCombinerUnit:
    """Elementwise mean over children outputs with shape checks
    (reference AverageCombinerUnit.java:29-93)."""

    def aggregate(self, msgs: List[pb.SeldonMessage]) -> pb.SeldonMessage:
        if not msgs:
            raise ValueError("AverageCombiner: no inputs")
        arrays = []
        names: List[str] = []
        kind = "dense"
        for m in msgs:
            arr = payloads.get_data_from_message(m)
            if not isinstance(arr, np.ndarray):
                raise ValueError("AverageCombiner: non-tensor input")
            arrays.append(arr.astype(np.float64))
            k = payloads.data_kind(m)
            if k in ("dense", "tensor", "ndarray"):
                kind = k
            if m.HasField("data") and m.data.names:
                names = list(m.data.names)
        shape0 = arrays[0].shape
        for i, a in enumerate(arrays[1:], 1):
            if a.shape != shape0:
                raise ValueError(
                    f"AverageCombiner: input {i} shape {a.shape} != {shape0}"
                )
        mean = np.mean(np.stack(arrays), axis=0)
        return payloads.build_message(mean, names=names or None, kind=kind)


def make_hardcoded(implementation, parameters=None):
    from seldon_tpu.orchestrator.spec import UnitImplementation

    params = {p.name: p.typed_value() for p in (parameters or [])}
    if implementation == UnitImplementation.SIMPLE_MODEL:
        return SimpleModelUnit()
    if implementation == UnitImplementation.SIMPLE_ROUTER:
        return SimpleRouterUnit()
    if implementation == UnitImplementation.RANDOM_ABTEST:
        return RandomABTestUnit(
            ratio_a=float(params.get("ratioA", 0.5)),
            seed=int(params.get("seed", 1337)),
        )
    if implementation == UnitImplementation.AVERAGE_COMBINER:
        return AverageCombinerUnit()
    raise ValueError(f"no hardcoded implementation for {implementation}")
