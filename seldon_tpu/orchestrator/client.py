"""Async internal client: engine -> unit microservice calls.

Reference: engine/.../service/InternalPredictionService.java:191-472 (REST
RestTemplate pool + gRPC cached channels, per-call deadlines, N retries on
connection failure) and grpc/GrpcChannelHandler.java (channel cache).

TPU-native: grpc.aio and aiohttp on one event loop; REST carries binary
proto (`application/x-protobuf`) by default — the dense-tensor fast path —
falling back to reference-style JSON only if a unit demands it."""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, List, Optional

import grpc
import grpc.aio

from seldon_tpu.core import payloads, tracing
from seldon_tpu.orchestrator.spec import Endpoint, EndpointType, PredictiveUnit
from seldon_tpu.proto import prediction_grpc
from seldon_tpu.proto import prediction_pb2 as pb

logger = logging.getLogger(__name__)

from seldon_tpu.core.http import (  # noqa: F401 (shared constants)
    JSON_CONTENT_TYPE,
    PROTO_CONTENT_TYPE,
    to_json_bytes,
)

# engine-side call name -> (service, rpc) — typed per-unit stubs mirroring
# the reference (InternalPredictionService.java:269-306).
_GRPC_METHODS = {
    "predict": ("Model", "Predict"),
    "transform_input": ("Generic", "TransformInput"),
    "transform_output": ("Generic", "TransformOutput"),
    "route": ("Router", "Route"),
    "aggregate": ("Combiner", "Aggregate"),
    "send_feedback": ("Generic", "SendFeedback"),
}

_REST_PATHS = {
    "predict": "/predict",
    "transform_input": "/transform-input",
    "transform_output": "/transform-output",
    "route": "/route",
    "aggregate": "/aggregate",
    "send_feedback": "/send-feedback",
}


def identity_headers(unit: PredictiveUnit) -> Dict[str, str]:
    """Engine -> unit hop-identity headers, the reference's
    `Seldon-model-name/image/version` contract
    (InternalPredictionService.java:191-370): downstream logging/tracing
    systems recover WHICH unit (and which image build) served each hop
    without parsing the graph. Keys are lowercase so the same dict is
    valid gRPC metadata (gRPC requires lowercase ASCII keys; HTTP headers
    are case-insensitive)."""
    image, sep, version = (unit.image or "").rpartition(":")
    # A tag colon always follows the last '/': "localhost:5000/img" is an
    # UNtagged image on a port-qualified registry, and "img@sha256:..." is
    # a digest ref — in both, the suffix after ':' contains no tag.
    if not sep or "/" in version or "@" in image:
        image, version = (unit.image or ""), ""
    return {
        "seldon-model-name": unit.name,
        "seldon-model-image": image,
        "seldon-model-version": version,
    }


class UnitCallError(Exception):
    def __init__(self, unit: str, method: str, detail: str, status: int = 500):
        super().__init__(f"{unit}.{method}: {detail}")
        self.unit = unit
        self.method = method
        self.detail = detail
        self.status = status


class InternalClient:
    """Cached-channel async client for unit calls."""

    def __init__(
        self,
        timeout_s: float = 30.0,
        retries: int = 3,
        max_message_bytes: int = 512 * 1024 * 1024,
    ):
        self.timeout_s = timeout_s
        self.retries = retries
        self._options = [
            ("grpc.max_send_message_length", max_message_bytes),
            ("grpc.max_receive_message_length", max_message_bytes),
        ]
        self._channels: Dict[str, grpc.aio.Channel] = {}
        self._http = None  # lazy aiohttp session

    # --- transport plumbing -------------------------------------------------

    def _channel(self, endpoint: Endpoint) -> grpc.aio.Channel:
        addr = f"{endpoint.service_host}:{endpoint.service_port}"
        ch = self._channels.get(addr)
        if ch is None:
            ch = grpc.aio.insecure_channel(addr, options=self._options)
            self._channels[addr] = ch
        return ch

    async def _http_session(self):
        if self._http is None:
            import aiohttp

            self._http = aiohttp.ClientSession()
        return self._http

    async def close(self):
        for ch in self._channels.values():
            await ch.close()
        self._channels.clear()
        if self._http is not None:
            await self._http.close()
            self._http = None

    # --- calls --------------------------------------------------------------

    async def call(
        self,
        unit: PredictiveUnit,
        method: str,
        request,
        response_cls=pb.SeldonMessage,
    ):
        """Invoke `method` on the unit's microservice with retries."""
        ep = unit.endpoint or Endpoint()
        last_err: Optional[Exception] = None
        identity = identity_headers(unit)
        for attempt in range(self.retries + 1):
            try:
                if ep.type == EndpointType.GRPC:
                    return await self._call_grpc(ep, method, request, identity)
                return await self._call_rest(
                    ep, method, request, response_cls, identity
                )
            except (grpc.aio.AioRpcError, OSError, asyncio.TimeoutError) as e:
                last_err = e
                # Only connection-level failures retry (reference retries on
                # connect failure only, InternalPredictionService.java:413-467)
                # — NOT timeouts: the unit may already be doing the work, and
                # retrying a slow call duplicates it.
                if isinstance(e, grpc.aio.AioRpcError):
                    retryable = e.code() == grpc.StatusCode.UNAVAILABLE
                else:
                    import aiohttp

                    retryable = isinstance(
                        e, (ConnectionRefusedError, ConnectionResetError,
                            ConnectionAbortedError, BrokenPipeError,
                            aiohttp.ClientConnectorError)
                    )
                if not retryable:
                    break
                if attempt < self.retries:
                    await asyncio.sleep(0.05 * (attempt + 1))
        detail = str(last_err)
        if isinstance(last_err, grpc.aio.AioRpcError):
            detail = f"{last_err.code().name}: {last_err.details()}"
        raise UnitCallError(unit.name, method, detail)

    async def _call_grpc(self, ep: Endpoint, method: str, request,
                         identity: Optional[Dict[str, str]] = None):
        ch = self._channel(ep)
        service, rpc_name = _GRPC_METHODS[method]
        stub = prediction_grpc.STUBS[service](ch)
        metadata = tuple(
            tracing.inject_current(dict(identity or {})).items()
        ) or None
        return await getattr(stub, rpc_name)(
            request, timeout=self.timeout_s, metadata=metadata
        )

    async def _call_rest(self, ep: Endpoint, method: str, request,
                         response_cls,
                         identity: Optional[Dict[str, str]] = None):
        session = await self._http_session()
        url = f"http://{ep.service_host}:{ep.service_port}{_REST_PATHS[method]}"
        if ep.content == "json":
            # Foreign-language units (docs/wrappers.md) speak JSON; our
            # own units prefer the binary-proto body (zero-copy dense).
            body_out = to_json_bytes(request)
            headers = {"Content-Type": JSON_CONTENT_TYPE,
                       **(identity or {})}
        else:
            body_out = request.SerializeToString()
            headers = {"Content-Type": PROTO_CONTENT_TYPE,
                       **(identity or {})}
        async with session.post(
            url,
            data=body_out,
            headers=tracing.inject_current(headers),
            timeout=self.timeout_s,
        ) as resp:
            body = await resp.read()
            if resp.status != 200:
                raise UnitCallError(
                    ep.service_host, method, body.decode("utf-8", "replace"),
                    resp.status,
                )
            ctype = resp.headers.get("Content-Type", "")
            try:
                if ctype.startswith(PROTO_CONTENT_TYPE):
                    return response_cls.FromString(body)
                return payloads.dict_to_message(body.decode(), response_cls)
            except Exception as e:
                # A 200 with an unparseable body (buggy foreign unit) is
                # a unit failure, not an engine crash — callers promise
                # ENGINE_UNIT_FAILURE semantics (docs/wrappers.md §2).
                raise UnitCallError(
                    ep.service_host, method,
                    f"unparseable {ctype or 'response'} body: {e}",
                ) from e
