"""Async internal client: engine -> unit microservice calls.

Reference: engine/.../service/InternalPredictionService.java:191-472 (REST
RestTemplate pool + gRPC cached channels, per-call deadlines, N retries on
connection failure) and grpc/GrpcChannelHandler.java (channel cache).

TPU-native: grpc.aio and aiohttp on one event loop; REST carries binary
proto (`application/x-protobuf`) by default — the dense-tensor fast path —
falling back to reference-style JSON only if a unit demands it."""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, List, Optional

import grpc
import grpc.aio

from seldon_tpu.core import payloads, tracing
from seldon_tpu.orchestrator.spec import Endpoint, EndpointType, PredictiveUnit
from seldon_tpu.proto import prediction_grpc
from seldon_tpu.proto import prediction_pb2 as pb

logger = logging.getLogger(__name__)

from seldon_tpu.core.http import (  # noqa: F401 (shared constants)
    JSON_CONTENT_TYPE,
    PROTO_CONTENT_TYPE,
    to_json_bytes,
)

# engine-side call name -> (service, rpc) — typed per-unit stubs mirroring
# the reference (InternalPredictionService.java:269-306).
_GRPC_METHODS = {
    "predict": ("Model", "Predict"),
    "transform_input": ("Generic", "TransformInput"),
    "transform_output": ("Generic", "TransformOutput"),
    "route": ("Router", "Route"),
    "aggregate": ("Combiner", "Aggregate"),
    "send_feedback": ("Generic", "SendFeedback"),
}

_REST_PATHS = {
    "predict": "/predict",
    "transform_input": "/transform-input",
    "transform_output": "/transform-output",
    "route": "/route",
    "aggregate": "/aggregate",
    "send_feedback": "/send-feedback",
}


def identity_headers(unit: PredictiveUnit) -> Dict[str, str]:
    """Engine -> unit hop-identity headers, the reference's
    `Seldon-model-name/image/version` contract
    (InternalPredictionService.java:191-370): downstream logging/tracing
    systems recover WHICH unit (and which image build) served each hop
    without parsing the graph. Keys are lowercase so the same dict is
    valid gRPC metadata (gRPC requires lowercase ASCII keys; HTTP headers
    are case-insensitive)."""
    image, sep, version = (unit.image or "").rpartition(":")
    # A tag colon always follows the last '/': "localhost:5000/img" is an
    # UNtagged image on a port-qualified registry, and "img@sha256:..." is
    # a digest ref — in both, the suffix after ':' contains no tag.
    if not sep or "/" in version or "@" in image:
        image, version = (unit.image or ""), ""
    return {
        "seldon-model-name": unit.name,
        "seldon-model-image": image,
        "seldon-model-version": version,
    }


class UnitCallError(Exception):
    def __init__(self, unit: str, method: str, detail: str, status: int = 500):
        super().__init__(f"{unit}.{method}: {detail}")
        self.unit = unit
        self.method = method
        self.detail = detail
        self.status = status


class InternalClient:
    """Cached-channel async client for unit calls."""

    def __init__(
        self,
        timeout_s: float = 30.0,
        retries: int = 3,
        max_message_bytes: int = 512 * 1024 * 1024,
    ):
        self.timeout_s = timeout_s
        self.retries = retries
        self._options = [
            ("grpc.max_send_message_length", max_message_bytes),
            ("grpc.max_receive_message_length", max_message_bytes),
        ]
        self._channels: Dict[str, grpc.aio.Channel] = {}
        self._http = None  # lazy aiohttp session
        # Per-call construction of stubs, identity dicts, and metadata
        # tuples showed up in the async hot-path profile (a stub __init__
        # builds a multicallable per RPC method); everything static per
        # (endpoint, method) or per unit is cached here.
        self._rpcs: Dict[tuple, object] = {}
        self._unit_metadata: Dict[str, tuple] = {}
        self._rest_static: Dict[tuple, tuple] = {}
        # Framed-proto fast-lane state (runtime/fastpath.py), shared by
        # the async and sync variants: endpoints that refused or
        # repeatedly failed the lane fall back to gRPC until a retry-
        # after deadline (a TIMED write-off, not permanent: a refused
        # connect during a unit's restart window must not demote the
        # lane for the process lifetime; re-probing costs one failed
        # connect per minute).
        self._fast_dead: Dict[tuple, float] = {}  # key -> retry-after ts
        self._fast_errs: Dict[tuple, int] = {}
        self._afast = None  # lazy AsyncFastClient

    # --- transport plumbing -------------------------------------------------

    def _channel(self, endpoint: Endpoint) -> grpc.aio.Channel:
        addr = f"{endpoint.service_host}:{endpoint.service_port}"
        ch = self._channels.get(addr)
        if ch is None:
            ch = grpc.aio.insecure_channel(addr, options=self._options)
            self._channels[addr] = ch
        return ch

    async def _http_session(self):
        if self._http is None:
            import aiohttp

            self._http = aiohttp.ClientSession()
        return self._http

    async def close(self):
        for ch in self._channels.values():
            await ch.close()
        self._channels.clear()
        self._rpcs.clear()  # bound to the closed channels
        if self._http is not None:
            await self._http.close()
            self._http = None
        if self._afast is not None:
            await self._afast.close()
            self._afast = None

    # --- calls --------------------------------------------------------------

    async def call(
        self,
        unit: PredictiveUnit,
        method: str,
        request,
        response_cls=pb.SeldonMessage,
    ):
        """Invoke `method` on the unit's microservice with retries."""
        ep = unit.endpoint or Endpoint()
        last_err: Optional[Exception] = None
        identity = self._identity_metadata(unit)
        for attempt in range(self.retries + 1):
            try:
                if ep.type == EndpointType.GRPC:
                    return await self._call_grpc(ep, method, request, identity)
                return await self._call_rest(
                    ep, method, request, response_cls, identity
                )
            except (grpc.RpcError, OSError, asyncio.TimeoutError) as e:
                # grpc.aio.AioRpcError (async lane) and the sync lane's
                # _InactiveRpcError are both grpc.RpcError with .code().
                last_err = e
                # Only connection-level failures retry (reference retries on
                # connect failure only, InternalPredictionService.java:413-467)
                # — NOT timeouts: the unit may already be doing the work, and
                # retrying a slow call duplicates it.
                if isinstance(e, grpc.RpcError):
                    retryable = e.code() == grpc.StatusCode.UNAVAILABLE
                else:
                    import aiohttp

                    # ConnectionError covers the fast lane's framed
                    # transport failures (stale persistent socket).
                    retryable = isinstance(
                        e, (ConnectionError, BrokenPipeError,
                            aiohttp.ClientConnectorError)
                    )
                if not retryable:
                    break
                if attempt < self.retries:
                    await self._backoff(attempt)
        detail = str(last_err)
        if isinstance(last_err, grpc.RpcError):
            detail = f"{last_err.code().name}: {last_err.details()}"
        raise UnitCallError(unit.name, method, detail)

    async def _backoff(self, attempt: int) -> None:
        await asyncio.sleep(0.05 * (attempt + 1))

    def _rpc(self, ep: Endpoint, method: str):
        """Bound multicallable for (endpoint, method) — cached: stub
        construction builds one multicallable per RPC of the service."""
        addr = f"{ep.service_host}:{ep.service_port}"
        key = (addr, method)
        rpc = self._rpcs.get(key)
        if rpc is None:
            service, rpc_name = _GRPC_METHODS[method]
            stub = prediction_grpc.STUBS[service](self._channel(ep))
            rpc = getattr(stub, rpc_name)
            self._rpcs[key] = rpc
        return rpc

    _FAST_RETRY_AFTER_S = 60.0

    def _fast_usable(self, ep: Endpoint) -> bool:
        """Fast lane applies when the endpoint declares it, it isn't in
        a write-off window, and the request is untraced (the frame
        carries no metadata — traced requests ride full gRPC so
        traceparent + identity headers reach the unit)."""
        if not ep.fast_port or tracing._current_span.get() is not None:
            return False
        import time

        deadline = self._fast_dead.get((ep.service_host, ep.fast_port))
        if deadline is not None:
            if time.monotonic() < deadline:
                return False
            # pop, not del: sync-lane worker threads share this client
            # unlocked and may race past the same expired window.
            self._fast_dead.pop((ep.service_host, ep.fast_port), None)
        return True

    def _fast_fail(self, ep: Endpoint, refused: bool) -> None:
        import time

        key = (ep.service_host, ep.fast_port)
        if refused:
            self._fast_dead[key] = time.monotonic() + self._FAST_RETRY_AFTER_S
            logger.warning(
                "fastPort %d refused on %s — gRPC for the next %.0fs",
                ep.fast_port, ep.service_host, self._FAST_RETRY_AFTER_S,
            )
            return
        n = self._fast_errs.get(key, 0) + 1
        self._fast_errs[key] = n
        if n >= 3:
            # e.g. the port is actually some OTHER server that accepts
            # and then drops the framed bytes: connect never refuses, so
            # repeated transport failures are the write-off signal.
            self._fast_dead[key] = time.monotonic() + self._FAST_RETRY_AFTER_S
            self._fast_errs.pop(key, None)
            logger.warning(
                "fastPort %d failed %d consecutive transports on %s — "
                "gRPC for the next %.0fs",
                ep.fast_port, n, ep.service_host, self._FAST_RETRY_AFTER_S,
            )

    async def _fast_transport(self, ep: Endpoint, method: str, request):
        """The lane's transport call — the ONLY thing the sync variant
        overrides; error policy lives once in _fast_attempt."""
        if self._afast is None:
            from seldon_tpu.runtime.fastpath import AsyncFastClient

            self._afast = AsyncFastClient(timeout_s=self.timeout_s)
        return await self._afast.call(
            ep.service_host, ep.fast_port, method, request
        )

    async def _fast_attempt(self, ep: Endpoint, method: str, request,
                            identity: tuple):
        """One fast-lane attempt. Returns (handled, out); handled False
        means fall through to gRPC for this call. Error policy:
        - framed unit error -> UnitCallError (attributed to the unit)
        - refused connect -> gRPC fallback for the write-off window
          (_FAST_RETRY_AFTER_S), handled False
        - stale pooled connection died -> retryable, NOT counted toward
          the write-off (the unit just restarted; a fresh connect works)
        - timeout -> not retried, not counted (slow unit, healthy lane)
        - fresh-connection transport failure -> counted; 3 in a row
          start a write-off window."""
        from seldon_tpu.runtime.fastpath import StaleConnection

        try:
            out = await self._fast_transport(ep, method, request)
            self._fast_errs.pop((ep.service_host, ep.fast_port), None)
            return True, out
        except RuntimeError as e:
            raise UnitCallError(
                _unit_name_of(identity, ep), method, str(e)
            ) from e
        except ConnectionRefusedError:
            self._fast_fail(ep, refused=True)
            return False, None
        except StaleConnection:
            raise  # retryable in call(); reconnects on the next attempt
        except TimeoutError:
            raise  # slow unit, not a broken lane: no write-off count
        except (ConnectionError, OSError):
            self._fast_fail(ep, refused=False)
            raise  # retryable in call(); next attempt may fall back

    async def _call_grpc(self, ep: Endpoint, method: str, request,
                         identity: tuple = ()):
        if self._fast_usable(ep):
            handled, out = await self._fast_attempt(
                ep, method, request, identity
            )
            if handled:
                return out
        rpc = self._rpc(ep, method)
        cur = tracing._current_span.get()
        if cur is None:  # tracing off: the static per-unit tuple as-is
            metadata = identity or None
        else:
            d = dict(identity)
            d[tracing._TRACEPARENT] = cur.context.to_traceparent()
            metadata = tuple(d.items())
        return await rpc(request, timeout=self.timeout_s, metadata=metadata)

    def _rest_parts(self, ep: Endpoint, method: str, identity: tuple):
        # identity is in the key: two units may share one endpoint, and
        # each hop must carry ITS unit's seldon-model-* headers.
        key = (ep.service_host, ep.service_port, method, ep.content,
               identity)
        parts = self._rest_static.get(key)
        if parts is None:
            url = (f"http://{ep.service_host}:{ep.service_port}"
                   f"{_REST_PATHS[method]}")
            ctype = (JSON_CONTENT_TYPE if ep.content == "json"
                     else PROTO_CONTENT_TYPE)
            headers = {"Content-Type": ctype, **dict(identity)}
            parts = (url, headers)
            self._rest_static[key] = parts
        return parts

    def _identity_metadata(self, unit: PredictiveUnit) -> tuple:
        md = self._unit_metadata.get(unit.name)
        if md is None:
            md = tuple(identity_headers(unit).items())
            self._unit_metadata[unit.name] = md
        return md

    async def _call_rest(self, ep: Endpoint, method: str, request,
                         response_cls, identity: tuple = ()):
        session = await self._http_session()
        url, headers = self._rest_parts(ep, method, identity)
        if ep.content == "json":
            # Foreign-language units (docs/wrappers.md) speak JSON; our
            # own units prefer the binary-proto body (zero-copy dense).
            body_out = to_json_bytes(request)
        else:
            body_out = request.SerializeToString()
        if tracing._current_span.get() is not None:
            headers = tracing.inject_current(dict(headers))
        async with session.post(
            url,
            data=body_out,
            headers=headers,
            timeout=self.timeout_s,
        ) as resp:
            body = await resp.read()
            if resp.status != 200:
                raise UnitCallError(
                    ep.service_host, method, body.decode("utf-8", "replace"),
                    resp.status,
                )
            ctype = resp.headers.get("Content-Type", "")
            try:
                if ctype.startswith(PROTO_CONTENT_TYPE):
                    return response_cls.FromString(body)
                return payloads.dict_to_message(body.decode(), response_cls)
            except Exception as e:
                # A 200 with an unparseable body (buggy foreign unit) is
                # a unit failure, not an engine crash — callers promise
                # ENGINE_UNIT_FAILURE semantics (docs/wrappers.md §2).
                raise UnitCallError(
                    ep.service_host, method,
                    f"unparseable {ctype or 'response'} body: {e}",
                ) from e


def _unit_name_of(identity: tuple, ep: Endpoint) -> str:
    """Unit name from the cached identity metadata (seldon-model-name);
    the endpoint host only as a last resort — failures must attribute to
    the UNIT, consistently across lanes."""
    for k, v in identity:
        if k == "seldon-model-name" and v:
            return v
    return ep.service_host


class SyncInternalClient(InternalClient):
    """BLOCKING gRPC variant for the sync servicer lane.

    The async walker code runs unchanged: these overrides are `async def`
    that complete without ever suspending (the blocking happens inside the
    call, on the gRPC worker thread), so `PredictorEngine.drive_sync` can
    drive a graph walk that leaves the process — the whole request then
    rides C-level completion queues (sync gRPC server + sync stubs) with
    no event loop anywhere on the hot path. Measured ~2x requests per
    engine core vs the asyncio lane on linear graphs; graphs that need
    fan-out parallelism (multi-child COMBINER) or REST/batched units stay
    on the async lane (see PredictorEngine.sync_drivable).
    """

    is_sync = True

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        from seldon_tpu.runtime.fastpath import FastClient

        self._fast = FastClient(timeout_s=self.timeout_s)

    def _channel(self, endpoint: Endpoint):
        addr = f"{endpoint.service_host}:{endpoint.service_port}"
        ch = self._channels.get(addr)
        if ch is None:
            ch = grpc.insecure_channel(addr, options=self._options)
            self._channels[addr] = ch
        return ch

    async def _fast_transport(self, ep: Endpoint, method: str, request):
        # Blocking (never-suspending) variant: per-thread persistent
        # sockets; error policy is the shared _fast_attempt.
        return self._fast.call(
            ep.service_host, ep.fast_port, method, request
        )

    async def _call_grpc(self, ep: Endpoint, method: str, request,
                         identity: tuple = ()):
        if self._fast_usable(ep):
            # awaiting _fast_attempt completes without suspending: the
            # overridden transport blocks instead of yielding.
            handled, out = await self._fast_attempt(
                ep, method, request, identity
            )
            if handled:
                return out
        rpc = self._rpc(ep, method)
        cur = tracing._current_span.get()
        if cur is None:
            metadata = identity or None
        else:
            d = dict(identity)
            d[tracing._TRACEPARENT] = cur.context.to_traceparent()
            metadata = tuple(d.items())
        return rpc(request, timeout=self.timeout_s, metadata=metadata)

    async def _backoff(self, attempt: int) -> None:
        import time

        time.sleep(0.05 * (attempt + 1))  # worker thread, not the loop

    async def _call_rest(self, ep: Endpoint, method: str, request,
                         response_cls, identity: tuple = ()):
        raise UnitCallError(
            _unit_name_of(identity, ep), method,
            "REST unit on the sync lane (sync_drivable should have "
            "excluded this graph)",
        )

    async def close(self):
        for ch in self._channels.values():
            ch.close()  # sync channels: close() is not awaitable
        self._channels.clear()
        self._rpcs.clear()
        self._fast.close()
