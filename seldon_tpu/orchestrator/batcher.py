"""Dynamic micro-batcher for MODEL leaf calls.

No reference equivalent — SURVEY.md §7 names this the key new hot-loop
component: the reference engine forwards each request alone, so a TPU leaf
would see batch-1 matmuls (MXU utilization ~0). Here, concurrent in-flight
requests to the same unit fuse along axis 0 into one leaf call within a
small time window, and the response splits back per request (BatchIndex
framing in the proto records the fusion for tracing).

Safety: only `data` payloads (dense/tensor/ndarray) with identical trailing
shapes and dtypes fuse; anything else falls through to a direct call."""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, List, Optional, Tuple

import numpy as np

from seldon_tpu.core import payloads
from seldon_tpu.orchestrator.spec import PredictiveUnit
from seldon_tpu.proto import prediction_pb2 as pb

logger = logging.getLogger(__name__)


class _Pending:
    __slots__ = ("msg", "arr", "future", "puid", "kind", "tag_sig")

    def __init__(self, msg, arr, future, puid, kind):
        self.msg = msg
        self.arr = arr
        self.future = future
        self.puid = puid
        self.kind = kind
        # Canonical request-tag fingerprint: only requests with IDENTICAL
        # tags co-batch, so the fused request can carry those tags and the
        # unit sees exactly what it would on the direct (unbatched) path.
        self.tag_sig = tuple(sorted(
            (k, v.SerializeToString(deterministic=True))
            for k, v in msg.meta.tags.items()
        ))


class MicroBatcher:
    def __init__(
        self,
        max_batch_size: int = 32,
        window_ms: float = 2.0,
        max_queue: int = 1024,
    ):
        self.max_batch_size = max_batch_size
        self.window_s = window_ms / 1000.0
        self.max_queue = max_queue
        # unit name -> (signature, pending list, flush handle)
        self._queues: Dict[str, List[_Pending]] = {}
        self._timers: Dict[str, asyncio.TimerHandle] = {}
        self._locks: Dict[str, asyncio.Lock] = {}
        self.stats = {"fused_calls": 0, "direct_calls": 0,
                      "batched_requests": 0, "tag_flushes": 0}

    @staticmethod
    def _batchable(msg: pb.SeldonMessage) -> Optional[np.ndarray]:
        if msg.WhichOneof("data_oneof") != "data":
            return None
        # A request already carrying a batch_index tag (nested/upstream
        # batching) must not fuse: the framing key would collide.
        if "batch_index" in msg.meta.tags:
            return None
        arr = payloads.data_to_array(msg.data)
        # ndim >= 2 required: a 1-D array is one sample's feature vector,
        # not a row batch — concatenating those would corrupt semantics.
        if not isinstance(arr, np.ndarray) or arr.ndim < 2 or arr.dtype.kind not in "fiub":
            return None
        return arr

    def _lock(self, name: str) -> asyncio.Lock:
        if name not in self._locks:
            self._locks[name] = asyncio.Lock()
        return self._locks[name]

    async def call(self, unit: PredictiveUnit, msg: pb.SeldonMessage, client):
        arr = self._batchable(msg)
        if arr is None:
            self.stats["direct_calls"] += 1
            return await client.call(unit, "predict", msg)

        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        pend = _Pending(
            msg, arr, fut, msg.meta.puid,
            payloads.data_kind(msg) or "dense",
        )
        to_exec: List[List[_Pending]] = []
        async with self._lock(unit.name):
            q = self._queues.setdefault(unit.name, [])
            if q and (
                q[0].arr.shape[1:] != arr.shape[1:]
                or q[0].arr.dtype != arr.dtype
                or q[0].tag_sig != pend.tag_sig
            ):
                # Shape/dtype/tag mismatch with the open batch: flush it
                # first. tag_flushes makes tag-driven batching collapse
                # observable (a per-request-unique upstream tag silently
                # degrades every leaf call to batch-1 otherwise).
                if q[0].tag_sig != pend.tag_sig:
                    self.stats["tag_flushes"] += 1
                to_exec.append(self._take(unit.name))
                q = self._queues.setdefault(unit.name, [])
            q.append(pend)
            n_rows = sum(p.arr.shape[0] for p in q)
            if n_rows >= self.max_batch_size or len(q) >= self.max_queue:
                to_exec.append(self._take(unit.name))
            elif len(q) == 1:
                self._timers[unit.name] = loop.call_later(
                    self.window_s,
                    lambda: asyncio.ensure_future(
                        self._timer_flush(unit, client)
                    ),
                )
        for batch in to_exec:
            # Execute OUTSIDE the lock so new submitters keep queueing.
            await self._execute(unit, batch, client)
        return await fut

    def _take(self, name: str) -> List[_Pending]:
        """Pop the open batch; caller must hold the unit lock."""
        q = self._queues.pop(name, [])
        timer = self._timers.pop(name, None)
        if timer is not None:
            timer.cancel()
        return q

    async def _timer_flush(self, unit: PredictiveUnit, client):
        async with self._lock(unit.name):
            q = self._take(unit.name)
        if q:
            await self._execute(unit, q, client)

    async def _execute(self, unit: PredictiveUnit, q: List[_Pending], client):
        if not q:
            return
        if len(q) == 1:
            p = q[0]
            self.stats["direct_calls"] += 1
            try:
                resp = await client.call(unit, "predict", p.msg)
                if not p.future.done():
                    p.future.set_result(resp)
            except Exception as e:
                if not p.future.done():
                    p.future.set_exception(e)
            return

        from seldon_tpu import native

        fused = native.fuse_rows([p.arr for p in q])
        kind = q[0].kind
        req = payloads.build_message(fused, kind=kind)
        req.meta.puid = q[0].puid or "fused"
        # Co-batched requests are guaranteed (by tag_sig grouping) to carry
        # IDENTICAL tags, so forwarding q[0]'s tags gives the unit the same
        # view as the direct path, and nothing cross-request can leak: any
        # tag echoed back belongs to every requester in the batch equally.
        for k, v in q[0].msg.meta.tags.items():
            req.meta.tags[k].CopyFrom(v)
        bi = pb.BatchIndex(
            puids=[p.puid for p in q],
            row_counts=[p.arr.shape[0] for p in q],
        )
        req.meta.tags["batch_index"].string_value = bi.SerializeToString().hex()
        self.stats["fused_calls"] += 1
        self.stats["batched_requests"] += len(q)
        try:
            resp = await client.call(unit, "predict", req)
            out = payloads.get_data_from_message(resp)
            if not isinstance(out, np.ndarray) or out.shape[0] != fused.shape[0]:
                raise ValueError(
                    f"batched response rows {getattr(out, 'shape', None)} "
                    f"!= request rows {fused.shape[0]}"
                )
            names = list(resp.data.names) if resp.HasField("data") else None
            # Non-numeric unit output (e.g. string class labels) can't ride
            # the dense/tensor encodings — fall back to ndarray for all
            # splits, matching construct_response's direct-path gate (kind
            # in "USO"; note bfloat16 has dtype.kind 'V' and IS numeric).
            numeric = out.dtype.kind not in "USO"
            row = 0
            for p in q:
                n = p.arr.shape[0]
                # Each request's reply uses ITS OWN payload kind, so the
                # wire encoding never depends on co-batched traffic.
                sub = payloads.build_message(
                    out[row: row + n], names=names,
                    kind=p.kind if numeric else "ndarray",
                )
                sub.meta.CopyFrom(resp.meta)
                sub.meta.puid = p.puid
                if "batch_index" in sub.meta.tags:
                    del sub.meta.tags["batch_index"]
                row += n
                if not p.future.done():
                    p.future.set_result(sub)
        except Exception as e:
            for p in q:
                if not p.future.done():
                    p.future.set_exception(e)
