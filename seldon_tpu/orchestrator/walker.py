"""The graph walk — engine hot loop.

Reference algorithm (engine/.../predictors/PredictiveUnitBean.java:106-199,
the forward path; :201-246 the feedback mirror):

  1. record requestPath[unit] = image
  2. transformInput (== predict for MODEL units)
  3. leaf -> return
  4. route -> branch index (-1 = broadcast to all children)
  5. fan out children (async)
  6. aggregate children outputs (COMBINER)
  7. transformOutput (OUTPUT_TRANSFORMER)
  merging Meta tags/puid at each hop, accumulating routing{} and metrics.

Redesign: one asyncio task tree instead of Spring @Async thread pools;
per-request context object accumulates meta (the reference threads
ConcurrentHashMaps through the recursion); MODEL leaf calls can flow
through the dynamic micro-batcher (batcher.py) — reference has none."""

from __future__ import annotations

import asyncio
import base64
import logging
import secrets
import time
from typing import Dict, List, Optional

from google.protobuf import json_format

from seldon_tpu.core import payloads, tracing
from seldon_tpu.orchestrator.client import (
    InternalClient,
    UnitCallError,
    identity_headers,
)
from seldon_tpu.orchestrator.spec import (
    HARDCODED_IMPLEMENTATIONS,
    EndpointType,
    PredictiveUnit,
    PredictorSpec,
    UnitType,
)
from seldon_tpu.orchestrator.units import make_hardcoded
from seldon_tpu.proto import prediction_pb2 as pb

logger = logging.getLogger(__name__)


def make_puid() -> str:
    """Random request id (reference: SecureRandom base32,
    PredictionService.java:80-92)."""
    return base64.b32encode(secrets.token_bytes(15)).decode().lower()


class _RequestCtx:
    """Per-request accumulators threaded through the walk (reference
    PredictiveUnitBean.java:74-76 ConcurrentHashMaps)."""

    def __init__(self, puid: str):
        self.puid = puid
        self.tags: Dict[str, object] = {}
        self.routing: Dict[str, int] = {}
        self.request_path: Dict[str, str] = {}
        self.metrics: List[pb.Metric] = []
        self.lock = asyncio.Lock()

    async def merge_response_meta(self, meta: pb.Meta) -> None:
        async with self.lock:
            for k, v in meta.tags.items():
                self.tags[k] = v
            self.metrics.extend(meta.metrics)

    def stamp(self, meta: pb.Meta) -> None:
        meta.puid = self.puid
        for k, v in self.tags.items():
            if isinstance(v, type(meta.tags[k])):
                meta.tags[k].CopyFrom(v)
            else:
                json_format.ParseDict(v, meta.tags[k])
        for k, i in self.routing.items():
            meta.routing[k] = i
        for k, v in self.request_path.items():
            meta.requestPath[k] = v
        for m in self.metrics:
            meta.metrics.add().CopyFrom(m)


class PredictorEngine:
    """Walks one PredictorSpec graph."""

    def __init__(
        self,
        spec: PredictorSpec,
        client: Optional[InternalClient] = None,
        batcher=None,
        metrics_hook=None,
        reward_hook=None,
        tracer: Optional[tracing.Tracer] = None,
    ):
        self.spec = spec
        self.client = client or InternalClient()
        self.batcher = batcher
        self.metrics_hook = metrics_hook  # callable(metric: pb.Metric, unit)
        self.reward_hook = reward_hook  # callable(unit, reward: float)
        self.tracer = tracer or tracing.get_tracer("engine")
        self._hardcoded = {
            u.name: make_hardcoded(u.implementation, u.parameters)
            for u in spec.graph.walk()
            if u.implementation in HARDCODED_IMPLEMENTATIONS
        }
        # Per-unit span name + attributes are static per spec: building
        # the f-string + identity dict per request showed up in the hot
        # path profile even with tracing disabled.
        self._all_hardcoded = self.batcher is None and all(
            u.name in self._hardcoded for u in spec.graph.walk()
        )
        # A walk never suspends when every unit is in-process OR the
        # client itself blocks (SyncInternalClient) — fan-outs then run
        # sequentially instead of as gathered tasks.
        self._sequential = self._all_hardcoded or bool(
            getattr(self.client, "is_sync", False)
        )
        self._span_info = {
            u.name: (
                f"unit.{u.name}",
                {"unit_type": str(u.type), **identity_headers(u)},
            )
            for u in spec.graph.walk()
        }
        # Solo-MODEL fast walk: the single most common deployed graph is
        # one network MODEL unit; its full walk is ~10 coroutine frames +
        # a ctx object per request. predict_sync collapses that to one
        # driven client call with identical meta semantics.
        g = spec.graph
        self._solo_unit = (
            g if (self.batcher is None and not g.children
                  and g.type == UnitType.MODEL
                  and g.name not in self._hardcoded)
            else None
        )

    @property
    def all_hardcoded(self) -> bool:
        """True when every unit runs in-process (no network hops) and no
        micro-batcher is interposed — the graph walk then never suspends,
        so predict()/send_feedback() coroutines can be driven to
        completion without an event loop (predict_sync). Cached at
        __init__ (spec/batcher/_hardcoded are fixed): it's read per
        fan-out node per request on the serving hot path."""
        return self._all_hardcoded

    @staticmethod
    def sync_drivable(spec: PredictorSpec, batcher=None) -> bool:
        """True when an engine built over `spec` with a BLOCKING gRPC
        client (SyncInternalClient) can serve the sync thread-pool lane:
        no micro-batcher (its fuse-wait must suspend), no REST-endpoint
        unit (the blocking client only speaks gRPC), and no COMBINER
        fan-out over network subtrees — a combiner calls ALL children
        per request and wants the async lane's PARALLEL gather (three
        200 ms units must cost ~200 ms, not ~600 ms). ROUTER graphs stay
        sync-drivable: a routed request walks exactly one branch (a rare
        broadcast route of -1 runs its branches sequentially)."""
        if batcher is not None:
            return False
        for u in spec.graph.walk():
            if (u.type == UnitType.COMBINER and len(u.children) > 1
                    and any(
                        x.implementation not in HARDCODED_IMPLEMENTATIONS
                        for c in u.children for x in c.walk()
                    )):
                return False
            if u.implementation in HARDCODED_IMPLEMENTATIONS:
                continue
            ep = u.endpoint
            if ep is not None and ep.type != EndpointType.GRPC:
                return False
        return True

    @staticmethod
    def drive_sync(coro):
        """Run a coroutine that never actually awaits IO to completion on
        the calling thread. Raises RuntimeError if it suspends (a
        network unit sneaked into a supposedly in-process graph)."""
        try:
            coro.send(None)
        except StopIteration as e:
            return e.value
        coro.close()
        raise RuntimeError(
            "graph walk suspended: predict_sync requires an in-process "
            "(hardcoded, unbatched) graph or a blocking SyncInternalClient"
        )

    def predict_sync(self, request: pb.SeldonMessage,
                     trace_parent=None) -> pb.SeldonMessage:
        """Synchronous predict for sync-lane graphs (in-process, or over
        the blocking SyncInternalClient) — the sync gRPC servicer path
        (orchestrator/server.py) calls this from worker threads with zero
        event-loop involvement."""
        if (self._solo_unit is not None and trace_parent is None
                and not self.tracer.enabled):
            return self._predict_solo(request)
        return self.drive_sync(self.predict(request, trace_parent))

    def _predict_solo(self, request: pb.SeldonMessage) -> pb.SeldonMessage:
        """One-network-MODEL fast walk. Produces byte-identical meta to
        the generic walk: the unit's own tags/metrics survive (absorb +
        stamp round-trips them), any routing/requestPath a unit tried to
        inject is dropped (meta.Clear parity), puid + requestPath are
        engine-stamped."""
        unit = self._solo_unit
        puid = request.meta.puid or make_puid()
        request.meta.puid = puid  # engine owns the request (see predict)
        out = self.drive_sync(self.client.call(unit, "predict", request))
        meta = out.meta
        if self.metrics_hook is not None:
            for m in meta.metrics:
                self.metrics_hook(m, unit)
        meta.puid = puid
        meta.ClearField("routing")
        meta.ClearField("requestPath")
        meta.requestPath[unit.name] = unit.image or unit.name
        return out

    # --- forward path -------------------------------------------------------

    async def predict(
        self,
        request: pb.SeldonMessage,
        trace_parent: Optional[tracing.SpanContext] = None,
    ) -> pb.SeldonMessage:
        """Walk the graph for one request and return the merged response.

        OWNERSHIP: the engine takes ownership of `request` and stamps
        `meta.puid` on it IN PLACE (a fresh puid is minted only when the
        field is empty). Server paths hand over a per-request message, so
        this is free; a library caller reusing one SeldonMessage across
        calls must `request.meta.puid = ""` between calls or every call
        reuses the first call's puid.
        """
        puid = request.meta.puid or make_puid()
        ctx = _RequestCtx(puid)
        # The engine owns the request message (every caller — REST parse,
        # gRPC servicers — hands over a per-request object): stamping the
        # puid in place saves a full message copy per request on the hot
        # path, and the logged request then carries its puid like the
        # reference's.
        msg = request
        msg.meta.puid = puid
        with self.tracer.span(
            "engine.predict", parent=trace_parent, attributes={"puid": puid}
        ):
            out = await self._get_output(msg, self.spec.graph, ctx)
        # The engine owns every message on the walk (unit responses are
        # parsed per-call; hardcoded units build fresh ones), so the
        # response is stamped IN PLACE — the old copy-into-a-new-message
        # was a full payload copy per request on the hot path.
        out.meta.Clear()
        ctx.stamp(out.meta)
        return out

    async def _get_output(
        self, msg: pb.SeldonMessage, unit: PredictiveUnit, ctx: _RequestCtx
    ) -> pb.SeldonMessage:
        ctx.request_path[unit.name] = unit.image or unit.name
        hard = self._hardcoded.get(unit.name)
        if not self.tracer.enabled:
            # Zero-allocation disabled path: no span-info tuple unpack,
            # no context-manager entry (even the shared noop CM costs a
            # __enter__/__exit__ pair per unit per request).
            return await self._walk_unit(msg, unit, hard, ctx)
        span_name, span_attrs = self._span_info[unit.name]
        with self.tracer.span(span_name, attributes=span_attrs):
            return await self._walk_unit(msg, unit, hard, ctx)

    async def _walk_unit(
        self, msg: pb.SeldonMessage, unit: PredictiveUnit, hard, ctx
    ) -> pb.SeldonMessage:

        # (2) transformInput / predict
        transformed = await self._transform_input(msg, unit, hard, ctx)

        # (3) leaf
        if not unit.children:
            return transformed

        # (4) route
        branch = await self._route(transformed, unit, hard, ctx)

        # (5) children fan-out
        if branch == -1:
            selected = unit.children
        else:
            if not 0 <= branch < len(unit.children):
                # -1 is the only legal sentinel; other negatives would hit
                # Python negative indexing and silently pick a wrong child.
                raise UnitCallError(
                    unit.name, "route",
                    f"branch {branch} out of range ({len(unit.children)} children)",
                )
            selected = [unit.children[branch]]
        if len(selected) == 1:
            # Direct await: no task/future churn for the common
            # single-branch case (routers, chains).
            child_outputs = [
                await self._get_output(transformed, selected[0], ctx)
            ]
        elif self._sequential:
            # In-process graph, or a blocking (sync-lane) client: awaits
            # complete without suspending either way, so sequential
            # iteration keeps the whole predict() coroutine synchronously
            # drivable (predict_sync) with identical results.
            child_outputs = [
                await self._get_output(transformed, c, ctx) for c in selected
            ]
        else:
            child_outputs = await asyncio.gather(
                *(self._get_output(transformed, c, ctx) for c in selected)
            )

        # (6) aggregate
        merged = await self._aggregate(list(child_outputs), unit, hard, ctx)

        # (7) transformOutput
        return await self._transform_output(merged, unit, hard, ctx)

    async def _transform_input(
        self, msg, unit: PredictiveUnit, hard, ctx
    ) -> pb.SeldonMessage:
        if unit.type == UnitType.MODEL:
            if hard is not None:
                out = hard.transform_input(msg)
            elif self.batcher is not None:
                out = await self.batcher.call(unit, msg, self.client)
            else:
                out = await self.client.call(unit, "predict", msg)
        elif unit.type == UnitType.TRANSFORMER:
            if hard is not None:
                out = hard.transform_input(msg)
            else:
                out = await self.client.call(unit, "transform_input", msg)
        else:
            return msg
        await self._absorb(out, unit, ctx)
        return out

    async def _route(self, msg, unit: PredictiveUnit, hard, ctx) -> int:
        if unit.type != UnitType.ROUTER:
            return -1
        if hard is not None:
            branch = hard.route(msg, len(unit.children))
        else:
            resp = await self.client.call(unit, "route", msg)
            branch = _extract_route(resp)
            await self._absorb(resp, unit, ctx)
        async with ctx.lock:
            ctx.routing[unit.name] = branch
        return branch

    async def _aggregate(
        self, outputs: List[pb.SeldonMessage], unit: PredictiveUnit, hard, ctx
    ) -> pb.SeldonMessage:
        if unit.type == UnitType.COMBINER:
            if hard is not None:
                out = hard.aggregate(outputs)
            else:
                req = pb.SeldonMessageList()
                req.seldonMessages.extend(outputs)
                out = await self.client.call(unit, "aggregate", req)
            await self._absorb(out, unit, ctx)
            return out
        if len(outputs) == 1:
            return outputs[0]
        raise UnitCallError(
            unit.name, "aggregate",
            f"{len(outputs)} child outputs but unit is not a COMBINER",
        )

    async def _transform_output(self, msg, unit: PredictiveUnit, hard, ctx):
        if unit.type != UnitType.OUTPUT_TRANSFORMER:
            return msg
        if hard is not None:
            out = hard.transform_output(msg)
        else:
            out = await self.client.call(unit, "transform_output", msg)
        await self._absorb(out, unit, ctx)
        return out

    async def _absorb(self, out: pb.SeldonMessage, unit: PredictiveUnit, ctx):
        """Merge a unit response's meta into the request context; surface
        custom metrics (reference PredictiveUnitBean.java:334-357)."""
        await ctx.merge_response_meta(out.meta)
        if self.metrics_hook is not None:
            for m in out.meta.metrics:
                self.metrics_hook(m, unit)

    # --- feedback mirror ----------------------------------------------------

    async def send_feedback(self, feedback: pb.Feedback) -> pb.SeldonMessage:
        """Follows stored meta.routing down the tree (reference
        PredictiveUnitBean.java:206-246)."""
        await self._send_feedback(feedback, self.spec.graph)
        resp = pb.SeldonMessage()
        resp.meta.puid = feedback.response.meta.puid or make_puid()
        return resp

    async def _send_feedback(self, feedback: pb.Feedback, unit: PredictiveUnit):
        hard = self._hardcoded.get(unit.name)
        if unit.type in (UnitType.MODEL, UnitType.ROUTER):
            if hard is not None:
                hard.send_feedback(feedback)
            else:
                try:
                    await self.client.call(unit, "send_feedback", feedback)
                except UnitCallError:
                    logger.warning("feedback to %s failed", unit.name,
                                   exc_info=True)
            if self.reward_hook is not None:
                # A dedicated hook, NOT a fabricated custom pb.Metric: the
                # name would collide with the built-in reward counter in
                # the prometheus registry and be dropped on every
                # feedback (engine-level rewards were never recorded).
                self.reward_hook(unit, feedback.reward)
        routing = feedback.response.meta.routing
        if unit.name in routing:
            branch = routing[unit.name]
            children = (
                unit.children if branch == -1
                else [unit.children[branch]]
                if 0 <= branch < len(unit.children)
                else []
            )
        else:
            children = unit.children
        if len(children) == 1 or self._sequential:
            # Mirrors the predict-path rule: keeps the coroutine
            # synchronously drivable for in-process/sync-lane graphs (the
            # sync gRPC servicer) and skips task churn for single-branch
            # mirrors.
            for c in children:
                await self._send_feedback(feedback, c)
        elif children:
            await asyncio.gather(
                *(self._send_feedback(feedback, c) for c in children)
            )

    async def close(self):
        await self.client.close()


def _extract_route(msg: pb.SeldonMessage) -> int:
    """Routers return the branch as the first element of their data payload
    (reference RoutingUtils semantics). A malformed router response is an
    error, NOT broadcast — silently fanning out to every branch would run
    all models and mask the router bug."""
    import numpy as np

    data = payloads.get_data_from_message(msg)
    try:
        arr = np.asarray(data).ravel()
        if arr.size == 0:
            raise ValueError("empty payload")
        return int(arr[0])
    except (TypeError, ValueError) as e:
        raise UnitCallError(
            "router", "route",
            f"malformed route response ({e}); expected branch index as "
            f"first data element",
        )
