"""Inference-graph spec: PredictorSpec / PredictiveUnit.

Parity with the reference CRD graph schema
(/root/reference/proto/seldon_deployment.proto:53-133 — PredictorSpec{graph,
replicas,traffic,...}, PredictiveUnit{name,children,type,implementation,
methods,endpoint,parameters,modelUri}) in the same JSON shape the reference
engine receives via the base64 `ENGINE_PREDICTOR` env
(engine/.../predictors/EnginePredictor.java:51-101)."""

from __future__ import annotations

import base64
import dataclasses
import enum
import json
import os
from typing import Any, Dict, List, Optional


class UnitType(str, enum.Enum):
    UNKNOWN_TYPE = "UNKNOWN_TYPE"
    ROUTER = "ROUTER"
    COMBINER = "COMBINER"
    MODEL = "MODEL"
    TRANSFORMER = "TRANSFORMER"
    OUTPUT_TRANSFORMER = "OUTPUT_TRANSFORMER"


class UnitImplementation(str, enum.Enum):
    UNKNOWN_IMPLEMENTATION = "UNKNOWN_IMPLEMENTATION"
    SIMPLE_MODEL = "SIMPLE_MODEL"
    SIMPLE_ROUTER = "SIMPLE_ROUTER"
    RANDOM_ABTEST = "RANDOM_ABTEST"
    AVERAGE_COMBINER = "AVERAGE_COMBINER"
    # Prepackaged servers (materialized into containers by the operator;
    # reference operator/constants/constants.go:4-13).
    SKLEARN_SERVER = "SKLEARN_SERVER"
    XGBOOST_SERVER = "XGBOOST_SERVER"
    TENSORFLOW_SERVER = "TENSORFLOW_SERVER"
    MLFLOW_SERVER = "MLFLOW_SERVER"
    JAX_SERVER = "JAX_SERVER"  # TPU-native flagship (no reference equivalent)


class EndpointType(str, enum.Enum):
    REST = "REST"
    GRPC = "GRPC"


@dataclasses.dataclass
class Endpoint:
    service_host: str = "localhost"
    service_port: int = 9000
    type: EndpointType = EndpointType.GRPC
    # REST body encoding: "proto" (binary SeldonMessage — the TPU-native
    # zero-copy default between our own units) or "json" (the lingua
    # franca for foreign-language units, e.g. examples/wrappers/go —
    # docs/wrappers.md).
    content: str = "proto"
    # Optional framed-proto fast lane (runtime/fastpath.py): seldon-tpu
    # native units serve it on gRPC-port+1 alongside gRPC/REST; 0 =
    # absent, the engine uses `type` as usual. Sync-lane only.
    fast_port: int = 0

    @staticmethod
    def from_dict(d: Dict) -> "Endpoint":
        content = str(d.get("content", "proto")).lower()
        if content not in ("proto", "json"):
            # Fail at spec-load time like EndpointType does — a typo here
            # would otherwise surface as an opaque parse error when the
            # engine POSTs proto bytes at a JSON-only unit.
            raise ValueError(
                f"endpoint content must be 'proto' or 'json', got {content!r}"
            )
        return Endpoint(
            service_host=d.get("service_host", d.get("serviceHost", "localhost")),
            service_port=int(d.get("service_port", d.get("servicePort", 9000))),
            type=EndpointType(d.get("type", "GRPC")),
            content=content,
            fast_port=int(d.get("fast_port", d.get("fastPort", 0))),
        )

    def to_dict(self) -> Dict:
        out = {
            "service_host": self.service_host,
            "service_port": self.service_port,
            "type": self.type.value,
        }
        if self.content != "proto":
            out["content"] = self.content
        if self.fast_port:
            out["fast_port"] = self.fast_port
        return out


@dataclasses.dataclass
class Parameter:
    name: str
    value: str
    type: str = "STRING"  # STRING|INT|FLOAT|DOUBLE|BOOL

    def typed_value(self) -> Any:
        if self.type == "INT":
            return int(self.value)
        if self.type in ("FLOAT", "DOUBLE"):
            return float(self.value)
        if self.type == "BOOL":
            return str(self.value).lower() in ("1", "true", "yes")
        return self.value


@dataclasses.dataclass
class PredictiveUnit:
    name: str
    type: UnitType = UnitType.UNKNOWN_TYPE
    implementation: UnitImplementation = UnitImplementation.UNKNOWN_IMPLEMENTATION
    children: List["PredictiveUnit"] = dataclasses.field(default_factory=list)
    endpoint: Optional[Endpoint] = None
    parameters: List[Parameter] = dataclasses.field(default_factory=list)
    model_uri: str = ""
    service_account: str = ""
    # Serving image name/version recorded into meta.requestPath (reference
    # PredictiveUnitState parses it from the container spec).
    image: str = ""

    @staticmethod
    def from_dict(d: Dict) -> "PredictiveUnit":
        return PredictiveUnit(
            name=d["name"],
            type=UnitType(d.get("type", "UNKNOWN_TYPE")),
            implementation=UnitImplementation(
                d.get("implementation", "UNKNOWN_IMPLEMENTATION")
            ),
            children=[PredictiveUnit.from_dict(c) for c in d.get("children", [])],
            endpoint=Endpoint.from_dict(d["endpoint"]) if d.get("endpoint") else None,
            parameters=[
                Parameter(p["name"], str(p["value"]), p.get("type", "STRING"))
                for p in d.get("parameters", [])
            ],
            model_uri=d.get("modelUri", d.get("model_uri", "")),
            service_account=d.get("serviceAccountName", ""),
            image=d.get("image", ""),
        )

    def to_dict(self) -> Dict:
        out: Dict[str, Any] = {"name": self.name, "type": self.type.value}
        if self.implementation != UnitImplementation.UNKNOWN_IMPLEMENTATION:
            out["implementation"] = self.implementation.value
        if self.children:
            out["children"] = [c.to_dict() for c in self.children]
        if self.endpoint:
            out["endpoint"] = self.endpoint.to_dict()
        if self.parameters:
            out["parameters"] = [
                {"name": p.name, "value": p.value, "type": p.type}
                for p in self.parameters
            ]
        if self.model_uri:
            out["modelUri"] = self.model_uri
        if self.image:
            out["image"] = self.image
        return out

    def walk(self):
        yield self
        for c in self.children:
            yield from c.walk()

    def find(self, name: str) -> Optional["PredictiveUnit"]:
        for u in self.walk():
            if u.name == name:
                return u
        return None


@dataclasses.dataclass
class PredictorSpec:
    name: str
    graph: PredictiveUnit
    replicas: int = 1
    traffic: int = 100
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    annotations: Dict[str, str] = dataclasses.field(default_factory=dict)

    @staticmethod
    def from_dict(d: Dict) -> "PredictorSpec":
        return PredictorSpec(
            name=d.get("name", "default"),
            graph=PredictiveUnit.from_dict(d["graph"]),
            replicas=int(d.get("replicas", 1)),
            # 0 = unset (proto3 default); the operator webhook distributes
            # traffic across predictors at defaulting time.
            traffic=int(d.get("traffic", 0)),
            labels=dict(d.get("labels", {})),
            annotations=dict(d.get("annotations", {})),
        )

    def to_dict(self) -> Dict:
        return {
            "name": self.name,
            "graph": self.graph.to_dict(),
            "replicas": self.replicas,
            "traffic": self.traffic,
            "labels": self.labels,
            "annotations": self.annotations,
        }


# Method sets per unit type (reference PredictorConfigBean.java:30-105).
TYPE_METHODS = {
    UnitType.MODEL: ("transform_input", "send_feedback"),
    UnitType.ROUTER: ("route", "send_feedback"),
    UnitType.COMBINER: ("aggregate",),
    UnitType.TRANSFORMER: ("transform_input",),
    UnitType.OUTPUT_TRANSFORMER: ("transform_output",),
    UnitType.UNKNOWN_TYPE: (),
}

# Implementations the engine runs in-process, no microservice call
# (reference PredictorConfigBean hardcoded-bean map).
HARDCODED_IMPLEMENTATIONS = {
    UnitImplementation.SIMPLE_MODEL,
    UnitImplementation.SIMPLE_ROUTER,
    UnitImplementation.RANDOM_ABTEST,
    UnitImplementation.AVERAGE_COMBINER,
}


def default_unit_types(unit: PredictiveUnit) -> None:
    """Fill in unit types from implementations (reference webhook defaulting,
    seldondeployment_webhook.go:115-127)."""
    impl_types = {
        UnitImplementation.SIMPLE_MODEL: UnitType.MODEL,
        UnitImplementation.SIMPLE_ROUTER: UnitType.ROUTER,
        UnitImplementation.RANDOM_ABTEST: UnitType.ROUTER,
        UnitImplementation.AVERAGE_COMBINER: UnitType.COMBINER,
        UnitImplementation.SKLEARN_SERVER: UnitType.MODEL,
        UnitImplementation.XGBOOST_SERVER: UnitType.MODEL,
        UnitImplementation.TENSORFLOW_SERVER: UnitType.MODEL,
        UnitImplementation.MLFLOW_SERVER: UnitType.MODEL,
        UnitImplementation.JAX_SERVER: UnitType.MODEL,
    }
    for u in unit.walk():
        if u.type == UnitType.UNKNOWN_TYPE:
            u.type = impl_types.get(u.implementation, UnitType.MODEL)


def validate_spec(spec: PredictorSpec) -> List[str]:
    """Graph sanity checks (reference validating webhook,
    seldondeployment_webhook.go:358-424). Returns list of problems."""
    problems: List[str] = []
    names: Dict[str, int] = {}
    for u in spec.graph.walk():
        names[u.name] = names.get(u.name, 0) + 1
        if u.type == UnitType.COMBINER and not u.children:
            problems.append(f"combiner {u.name!r} has no children")
        if u.type == UnitType.ROUTER and not u.children:
            problems.append(f"router {u.name!r} has no children")
        if (
            u.implementation == UnitImplementation.UNKNOWN_IMPLEMENTATION
            and u.endpoint is None
            and u.type in (UnitType.MODEL, UnitType.TRANSFORMER,
                           UnitType.OUTPUT_TRANSFORMER, UnitType.ROUTER,
                           UnitType.COMBINER)
        ):
            problems.append(f"unit {u.name!r} has neither implementation nor endpoint")
        if u.implementation in (
            UnitImplementation.SKLEARN_SERVER,
            UnitImplementation.XGBOOST_SERVER,
            UnitImplementation.TENSORFLOW_SERVER,
            UnitImplementation.MLFLOW_SERVER,
            UnitImplementation.JAX_SERVER,
        ) and not u.model_uri:
            problems.append(f"prepackaged unit {u.name!r} requires modelUri")
    for n, c in names.items():
        if c > 1:
            problems.append(f"duplicate unit name {n!r}")
    return problems


def load_predictor_spec(
    env_var: str = "ENGINE_PREDICTOR",
    fallback_path: str = "./deploymentdef.json",
) -> PredictorSpec:
    """Reference EnginePredictor.init(): base64(JSON) env, then file, then a
    hardwired SIMPLE_MODEL spec (EnginePredictor.java:51-101,117-137)."""
    raw = os.environ.get(env_var)
    if raw:
        d = json.loads(base64.b64decode(raw).decode("utf-8"))
    elif os.path.exists(fallback_path):
        with open(fallback_path) as f:
            d = json.load(f)
    else:
        d = {
            "name": "default",
            "graph": {
                "name": "simple-model",
                "type": "MODEL",
                "implementation": "SIMPLE_MODEL",
            },
        }
    spec = PredictorSpec.from_dict(d)
    default_unit_types(spec.graph)
    problems = validate_spec(spec)
    if problems:
        raise ValueError(f"invalid predictor spec: {problems}")
    return spec
