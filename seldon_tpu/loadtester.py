"""Load tester — the reference's locust driver, TPU-build edition.

Reference: util/loadtester/scripts/predict_rest_locust.py:1-157 (+ the
master/slave helm chart). One asyncio process with N closed-loop clients
replaces the locust cluster: an event loop sustains tens of thousands of
in-flight HTTP requests, and the serving side is the bottleneck long
before the driver is.

  python -m seldon_tpu.loadtester http://host:8000 \
      --clients 64 --seconds 30 --transport rest \
      [--payload '{"data":{"ndarray":[[1.0]]}}'] [--grpc-host host:5001]

Prints one JSON line: req/s, error count, p50/p90/p99 latency — the same
shape bench_orchestrator.py reports, so numbers are directly comparable.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import sys
import time
from typing import List, Optional

import numpy as np

from seldon_tpu.core import tracing

logger = logging.getLogger(__name__)


async def _closed_loop(url_path: str, body: bytes, clients: int,
                       seconds: float, on_response=None, on_reject=None):
    """Shared closed-loop HTTP driver: N workers hammer one endpoint
    until the deadline. `on_response` (async, gets the aiohttp response)
    does transport-specific accounting; non-200s and exceptions count
    as errors and are excluded from latency. `on_reject(status)` lets a
    transport classify non-200s (429 shed vs 503 draining vs real
    failure) instead of lumping them into one error count."""
    import aiohttp

    stop_at = time.perf_counter() + seconds
    latencies: List[float] = []
    errors = [0]
    headers = {"Content-Type": "application/json"}

    async def worker(session):
        n = 0
        while time.perf_counter() < stop_at:
            t0 = time.perf_counter()
            try:
                # Callable bodies generate a fresh payload per request
                # (shared-prefix workloads need per-request prompts).
                data = body() if callable(body) else body
                async with session.post(url_path, data=data,
                                        headers=headers) as r:
                    if r.status != 200:
                        await r.read()
                        errors[0] += 1
                        if on_reject is not None:
                            on_reject(r.status)
                        continue
                    if on_response is not None:
                        # t0 lets transports time INSIDE the response
                        # (streaming TTFT / inter-chunk gaps).
                        await on_response(r, t0)
                    else:
                        await r.read()
            except Exception:
                errors[0] += 1
                continue
            latencies.append(time.perf_counter() - t0)
            n += 1
        return n

    conn = aiohttp.TCPConnector(limit=clients)
    async with aiohttp.ClientSession(connector=conn) as session:
        t0 = time.perf_counter()
        counts = await asyncio.gather(
            *[worker(session) for _ in range(clients)]
        )
        dt = time.perf_counter() - t0
    return sum(counts), dt, latencies, errors[0]


async def run_rest(url: str, payload: bytes, clients: int, seconds: float,
                   path: str = "/api/v0.1/predictions"):
    return await _closed_loop(url.rstrip("/") + path, payload, clients,
                              seconds)


async def run_grpc(target: str, payload_rows, clients: int, seconds: float):
    import grpc.aio

    from seldon_tpu.core import payloads as plib
    from seldon_tpu.proto import prediction_grpc

    channel = grpc.aio.insecure_channel(target)
    stub = prediction_grpc.SeldonStub(channel)
    req = plib.build_message(np.asarray(payload_rows, np.float32),
                             kind="ndarray")
    stop_at = time.perf_counter() + seconds
    latencies: List[float] = []
    errors = [0]

    async def worker():
        n = 0
        while time.perf_counter() < stop_at:
            t0 = time.perf_counter()
            try:
                await stub.Predict(req)
            except Exception:
                errors[0] += 1
                continue
            latencies.append(time.perf_counter() - t0)
            n += 1
        return n

    t0 = time.perf_counter()
    counts = await asyncio.gather(*[worker() for _ in range(clients)])
    dt = time.perf_counter() - t0
    await channel.close()
    return sum(counts), dt, latencies, errors[0]


def parse_decode_len_dist(spec: str) -> Optional[tuple]:
    """Parse --decode-len-dist. Supported: "uniform:a,b" — each request
    draws max_new_tokens uniformly from [a, b]. Empty spec -> None
    (every request uses the fixed --max-new-tokens)."""
    if not spec:
        return None
    kind, _, rest = spec.partition(":")
    if kind != "uniform":
        raise ValueError(
            f"unknown decode-len-dist {spec!r} (supported: uniform:a,b)"
        )
    try:
        a, b = (int(x) for x in rest.split(","))
    except Exception:
        raise ValueError(
            f"decode-len-dist {spec!r} needs two ints: uniform:a,b"
        )
    if not 1 <= a <= b:
        raise ValueError(
            f"decode-len-dist bounds must satisfy 1 <= a <= b, got {spec!r}"
        )
    return (a, b)


class _StreamAborted(Exception):
    """Stream ended in a non-completed outcome (already accounted)."""


async def run_generate(url: str, clients: int, seconds: float,
                       prompt: str = "benchmark prompt",
                       max_new_tokens: int = 32,
                       temperature: float = 0.0,
                       shared_prefix_frac: float = 0.0,
                       shared_prefix: str = "",
                       stream: bool = True,
                       decode_len_dist: str = "",
                       cancel_frac: float = 0.0,
                       deadline_ms: int = 0,
                       deadline_frac: float = 1.0,
                       trace_sample: float = 0.0):
    """LLM serving load: closed-loop generation clients. Latency is full
    completion time; tokens/s is the serving-throughput number. Greedy
    by default so completion lengths — and therefore tokens/s — are
    reproducible across runs.

    stream=True (default) drives /generate_stream (NDJSON, one line per
    decode-chunk burst) and records per-stream TTFT (request send ->
    first line) and inter-token latency (gap between consecutive lines,
    divided by the tokens the later line carried) — the numbers that
    make a prefill stall visible. stream=False reverts to the unary
    /generate endpoint.

    shared_prefix_frac > 0 switches to the SHARED-PREFIX workload: that
    fraction of requests opens with one common system prompt (the rest
    get per-request cold prefixes), so an engine with
    EngineConfig.prefix_cache serves them off retained KV — watch
    jaxserver_prefix_hits / prefix_tokens_saved move.

    decode_len_dist (e.g. "uniform:8,256") draws a fresh max_new_tokens
    per request — the short/long decode mix that exposes paged-KV pool
    churn and fragmentation (a fixed length never stresses the
    allocator's reuse path).

    Lifecycle injection: cancel_frac > 0 makes that fraction of
    streaming clients drop the connection after the first chunk (what a
    vanished browser does — the engine should cancel, not decode to
    max_tokens); deadline_ms > 0 stamps a per-request TTL on every
    request. Every request lands in exactly one `outcomes` bucket
    ({completed, shed, draining, deadline, cancelled, error}); `errors`
    stays the legacy everything-not-completed total. deadline_frac < 1
    stamps the TTL on only that fraction of requests — the
    MIXED-deadline wave an EDF scheduler (PILOT=1) reorders, leaving
    the rest to the no-deadline aging path.

    trace_sample > 0 stamps that fraction of requests with a freshly
    generated W3C traceparent (riding meta.tags like deadline_ms — the
    server-side engine adopts it when TRACING=1), and the sampled trace
    ids come back in the outcome ledger so a run's server-side spans
    can be pulled from the TRACING_FILE JSONL sink by trace id."""
    dist = parse_decode_len_dist(decode_len_dist)
    len_rng = np.random.default_rng(1)
    cancel_rng = np.random.default_rng(2)
    trace_rng = np.random.default_rng(3)
    deadline_rng = np.random.default_rng(4)
    sampled_traces: List[str] = []
    tokens = [0]
    ttfts: List[float] = []
    itls: List[float] = []
    outcomes = {"completed": 0, "shed": 0, "draining": 0,
                "deadline": 0, "cancelled": 0, "error": 0}

    def on_reject(status: int) -> None:
        # Pre-stream lifecycle statuses (engine.KIND_HTTP_STATUS): a TTL
        # that lapses while queued is a 504, not a trailer.
        if status == 429:
            outcomes["shed"] += 1
        elif status == 503:
            outcomes["draining"] += 1
        elif status == 504:
            outcomes["deadline"] += 1
        elif status == 499:
            outcomes["cancelled"] += 1
        else:
            outcomes["error"] += 1

    async def count_tokens(r, t0):
        out = await r.json()
        tokens[0] += int(out.get("completion_tokens", 0))
        outcomes["completed"] += 1

    async def consume_stream(r, t0):
        last = None
        n_total = 0
        want_cancel = cancel_frac > 0.0 and (
            cancel_rng.random() < cancel_frac
        )
        async for line in r.content:
            if not line.strip():
                continue
            now = time.perf_counter()
            out = json.loads(line)
            if "error" in out:
                # In-band trailer (headers already went out 200): the
                # `kind` field says how the request actually ended.
                kind = out.get("kind", "")
                outcomes[
                    kind if kind in ("deadline", "cancelled") else "error"
                ] += 1
                raise _StreamAborted(out["error"])
            n_toks = len(out.get("token_ids", ()))
            if last is None:
                ttfts.append(now - t0)
            elif n_toks:
                # One burst may carry several tokens: spread the gap so
                # the percentile reflects per-TOKEN latency.
                itls.extend([(now - last) / n_toks] * n_toks)
            last = now
            n_total = int(out.get("completion_tokens", n_total))
            if want_cancel:
                # Simulated client disconnect mid-stream: hard-close the
                # connection and walk away (no graceful shutdown).
                outcomes["cancelled"] += 1
                r.close()
                raise _StreamAborted("client cancelled")
        tokens[0] += n_total
        outcomes["completed"] += 1

    def payload(p: str) -> bytes:
        mnt = max_new_tokens if dist is None else int(
            len_rng.integers(dist[0], dist[1] + 1)
        )
        d = {
            "prompt": p, "max_new_tokens": mnt,
            "temperature": temperature,
        }
        tags = {}
        if deadline_ms > 0 and (
            deadline_frac >= 1.0 or deadline_rng.random() < deadline_frac
        ):
            # The REST edge parses this into a proto GenerateRequest,
            # which has no deadline field — the TTL rides meta.tags
            # (see seldon_methods._generate_request_dict).
            tags["deadline_ms"] = deadline_ms
        if trace_sample > 0.0 and trace_rng.random() < trace_sample:
            tp = tracing.new_traceparent()
            sampled_traces.append(tp.split("-")[1])  # bare trace id
            tags["traceparent"] = tp
        if tags:
            d["meta"] = {"tags": tags}
        return json.dumps(d).encode()

    if shared_prefix_frac > 0.0:
        # Long enough to span several prefix-cache blocks under the byte
        # tokenizer; uniqueness lives strictly AFTER the shared part.
        pre = shared_prefix or (
            "You are a serving benchmark assistant. Answer tersely. " * 4
        )
        rng = np.random.default_rng(0)
        uid = [0]

        def body() -> bytes:
            uid[0] += 1
            head = (pre if rng.random() < shared_prefix_frac
                    else f"cold prefix {uid[0]:08d}. ")
            return payload(f"{head}{prompt} #{uid[0]}")
    elif dist is not None:
        def body() -> bytes:  # fresh per-request decode length
            return payload(prompt)
    else:
        body = payload(prompt)
    path = "/generate_stream" if stream else "/generate"
    total, dt, lats, errors = await _closed_loop(
        url.rstrip("/") + path, body, clients, seconds,
        on_response=consume_stream if stream else count_tokens,
        on_reject=on_reject,
    )
    stream_stats = {}
    if stream:
        for name, samples in (("ttft", ttfts), ("itl", itls)):
            arr = np.asarray(samples) * 1000.0 if samples else np.zeros(1)
            for q in (50, 95, 99):
                stream_stats[f"{name}_p{q}_ms"] = round(
                    float(np.percentile(arr, q)), 2
                )
    if trace_sample > 0.0:
        # First few sampled ids in the ledger (the full run may sample
        # thousands): each one keys the server's TRACING_FILE JSONL sink.
        stream_stats["trace_sampled"] = len(sampled_traces)
        stream_stats["trace_ids"] = sampled_traces[:16]
    return total, dt, lats, errors, tokens[0], stream_stats, outcomes


def _compile_counts(url: str) -> dict:
    """Best-effort /debug/compile poll after a run: folds the server's
    compile-variant and live-retrace counts into the ledger so load
    results carry their lattice cost. Empty when the server has no
    compile ledger (COMPILE_LEDGER off -> the route 404s)."""
    import urllib.request
    try:
        # Short timeout: this poll runs after the load window closed, so
        # a server mid-drain may never answer — don't hold the ledger
        # line hostage for it.
        with urllib.request.urlopen(
            url.rstrip("/") + "/debug/compile", timeout=2
        ) as resp:
            comp = json.loads(resp.read())
        # Per-family counts alongside the total: a key's family is its
        # first '/'-segment ("admit-prefix/64/16/1" -> "admit-prefix"),
        # so the graftragged collapse is legible in the post-run ledger
        # — a ragged run shows {"deactivate": 1, "ragged": 1} where the
        # bucketed lattice fans out per family.
        by_family: dict = {}
        for entry in comp.get("lattice", []):
            fam = str(entry["key"]).split("/", 1)[0]
            by_family[fam] = by_family.get(fam, 0) + 1
        return {
            "compile_variants": int(comp["dispatched_variants"]),
            "compile_variants_by_family": dict(sorted(by_family.items())),
            "live_retraces": int(comp["live_retrace_count"]),
            "compile_s_total": float(comp["compile_s_total"]),
        }
    except (OSError, ValueError, KeyError) as exc:
        # 404 (ledger off), connection teardown, or a foreign schema —
        # the ledger line simply goes without compile counters.
        logger.debug("loadtester: /debug/compile poll failed (%s: %s) — "
                     "ledger carries no compile counters",
                     type(exc).__name__, exc)
        return {}


def _sched_counts(url: str) -> dict:
    """Best-effort /debug/sched poll after a run: folds the server's
    waste attribution (padding_waste_frac, budget utilization, the
    goodput-gap scalar + breakdown) into the ledger. Empty when the
    server has no sched ledger (SCHED_LEDGER off -> the route 404s)."""
    import urllib.request
    try:
        # Same short-timeout rationale as _compile_counts above.
        with urllib.request.urlopen(
            url.rstrip("/") + "/debug/sched", timeout=2
        ) as resp:
            sched = json.loads(resp.read())
        gap = sched["goodput_gap"]
        return {
            "padding_waste_frac": float(sched["padding_waste_frac"]),
            "budget_utilization": float(sched["budget_utilization"]),
            "goodput_gap": round(
                float(gap["bucket_pad_frac"]) + float(gap["group_pad_frac"])
                + float(gap.get("spec_rejected_frac", 0.0))
                + float(gap["frag_frac"]), 6
            ),
            "goodput_gap_breakdown": {
                k: float(v) for k, v in gap.items()
            },
            # graftspec acceptance accounting (all-zero when SPEC off;
            # tolerant of a pre-spec server schema).
            "spec_acceptance_rate": float(
                sched.get("spec", {}).get("acceptance_rate", 1.0)
            ),
            "spec_drafted_tokens": int(
                sched.get("spec", {}).get("drafted_tokens", 0)
            ),
            "spec_accepted_tokens": int(
                sched.get("spec", {}).get("accepted_tokens", 0)
            ),
            "sched_conservation_breaches": int(
                sched["conservation"]["breaches"]
            ),
        }
    except (OSError, ValueError, KeyError) as exc:
        logger.debug("loadtester: /debug/sched poll failed (%s: %s) — "
                     "ledger carries no waste counters",
                     type(exc).__name__, exc)
        return {}


def _pilot_counts(url: str) -> dict:
    """Best-effort /debug/pilot poll after a run: folds the controller's
    final decision count, knob values and EDF counters into the ledger —
    the "what did the autopilot actually do" line for a load run. Empty
    when the server flies no pilot (PILOT off -> the route 404s)."""
    import urllib.request
    try:
        # Same short-timeout rationale as _compile_counts above.
        with urllib.request.urlopen(
            url.rstrip("/") + "/debug/pilot", timeout=2
        ) as resp:
            pilot = json.loads(resp.read())
        return {
            "pilot_decisions": int(pilot["decisions_total"]),
            "pilot_decisions_by_knob": {
                k: int(v) for k, v in pilot["decisions_by_knob"].items()
            },
            "pilot_knobs": dict(pilot["knobs"]),
            "pilot_edf_inversions": int(pilot["edf"]["inversions"]),
            "pilot_expired_at_pop": int(pilot["edf"]["expired_at_pop"]),
            "pilot_goodput_delta": float(
                pilot["counterfactual"]["goodput_delta"]
            ),
        }
    except (OSError, ValueError, KeyError) as exc:
        logger.debug("loadtester: /debug/pilot poll failed (%s: %s) — "
                     "ledger carries no pilot counters",
                     type(exc).__name__, exc)
        return {}


def _roof_counts(url: str) -> dict:
    """Best-effort /debug/roof poll after a run: folds graftroof's
    headline roofline numbers (achieved mfu/mbu, the host share of
    boundary wall time, the conservation-audit breach count) into the
    ledger. Empty when the server has no roof ledger (ROOF_LEDGER off
    -> the route 404s)."""
    import urllib.request
    try:
        # Same short-timeout rationale as _compile_counts above.
        with urllib.request.urlopen(
            url.rstrip("/") + "/debug/roof", timeout=2
        ) as resp:
            roof = json.loads(resp.read())
        return {
            "mfu": float(roof["totals"]["mfu"]),
            "mbu": float(roof["totals"]["mbu"]),
            "host_frac": float(roof["host_frac"]),
            "roof_conservation_breaches": int(
                roof["conservation"]["breaches"]
            ),
        }
    except (OSError, ValueError, KeyError) as exc:
        logger.debug("loadtester: /debug/roof poll failed (%s: %s) — "
                     "ledger carries no roofline counters",
                     type(exc).__name__, exc)
        return {}


def report(transport: str, total: int, dt: float, latencies, errors: int,
           clients: int, extra: Optional[dict] = None) -> dict:
    lats = np.asarray(latencies) * 1000.0 if latencies else np.zeros(1)
    out = {
        "metric": f"loadtest_{transport}_req_per_s",
        "value": round(total / dt, 1) if dt else 0.0,
        "unit": f"req/s ({clients} clients)",
        "detail": {
            "requests": total,
            "errors": errors,
            "p50_ms": round(float(np.percentile(lats, 50)), 2),
            "p90_ms": round(float(np.percentile(lats, 90)), 2),
            "p99_ms": round(float(np.percentile(lats, 99)), 2),
            **(extra or {}),
        },
    }
    print(json.dumps(out))
    return out


def main(argv: Optional[List[str]] = None) -> None:
    parser = argparse.ArgumentParser(description="seldon-tpu load tester")
    parser.add_argument("url", help="engine base URL (http://host:port)")
    parser.add_argument("--clients", type=int, default=64)
    parser.add_argument("--seconds", type=float, default=30.0)
    parser.add_argument("--transport", choices=["rest", "grpc", "generate"],
                        default="rest")
    parser.add_argument("--payload",
                        default='{"data": {"ndarray": [[1.0, 2.0]]}}')
    parser.add_argument("--grpc-host", default="",
                        help="host:port for --transport grpc")
    parser.add_argument("--path", default="/api/v0.1/predictions")
    parser.add_argument("--prompt", default="benchmark prompt")
    parser.add_argument("--max-new-tokens", type=int, default=32)
    parser.add_argument("--temperature", type=float, default=0.0)
    parser.add_argument("--shared-prefix-frac", type=float, default=0.0,
                        help="fraction of /generate requests opening with "
                             "one shared system prompt (prefix-cache "
                             "workload); 0 disables")
    parser.add_argument("--shared-prefix", default="",
                        help="override the shared system prompt text")
    parser.add_argument("--decode-len-dist", default="",
                        help="--transport generate: per-request "
                             "max_new_tokens distribution, e.g. "
                             "uniform:8,256 (short/long decode mix — the "
                             "workload that exposes paged-KV pool churn); "
                             "empty uses --max-new-tokens for every "
                             "request")
    parser.add_argument("--no-stream", action="store_true",
                        help="--transport generate: use the unary "
                             "/generate endpoint instead of streaming "
                             "/generate_stream (drops TTFT/ITL "
                             "percentiles from the summary)")
    parser.add_argument("--cancel-frac", type=float, default=0.0,
                        help="--transport generate: fraction of streaming "
                             "clients that drop the connection after the "
                             "first chunk (mid-stream disconnect "
                             "injection); 0 disables")
    parser.add_argument("--deadline-ms", type=int, default=0,
                        help="--transport generate: per-request TTL in "
                             "ms stamped on every request (deadline "
                             "injection); 0 disables")
    parser.add_argument("--deadline-frac", type=float, default=1.0,
                        help="--transport generate: fraction of requests "
                             "the --deadline-ms TTL is stamped on (mixed-"
                             "deadline wave for the EDF scheduler); 1.0 "
                             "stamps every request")
    parser.add_argument("--trace-sample", type=float, default=0.0,
                        help="--transport generate: fraction of requests "
                             "stamped with a generated W3C traceparent "
                             "(server adopts it when TRACING=1); sampled "
                             "trace ids print in the outcome ledger for "
                             "span-sink lookup. 0 disables")
    args = parser.parse_args(argv)

    if args.transport == "generate":
        total, dt, lats, errors, toks, stream_stats, outcomes = asyncio.run(
            run_generate(args.url, args.clients, args.seconds,
                         args.prompt, args.max_new_tokens,
                         args.temperature, args.shared_prefix_frac,
                         args.shared_prefix, stream=not args.no_stream,
                         decode_len_dist=args.decode_len_dist,
                         cancel_frac=args.cancel_frac,
                         deadline_ms=args.deadline_ms,
                         deadline_frac=args.deadline_frac,
                         trace_sample=args.trace_sample)
        )
        extra = {"completion_tokens": toks,
                 "tokens_per_s": round(toks / dt, 1) if dt else 0.0,
                 "outcomes": outcomes,
                 **stream_stats}
        if args.shared_prefix_frac > 0.0:
            extra["shared_prefix_frac"] = args.shared_prefix_frac
        if args.decode_len_dist:
            extra["decode_len_dist"] = args.decode_len_dist
        extra.update(_compile_counts(args.url))
        extra.update(_sched_counts(args.url))
        pilot = _pilot_counts(args.url)
        extra.update(pilot)
        roof = _roof_counts(args.url)
        extra.update(roof)
        report("generate", total, dt, lats, errors, args.clients,
               extra=extra)
        if pilot:
            # Human-readable autopilot postscript (the JSON ledger line
            # above stays machine-parseable and last-but-one).
            print(
                f"pilot: {pilot['pilot_decisions']} decisions, "
                f"final knobs {pilot['pilot_knobs']}, "
                f"{pilot['pilot_edf_inversions']} EDF inversions",
                file=sys.stderr,
            )
        if roof:
            # Roofline postscript: how hard the hardware ran and how
            # much of each boundary the host ate.
            print(
                f"roof: mfu={roof['mfu']:.4f} mbu={roof['mbu']:.4f} "
                f"host_frac={roof['host_frac']:.4f}",
                file=sys.stderr,
            )
        return
    if args.transport == "rest":
        total, dt, lats, errors = asyncio.run(
            run_rest(args.url, args.payload.encode(), args.clients,
                     args.seconds, args.path)
        )
    else:
        rows = json.loads(args.payload)["data"]["ndarray"]
        target = args.grpc_host or args.url.replace("http://", "")
        total, dt, lats, errors = asyncio.run(
            run_grpc(target, rows, args.clients, args.seconds)
        )
    report(args.transport, total, dt, lats, errors, args.clients)


if __name__ == "__main__":  # pragma: no cover
    main()
