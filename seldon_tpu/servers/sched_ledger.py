"""Scheduler waste ledger: where the goodput gap goes, boundary by
boundary.

The compile ledger says what compiled and the flight recorder says what
ran; neither says why dispatched capacity was not useful tokens.  This
ledger attributes every token-slot the scheduler offered to exactly one
of: useful prompt/chunk work, bucket padding (a prompt or chunk rounded
up to its lattice bucket), group padding (admission groups replicated
up to the next power of two), or — under SPEC=1 — rejected draft
positions (verify-wave slots whose proposed token the target refused;
graftspec's speculative price, the fourth waste category).  Chunked-
prefill budget passes additionally
record fragmentation — dispatch-token-budget left on the table while
prefill work was still queued — and scheduler ticks with nothing to do
at all count as idle boundaries.  Alongside the token ledger it keeps a
queue-wait decomposition: each request's submit -> first-dispatch wait
is split into pool-stall / bucket-mismatch / budget-contention /
scheduler-interval components at attribution time, each clamped so the
components always sum to the measured wait.

Design constraints (the compile-ledger discipline, applied again):

 * every mutator runs on the scheduler thread — dispatch taps, budget
   accounting and wait attribution under ``_book``, idle ticks on the
   loop between dispatches — single-writer, GIL-atomic stores, no
   locks, no blocking, no device access.
 * ``audit()`` runs under ``_book`` next to graftsan's boundary audits
   and checks the conservation invariants below; ``snapshot()`` (debug
   route thread) tolerates a torn *window*, never a torn record.
 * env-only gating: ``SCHED_LEDGER=1`` enables it; off -> ``from_env()``
   returns None and the engine keeps a None attribute plus the raw
   dispatch path — zero hot-path cost, not even a branch inside the
   jit call sequence.

Conservation invariants (checked by ``audit()``; gated in CI by
``tools/sched_audit.py`` via ``make sched-audit``):

 * ``useful_tokens + bucket_pad_tokens + group_pad_tokens +
   spec_rejected_tokens == dispatch_cells`` — every offered token-slot
   attributed, exactly;
 * ``spec.accepted_tokens + spec.rejected_tokens ==
   spec.drafted_tokens`` — every drafted token resolved one way
   (re-summed in CI by ``tools/spec_audit.py`` via ``make spec-audit``);
 * ``frag_tokens <= budget_offered_tokens - budget_used_tokens`` —
   fragmentation only counts budget left while work was still queued;
 * the wait components sum to the total measured wait within 1%.

``snapshot()`` is the documented ``/debug/sched`` schema::

    {
      "boundaries": int,            # dispatch + idle scheduler ticks
      "dispatch_boundaries": int,
      "idle_boundaries": int,
      "dispatch_cells": int,        # token-slots offered by dispatches
      "useful_tokens": int,
      "bucket_pad_tokens": int,
      "group_pad_tokens": int,
      "spec_rejected_tokens": int,  # rejected verify-wave positions
      "frag_tokens": int,
      "budget_offered_tokens": int, # chunked-prefill budget passes
      "budget_used_tokens": int,
      "budget_starved_passes": int, # passes that ended with work queued
      "padding_waste_frac": float,  # (bucket + group) / cells
      "budget_utilization": float,  # used / offered (1.0 w/o budget)
      "goodput_gap": {              # fractions of offered opportunity
        "bucket_pad_frac": float,   #   (cells + frag tokens) lost to
        "group_pad_frac": float,    #   each cause; idle_frac is the
        "spec_rejected_frac": float,#   share of scheduler ticks that
        "frag_frac": float,         #   dispatched nothing at all
        "idle_frac": float,
      },
      "spec": {                     # graftspec acceptance accounting
        "drafted_tokens": int,      #   (all zero when SPEC is off)
        "accepted_tokens": int,
        "rejected_tokens": int,
        "verify_waves": int,
        "acceptance_rate": float,   # accepted / drafted (1.0 if none)
      },
      "pool_stall_events": int,
      "pool_stall_requests": int,   # requests whose admission stalled
      "preemptions": int,
      "preempted_tokens": int,      # prompt + generated work discarded
      "wait": {"requests": int, "total_ms": float, "pool_ms": float,
               "bucket_ms": float, "budget_ms": float,
               "sched_ms": float,
               "predicted_ms": float},  # graftroof cost stamp (not a
                                        # wait component; excluded from
                                        # the conservation re-sum)
      "conservation": {"checked": int, "breaches": int,
                       "last_breach": str | None},
      "by_shape": [                 # per-variant waste, compile-ledger
        {"key": str,                #   key spellings ("admit/64/4")
         "dispatches": int, "cells": int, "useful_tokens": int,
         "bucket_pad_tokens": int, "group_pad_tokens": int,
         "spec_rejected_tokens": int}
      ],
    }
"""

from __future__ import annotations

import logging
import os
import time
from typing import Any, Dict, List, Optional, Tuple

from seldon_tpu.servers.compile_ledger import key_str

logger = logging.getLogger(__name__)

Key = Tuple[Any, ...]

# Per-shape table cap: past it, new shapes fold into one overflow row so
# the snapshot payload stays bounded (totals keep exact counts).
_MAX_SHAPES = 128
# Wait-mark cap: requests that never reach a first dispatch (shed while
# queued) would otherwise leak their marks; past the cap the oldest mark
# is dropped and that request's wait degrades to the sched component.
_MAX_WAIT_MARKS = 4096
_OVERFLOW_KEY: Key = ("other",)


class SchedLedger:
    """Per-boundary waste attribution + queue-wait decomposition."""

    def __init__(self):
        # Token ledger — mutated only by the scheduler thread under
        # _book (dispatch taps), read via bulk copies in snapshot().
        self._dispatch_boundaries = 0
        self._idle_boundaries = 0
        self._cells = 0
        self._useful = 0
        self._bucket_pad = 0
        self._group_pad = 0
        self._spec_rejected = 0
        self._frag = 0
        self._budget_offered = 0
        self._budget_used = 0
        self._budget_starved = 0
        self._pool_stall_events = 0
        self._pool_stall_requests = 0
        self._preemptions = 0
        self._preempted_tokens = 0
        # graftspec acceptance accounting: every drafted token resolves
        # to accepted or rejected (audited below).
        self._spec_drafted = 0
        self._spec_accepted = 0
        self._spec_waves = 0
        # key -> [dispatches, cells, useful, bucket_pad, group_pad,
        #         spec_rejected]
        self._shapes: Dict[Key, List[int]] = {}
        # Queue-wait decomposition: rid -> first-cause timestamps, popped
        # at first dispatch; _budget_full_at is the latest budget pass
        # that ended with prefill work still queued.
        self._wait_marks: Dict[int, Dict[str, float]] = {}
        self._budget_full_at: Optional[float] = None
        self._wait_requests = 0
        self._wait_total_ms = 0.0
        self._wait_pool_ms = 0.0
        self._wait_bucket_ms = 0.0
        self._wait_budget_ms = 0.0
        self._wait_sched_ms = 0.0
        self._wait_predicted_ms = 0.0  # graftroof cost stamp (off-path 0)
        # Current-wave delta marks for boundary_waste() (the recorder's
        # per-boundary waste_frac counter lane).
        self._wave_cells = 0
        self._wave_pad = 0
        # Conservation audit state.
        self._audit_checked = 0
        self._audit_breaches = 0
        self._last_breach: Optional[str] = None

    # -- hot path (scheduler thread) -----------------------------------------

    def note_group(self, key: Key, cells: int, useful: int,
                   bucket_pad: int, group_pad: int,
                   spec_rejected: int = 0) -> None:
        """One dispatched admission/chunk/verify group: `cells` token-
        slots offered by its static shape, split exactly into useful
        tokens, bucket padding, pow2 group-replication padding and —
        for graftspec verify waves — rejected draft positions."""
        self._cells += cells
        self._useful += useful
        self._bucket_pad += bucket_pad
        self._group_pad += group_pad
        self._spec_rejected += spec_rejected
        self._wave_cells += cells
        self._wave_pad += bucket_pad + group_pad + spec_rejected
        rec = self._shapes.get(key)
        if rec is None:
            if len(self._shapes) >= _MAX_SHAPES:
                key = _OVERFLOW_KEY
                rec = self._shapes.get(key)
            if rec is None:
                rec = [0, 0, 0, 0, 0, 0]
                self._shapes[key] = rec
        rec[0] += 1
        rec[1] += cells
        rec[2] += useful
        rec[3] += bucket_pad
        rec[4] += group_pad
        rec[5] += spec_rejected

    def note_spec(self, drafted: int, accepted: int,
                  rejected: int) -> None:
        """One verify wave's acceptance split. `rejected` is carried by
        the caller (not derived) so audit() can re-sum the identity
        accepted + rejected == drafted from independently-counted
        inputs."""
        self._spec_drafted += drafted
        self._spec_accepted += accepted
        self._spec_waves += 1
        if accepted + rejected != drafted:
            self._breach(
                f"spec wave accounting: accepted {accepted} + rejected "
                f"{rejected} != drafted {drafted}"
            )

    def note_budget(self, offered: int, used: int, starved: bool) -> None:
        """One chunked-prefill budget pass. `starved`: prefill work was
        still queued when the pass ended — unspent budget then counts as
        fragmentation, and the pass marks budget contention for the
        wait decomposition (even a fully-spent pass contends)."""
        self._budget_offered += offered
        self._budget_used += used
        if starved:
            self._budget_starved += 1
            self._frag += offered - used
            self._budget_full_at = time.perf_counter()

    def note_boundary(self) -> None:
        """One scheduler tick that dispatched device work."""
        self._dispatch_boundaries += 1

    def note_idle(self) -> None:
        """One scheduler tick with nothing to dispatch (loop idle
        branch — scheduler thread, outside _book is fine: same single
        writer as every other mutator)."""
        self._idle_boundaries += 1

    def boundary_waste(self) -> float:
        """Padding fraction of the wave dispatched since the last call
        (scheduler thread only) — feeds the per-boundary waste_frac the
        flight recorder turns into a Perfetto counter lane."""
        cells, pad = self._wave_cells, self._wave_pad
        self._wave_cells = 0
        self._wave_pad = 0
        return pad / cells if cells else 0.0

    def _mark(self, rid: int) -> Dict[str, float]:
        m = self._wait_marks.get(rid)
        if m is None:
            if len(self._wait_marks) >= _MAX_WAIT_MARKS:
                self._wait_marks.pop(next(iter(self._wait_marks)))
            m = {}
            self._wait_marks[rid] = m
        return m

    def note_pool_stall(self, rid: int) -> None:
        """Head-of-line request `rid` could not be admitted because the
        KV pool had no capacity. First stall stamps the wait mark."""
        self._pool_stall_events += 1
        self._mark(rid).setdefault("pool", time.perf_counter())

    def note_bucket_defer(self, rid: int) -> None:
        """Head-of-line request `rid` was left queued behind a full
        engine whose last admitted group used a DIFFERENT bucket — the
        bucket-mismatch wait cause."""
        self._mark(rid).setdefault("bucket", time.perf_counter())

    def note_preempt(self, rid: int, tokens: int) -> None:
        """A live stream was preempted to free pool blocks; `tokens` is
        the prefill + decode work thrown away with it."""
        self._preemptions += 1
        self._preempted_tokens += tokens

    def note_first_dispatch(self, rid: int, submitted_at: float,
                            now: float, predicted_ms: float = 0.0) -> None:
        """Attribute one request's queue wait at its first dispatch.
        Components are claimed in priority order (pool stall, then
        bucket mismatch, then budget contention), each clamped to the
        wait still unclaimed, so they sum to the measured wait exactly;
        the remainder is the inherent scheduler-boundary interval.
        `predicted_ms` is the roofline cost model's service-time
        estimate for the request (graftroof; 0.0 when that ledger is
        off) — accumulated beside the wait so waits can be read against
        the predicted work they bought, without entering the
        conservation re-sum."""
        wait_ms = max(0.0, 1000.0 * (now - submitted_at))
        m = self._wait_marks.pop(rid, None) or {}
        pool_ms = bucket_ms = budget_ms = 0.0
        if "pool" in m:
            self._pool_stall_requests += 1
            pool_ms = min(wait_ms, max(0.0, 1000.0 * (now - m["pool"])))
        rem = wait_ms - pool_ms
        if "bucket" in m and rem > 0.0:
            bucket_ms = min(rem, max(0.0, 1000.0 * (now - m["bucket"])))
            rem -= bucket_ms
        t = self._budget_full_at
        if t is not None and t >= submitted_at and rem > 0.0:
            budget_ms = min(rem, max(0.0, 1000.0 * (now - t)))
            rem -= budget_ms
        self._wait_requests += 1
        self._wait_total_ms += wait_ms
        self._wait_pool_ms += pool_ms
        self._wait_bucket_ms += bucket_ms
        self._wait_budget_ms += budget_ms
        self._wait_sched_ms += rem
        self._wait_predicted_ms += max(0.0, predicted_ms)

    # -- conservation audit (under _book) ------------------------------------

    def audit(self) -> None:
        """Conservation check, run under ``_book`` at boundary
        processing (both the sync scheduler and the fetcher thread) —
        the graftsan boundary-audit slot. Token counters only mutate
        under ``_book``, so the identities below can never be
        legitimately torn here; a breach is real attribution drift."""
        self._audit_checked += 1
        attributed = (self._useful + self._bucket_pad + self._group_pad
                      + self._spec_rejected)
        if attributed != self._cells:
            self._breach(
                f"attributed tokens {attributed} != dispatched cells "
                f"{self._cells} (useful {self._useful} + bucket "
                f"{self._bucket_pad} + group {self._group_pad} + spec "
                f"rejected {self._spec_rejected})"
            )
        if self._spec_rejected > self._spec_drafted - self._spec_accepted:
            self._breach(
                f"spec rejected cells {self._spec_rejected} exceed "
                f"unaccepted drafts "
                f"{self._spec_drafted - self._spec_accepted}"
            )
        if self._frag > self._budget_offered - self._budget_used:
            self._breach(
                f"frag tokens {self._frag} exceed unspent budget "
                f"{self._budget_offered - self._budget_used}"
            )
        parts = (self._wait_pool_ms + self._wait_bucket_ms
                 + self._wait_budget_ms + self._wait_sched_ms)
        if abs(parts - self._wait_total_ms) > max(
            1.0, 0.01 * self._wait_total_ms
        ):
            self._breach(
                f"wait components {parts:.3f} ms != total wait "
                f"{self._wait_total_ms:.3f} ms"
            )

    def _breach(self, msg: str) -> None:
        self._audit_breaches += 1
        self._last_breach = msg
        logger.warning("sched-ledger conservation breach: %s", msg)

    # -- readers -------------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        shapes = {k: list(v) for k, v in self._shapes.items()}
        cells = self._cells
        frag = self._frag
        boundaries = self._dispatch_boundaries + self._idle_boundaries
        # Opportunity = every token-slot dispatched plus budget tokens
        # that went undispatched with work queued — the denominator the
        # goodput-gap fractions share.
        opportunity = cells + frag
        return {
            "boundaries": boundaries,
            "dispatch_boundaries": self._dispatch_boundaries,
            "idle_boundaries": self._idle_boundaries,
            "dispatch_cells": cells,
            "useful_tokens": self._useful,
            "bucket_pad_tokens": self._bucket_pad,
            "group_pad_tokens": self._group_pad,
            "spec_rejected_tokens": self._spec_rejected,
            "frag_tokens": frag,
            "budget_offered_tokens": self._budget_offered,
            "budget_used_tokens": self._budget_used,
            "budget_starved_passes": self._budget_starved,
            "padding_waste_frac": (
                round((self._bucket_pad + self._group_pad
                       + self._spec_rejected) / cells, 6)
                if cells else 0.0
            ),
            "budget_utilization": (
                round(self._budget_used / self._budget_offered, 6)
                if self._budget_offered else 1.0
            ),
            "goodput_gap": {
                "bucket_pad_frac": (
                    round(self._bucket_pad / opportunity, 6)
                    if opportunity else 0.0
                ),
                "group_pad_frac": (
                    round(self._group_pad / opportunity, 6)
                    if opportunity else 0.0
                ),
                "spec_rejected_frac": (
                    round(self._spec_rejected / opportunity, 6)
                    if opportunity else 0.0
                ),
                "frag_frac": (
                    round(frag / opportunity, 6) if opportunity else 0.0
                ),
                "idle_frac": (
                    round(self._idle_boundaries / boundaries, 6)
                    if boundaries else 0.0
                ),
            },
            "pool_stall_events": self._pool_stall_events,
            "pool_stall_requests": self._pool_stall_requests,
            "preemptions": self._preemptions,
            "preempted_tokens": self._preempted_tokens,
            "spec": {
                "drafted_tokens": self._spec_drafted,
                "accepted_tokens": self._spec_accepted,
                "rejected_tokens": (
                    self._spec_drafted - self._spec_accepted
                ),
                "verify_waves": self._spec_waves,
                "acceptance_rate": (
                    round(self._spec_accepted / self._spec_drafted, 6)
                    if self._spec_drafted else 1.0
                ),
            },
            "wait": {
                "requests": self._wait_requests,
                "total_ms": round(self._wait_total_ms, 3),
                "pool_ms": round(self._wait_pool_ms, 3),
                "bucket_ms": round(self._wait_bucket_ms, 3),
                "budget_ms": round(self._wait_budget_ms, 3),
                "sched_ms": round(self._wait_sched_ms, 3),
                "predicted_ms": round(self._wait_predicted_ms, 3),
            },
            "conservation": {
                "checked": self._audit_checked,
                "breaches": self._audit_breaches,
                "last_breach": self._last_breach,
            },
            "by_shape": [
                {
                    "key": key_str(k),
                    "dispatches": v[0],
                    "cells": v[1],
                    "useful_tokens": v[2],
                    "bucket_pad_tokens": v[3],
                    "group_pad_tokens": v[4],
                    "spec_rejected_tokens": v[5],
                }
                for k, v in sorted(shapes.items(), key=lambda kv:
                                   key_str(kv[0]))
            ],
        }


def from_env() -> Optional[SchedLedger]:
    """Ledger iff SCHED_LEDGER=1; None otherwise — callers keep a None
    attribute and the raw dispatch path (compile-ledger idiom)."""
    if os.environ.get("SCHED_LEDGER", "0") not in ("1", "true", "True"):
        return None
    return SchedLedger()
