"""Continuous-batching inference engine (the TPU serving hot loop).

Reference comparison: the reference has NO batching anywhere — each request
walks the graph and hits a Flask worker alone (SURVEY.md §7 "dynamic
batching ... the key new hot-loop component"). This engine is the TPU-native
answer, vLLM-style iteration-level scheduling mapped onto XLA's static-shape
world:

 * A fixed pool of B slots shares one pre-allocated KV cache
   [L, B, Smax, Hkv, Dh]; decode runs in CHUNKS of `decode_chunk` steps —
   one jitted `lax.scan` over all slots per dispatch — so the host pays
   one dispatch + one sync per K tokens/slot instead of per token.
   Per-row EOS/length termination inside the chunk is value-level masking.
 * Admission is ONE fused jitted call per group: waiting requests with the
   same prompt bucket are prefilled together [G, Sb] (G padded to a power
   of two, bounding compile variants), scattered into their slots, first
   tokens sampled, and slot state armed — all device-side, no host sync
   until the boundary read.
 * The scheduler dispatches all admissions, then the decode chunk, then
   reads everything in one wave — device stays busy while the host waits,
   and host round-trip latency is amortized over K steps x B slots.
 * `warmup()` pre-compiles every (prompt-bucket x group-size) admission
   variant plus the chunk step, so first requests never eat a compile.
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import logging
import os
import queue
import threading
import time
from typing import (Any, Deque, Dict, List, NamedTuple, Optional, Sequence,
                    Tuple)

import jax
import jax.numpy as jnp
import numpy as np

from seldon_tpu.core import tracing
from seldon_tpu.models import ragged_attention, tp_sharding, transformer
from seldon_tpu.models import spec_decode as spec_model
from seldon_tpu.models.config import ModelConfig
from seldon_tpu.models.sampling import SamplingParams, sample_per_row
from seldon_tpu.servers import compile_ledger, controller, cost_model
from seldon_tpu.servers import flight_recorder, graftsan, hbm_ledger
from seldon_tpu.servers import sched_ledger, shape_lattice, supervisor
from seldon_tpu.servers.chaos import ChaosConfig, ChaosMonkey

logger = logging.getLogger(__name__)


# HTTP status per error-item kind, for errors that surface BEFORE any
# stream bytes went out (after that they ride the in-band trailer).
# Transports duck-read `http_status` off the exception, so attaching it
# where the typed exception is built keeps the wrapper engine-agnostic.
KIND_HTTP_STATUS = {
    "capacity": 429,
    "draining": 503,
    "shutdown": 503,
    "preempted": 503,
    "deadline": 504,  # client-set TTL lapsed — not a server fault
    "cancelled": 499,  # client closed the connection (nginx convention)
    "poison": 500,  # quarantined: deterministically faults the wave
}


class EngineOverloaded(RuntimeError):
    """Admission queue is full — the request was shed at submit time.
    Retriable with backoff; transports map it to HTTP 429."""

    http_status = 429
    retriable = True


class EngineDraining(RuntimeError):
    """The engine is draining or stopped and not admitting new work.
    Retriable against another replica; transports map it to HTTP 503."""

    http_status = 503
    retriable = True


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    max_slots: int = 32
    max_seq_len: int = 2048
    prompt_buckets: Sequence[int] = (32, 128, 512, 1024)
    max_admit: int = 8  # largest batched-prefill group (power of two)
    decode_chunk: int = 8  # decode steps per dispatch (latency/thruput knob)
    idle_sleep_s: float = 0.002
    # Boundary fetches on a dedicated thread so dispatches never wait on
    # a host<->device round trip (auto-disabled on multi-process meshes:
    # SPMD dispatch decisions must not depend on fetch timing).
    async_fetch: bool = True
    # Prefill-priority scheduling: scale the dispatched chunk length with
    # slot occupancy so ONE engine holds both the TTFT SLO and saturated
    # throughput. A request can only be admitted at a chunk boundary;
    # with mostly-free slots (under-capacity, latency-sensitive regime) a
    # long chunk is pure admission latency, while at saturation nothing
    # can be admitted mid-chunk anyway — so: near-empty -> min_chunk
    # boundaries, near-full -> decode_chunk. Compiles one chunk variant
    # per power-of-two rung (min_chunk..decode_chunk).
    adaptive_chunk: bool = True
    min_chunk: int = 4
    # Prompt prefix KV cache (opt-in): reuse device-resident KV of
    # previously-seen block-aligned prompt prefixes so admissions prefill
    # only the uncached suffix (servers/prefix_cache.py). False keeps the
    # admission path byte-identical to the pre-prefix engine. Single-
    # process meshes only (the index is host-side; multi-process SPMD
    # dispatch must not depend on per-host trie state).
    prefix_cache: bool = False
    prefix_block: int = 16  # trie granularity; reuse is block-aligned
    prefix_cache_bytes: int = 256 << 20  # HBM budget for retained KV
    # Stall-free scheduling (opt-in): split admissions into block-aligned
    # prefill CHUNKS of `prefill_chunk` tokens and pack at most
    # `dispatch_token_budget` prefill tokens into each scheduler dispatch
    # alongside the decode chunk, instead of draining the admission queue
    # first — a long-prompt arrival no longer stalls in-flight streams
    # for its whole prefill, so tail ITL stays flat under mixed traffic
    # (Sarathi-style chunked prefill). Chunk k prefills against the KV of
    # chunks 0..k-1 already resident in the slot cache
    # (transformer.prefill_with_prefix); the final chunk samples the
    # first token exactly like the one-shot path, so greedy outputs stay
    # bit-identical. False keeps the dispatch path byte-identical to the
    # uninterleaved engine.
    chunked_prefill: bool = False
    prefill_chunk: int = 128  # power of two, multiple of prefix_block
    dispatch_token_budget: int = 0  # prefill tokens per dispatch; 0 -> chunk
    # Paged KV cache (opt-in): replace the per-slot contiguous KV slab
    # with a global block pool + per-slot block tables, so a stream
    # allocates KV in `kv_block`-token blocks as it decodes instead of
    # reserving max_seq_len up front — short-decode traffic packs several
    # times more concurrent streams into the same HBM budget, and prefix-
    # cache hits share prompt blocks zero-copy (refcounts, not device
    # copies; copy-on-write when a stream writes into a partially-filled
    # shared block). False keeps the dense dispatch path byte-identical.
    # Single-process meshes only (host-side allocator, like prefix_cache).
    paged_kv: bool = False
    kv_block: int = 16  # tokens per pool block; power of two
    kv_pool_blocks: int = 0  # pool size incl. trash block; 0 -> dense-equiv
    # Ragged unified dispatch (opt-in; graftragged): every scheduler wave
    # runs ONE fused kernel over all slots — mixed cold prefills, chunk
    # continuations and decode steps in a single compiled variant
    # (models/ragged_attention.py), collapsing the (bucket, group, width)
    # jit lattice to key ("ragged", chunk) plus ("deactivate",). Requires
    # paged_kv + chunked_prefill (block tables are the wave's only KV
    # currency; the wave IS a chunk boundary). False keeps every dispatch
    # byte-identical to the bucketed engine.
    ragged: bool = False
    ragged_chunk: int = 0  # per-slot tokens per wave; 0 -> prefill_chunk
    # Ragged attention kernel leg (graftkern): "masked" = the bit-exact
    # full-width baseline above; "sparse" = the block-sparse jnp walker
    # (ops/ragged_paged_attention.py) that touches only live KV blocks
    # and skips dead prefill legs — the CPU/default-perf leg; "pallas"
    # = the Mosaic kernel for the same walk (interpret-mode on CPU).
    # All legs compile into the SAME single ("ragged", C) variant.
    # Greedy outputs are token-identical across legs; non-greedy
    # sampling may diverge in ulps (masked is the any-temperature
    # exactness leg). Also selects the spec verify_wave leg.
    ragged_kernel: str = "masked"
    # > 0: waves whose longest live row needs more than this many pool
    # blocks run the masked leg via an in-trace lax.cond (never
    # truncates, never adds a variant). 0 = no budget (sparse always).
    ragged_block_budget: int = 0
    # Speculative decoding (opt-in; graftspec): a resident drafter
    # proposes up to `spec_k` tokens per live slot each wave and the
    # target model verifies all k+1 positions in ONE wide dispatch
    # (models/spec_decode.py) — accepted prefixes commit, the first
    # mismatch rolls the row back by a host-side block-table trim.
    # Sampling keys are sequential per position, so verification is
    # EXACT: outputs are bit-identical to the spec-off engine at any
    # temperature. Requires paged_kv (rollback is a table trim);
    # mutually exclusive with ragged (each replaces the decode
    # dispatch). `spec_draft` names a draft checkpoint preset (the 1B
    # next to an 8B target); "" uses the zero-dispatch n-gram drafter
    # (servers/spec_decode.py). False keeps every dispatch
    # byte-identical to the spec-off engine.
    spec_decode: bool = False
    spec_k: int = 4  # max drafted tokens/wave; rungs are pow2 1..spec_k
    spec_draft: str = ""  # draft model preset; "" -> n-gram drafter
    # graftmesh (opt-in): exact tensor parallelism over the mesh's 'tp'
    # axis (models/tp_sharding.py). tp > 1 shards the qkv / gate / up
    # projections and the KV cache's head axis across tp devices and
    # runs every dispatch family SPMD, with greedy output bit-identical
    # to tp=1 (output-dim-only sharding — no contraction is ever
    # partitioned, so per-element reduction order matches a single
    # chip). Requires a mesh whose 'tp' axis is exactly this size
    # (servers/mesh_engine.build_tp_mesh) and tp | n_kv_heads,
    # tp | n_heads, tp | d_ff. tp=1 (default) keeps every code path
    # byte-identical to the pre-mesh engine — deliberately a CONFIG
    # axis, not a global, so per-tier TP groups (Nitsum) can coexist
    # in one process later. flash/ring attention kernels are not
    # tp-threaded; engine __init__ rejects the combination.
    tp: int = 1
    # Request-lifecycle hardening (defaults keep the dispatch path
    # byte-identical): TTL applied to requests that set no
    # SamplingParams.deadline_ms of their own, a bound on the admission
    # queue (submit raises EngineOverloaded instead of queueing
    # unboundedly; 0 = unbounded), and deterministic fault injection
    # (servers/chaos.py; None also consults ChaosConfig.from_env so the
    # CHAOS=1 env gate works without plumbing a config through).
    default_deadline_ms: int = 0
    max_queue: int = 0
    chaos: Optional[ChaosConfig] = None
    # graftheal supervised fault recovery (servers/supervisor.py; False
    # also consults the HEAL=1 env gate via supervisor.build, so
    # recovery can be enabled without config plumbing). Off keeps the
    # _fail_all failure path byte-identical to the pre-heal engine:
    # a faulted wave fails every live request. On, innocent in-flight
    # requests are resurrected by replaying their committed tokens
    # through the normal admission path (bit-identical continuation via
    # per-position sampling keys), bounded by a per-request replay
    # budget; heal_watchdog_ms > 0 additionally bounds every boundary
    # device fetch so a hung wave faults instead of wedging.
    heal: bool = False
    heal_max_retries: int = 4
    heal_watchdog_ms: int = 0

    def __post_init__(self):
        def pow2(n: int) -> bool:
            return n >= 1 and (n & (n - 1)) == 0

        if self.min_chunk > self.decode_chunk:
            raise ValueError(
                f"min_chunk ({self.min_chunk}) must not exceed decode_chunk "
                f"({self.decode_chunk}) — the adaptive ladder interpolates "
                f"between them"
            )
        if not pow2(self.max_admit):
            raise ValueError(
                f"max_admit ({self.max_admit}) must be a power of two — "
                f"admission groups are padded to pow2 to bound jit variants"
            )
        for b in self.prompt_buckets:
            if not pow2(b):
                raise ValueError(
                    f"prompt_buckets entry {b} must be a power of two — "
                    f"each bucket is a compiled prefill variant"
                )
        if self.chunked_prefill:
            if not pow2(self.prefill_chunk):
                raise ValueError(
                    f"prefill_chunk ({self.prefill_chunk}) must be a power "
                    f"of two — each chunk length is a compiled variant"
                )
            if self.prefill_chunk % self.prefix_block:
                raise ValueError(
                    f"prefill_chunk ({self.prefill_chunk}) must be a "
                    f"multiple of the KV block size prefix_block "
                    f"({self.prefix_block}) so chunk boundaries never split "
                    f"a prefix-cache block"
                )
            if self.dispatch_token_budget and (
                self.dispatch_token_budget < self.prefill_chunk
            ):
                raise ValueError(
                    f"dispatch_token_budget ({self.dispatch_token_budget}) "
                    f"must be 0 (one chunk per dispatch) or >= prefill_chunk "
                    f"({self.prefill_chunk}) — a dispatch must fit at least "
                    f"one chunk to make progress"
                )
        if self.paged_kv:
            if not pow2(self.kv_block):
                raise ValueError(
                    f"kv_block ({self.kv_block}) must be a power of two — "
                    f"block offsets are computed with pow2 div/mod"
                )
            if self.kv_block % self.prefix_block:
                raise ValueError(
                    f"kv_block ({self.kv_block}) must be a multiple of "
                    f"prefix_block ({self.prefix_block}) so trie spans never "
                    f"straddle a pool block"
                )
            if self.max_seq_len % self.kv_block:
                raise ValueError(
                    f"max_seq_len ({self.max_seq_len}) must be a multiple of "
                    f"kv_block ({self.kv_block}) — block tables are "
                    f"max_seq_len / kv_block entries wide"
                )
            if any(b % self.kv_block for b in self.prompt_buckets):
                raise ValueError(
                    f"every prompt_buckets entry ({self.prompt_buckets}) "
                    f"must be a multiple of kv_block ({self.kv_block}) — "
                    f"warm prefix widths are bucketed and must cover whole "
                    f"pool blocks"
                )
            if self.chunked_prefill and self.prefill_chunk % self.kv_block:
                raise ValueError(
                    f"prefill_chunk ({self.prefill_chunk}) must be a "
                    f"multiple of kv_block ({self.kv_block}) under paged_kv "
                    f"so chunk boundaries append whole pool blocks"
                )
            if self.kv_pool_blocks and self.kv_pool_blocks < 2:
                raise ValueError(
                    f"kv_pool_blocks ({self.kv_pool_blocks}) must be >= 2 "
                    f"(1 reserved trash block + 1 usable) or 0 for the "
                    f"dense-equivalent budget"
                )
        if self.ragged:
            if not (self.paged_kv and self.chunked_prefill):
                raise ValueError(
                    "ragged=True requires paged_kv=True and "
                    "chunked_prefill=True — the unified wave walks block "
                    "tables and admits prompts chunkwise"
                )
            rc = self.ragged_chunk or self.prefill_chunk
            if not pow2(rc):
                raise ValueError(
                    f"ragged_chunk ({rc}) must be a power of two — it is "
                    f"the ONE compiled wave width"
                )
            if rc % self.kv_block:
                raise ValueError(
                    f"ragged_chunk ({rc}) must be a multiple of kv_block "
                    f"({self.kv_block}) so wave boundaries append whole "
                    f"pool blocks"
                )
        if self.ragged_kernel not in ("masked", "sparse", "pallas"):
            raise ValueError(
                f"ragged_kernel ({self.ragged_kernel!r}) must be one of "
                f"'masked', 'sparse', 'pallas'"
            )
        if self.ragged_block_budget < 0:
            raise ValueError(
                f"ragged_block_budget ({self.ragged_block_budget}) must "
                f"be >= 0 (0 = no budget)"
            )
        if self.spec_decode:
            if not self.paged_kv:
                raise ValueError(
                    "spec_decode=True requires paged_kv=True — rollback "
                    "after a rejected draft is a host-side block-table "
                    "trim, which only the paged engine supports"
                )
            if self.ragged:
                raise ValueError(
                    "spec_decode=True is incompatible with ragged=True — "
                    "each replaces the decode dispatch (a verify wave IS "
                    "a ragged decode wave with k+1 tokens per slot)"
                )
            if not pow2(self.spec_k):
                raise ValueError(
                    f"spec_k ({self.spec_k}) must be a power of two — "
                    f"verify variants compile one rung per pow2 k, and "
                    f"the pilot walks that ladder"
                )
        if self.tp < 1:
            raise ValueError(
                f"tp ({self.tp}) must be >= 1 (1 = no tensor parallelism)"
            )
        if self.default_deadline_ms < 0:
            raise ValueError(
                f"default_deadline_ms ({self.default_deadline_ms}) must be "
                f">= 0 (0 disables the default TTL)"
            )
        if self.max_queue < 0:
            raise ValueError(
                f"max_queue ({self.max_queue}) must be >= 0 (0 leaves the "
                f"admission queue unbounded)"
            )
        if self.heal_max_retries < 1:
            raise ValueError(
                f"heal_max_retries ({self.heal_max_retries}) must be >= 1 "
                f"— a request must be allowed at least one resurrection "
                f"or heal can never recover anything"
            )
        if self.heal_watchdog_ms < 0:
            raise ValueError(
                f"heal_watchdog_ms ({self.heal_watchdog_ms}) must be >= 0 "
                f"(0 disables the boundary-fetch watchdog)"
            )


@dataclasses.dataclass
class _Request:
    rid: int
    tokens: List[int]
    params: SamplingParams
    out: "queue.Queue[Optional[dict]]"
    submitted_at: float
    first_token_at: Optional[float] = None
    n_generated: int = 0
    slot: int = -1
    # Host-side upper bound of tokens produced by dispatched-but-unread
    # chunks (admission token + decode_chunk per dispatched chunk) —
    # drives optimistic slot recycling; the device's `remaining` counter
    # guarantees the row really is frozen once the budget is spent.
    expected: int = 0
    finished: bool = False
    # Prefix-cache state: match length (None until looked up; multiple of
    # prefix_block) and the pinned trie path, held until _complete so a
    # live slot's prefix can never be evicted.
    prefix_len: Optional[int] = None
    prefix_handle: Any = None
    # Chunked-prefill state: prompt tokens whose KV is already resident in
    # the slot cache (prefix-cache hit + dispatched chunks), and whether
    # the request is still mid-prefill (holds a slot, but decode rosters
    # must skip it — no tokens exist yet and device `active` is False).
    prefill_done: int = 0
    prefilling: bool = False
    # Paged-KV state: every pool block this request's table row points
    # at — owned and zero-copy-shared alike each carry one allocator ref
    # taken at admission/growth, so release is a uniform unref sweep.
    block_ids: List[int] = dataclasses.field(default_factory=list)
    # Speculative-decoding / graftheal state: every token emitted so
    # far, in order — the drafter's history source and the heal
    # supervisor's replay source. Only populated when spec_decode or
    # heal is on; otherwise the engine never appends.
    gen_hist: List[int] = dataclasses.field(default_factory=list)
    # graftheal: how many gen_hist tokens have been folded into
    # `tokens` by resurrection replays. The drafter's history is
    # tokens + gen_hist[replayed:]; n_generated counts tokens since the
    # CURRENT admission, so replayed + n_generated is the client-
    # delivered total.
    replayed: int = 0
    # Observability: when the scheduler first dispatched work for this
    # request (queue-wait = first_dispatch_at - submitted_at) and when its
    # latest token burst was emitted (drives the ITL histogram).
    first_dispatch_at: Optional[float] = None
    last_burst_at: Optional[float] = None
    # Lifecycle: absolute deadline (perf_counter seconds, None = no TTL)
    # and the cancel flag — set from any thread (a GIL-atomic bool
    # store), acted on by the scheduler at the next boundary reap.
    deadline: Optional[float] = None
    cancelled: bool = False
    # Tracing: the adopted caller SpanContext (parsed once at submit;
    # None when tracing is off or no traceparent arrived) and the
    # terminal outcome kind, stamped by _fail_req ("" at _complete =
    # normal completion). Lifecycle spans are emitted retroactively at
    # terminal time from the timestamps above, so the hot path never
    # carries open span objects.
    trace: Any = None
    outcome: str = ""


class _PendingWave(NamedTuple):
    """One dispatched-but-unfetched boundary: the admission groups, the
    decode-chunk device handles, the slot->request roster snapshot, the
    DISPATCH_TIMING token, and the device-state epoch the wave was
    dispatched against. Named so the failure paths (_fail_all /
    _shutdown_sweep) read fields by name — the next timing-tuple growth
    can't silently misalign failure accounting. Still iterable, so
    `_process_boundary(*pending)` is unchanged.

    `epoch` exists for graftheal: a wave dispatched before a fault's
    device-state rebuild must be DISCARDED if it surfaces afterwards —
    its roster references pre-rebuild slots, and delivering its tokens
    to a resurrected (unfinished) request would double them. Pre-heal
    this race was benign because every wrecked request was terminally
    failed; resurrection makes staleness load-bearing."""

    admits: List[Tuple[List["_Request"], Any, Any, Any]]
    chunk_handles: Any
    roster: Optional[List[Optional["_Request"]]]
    timing: Any
    epoch: int = 0


class EngineStats:
    def __init__(self):
        # Guards every mutable counter below. The scheduler thread, the
        # boundary fetcher and submit() all bump counters concurrently;
        # graftlint's lock-guard pass enforces the `with self.lock:`
        # discipline tree-wide via the guarded-by annotations.
        self.lock = threading.Lock()
        self.requests = 0  # graftlint: guarded-by(lock) via(stats)
        self.completed = 0  # graftlint: guarded-by(lock) via(stats)
        self.tokens_out = 0  # graftlint: guarded-by(lock) via(stats)
        self.ttft_sum = 0.0  # graftlint: guarded-by(lock) via(stats)
        self.ttft_count = 0  # graftlint: guarded-by(lock) via(stats)
        # Scheduler observability: decode dispatches and total steps
        # dispatched — their ratio is the effective (adaptive) chunk
        # length, the knob the occupancy policy is turning.
        self.decode_dispatches = 0  # graftlint: guarded-by(lock) via(stats)
        self.decode_steps = 0  # graftlint: guarded-by(lock) via(stats)
        # Prefix-cache observability: admissions that reused cached KV,
        # prompt tokens whose prefill was skipped, and trie nodes evicted
        # under the byte budget.
        self.prefix_hits = 0  # graftlint: guarded-by(lock) via(stats)
        self.prefix_tokens_saved = 0  # graftlint: guarded-by(lock) via(stats)
        self.prefix_evictions = 0  # graftlint: guarded-by(lock) via(stats)
        # Admission-queue observability: depth sampled at each dispatch,
        # and submit -> first-dispatch wait per request.
        self.queue_depth = 0  # graftlint: guarded-by(lock) via(stats)
        self.queue_wait_sum = 0.0  # graftlint: guarded-by(lock) via(stats)
        self.queue_wait_count = 0  # graftlint: guarded-by(lock) via(stats)
        # Inter-token latency histogram (ms, per decode-chunk burst gap).
        # Fixed edges keep the lock hold O(buckets) and make prometheus
        # export trivial; quantiles read the bucket upper edge.
        self.itl_edges_ms = (2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 200.0,
                             500.0, 1000.0)
        self.itl_counts = [0] * (len(self.itl_edges_ms) + 1)  # graftlint: guarded-by(lock) via(stats)
        self.itl_sum_ms = 0.0  # graftlint: guarded-by(lock) via(stats)
        # Chunked-prefill observability: chunks dispatched, prompt tokens
        # they covered, and how full the per-dispatch token budget ran
        # (budget_tokens / (budget_dispatches * budget) = utilization).
        self.prefill_chunks = 0  # graftlint: guarded-by(lock) via(stats)
        self.prefill_chunk_tokens = 0  # graftlint: guarded-by(lock) via(stats)
        self.budget_dispatches = 0  # graftlint: guarded-by(lock) via(stats)
        self.budget_tokens = 0  # graftlint: guarded-by(lock) via(stats)
        self.budget_limit = 0  # graftlint: guarded-by(lock) via(stats)
        # Paged-KV observability: admissions whose warm prefix was shared
        # by refcount alone (no device KV traffic), copy-on-write block
        # copies, admissions stalled on pool exhaustion, streams preempted
        # to free blocks for an active decoder, and — for contrast — warm
        # admissions that DID move prefix KV through the device (dense
        # gather/seed paths; provably zero in paged mode).
        self.zero_copy_admissions = 0  # graftlint: guarded-by(lock) via(stats)
        self.cow_copies = 0  # graftlint: guarded-by(lock) via(stats)
        self.pool_stalls = 0  # graftlint: guarded-by(lock) via(stats)
        self.preemptions = 0  # graftlint: guarded-by(lock) via(stats)
        self.prefix_seed_copies = 0  # graftlint: guarded-by(lock) via(stats)
        # Set by the paged engine to the allocator's snapshot() — merged
        # into snapshot() as pool_blocks_* gauges (zeros when dense, so
        # the prometheus surface is unconditional).
        self.pool_gauges = None  # graftlint: guarded-by(lock) via(stats)
        # Lifecycle observability: requests shed before admission
        # (overload rejects, drain, queued deadline/cancel), cancels
        # honored (queued or in-flight), deadline expiries (queued or
        # in-flight), and submits bounced off the max_queue bound.
        self.shed_total = 0  # graftlint: guarded-by(lock) via(stats)
        self.cancelled_total = 0  # graftlint: guarded-by(lock) via(stats)
        self.deadline_expired_total = 0  # graftlint: guarded-by(lock) via(stats)
        self.queue_rejects = 0  # graftlint: guarded-by(lock) via(stats)
        # SLO attainment: per-request deadline margin at terminal time
        # (ms of deadline left; negative = finished/expired late) and
        # goodput — completions that beat their deadline vs deadline-
        # bearing requests that did not (expiries, cancels, late
        # completions) vs requests that carried no deadline at all.
        # Same fixed-edge idiom as the ITL histogram.
        self.deadline_margin_edges_ms = (
            -1000.0, -500.0, -200.0, -100.0, -50.0, -20.0, 0.0,
            20.0, 50.0, 100.0, 200.0, 500.0, 1000.0,
        )
        self.deadline_margin_counts = [0] * (
            len(self.deadline_margin_edges_ms) + 1
        )  # graftlint: guarded-by(lock) via(stats)
        self.deadline_margin_sum_ms = 0.0  # graftlint: guarded-by(lock) via(stats)
        self.deadline_met_total = 0  # graftlint: guarded-by(lock) via(stats)
        self.deadline_missed_total = 0  # graftlint: guarded-by(lock) via(stats)
        self.completed_no_deadline_total = 0  # graftlint: guarded-by(lock) via(stats)
        # Per-variant dispatch timing (DISPATCH_TIMING=1; empty dict —
        # and no record_variant_locked calls — otherwise). Keyed by the
        # compile-ledger variant string ("admit/64/4"); duration is the
        # boundary-level host wall time measured at the deliberate
        # device_get sync, bucketed on the same fixed-edge idiom as ITL.
        self.dispatch_edges_ms = (0.5, 1.0, 2.0, 5.0, 10.0, 20.0, 50.0,
                                  100.0, 200.0, 500.0)
        self.variant_ms = {}  # graftlint: guarded-by(lock) via(stats)
        # Scheduler-waste observability (SCHED_LEDGER=1; all stay zero
        # — and no record_waste_locked calls — otherwise). Token counts
        # mirror the sched ledger's conservation-audited totals; the
        # histogram buckets each dispatched boundary's padding fraction
        # on the same fixed-edge idiom as ITL.
        self.sched_boundaries = 0  # graftlint: guarded-by(lock) via(stats)
        self.sched_idle_boundaries = 0  # graftlint: guarded-by(lock) via(stats)
        self.sched_useful_tokens = 0  # graftlint: guarded-by(lock) via(stats)
        self.sched_bucket_pad_tokens = 0  # graftlint: guarded-by(lock) via(stats)
        self.sched_group_pad_tokens = 0  # graftlint: guarded-by(lock) via(stats)
        self.sched_frag_tokens = 0  # graftlint: guarded-by(lock) via(stats)
        self.waste_edges_frac = (0.01, 0.02, 0.05, 0.10, 0.20, 0.35,
                                 0.50, 0.75)
        self.waste_counts = [0] * (len(self.waste_edges_frac) + 1)  # graftlint: guarded-by(lock) via(stats)

    def record_waste_locked(self, frac: float) -> None:  # graftlint: holds(lock)
        """Caller holds self.lock. One dispatched boundary's padding
        fraction (pad cells / offered cells) from the sched ledger."""
        i = 0
        for edge in self.waste_edges_frac:
            if frac <= edge:
                break
            i += 1
        self.waste_counts[i] += 1
        self.sched_boundaries += 1

    def record_variant_locked(self, key: str, ms: float) -> None:  # graftlint: holds(lock)
        """Caller holds self.lock. One boundary duration for `key`."""
        h = self.variant_ms.get(key)
        if h is None:
            h = {"count": 0, "sum_ms": 0.0,
                 "counts": [0] * (len(self.dispatch_edges_ms) + 1)}
            self.variant_ms[key] = h
        i = 0
        for edge in self.dispatch_edges_ms:
            if ms <= edge:
                break
            i += 1
        h["counts"][i] += 1
        h["count"] += 1
        h["sum_ms"] += ms

    def record_slo_locked(self, margin_ms: Optional[float],  # graftlint: holds(lock)
                          ok: bool) -> None:
        """Caller holds self.lock. margin_ms None = the request carried
        no deadline; ok = the terminal outcome was a normal completion.
        Goodput counts a deadline-bearing request as met only when it
        completed normally with margin to spare."""
        if margin_ms is None:
            if ok:
                self.completed_no_deadline_total += 1
            return
        i = 0
        for edge in self.deadline_margin_edges_ms:
            if margin_ms <= edge:
                break
            i += 1
        self.deadline_margin_counts[i] += 1
        self.deadline_margin_sum_ms += margin_ms
        if ok and margin_ms >= 0.0:
            self.deadline_met_total += 1
        else:
            self.deadline_missed_total += 1

    def record_itl_locked(self, ms: float) -> None:  # graftlint: holds(lock)
        """Caller holds self.lock."""
        i = 0
        for edge in self.itl_edges_ms:
            if ms <= edge:
                break
            i += 1
        self.itl_counts[i] += 1
        self.itl_sum_ms += ms

    def _itl_quantile_locked(self, q: float) -> float:  # graftlint: holds(lock)
        total = sum(self.itl_counts)
        if not total:
            return 0.0
        target = q * total
        cum = 0
        for i, c in enumerate(self.itl_counts):
            cum += c
            if cum >= target:
                if i < len(self.itl_edges_ms):
                    return self.itl_edges_ms[i]
                return 2.0 * self.itl_edges_ms[-1]  # overflow bucket
        return 2.0 * self.itl_edges_ms[-1]

    def snapshot(self) -> Dict[str, float]:
        with self.lock:
            gauges = self.pool_gauges
        # Called outside the stats lock: the allocator snapshot takes its
        # own lock and must stay a leaf in the lock order.
        pool = (
            gauges() if gauges is not None
            else {"total": 0, "used": 0, "free": 0, "shared": 0}
        )
        with self.lock:
            itl_count = sum(self.itl_counts)
            return {
                "pool_blocks_total": pool["total"],
                "pool_blocks_used": pool["used"],
                "pool_blocks_free": pool["free"],
                "pool_blocks_shared": pool["shared"],
                "zero_copy_admissions": self.zero_copy_admissions,
                "cow_copies": self.cow_copies,
                "pool_stalls": self.pool_stalls,
                "preemptions": self.preemptions,
                "prefix_seed_copies": self.prefix_seed_copies,
                "requests": self.requests,
                "completed": self.completed,
                "tokens_out": self.tokens_out,
                "mean_ttft_ms": (
                    1000.0 * self.ttft_sum / self.ttft_count
                    if self.ttft_count
                    else 0.0
                ),
                "decode_dispatches": self.decode_dispatches,
                "decode_steps": self.decode_steps,
                "prefix_hits": self.prefix_hits,
                "prefix_tokens_saved": self.prefix_tokens_saved,
                "prefix_evictions": self.prefix_evictions,
                "queue_depth": self.queue_depth,
                "mean_queue_wait_ms": (
                    1000.0 * self.queue_wait_sum / self.queue_wait_count
                    if self.queue_wait_count
                    else 0.0
                ),
                "itl_count": itl_count,
                "mean_itl_ms": (
                    self.itl_sum_ms / itl_count if itl_count else 0.0
                ),
                "itl_p50_ms": self._itl_quantile_locked(0.50),
                "itl_p95_ms": self._itl_quantile_locked(0.95),
                "itl_p99_ms": self._itl_quantile_locked(0.99),
                "prefill_chunks": self.prefill_chunks,
                "prefill_chunk_tokens": self.prefill_chunk_tokens,
                "budget_utilization": (
                    self.budget_tokens
                    / (self.budget_dispatches * self.budget_limit)
                    if self.budget_dispatches and self.budget_limit
                    else 0.0
                ),
                "shed_total": self.shed_total,
                "cancelled_total": self.cancelled_total,
                "deadline_expired_total": self.deadline_expired_total,
                "queue_rejects": self.queue_rejects,
                "deadline_margin_edges_ms": list(
                    self.deadline_margin_edges_ms
                ),
                "deadline_margin_counts": list(self.deadline_margin_counts),
                "deadline_margin_sum_ms": self.deadline_margin_sum_ms,
                "deadline_met_total": self.deadline_met_total,
                "deadline_missed_total": self.deadline_missed_total,
                "completed_no_deadline_total":
                    self.completed_no_deadline_total,
                "goodput": (
                    self.deadline_met_total
                    / (self.deadline_met_total + self.deadline_missed_total)
                    if (self.deadline_met_total + self.deadline_missed_total)
                    else 1.0
                ),
                "sched_boundaries": self.sched_boundaries,
                "sched_idle_boundaries": self.sched_idle_boundaries,
                "sched_useful_tokens": self.sched_useful_tokens,
                "sched_bucket_pad_tokens": self.sched_bucket_pad_tokens,
                "sched_group_pad_tokens": self.sched_group_pad_tokens,
                "sched_frag_tokens": self.sched_frag_tokens,
                "padding_waste_frac": (
                    (self.sched_bucket_pad_tokens
                     + self.sched_group_pad_tokens)
                    / (self.sched_useful_tokens
                       + self.sched_bucket_pad_tokens
                       + self.sched_group_pad_tokens)
                    if (self.sched_useful_tokens
                        + self.sched_bucket_pad_tokens
                        + self.sched_group_pad_tokens)
                    else 0.0
                ),
                "waste_edges_frac": list(self.waste_edges_frac),
                "waste_counts": list(self.waste_counts),
                "dispatch_edges_ms": list(self.dispatch_edges_ms),
                "variant_timing": {
                    k: {"count": h["count"], "sum_ms": h["sum_ms"],
                        "counts": list(h["counts"])}
                    for k, h in self.variant_ms.items()
                },
            }


class InferenceEngine:
    """Slot-based continuous batching over a single sharded model."""

    def __init__(
        self,
        params: Any,
        cfg: ModelConfig,
        engine_cfg: Optional[EngineConfig] = None,
        mesh=None,
        draft: Optional[Tuple[Any, ModelConfig]] = None,
    ):
        self.cfg = cfg.validate()
        self.ecfg = engine_cfg or EngineConfig()
        self.params = params
        self.mesh = mesh
        # graftmesh: exact tensor parallelism (EngineConfig.tp > 1;
        # models/tp_sharding.py). The gate is the CONFIG field, never
        # the mesh shape — multi-process slice serving already passes a
        # Megatron-sharded mesh here with the default config and must
        # stay byte-identical. With tp > 1 the weights commit onto the
        # mesh under the exact-TP table and self._tp threads sharding
        # constraints through every jitted impl below; tp=1 leaves
        # self._tp None and every partial without the kwarg.
        self._tp = None
        if self.ecfg.tp > 1:
            tp_sharding.validate(self.cfg, self.ecfg.tp)
            if self.cfg.attn_impl in ("flash", "ring"):
                raise ValueError(
                    f"tp={self.ecfg.tp} is not supported with "
                    f"attn_impl={self.cfg.attn_impl!r} — only the gqa "
                    f"attention family is tp-threaded"
                )
            self._tp = tp_sharding.hints(mesh, self.ecfg.tp)
            self.params = tp_sharding.shard_params(mesh, self.cfg, params)
        B = self.ecfg.max_slots

        # Prompt buckets clamped to the cache window (empty -> whole window).
        Smax = self.ecfg.max_seq_len
        self._buckets = tuple(
            b for b in self.ecfg.prompt_buckets if b <= Smax
        ) or (Smax,)

        # Paged KV cache (opt-in, single-process only — the block
        # allocator and tables are host-side state, and multi-process
        # SPMD dispatch decisions must be identical on every host). When
        # enabled, state["cache"] holds one global block pool
        # [L, NB, Hkv, kv_block, (Dh)] instead of the per-slot slab, and
        # every dispatch site branches to a paged twin that reads/writes
        # KV through per-slot int32 block tables. paged_kv=False leaves
        # every dense code path byte-identical.
        self._paged = bool(self.ecfg.paged_kv)
        if self._paged and jax.process_count() > 1:
            logger.warning(
                "paged_kv disabled: host-side block allocator requires a "
                "single-process mesh"
            )
            self._paged = False
        self._paged_prefix = None
        if self._paged:
            from seldon_tpu.servers.block_pool import BlockAllocator

            self._kv_block = self.ecfg.kv_block
            self._nbs = Smax // self._kv_block  # block-table width
            # Default pool: the dense slab's exact token budget
            # (B * Smax tokens) plus the reserved trash block — same HBM,
            # but blocks only bind to streams as they are written.
            self._num_blocks = (
                self.ecfg.kv_pool_blocks or B * self._nbs + 1
            )
            self._allocator = BlockAllocator(self._num_blocks)
            self._table_host = np.zeros((B, self._nbs), np.int32)  # graftlint: guarded-by(_book)

        self._state = self._fresh_state()
        self._active_host = np.zeros((B,), bool)  # control-flow mirror  # graftlint: guarded-by(_book)
        # Serializes slot/free-list/active bookkeeping between the
        # scheduler thread and the boundary-fetcher thread.
        self._book = threading.Lock()
        self._async_fetch = (
            self.ecfg.async_fetch and jax.process_count() == 1
        )
        self._fetch_q: "queue.Queue" = queue.Queue(maxsize=4)
        self._fetcher: Optional[threading.Thread] = None
        self._dispatch_wreck = None  # partial boundary for error paths  # graftlint: guarded-by(_book)
        # Bumped by every device-state rebuild; waves dispatched against
        # an older epoch are discarded at fetch time (see _PendingWave).
        self._wave_epoch = 0  # graftlint: guarded-by(_book)
        # Every dispatched-but-unretired wave, registered under _book at
        # dispatch time and retired under _book by the fetcher (after
        # processing OR after an epoch-stale discard). Requests
        # optimistically recycled out of _slots live ONLY in their
        # wave's roster, and a wave is invisible to _fetch_q scavenging
        # twice per boundary: between dispatch and the (bounded,
        # lock-free) put, and between the fetcher's get and its epoch
        # check. This registry is therefore the authoritative gather
        # source for wave-fault recovery — _gather_wrecked walks it
        # instead of draining the queue, which raced the scheduler's
        # puts and stranded whole waves (epoch-discarded unread, their
        # requests in no book).
        self._inflight_waves: List[_PendingWave] = []  # graftlint: guarded-by(_book)

        # Host-side bookkeeping.
        self._slots: List[Optional[_Request]] = [None] * B  # graftlint: guarded-by(_book)
        self._free: List[int] = list(range(B))  # graftlint: guarded-by(_book)
        self._pending: "queue.Queue[_Request]" = queue.Queue()
        self._waiting: Deque[_Request] = collections.deque()  # graftlint: guarded-by(_book)
        self._rid = 0  # graftlint: guarded-by(_rid_lock)
        self._rid_lock = threading.Lock()
        # rid -> live request, the cancel() routing table (pruned in
        # _complete; shares _rid_lock — both are submit-path touches).
        self._requests: Dict[int, _Request] = {}  # graftlint: guarded-by(_rid_lock)
        self.stats = EngineStats()
        if self._paged:
            self.stats.pool_gauges = self._allocator.snapshot
        self._stop = threading.Event()
        self._draining = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Deterministic fault injection (opt-in; ChaosConfig.from_env
        # lets the CHAOS=1 gate enable it without config plumbing).
        chaos_cfg = self.ecfg.chaos or ChaosConfig.from_env()
        self._chaos: Optional[ChaosMonkey] = None
        if chaos_cfg is not None and chaos_cfg.any_enabled():
            self._chaos = ChaosMonkey(chaos_cfg)
            logger.warning("chaos fault injection enabled: %s", chaos_cfg)
        # graftheal supervised recovery (opt-in; supervisor.build also
        # consults the HEAL=1 env gate). None keeps the _fail_all
        # failure path — and every hot path — byte-identical.
        self._heal: Optional[supervisor.HealSupervisor] = \
            supervisor.build(self.ecfg)
        if self._heal is not None:
            logger.warning(
                "graftheal supervised recovery enabled: %s",
                self._heal.describe(),
            )

        # Largest power of two <= min(max_admit, max_slots).
        ma = max(1, min(self.ecfg.max_admit, B))
        self._max_admit = 1 << (ma.bit_length() - 1)

        # Context-parallel prefill: with attn_impl=="ring" and a mesh
        # carrying a real 'sp' axis, admissions prefill with the prompt
        # sequence sharded across the ring (long-prompt scaling;
        # transformer.prefill). Decode is untouched (T-unsharded cache).
        self._ring_mesh = (
            mesh
        ) if (
            mesh is not None
            and self.cfg.attn_impl == "ring"
            and dict(mesh.shape).get("sp", 1) > 1
        ) else None
        # Conditional tp kwarg: tp=1 partials carry no extra binding at
        # all, so their jit signatures — and traces — are byte-identical
        # to a build without graftmesh.
        tpkw = {"tp": self._tp} if self._tp is not None else {}
        self._jit_admit = jax.jit(
            functools.partial(self._admit_impl, cfg=self.cfg, mesh=mesh,
                              ring_mesh=self._ring_mesh, **tpkw),
            donate_argnums=(1,),
        )
        # Prefix KV cache (opt-in, single-process only — the trie is
        # host-side state, and multi-process SPMD dispatch decisions must
        # be identical on every host). When enabled, COLD admissions run
        # through a variant that also returns the freshly-computed
        # cache-dtype KV (for trie insertion) and WARM admissions run the
        # suffix-only path; self._jit_admit itself stays untouched, so
        # prefix_cache=False keeps today's admission path byte-identical.
        self._prefix = None
        self._jit_admit_sub = None
        self._jit_admit_prefix = None
        if self.ecfg.prefix_cache and self._paged:
            # Paged engines index BLOCK IDS, not KV copies: warm hits
            # refcount cached blocks straight into the new slot's table
            # (zero-copy); the dense PrefixIndex machinery below (gather,
            # seed, insert-with-KV) never runs, so self._prefix stays
            # None and every `_prefix is not None` dense branch stays off.
            from seldon_tpu.servers.prefix_cache import PagedPrefixIndex

            self._paged_prefix = PagedPrefixIndex(
                block=self.ecfg.prefix_block,
                kv_block=self._kv_block,
                allocator=self._allocator,
            )
        elif self.ecfg.prefix_cache:
            if jax.process_count() > 1:
                logger.warning(
                    "prefix_cache disabled: host-side KV index requires a "
                    "single-process mesh"
                )
            else:
                from seldon_tpu.servers.prefix_cache import PrefixIndex

                self._prefix = PrefixIndex(
                    block=self.ecfg.prefix_block,
                    byte_budget=self.ecfg.prefix_cache_bytes,
                )
                self._jit_admit_sub = jax.jit(
                    functools.partial(
                        self._admit_impl, cfg=self.cfg, mesh=mesh,
                        ring_mesh=self._ring_mesh, return_sub=True, **tpkw,
                    ),
                    donate_argnums=(1,),
                )
                self._jit_admit_prefix = jax.jit(
                    functools.partial(
                        self._admit_prefix_impl, cfg=self.cfg, mesh=mesh,
                        **tpkw,
                    ),
                    donate_argnums=(1,),
                )
        # Chunked prefill (opt-in): chunk lengths are bucketed like
        # prompts (`_chunk_buckets` = prompt-bucket rungs clamped to the
        # chunk, so a short final chunk compiles against a snug shape),
        # and resident-prefix widths reuse the prompt buckets. The chunk
        # kernel is one jit keyed on (G, Sc) + static prefix_width.
        self._chunked = bool(self.ecfg.chunked_prefill)
        self._prefilling: Deque[_Request] = collections.deque()  # graftlint: guarded-by(_book)
        self._jit_admit_chunk = None
        self._jit_seed_prefix = None
        self._jit_admit_chunk_paged = None
        if self._chunked:
            C = min(self.ecfg.prefill_chunk, max(self._buckets))
            self._prefill_chunk = C
            self._chunk_buckets = tuple(sorted(
                {min(b, C) for b in self._buckets} | {C}
            ))
            if self._paged:
                self._jit_admit_chunk_paged = jax.jit(
                    functools.partial(
                        self._paged_admit_chunk_impl, cfg=self.cfg,
                        mesh=mesh, **tpkw,
                    ),
                    static_argnames=("prefix_width",),
                    donate_argnums=(1,),
                )
            else:
                self._jit_admit_chunk = jax.jit(
                    functools.partial(
                        self._admit_chunk_impl, cfg=self.cfg, mesh=mesh,
                        return_sub=self._prefix is not None, **tpkw,
                    ),
                    static_argnames=("prefix_width",),
                    donate_argnums=(1,),
                )
            if self._prefix is not None:
                self._jit_seed_prefix = jax.jit(
                    self._seed_prefix_impl, donate_argnums=(0,)
                )
        # Paged dispatch twins: one-shot admission (cold AND warm — the
        # static prefix_width keys the variant, 0 = cold), the block-
        # table decode chunk ladder, and the copy-on-write block copy.
        # The block table is passed as a fresh device array per dispatch
        # (never donated); the pool itself lives inside the donated state.
        self._jit_admit_paged = None
        self._jit_chunks_paged = None
        self._jit_cow = None
        if self._paged:
            self._jit_admit_paged = jax.jit(
                functools.partial(
                    self._paged_admit_impl, cfg=self.cfg, mesh=mesh,
                    **tpkw,
                ),
                static_argnames=("prefix_width",),
                donate_argnums=(1,),
            )
            self._jit_cow = jax.jit(
                self._cow_copy_impl, donate_argnums=(0,)
            )
        # Chunk-length ladder: exactly the three rungs the policy uses
        # (min / geometric mid / top) — every rung costs a full chunk
        # compile, so no speculative intermediates.
        # adaptive_chunk=False keeps the single fixed length.
        top = max(1, self.ecfg.decode_chunk)
        if self.ecfg.adaptive_chunk and top > self.ecfg.min_chunk:
            lo = max(1, min(self.ecfg.min_chunk, top))
            mid = 1 << int(round((lo * top) ** 0.5)).bit_length() - 1
            sizes = [lo, mid, top]
        else:
            sizes = [top]
        self._chunk_sizes = tuple(sorted(set(sizes)))
        self._jit_chunks = {
            n: jax.jit(
                functools.partial(
                    self._chunk_impl,
                    cfg=self.cfg,
                    n_steps=n,
                    mesh=mesh,
                    **tpkw,
                ),
                donate_argnums=(1,),
            )
            for n in self._chunk_sizes
        }
        if self._paged:
            self._jit_chunks_paged = {
                n: jax.jit(
                    functools.partial(
                        self._paged_chunk_impl,
                        cfg=self.cfg,
                        n_steps=n,
                        mesh=mesh,
                        **tpkw,
                    ),
                    donate_argnums=(1,),
                )
                for n in self._chunk_sizes
            }
        # Lifecycle reaping: one masked write freezes cancelled/expired
        # rows. Dispatched ONLY when a reap actually removed a slot, so
        # engines that never see a cancel/deadline keep their dispatch
        # sequence byte-identical.
        self._jit_deactivate = jax.jit(
            self._deactivate_impl, donate_argnums=(0,)
        )
        # graftragged (opt-in): the unified ragged wave — ONE jit serving
        # every mix of cold prefills / chunk continuations / decodes over
        # all B slots (models/ragged_attention.py), so the whole chunk /
        # bucket / group ladder above never dispatches and warmup
        # collapses to {("ragged", C), ("deactivate",)}. Requires the
        # paged + chunked engines (validated in EngineConfig); inherits
        # their single-process restriction through self._paged.
        self._ragged = (
            bool(self.ecfg.ragged) and self._paged and self._chunked
        )
        self._jit_ragged = None
        if self._ragged:
            self._ragged_chunk = min(
                self.ecfg.ragged_chunk or self._prefill_chunk,
                max(self._buckets),
            )
            # graftkern: the kernel leg is a Python constant closed over
            # at jit time — swapping it swaps the trace, never the
            # lattice key, so masked/sparse/pallas all stay inside the
            # ONE ("ragged", C) variant.
            self._jit_ragged = jax.jit(
                functools.partial(
                    self._ragged_impl, cfg=self.cfg, mesh=mesh,
                    kernel=self.ecfg.ragged_kernel,
                    block_budget=self.ecfg.ragged_block_budget, **tpkw,
                ),
                donate_argnums=(1,),
            )
        # graftspec (opt-in): speculative decoding. Each boundary a
        # drafter proposes up to spec_k tokens per live decode slot and
        # ONE wide verify dispatch (models/spec_decode.verify_wave)
        # scores all k+1 positions against the paged pool — the decode
        # chunk ladder never dispatches; ("verify", k) rungs replace it
        # in the lattice. Verification is exact-match against the
        # target's own sequentially-keyed samples, so output streams
        # are bit-identical to spec-off at ANY temperature. Requires
        # the paged engine (validated in EngineConfig); inherits its
        # single-process restriction through self._paged. The loop runs
        # synchronously (process-before-next-dispatch) because rollback
        # must trim block-table tails before the next wave sizes its
        # block growth.
        self._spec = bool(self.ecfg.spec_decode) and self._paged
        if self.ecfg.spec_decode and not self._spec:
            logger.warning(
                "spec_decode disabled: the paged engine it rides on was "
                "disabled (multi-process mesh)"
            )
        self._jit_verify = None
        self._jit_draft = None
        self._drafter = None
        if self._spec:
            from seldon_tpu.servers import spec_decode as spec_host

            self._async_fetch = False
            # Pow2 k ladder 1..spec_k: one verify compile per rung, and
            # the pilot's spec_k knob walks rung-to-rung.
            self._spec_rungs = tuple(
                1 << i for i in range(self.ecfg.spec_k.bit_length())
            )
            self._spec_k_live = self._spec_rungs[-1]  # graftlint: guarded-by(_book)
            self._jit_verify = jax.jit(
                functools.partial(
                    self._verify_impl, cfg=self.cfg, mesh=mesh,
                    kernel=self.ecfg.ragged_kernel,
                    block_budget=self.ecfg.ragged_block_budget, **tpkw,
                ),
                donate_argnums=(1,),
            )
            # Draft model (optional second checkpoint): greedy k-token
            # proposal over a fixed sliding history window, one jit per
            # rung keyed ("draft", k). Without it the host-side n-gram
            # drafter proposes for free.
            self._draft_cfg = None
            self._spec_window = min(64, Smax)
            if draft is not None:
                dparams, dcfg = draft
                self._draft_cfg = dcfg.validate()
                self._jit_draft = {
                    kk: jax.jit(
                        functools.partial(
                            spec_model.draft_tokens,
                            dparams,
                            cfg=self._draft_cfg,
                            k=kk,
                        )
                    )
                    for kk in self._spec_rungs
                }
            self._drafter = spec_host.make_drafter(
                self._jit_draft, self._spec_window, self.cfg.pad_token_id
            )
            # Acceptance accounting (host, under _book): feeds gauges,
            # /debug/sched via the sled, and the pilot's spec_k rule.
            self._spec_drafted = 0  # graftlint: guarded-by(_book)
            self._spec_accepted = 0  # graftlint: guarded-by(_book)
            self._spec_waves = 0  # graftlint: guarded-by(_book)
            # In-flight wave descriptor (k, wave mask, n_wave) between
            # dispatch and _spec_post_process.
            self._spec_wave = None  # graftlint: guarded-by(_book)
        # Request-scoped tracing + flight recorder (both env-gated, both
        # zero hot-path cost when off). Lifecycle spans are emitted
        # retroactively at terminal time from _Request timestamps;
        # perf_counter values convert to wall-clock ns through this
        # init-time epoch pairing (Span timestamps are time_ns-domain).
        self._tracer = tracing.get_tracer("engine")
        self._recorder = flight_recorder.from_env()
        self._epoch_perf = time.perf_counter()
        self._epoch_ns = time.time_ns()
        # Env-gated device-profile window: jax.profiler capture over the
        # first TRACE_PROFILE_N dispatched boundaries (0 = off), so the
        # device timeline can be lined up against the recorder's wall-
        # clock boundary records (tools/profile_decode.py parse pattern).
        self._profile_n = int(os.environ.get("TRACE_PROFILE_N", "0") or 0)
        self._profile_dir = os.environ.get(
            "TRACE_PROFILE_DIR", "/tmp/seldon-tpu-profile"
        )
        self._profile_count = 0
        self._profile_active = False
        # Compile & device observatory: variant ledger + live-retrace
        # witness (COMPILE_LEDGER=1), per-variant boundary timing
        # (DISPATCH_TIMING=1), HBM byte accounting (HBM_LEDGER=1). All
        # None/False when off, and every dispatch site keeps its raw
        # un-timed jit call on the off path — same zero-overhead-off
        # contract as the recorder above.
        self._cledger = compile_ledger.from_env()
        if self._cledger is not None and self._tp is not None:
            # One lattice serves the whole TP group: SPMD partitioning
            # happens inside each jit, so variant keys — and the sealed
            # lattice — are identical to tp=1. The snapshot carries the
            # group geometry so /debug/compile readers can tell an
            # 8-way mesh seal from a single-chip one.
            self._cledger.set_mesh(self.ecfg.tp,
                                   int(self._tp.mesh.devices.size))
        self._timing_on = os.environ.get(
            "DISPATCH_TIMING", "0"
        ) in ("1", "true", "True")
        # graftroof (ROOF_LEDGER=1; None — and zero hot-path code —
        # otherwise): analytical FLOPs/bytes pricing of every dispatch
        # key joined with the measured wave timing into per-variant
        # MFU/MBU, plus the host-pre/device/host-post boundary
        # decomposition served at /debug/roof. The roofline IS the
        # timing join, so ROOF_LEDGER implies DISPATCH_TIMING (the
        # PILOT-implies-sched-ledger idiom).
        self._roof = cost_model.from_env()
        if self._roof is not None:
            self._timing_on = True
            dev = jax.devices()[0]
            self._roof.bind(
                self.cfg,
                max_slots=self.ecfg.max_slots,
                max_seq_len=self.ecfg.max_seq_len,
                kv_block=self._kv_block if self._paged else 0,
                ragged_chunk=self._ragged_chunk if self._ragged else 0,
                draft_cfg=getattr(self, "_draft_cfg", None),
                platform=(getattr(dev, "device_kind", "") or dev.platform),
                tp=self.ecfg.tp if self._tp is not None else 1,
            )
        self._observe = self._cledger is not None or self._timing_on
        # Variant keys dispatched since the last boundary sync, paired
        # with the boundary wall time in _process_boundary. Written only
        # by the scheduler thread between dispatch and boundary.
        self._wave_keys: List[Tuple[Any, ...]] = []
        # Roofline decomposition taps (all dead when _roof is None):
        # dispatch-step entry stamp and the wave's accumulated jit
        # enqueue seconds. Same single-writer contract as _wave_keys
        # (scheduler thread between dispatch and boundary; warmup and
        # the pre-thread start() reset run before the scheduler exists).
        self._step_t0 = 0.0
        self._wave_enq_s = 0.0
        self._hbm = hbm_ledger.from_env()
        if self._hbm is not None:
            if self._tp is None:
                self._hbm.set_static("weights", sum(
                    int(x.nbytes)
                    for x in jax.tree_util.tree_leaves(params)
                ))
                self._hbm.gauge("kv_cache", self._hbm_kv_reserved_bytes)
                self._hbm.gauge("kv_live", self._hbm_kv_live_bytes)
                self._hbm.gauge("prefix_cache", self._hbm_prefix_bytes)
            else:
                # Per-device accounting on the mesh: weights are priced
                # from each leaf's committed shard shape (replicated
                # leaves cost a full copy per device, sharded leaves
                # their slice — the exact-TP split); the mesh-total is
                # devices x per-device resident bytes, so the ledger's
                # conservation total == sum(categories) keeps holding
                # per device AND mesh-wide. KV shards exactly on the
                # head axis, so per-device = logical // tp.
                tpn = self.ecfg.tp
                self._hbm.set_devices(tpn)
                per_dev = self._hbm_weights_device_bytes()
                self._hbm.set_static("weights", per_dev * tpn,
                                     per_device=per_dev)
                self._hbm.gauge(
                    "kv_cache", self._hbm_kv_reserved_bytes,
                    per_device_fn=lambda:
                        self._hbm_kv_reserved_bytes() // tpn)
                self._hbm.gauge(
                    "kv_live", self._hbm_kv_live_bytes,
                    per_device_fn=lambda:
                        self._hbm_kv_live_bytes() // tpn)
                self._hbm.gauge("prefix_cache", self._hbm_prefix_bytes)
        # Scheduler waste observatory (SCHED_LEDGER=1; None — and zero
        # hot-path code — otherwise): per-boundary goodput attribution,
        # queue-wait decomposition, and the conservation audit that
        # runs next to graftsan's boundary audits.
        self._sled = sched_ledger.from_env()
        # graftpilot (PILOT=1 auto / PILOT=hold pinned; None — and the
        # raw FIFO dispatch path — otherwise): bounded feedback
        # controller over dispatch_token_budget / admission group size /
        # chunk rung plus EDF deadline ordering, with the decision
        # ledger served at /debug/pilot. The sched ledger is its signal
        # source, so PILOT implies one even without SCHED_LEDGER=1.
        self._pilot = controller.from_env()
        if self._pilot is not None:
            if self._sled is None:
                self._sled = sched_ledger.SchedLedger()
            self._pilot.bind(
                chunked=self._chunked,
                prefill_chunk=self._prefill_chunk if self._chunked else 0,
                max_slots=self.ecfg.max_slots,
                max_admit=self._max_admit,
                dispatch_token_budget=self.ecfg.dispatch_token_budget,
                spec=self._spec,
                spec_rungs=self._spec_rungs if self._spec else (),
            )
        # Runtime concurrency sanitizer (GRAFTSAN=1; None — and zero
        # hot-path code — otherwise). Wraps every lock above in an
        # order-asserting proxy, so this must stay the LAST piece of
        # engine state __init__ builds.
        self._san = graftsan.instrument(self)

    def _fresh_state(self) -> Dict[str, Any]:
        B, Smax = self.ecfg.max_slots, self.ecfg.max_seq_len
        if self._paged:
            cache = transformer.init_paged_cache(
                self.cfg, self._num_blocks, self._kv_block
            )
        else:
            cache = transformer.init_cache(self.cfg, B, Smax)
        state = {
            "cache": cache,
            "last_tok": jnp.zeros((B,), jnp.int32),
            "pos": jnp.zeros((B,), jnp.int32),
            "active": jnp.zeros((B,), jnp.bool_),
            "temp": jnp.ones((B,), jnp.float32),
            "top_k": jnp.zeros((B,), jnp.int32),
            "top_p": jnp.ones((B,), jnp.float32),
            "seeds": jnp.zeros((B,), jnp.uint32),
            "remaining": jnp.zeros((B,), jnp.int32),
        }
        if self._tp is not None:
            # Commit the state onto the mesh (KV heads on 'tp', per-slot
            # scalars replicated) so the FIRST dispatch already sees the
            # shardings every impl's constrain_state pins — one stable
            # jit cache key from wave zero.
            state = tp_sharding.shard_state(self._tp.mesh, state)
        return state

    # --- jitted kernels -----------------------------------------------------

    @staticmethod
    def _replicate(mesh, *arrays):
        """Pin host-visible outputs to full replication. On a
        multi-PROCESS mesh, device_get needs every shard addressable
        locally — without this GSPMD may shard the small result arrays
        across hosts. No-op cost on a single chip."""
        if mesh is None:
            return arrays
        from jax.sharding import NamedSharding, PartitionSpec

        rep = NamedSharding(mesh, PartitionSpec())
        return tuple(
            jax.lax.with_sharding_constraint(a, rep) for a in arrays
        )

    @staticmethod
    def _admit_impl(
        params, state, toks, plens, seeds, temps, top_ks, top_ps,
        max_news, slots, *, cfg, mesh=None, ring_mesh=None,
        return_sub=False, tp=None,
    ):
        """Fused admission: prefill [G, Sb], scatter into cache slots, sample
        first tokens, arm slot state. One dispatch, no host sync.

        Each row's first token is keyed by fold_in(key(seed), plen), matching
        the decode convention fold_in(key(seed), pos+1): the same seed and
        prompt reproduce the completion regardless of co-batched traffic.
        Duplicate slot indices (admission padding rows) carry identical data,
        so the duplicate scatter writes are well-defined."""
        G, Sb = toks.shape
        sub = transformer.init_cache(cfg, G, Sb)
        if ring_mesh is not None:
            sp = dict(ring_mesh.shape).get("sp", 1)
            if Sb % sp != 0:  # static per-bucket decision
                ring_mesh = None
        logits, sub = transformer.prefill(params, toks, plens, sub, cfg,
                                          ring_mesh=ring_mesh, tp=tp)
        keys = jax.vmap(
            lambda s, p: jax.random.fold_in(jax.random.key(s), p)
        )(seeds, plens)
        first = sample_per_row(logits, keys, temps, top_ks, top_ps)

        cache = state["cache"]
        Smax = cache["k"].shape[3]
        first_done = (
            (first == cfg.eos_token_id)
            | (max_news <= 1)
            | (plens + 1 >= Smax)
        )
        # Scatter EVERY cache array (k/v + scales for quantized caches —
        # all share the head-major [L, B, Hkv, T, ...] layout, with T at
        # dim 3 of k/v and trailing on the scales, so one indexing
        # expression covers them all).
        new_cache = {
            key: cache[key].at[:, slots, :, :Sb].set(
                sub[key].astype(cache[key].dtype)
            )
            for key in cache
        }
        new_state = {
            "cache": new_cache,
            "last_tok": state["last_tok"].at[slots].set(first),
            "pos": state["pos"].at[slots].set(plens),
            "active": state["active"].at[slots].set(~first_done),
            "temp": state["temp"].at[slots].set(temps),
            "top_k": state["top_k"].at[slots].set(top_ks),
            "top_p": state["top_p"].at[slots].set(top_ps),
            "seeds": state["seeds"].at[slots].set(seeds),
            "remaining": state["remaining"].at[slots].set(max_news - 1),
        }
        if tp is not None:
            new_state = tp.constrain_state(new_state)
        first, first_done = InferenceEngine._replicate(
            mesh, first, first_done
        )
        if return_sub:
            # Prefix-cache insertion path: `sub` already holds the
            # cache-dtype KV writes [L, G, Hkv, Sb, (Dh)] the host slices
            # into trie blocks.
            return new_state, first, first_done, sub
        return new_state, first, first_done

    @staticmethod
    def _admit_prefix_impl(
        params, state, toks, plens, prefix_lens, prefix_kv, seeds, temps,
        top_ks, top_ps, max_news, slots, *, cfg, mesh=None, tp=None,
    ):
        """Fused WARM admission: suffix-only prefill attending to reused
        prefix KV, prefix + suffix scattered into the slot cache, first
        tokens sampled, slot state armed — the prefix-cache twin of
        _admit_impl.

        `toks` holds ONLY each prompt's uncached suffix [G, Sq]; `plens`
        are FULL prompt lengths, so the first-token sampling key
        fold_in(key(seed), plen) matches the cold path bit-for-bit.
        `prefix_kv` arrives in cache storage dtype [L, G, Hkv, Pb, (Dh)]
        (gathered host-side from the trie, zero-padded past each row's
        prefix_len — the padded tail is overwritten by the suffix scatter
        below, and decode's strict t < pos mask never reads past-plen
        garbage before it is rewritten)."""
        G, Sq = toks.shape
        logits, kv = transformer.prefill_with_prefix(
            params, toks, plens, prefix_kv, prefix_lens, cfg, tp=tp
        )
        keys = jax.vmap(
            lambda s, p: jax.random.fold_in(jax.random.key(s), p)
        )(seeds, plens)
        first = sample_per_row(logits, keys, temps, top_ks, top_ps)

        cache = state["cache"]
        Smax = cache["k"].shape[3]
        first_done = (
            (first == cfg.eos_token_id)
            | (max_news <= 1)
            | (plens + 1 >= Smax)
        )
        if cfg.kv_cache_dtype == "int8":
            kq, ks = transformer._quantize_kv(kv["k"])
            vq, vs = transformer._quantize_kv(kv["v"])
            writes = {"k": kq, "v": vq, "k_scale": ks, "v_scale": vs}
        else:
            dt = cache["k"].dtype
            writes = {"k": kv["k"].astype(dt), "v": kv["v"].astype(dt)}
        Pb = prefix_kv["k"].shape[3]
        # Suffix rows land at absolute positions prefix_len + i; rows past
        # the cache window drop out of the scatter (jax default OOB mode).
        spos = prefix_lens[:, None] + jnp.arange(Sq)[None, :]  # [G, Sq]
        new_cache = {}
        for key in cache:
            c = cache[key].at[:, slots, :, :Pb].set(
                prefix_kv[key].astype(cache[key].dtype)
            )
            # Advanced indices (slots, spos) broadcast to [G, Sq] and land
            # in front: update operand is writes[key] [L, G, Hkv, Sq, ...]
            # with G and Sq moved to the front.
            new_cache[key] = c.at[:, slots[:, None], :, spos].set(
                jnp.moveaxis(writes[key], (1, 3), (0, 1))
            )
        new_state = {
            "cache": new_cache,
            "last_tok": state["last_tok"].at[slots].set(first),
            "pos": state["pos"].at[slots].set(plens),
            "active": state["active"].at[slots].set(~first_done),
            "temp": state["temp"].at[slots].set(temps),
            "top_k": state["top_k"].at[slots].set(top_ks),
            "top_p": state["top_p"].at[slots].set(top_ps),
            "seeds": state["seeds"].at[slots].set(seeds),
            "remaining": state["remaining"].at[slots].set(max_news - 1),
        }
        if tp is not None:
            new_state = tp.constrain_state(new_state)
        first, first_done = InferenceEngine._replicate(
            mesh, first, first_done
        )
        return new_state, first, first_done, writes

    @staticmethod
    def _admit_chunk_impl(
        params, state, toks, plens, starts, seeds, temps, top_ks, top_ps,
        max_news, slots, finals, *, prefix_width, cfg, mesh=None,
        return_sub=False, tp=None,
    ):
        """Fused prefill CHUNK: run `toks` [G, Sc] (tokens
        [start, start+Sc) of each prompt) through prefill_with_prefix
        against the KV that chunks 0..k-1 (and any prefix-cache hit)
        already scattered into the slot cache, then scatter the fresh
        suffix KV back. Rows with finals=True are each prompt's LAST
        chunk: they sample the first token under the same
        fold_in(key(seed), plen) key as _admit_impl — co-batched chunk
        traffic cannot perturb greedy outputs — and arm the slot. Non-
        final rows only deposit KV; their sampled token is discarded.

        `prefix_width` (static) buckets how much resident KV the chunk
        attends to: the slice cache[:, slots, :, :W] covers every row's
        start (start <= W), and prefill_with_prefix's t < start mask
        hides the tail. pos is set to start+Sc (clamped to plen) even
        mid-prefill so the decode chunks interleaved between prefill
        chunks scatter their dead-row garbage write exactly where the
        NEXT chunk's scatter lands first — never inside KV already
        written."""
        G, Sc = toks.shape
        cache = state["cache"]
        Smax = cache["k"].shape[3]
        prefix_kv = {
            key: cache[key][:, slots, :, :prefix_width] for key in cache
        }
        logits, kv = transformer.prefill_with_prefix(
            params, toks, plens, prefix_kv, starts, cfg, tp=tp
        )
        keys = jax.vmap(
            lambda s, p: jax.random.fold_in(jax.random.key(s), p)
        )(seeds, plens)
        first = sample_per_row(logits, keys, temps, top_ks, top_ps)
        first_done = (
            (first == cfg.eos_token_id)
            | (max_news <= 1)
            | (plens + 1 >= Smax)
        )
        new_pos = jnp.minimum(plens, starts + Sc)
        if cfg.kv_cache_dtype == "int8":
            kq, ks = transformer._quantize_kv(kv["k"])
            vq, vs = transformer._quantize_kv(kv["v"])
            writes = {"k": kq, "v": vq, "k_scale": ks, "v_scale": vs}
        else:
            dt = cache["k"].dtype
            writes = {"k": kv["k"].astype(dt), "v": kv["v"].astype(dt)}
        # Chunk rows land at absolute positions start + i (same advanced-
        # indexing shape as _admit_prefix_impl's suffix scatter); padding
        # rows duplicate a real row's slot + data, so duplicate writes
        # are well-defined.
        spos = starts[:, None] + jnp.arange(Sc)[None, :]  # [G, Sc]
        new_cache = {
            key: cache[key].at[:, slots[:, None], :, spos].set(
                jnp.moveaxis(writes[key], (1, 3), (0, 1))
            )
            for key in cache
        }
        new_state = {
            "cache": new_cache,
            "last_tok": state["last_tok"].at[slots].set(first),
            "pos": state["pos"].at[slots].set(new_pos),
            "active": state["active"].at[slots].set(finals & ~first_done),
            "temp": state["temp"].at[slots].set(temps),
            "top_k": state["top_k"].at[slots].set(top_ks),
            "top_p": state["top_p"].at[slots].set(top_ps),
            "seeds": state["seeds"].at[slots].set(seeds),
            "remaining": state["remaining"].at[slots].set(max_news - 1),
        }
        if tp is not None:
            new_state = tp.constrain_state(new_state)
        first, first_done = InferenceEngine._replicate(
            mesh, first, first_done
        )
        if return_sub:
            return new_state, first, first_done, writes
        return new_state, first, first_done

    @staticmethod
    def _seed_prefix_impl(state, prefix_kv, slot):
        """Chunked-prefill warm start: scatter a prefix-cache hit's
        trie-gathered KV [L, Hkv, W, (Dh)] into one slot's cache rows
        [0, W), so every chunk reads resident KV uniformly whether it
        came from the trie or from earlier chunks."""
        cache = state["cache"]
        W = prefix_kv["k"].shape[2]
        new_cache = {
            key: cache[key].at[:, slot, :, :W].set(
                prefix_kv[key].astype(cache[key].dtype)
            )
            for key in cache
        }
        return {**state, "cache": new_cache}

    @staticmethod
    def _chunk_impl(params, state, *, cfg, n_steps, mesh=None, tp=None):
        """`n_steps` decode iterations over every slot in one lax.scan.
        Per-row termination (EOS / length budget / cache window) is
        value-level: finished rows stop advancing and emit invalid tokens
        until the chunk boundary. Returns (state, toks [K,B], valid [K,B])."""
        Smax = state["cache"]["k"].shape[3]

        def step(carry, _):
            run = carry["active"]
            logits, cache = transformer.decode_step(
                params, carry["last_tok"], carry["pos"], carry["cache"],
                cfg, tp=tp,
            )
            keys = jax.vmap(
                lambda s, p: jax.random.fold_in(jax.random.key(s), p + 1)
            )(carry["seeds"], carry["pos"])
            # Mask inactive rows' knobs so stale top_k/top_p in freed slots
            # can't force the sampler's O(V log V) masking path forever.
            tok = sample_per_row(
                logits,
                keys,
                carry["temp"],
                jnp.where(run, carry["top_k"], 0),
                jnp.where(run, carry["top_p"], 1.0),
            )
            tok = jnp.where(run, tok, cfg.pad_token_id)
            pos = carry["pos"] + run.astype(jnp.int32)
            remaining = carry["remaining"] - run.astype(jnp.int32)
            done = run & (
                (tok == cfg.eos_token_id)
                | (remaining <= 0)
                | (pos >= Smax - 1)
            )
            new_carry = {
                **carry,
                "cache": cache,
                "last_tok": jnp.where(run, tok, carry["last_tok"]),
                "pos": pos,
                "active": carry["active"] & ~done,
                "remaining": remaining,
            }
            return new_carry, (tok, run)

        state, (toks, valid) = jax.lax.scan(step, state, None, length=n_steps)
        if tp is not None:
            state = tp.constrain_state(state)
        toks, valid, active = InferenceEngine._replicate(
            mesh, toks, valid, state["active"]
        )
        return state, toks, valid, active

    # --- paged-KV kernels ---------------------------------------------------

    @staticmethod
    def _paged_admit_impl(
        params, state, table, toks, plens, prefix_lens, seeds, temps,
        top_ks, top_ps, max_news, slots, *, prefix_width, cfg, mesh=None,
        tp=None,
    ):
        """Paged fused admission — ONE kernel covers cold and warm.

        prefix_width == 0 (cold): full-prompt prefill into a scratch
        cache, exactly _admit_impl's math, then the writes scatter into
        the pool THROUGH the group's block tables instead of contiguous
        slot rows. prefix_width > 0 (warm): the reused prefix is a pure
        GATHER of the table's first prefix_width/kv_block blocks — the
        blocks a zero-copy admission just refcounted from the trie — fed
        to the same prefill_with_prefix as the dense warm path, so greedy
        outputs stay bit-identical while the admission moves no prefix
        KV at all. Suffix positions past a row's allocated blocks route
        to the trash block (paged_scatter_tokens), mirroring the dense
        path's dropped OOB scatter rows."""
        G, Sb = toks.shape
        pool = state["cache"]
        block = pool["k"].shape[3]
        Smax = table.shape[1] * block
        if prefix_width:
            prefix_kv = transformer.paged_prefix_view(
                pool, table, prefix_width // block
            )
            logits, kv = transformer.prefill_with_prefix(
                params, toks, plens, prefix_kv, prefix_lens, cfg, tp=tp
            )
            if cfg.kv_cache_dtype == "int8":
                kq, ks = transformer._quantize_kv(kv["k"])
                vq, vs = transformer._quantize_kv(kv["v"])
                writes = {"k": kq, "v": vq, "k_scale": ks, "v_scale": vs}
            else:
                dt = pool["k"].dtype
                writes = {"k": kv["k"].astype(dt), "v": kv["v"].astype(dt)}
            spos = prefix_lens[:, None] + jnp.arange(Sb)[None, :]
        else:
            sub = transformer.init_cache(cfg, G, Sb)
            logits, writes = transformer.prefill(params, toks, plens, sub,
                                                 cfg, tp=tp)
            spos = jnp.broadcast_to(jnp.arange(Sb)[None, :], (G, Sb))
        keys = jax.vmap(
            lambda s, p: jax.random.fold_in(jax.random.key(s), p)
        )(seeds, plens)
        first = sample_per_row(logits, keys, temps, top_ks, top_ps)
        first_done = (
            (first == cfg.eos_token_id)
            | (max_news <= 1)
            | (plens + 1 >= Smax)
        )
        new_pool = transformer.paged_scatter_tokens(pool, writes, table,
                                                    spos)
        new_state = {
            "cache": new_pool,
            "last_tok": state["last_tok"].at[slots].set(first),
            "pos": state["pos"].at[slots].set(plens),
            "active": state["active"].at[slots].set(~first_done),
            "temp": state["temp"].at[slots].set(temps),
            "top_k": state["top_k"].at[slots].set(top_ks),
            "top_p": state["top_p"].at[slots].set(top_ps),
            "seeds": state["seeds"].at[slots].set(seeds),
            "remaining": state["remaining"].at[slots].set(max_news - 1),
        }
        if tp is not None:
            new_state = tp.constrain_state(new_state)
        first, first_done = InferenceEngine._replicate(
            mesh, first, first_done
        )
        return new_state, first, first_done

    @staticmethod
    def _paged_admit_chunk_impl(
        params, state, table, toks, plens, starts, seeds, temps, top_ks,
        top_ps, max_news, slots, finals, *, prefix_width, cfg, mesh=None,
        tp=None,
    ):
        """Paged twin of _admit_chunk_impl: the resident KV of chunks
        0..k-1 (and any zero-copy warm prefix) is a block-table GATHER of
        each row's first prefix_width/kv_block blocks instead of a slab
        slice, and the fresh chunk KV scatters back through the table.
        Attention math, sampling keys, and slot-state writes are
        identical, so greedy outputs match the dense chunked path
        bit-for-bit. No writes are returned — paged trie insertion is
        host-side block bookkeeping, not device KV."""
        G, Sc = toks.shape
        pool = state["cache"]
        block = pool["k"].shape[3]
        Smax = table.shape[1] * block
        prefix_kv = transformer.paged_prefix_view(
            pool, table, prefix_width // block
        )
        logits, kv = transformer.prefill_with_prefix(
            params, toks, plens, prefix_kv, starts, cfg, tp=tp
        )
        keys = jax.vmap(
            lambda s, p: jax.random.fold_in(jax.random.key(s), p)
        )(seeds, plens)
        first = sample_per_row(logits, keys, temps, top_ks, top_ps)
        first_done = (
            (first == cfg.eos_token_id)
            | (max_news <= 1)
            | (plens + 1 >= Smax)
        )
        new_pos = jnp.minimum(plens, starts + Sc)
        if cfg.kv_cache_dtype == "int8":
            kq, ks = transformer._quantize_kv(kv["k"])
            vq, vs = transformer._quantize_kv(kv["v"])
            writes = {"k": kq, "v": vq, "k_scale": ks, "v_scale": vs}
        else:
            dt = pool["k"].dtype
            writes = {"k": kv["k"].astype(dt), "v": kv["v"].astype(dt)}
        spos = starts[:, None] + jnp.arange(Sc)[None, :]
        new_pool = transformer.paged_scatter_tokens(pool, writes, table,
                                                    spos)
        new_state = {
            "cache": new_pool,
            "last_tok": state["last_tok"].at[slots].set(first),
            "pos": state["pos"].at[slots].set(new_pos),
            "active": state["active"].at[slots].set(finals & ~first_done),
            "temp": state["temp"].at[slots].set(temps),
            "top_k": state["top_k"].at[slots].set(top_ks),
            "top_p": state["top_p"].at[slots].set(top_ps),
            "seeds": state["seeds"].at[slots].set(seeds),
            "remaining": state["remaining"].at[slots].set(max_news - 1),
        }
        if tp is not None:
            new_state = tp.constrain_state(new_state)
        first, first_done = InferenceEngine._replicate(
            mesh, first, first_done
        )
        return new_state, first, first_done

    @staticmethod
    def _paged_chunk_impl(params, state, table, *, cfg, n_steps, mesh=None,
                          tp=None):
        """Paged twin of _chunk_impl: `n_steps` decode iterations reading
        K/V through the block tables (transformer.paged_decode_step).
        Per-row termination, sampling keys and masking are identical, so
        greedy tokens match the dense chunk bit-for-bit. Inactive rows'
        garbage writes route through table entry 0 (trash) once the host
        zeroes a freed row — the paged analogue of the dense path's
        frozen-position scribble."""
        block = state["cache"]["k"].shape[3]
        Smax = table.shape[1] * block

        def step(carry, _):
            run = carry["active"]
            logits, pool = transformer.paged_decode_step(
                params, carry["last_tok"], carry["pos"], carry["cache"],
                table, cfg, tp=tp,
            )
            keys = jax.vmap(
                lambda s, p: jax.random.fold_in(jax.random.key(s), p + 1)
            )(carry["seeds"], carry["pos"])
            tok = sample_per_row(
                logits,
                keys,
                carry["temp"],
                jnp.where(run, carry["top_k"], 0),
                jnp.where(run, carry["top_p"], 1.0),
            )
            tok = jnp.where(run, tok, cfg.pad_token_id)
            pos = carry["pos"] + run.astype(jnp.int32)
            remaining = carry["remaining"] - run.astype(jnp.int32)
            done = run & (
                (tok == cfg.eos_token_id)
                | (remaining <= 0)
                | (pos >= Smax - 1)
            )
            new_carry = {
                **carry,
                "cache": pool,
                "last_tok": jnp.where(run, tok, carry["last_tok"]),
                "pos": pos,
                "active": carry["active"] & ~done,
                "remaining": remaining,
            }
            return new_carry, (tok, run)

        state, (toks, valid) = jax.lax.scan(step, state, None,
                                            length=n_steps)
        if tp is not None:
            state = tp.constrain_state(state)
        toks, valid, active = InferenceEngine._replicate(
            mesh, toks, valid, state["active"]
        )
        return state, toks, valid, active

    @staticmethod
    def _deactivate_impl(state, keep):
        """Freeze rows where keep=False (cancel/deadline reap): dropping
        `active` and zeroing `remaining` makes the row indistinguishable
        from one that just hit EOS — the decode chunk's masking already
        handles frozen pos, clamped sampler knobs, and (paged) trash-
        routed garbage writes, so no new device invariants appear."""
        return {
            **state,
            "active": state["active"] & keep,
            "remaining": jnp.where(keep, state["remaining"], 0),
        }

    @staticmethod
    def _cow_copy_impl(state, src, dst):
        """Copy-on-write block copy: duplicate pool block `src` into
        `dst` (every cache array — k/v and int8 scales). src/dst are
        traced scalars, so all CoW copies share one compile. Dispatched
        BEFORE the warm admission that writes into `dst`, and `src` is
        pinned by the request's trie handle, so device ordering makes
        the copy race-free."""
        pool = state["cache"]
        new_pool = {
            key: pool[key].at[:, dst].set(pool[key][:, src])
            for key in pool
        }
        return {**state, "cache": new_pool}

    @staticmethod
    def _ragged_impl(
        params, state, table, tokens, plens, starts, seeds, temps,
        top_ks, top_ps, max_news, finals, is_prefill, *, cfg, mesh=None,
        tp=None, kernel="masked", block_budget=0,
    ):
        """graftragged: the ONE unified wave — every slot's prefill
        segment of the flat token buffer plus one decode step for every
        armed row, fused into a single trace
        (models/ragged_attention.ragged_wave). Descriptors are [B]
        arrays, the token buffer is [B * ragged_chunk]; nothing about
        the live mix is a shape, so this compiles exactly once. The
        wave math IS _paged_admit_chunk_impl + _paged_chunk_impl(1)
        with masking instead of slot-gather, so greedy outputs stay
        bit-identical to the bucketed engine (tests/test_ragged.py)."""
        state, first, first_done, toks, valid = ragged_attention.ragged_wave(
            params, state, table, tokens, plens, starts, seeds, temps,
            top_ks, top_ps, max_news, finals, is_prefill, cfg, tp=tp,
            kernel=kernel, block_budget=block_budget,
        )
        if tp is not None:
            state = tp.constrain_state(state)
        first, first_done, toks, valid, active = InferenceEngine._replicate(
            mesh, first, first_done, toks, valid, state["active"]
        )
        return state, first, first_done, toks, valid, active

    @staticmethod
    def _verify_impl(params, state, table, drafts, wave, *, cfg,
                     mesh=None, tp=None, kernel="masked",
                     block_budget=0):
        """graftspec: ONE wide verify dispatch replacing up to k + 1
        sequential decode steps (models/spec_decode.verify_wave). The
        k rung is carried by the drafts width — one compile per rung,
        keyed ("verify", k) in the lattice. Returns the decode chunk's
        exact contract (toks/valid are [k+1, B] True-prefix columns),
        so _process_chunk consumes a wave unchanged."""
        state, toks, valid = spec_model.verify_wave(
            params, state, table, drafts, wave, cfg, tp=tp,
            kernel=kernel, block_budget=block_budget,
        )
        if tp is not None:
            state = tp.constrain_state(state)
        toks, valid, active = InferenceEngine._replicate(
            mesh, toks, valid, state["active"]
        )
        return state, toks, valid, active

    # --- public API ---------------------------------------------------------

    def submit(
        self, tokens: Sequence[int], params: Optional[SamplingParams] = None
    ) -> "queue.Queue[Optional[dict]]":
        """Enqueue a request. Returns a queue yielding
        {"tokens": [int, ...], "ttft_ms": float?} dicts (one per scheduler
        boundary — tokens arrive in decode-chunk bursts), then None at
        end."""
        params = params or SamplingParams()
        if len(tokens) == 0:
            raise ValueError("empty prompt")
        max_prompt = max(self._buckets)
        if len(tokens) > max_prompt:
            raise ValueError(
                f"prompt length {len(tokens)} exceeds max bucket {max_prompt}"
            )
        if len(tokens) + params.max_new_tokens > self.ecfg.max_seq_len:
            raise ValueError(
                f"prompt length {len(tokens)} + max_new_tokens "
                f"{params.max_new_tokens} exceeds max_seq_len "
                f"{self.ecfg.max_seq_len}; the decode would be truncated "
                f"mid-stream — lower max_new_tokens or shorten the prompt"
            )
        if self._paged:
            need = -(-len(tokens) // self._kv_block)
            if need > self._num_blocks - 1:
                raise ValueError(
                    f"prompt needs {need} kv blocks but the pool holds "
                    f"{self._num_blocks - 1}; it can never be admitted — "
                    f"raise kv_pool_blocks or shorten the prompt"
                )
        if self._draining.is_set() or self._stop.is_set():
            raise EngineDraining(
                "engine is draining; retry against another replica"
            )
        if self.ecfg.max_queue:
            # _book makes the depth a coherent snapshot: _waiting is the
            # scheduler's queue and mutates under the bookkeeping lock.
            with self._book:
                depth = self._pending.qsize() + len(self._waiting)
        if self.ecfg.max_queue and depth >= self.ecfg.max_queue:
            with self.stats.lock:
                self.stats.queue_rejects += 1
                self.stats.shed_total += 1
            raise EngineOverloaded(
                f"admission queue full ({self.ecfg.max_queue} requests); "
                f"retry with backoff"
            )
        now = time.perf_counter()
        out_q = (
            queue.Queue() if self._san is None
            else graftsan.TerminalQueue(self._san)
        )
        req = _Request(0, list(tokens), params, out_q, now)
        ttl_ms = params.deadline_ms or self.ecfg.default_deadline_ms
        if ttl_ms:
            req.deadline = now + ttl_ms / 1000.0
        with self._rid_lock:
            self._rid += 1
            req.rid = self._rid
            self._requests[req.rid] = req
        # Transports read the rid off the returned queue to cancel() a
        # request whose client vanished mid-stream.
        req.out.rid = req.rid
        if self._tracer.enabled and params.traceparent:
            req.trace = tracing.SpanContext.from_traceparent(
                params.traceparent
            )
        if self._recorder is not None:
            self._recorder.record(
                "submit", req.rid,
                {"prompt_tokens": len(req.tokens), "deadline_ms": ttl_ms},
            )
        with self.stats.lock:
            self.stats.requests += 1
        self._pending.put(req)
        return req.out

    def generate_blocking(
        self, tokens: Sequence[int], params: Optional[SamplingParams] = None
    ) -> Dict[str, Any]:
        """Submit and collect the full completion. Raises RuntimeError if the
        engine failed the request (bad params, decode error)."""
        out = self.submit(tokens, params)
        toks: List[int] = []
        ttft_ms = None
        error = None
        while True:
            item = out.get()
            if item is None:
                break
            if "error" in item:
                error = item
                continue
            toks.extend(item["tokens"])
            if ttft_ms is None:
                ttft_ms = item.get("ttft_ms")
        if error is not None:
            exc = RuntimeError(f"generation failed: {error['error']}")
            # Typed-outcome surface for transports: lifecycle kind plus
            # whether a retry elsewhere could succeed.
            exc.kind = error.get("kind", "internal")
            exc.retriable = bool(error.get("retriable", False))
            exc.http_status = KIND_HTTP_STATUS.get(exc.kind, 500)
            raise exc
        return {"token_ids": toks, "ttft_ms": ttft_ms}

    def cancel(self, rid: int) -> bool:
        """Flag a request for cancellation; the scheduler reaps it at the
        next boundary (queued -> shed, in-flight -> device row frozen and
        slot/blocks/trie refs freed). Returns False for unknown or
        already-finished rids — cancel is then a harmless no-op, which is
        exactly what a disconnect race wants. Thread-safe."""
        with self._rid_lock:
            req = self._requests.get(rid)
        if req is None or req.finished:
            return False
        req.cancelled = True
        return True

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def debug_timeline(self) -> Optional[Dict[str, Any]]:
        """Flight-recorder snapshot (oldest-first records + epoch info),
        or None when FLIGHT_RECORDER is off — the /debug/timeline
        payload, and tools/trace_view.py's input."""
        if self._recorder is None:
            return None
        return self._recorder.snapshot()

    def debug_compile(self) -> Optional[Dict[str, Any]]:
        """Compile-ledger snapshot (variant lattice, warmup coverage,
        live-retrace witnesses, cumulative compile seconds), or None
        when COMPILE_LEDGER is off — the /debug/compile payload."""
        if self._cledger is None:
            return None
        return self._cledger.snapshot()

    def debug_hbm(self) -> Optional[Dict[str, Any]]:
        """HBM-ledger snapshot (per-category bytes + high-watermarks),
        or None when HBM_LEDGER is off — the /debug/hbm payload."""
        if self._hbm is None:
            return None
        return self._hbm.snapshot()

    def debug_sched(self) -> Optional[Dict[str, Any]]:
        """Sched-ledger snapshot (per-boundary waste attribution,
        goodput-gap decomposition, queue-wait components, conservation
        audit), or None when SCHED_LEDGER is off — the /debug/sched
        payload."""
        if self._sled is None:
            return None
        return self._sled.snapshot()

    def debug_pilot(self) -> Optional[Dict[str, Any]]:
        """Pilot-controller snapshot (live knobs, envelope, EDF
        counters, decision ledger with counterfactual effects), or None
        when PILOT is off — the /debug/pilot payload. Unlike the other
        ledgers the controller's state is guarded-by(_book) (it IS
        scheduler state), so the snapshot takes the lock: cold path,
        bounded ledger, legal from the HTTP thread."""
        if self._pilot is None:
            return None
        with self._book:
            return self._pilot.snapshot()

    def debug_roof(self) -> Optional[Dict[str, Any]]:
        """Roofline snapshot (per-variant MFU/MBU against the platform
        peaks, host-pre/device/host-post boundary decomposition,
        conservation audit), or None when ROOF_LEDGER is off — the
        /debug/roof payload. Lock-free like the sched ledger: the
        window may tear, a record never does."""
        if self._roof is None:
            return None
        return self._roof.snapshot()

    def roof_predict_ms(self, prompt_len: int,
                        max_new: int) -> Optional[float]:
        """Cost-model roofline estimate for one request at this
        engine's geometry (bench/tier-routing surface), or None when
        ROOF_LEDGER is off."""
        if self._roof is None:
            return None
        return self._roof.predict_request_ms(prompt_len, max_new)

    def _hbm_weights_device_bytes(self) -> int:
        """Per-device resident weight bytes under the committed
        shardings: each leaf costs its shard shape (full shape when
        replicated — the exact-TP scheme keeps wo / w_down / embeddings
        whole on every chip). Shape metadata only — no sync."""
        total = 0
        for x in jax.tree_util.tree_leaves(self.params):
            shp = x.shape
            sh = getattr(x, "sharding", None)
            if sh is not None:
                shp = sh.shard_shape(x.shape)
            total += int(np.prod(shp, dtype=np.int64)) * x.dtype.itemsize
        return total

    def _hbm_kv_reserved_bytes(self) -> int:
        """Static KV reservation: the full cache tree (dense slot slab
        or paged block pool). nbytes is shape metadata — no sync."""
        return sum(
            int(x.nbytes)
            for x in jax.tree_util.tree_leaves(self._state["cache"])
        )

    def _hbm_kv_live_bytes(self) -> int:
        """Bytes of the reservation actually holding request state:
        used blocks (paged) or occupied slots (dense), prorated over
        the reservation. Snapshot-path only — allocator/_book locks are
        taken cold here, never from the scheduler."""
        total = self._hbm_kv_reserved_bytes()
        if self._paged:
            snap = self._allocator.snapshot()
            return total * snap["used"] // max(1, snap["total"])
        return total * self.slots_busy() // max(1, self.ecfg.max_slots)

    def _hbm_prefix_bytes(self) -> int:
        """Dense prefix-trie KV bytes (its KV copies live outside the
        slot slab). Paged prefix shares pool blocks already counted in
        kv_live, so it reports 0 rather than double-count."""
        if self._prefix is None:
            return 0
        return int(self._prefix.snapshot().get("bytes", 0))

    def drain(self, timeout: float = 30.0) -> bool:
        """Graceful drain: stop admitting (submit raises EngineDraining),
        shed everything still queued with a retriable error, and wait up
        to `timeout` seconds for in-flight requests to finish. Returns
        True once the engine is quiescent. The scheduler keeps running —
        call stop() afterwards to halt the threads (stop() drains any
        leftovers itself)."""
        if self._recorder is not None and not self._draining.is_set():
            self._recorder.record("drain", -1, {"timeout_s": timeout})
        self._draining.set()
        if self._thread is None or not self._thread.is_alive():
            # No scheduler to shed queued work on our behalf.
            with self._book:
                self._shed_queued_locked()
        deadline = time.perf_counter() + max(0.0, timeout)
        while time.perf_counter() < deadline:
            with self._book:
                idle = (
                    all(r is None for r in self._slots)
                    and not self._waiting
                    and not self._prefilling
                    and self._pending.empty()
                    and (self._heal is None or self._heal.pen_empty())
                )
            if idle and self._fetch_q.empty():
                return True
            time.sleep(0.005)
        return False

    def debug_lifecycle_check(self) -> Dict[str, Any]:
        """Leak audit for tests/soaks: with no queued or in-flight work,
        every entry in the returned dict is a leak — a slot still held, a
        free-list hole, an armed active row, a dangling registry entry,
        pool blocks that never came back, or trie nodes pinned by dead
        handles. Unpinned trie RETENTION is flushed first (it is cache,
        not a leak). Empty dict == clean."""
        leaks: Dict[str, Any] = {}
        with self._book:
            held = [r.rid for r in self._slots if r is not None]
            if held:
                leaks["slots"] = held
            if len(self._free) + len(held) != self.ecfg.max_slots:
                leaks["free_list"] = len(self._free)
            if self._active_host.any():
                leaks["active_host"] = int(self._active_host.sum())
            if self._waiting or not self._pending.empty():
                leaks["queued"] = len(self._waiting) + self._pending.qsize()
            if self._prefilling:
                leaks["prefilling"] = [r.rid for r in self._prefilling]
            with self._rid_lock:
                if self._requests:
                    leaks["registry"] = sorted(self._requests)
            if self._heal is not None and not self._heal.pen_empty():
                leaks["heal_pen"] = sorted(
                    r.rid for r in self._heal.pen_scan()
                )
            if self._paged:
                if self._paged_prefix is not None:
                    self._paged_prefix.flush()
                    if self._paged_prefix.n_nodes:
                        leaks["trie_pins"] = self._paged_prefix.n_nodes
                snap = self._allocator.snapshot()
                if snap["used"]:
                    leaks["pool_blocks"] = snap
            elif self._prefix is not None:
                self._prefix.flush()
                if self._prefix.n_nodes:
                    leaks["trie_pins"] = self._prefix.n_nodes
        return leaks

    def chaos_counts(self) -> Dict[str, int]:
        """Injected-fault counters (all zero when chaos is disabled)."""
        return self._chaos.snapshot() if self._chaos is not None else {
            "dispatch_faults": 0, "alloc_faults": 0,
            "slow_boundaries": 0, "disconnects": 0,
            "nan_injects": 0, "hangs": 0, "sticky_faults": 0,
        }

    def debug_health(self) -> Optional[Dict[str, Any]]:
        """graftheal supervisor snapshot for the /debug/health endpoint
        (None when HEAL is off — the raw failure path is in effect)."""
        return self._heal.snapshot() if self._heal is not None else None

    def slots_busy(self) -> int:
        """Occupied-slot count, read under the bookkeeping lock. The one
        sanctioned way for metrics exporters to observe slot occupancy."""
        with self._book:
            return sum(1 for r in self._slots if r is not None)

    def live_requests(self) -> List["_Request"]:
        """Snapshot of the requests currently holding slots, taken under
        the bookkeeping lock. The list is a copy; the _Request objects are
        live, so only probe/diagnostic readers should use this."""
        with self._book:
            return [r for r in self._slots if r is not None]

    def table_host_snapshot(self) -> np.ndarray:
        """Copy of the host-side block table under the bookkeeping lock,
        for probes that replay the decode kernel outside the engine."""
        with self._book:
            return self._table_host.copy()

    def start(self):
        if self._thread is None:
            self._stop.clear()  # allow stop() -> start() restart
            self._draining.clear()
            # Warmup dispatches never meet a boundary; drop their keys so
            # the first live wave's timing isn't charged to them.
            self._wave_keys = []
            self._wave_enq_s = 0.0
            if self._async_fetch:
                self._fetcher = threading.Thread(
                    target=self._fetch_loop, daemon=True
                )
                self._fetcher.start()
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()

    def stop(self):
        self._draining.set()
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if self._fetcher is not None:
            # Sentinel AFTER the last real item; bounded retries so a
            # dead/wedged fetcher can't hang shutdown on a full queue.
            for _ in range(60):
                try:
                    self._fetch_q.put(None, timeout=0.5)
                    break
                except queue.Full:
                    if not self._fetcher.is_alive():
                        break
            self._fetcher.join(timeout=30)
            self._fetcher = None
        # No waiter may be left hanging: everything still queued or in
        # flight gets a retriable shutdown error + None sentinel.
        self._shutdown_sweep()

    def _shed_queued_locked(self) -> None:  # graftlint: holds(_book)
        """Fail every queued (not yet admitted) request with a retriable
        draining error. Caller holds _book or the scheduler is stopped."""
        self._drain_pending()
        while self._waiting:
            req = self._waiting.popleft()
            with self.stats.lock:
                self.stats.shed_total += 1
            self._fail_req(
                req, "engine draining: request was not admitted",
                kind="draining", retriable=True,
            )

    def _shutdown_sweep(self) -> None:
        """After the scheduler threads exit: fail everything that never
        reached a terminal state — queued requests, live slots, mid-
        prefill requests, and requests alive only inside un-fetched
        boundary rosters (optimistic recycling moves them out of _slots
        before their results are read). Idempotent via _fail_req."""
        # The scheduler threads are already joined, so _book is
        # uncontended here — taking it keeps the holds(_book)
        # protocol of _drain_pending/_fail_req honest.
        with self._book:
            live: Dict[int, _Request] = {}
            while True:
                try:
                    item = self._fetch_q.get_nowait()
                except queue.Empty:
                    break
                if item is None:
                    continue
                for group, _, _, _ in item.admits:
                    for req in group:
                        live[req.rid] = req
                for req in item.roster or []:
                    if req is not None:
                        live[req.rid] = req
            for req in self._slots:
                if req is not None:
                    live[req.rid] = req
            for req in self._prefilling:
                live[req.rid] = req
            if self._heal is not None:
                # Penned resurrectees are in neither _slots nor _waiting.
                for req in self._heal.pen_take(0.0, flush=True):
                    live.setdefault(req.rid, req)
            self._drain_pending()
            while self._waiting:
                req = self._waiting.popleft()
                live[req.rid] = req
            # The registry is authoritative for any straggler the scans above
            # missed (e.g. recycled out of _slots with its boundary already
            # fetched but the request failed mid-processing).
            with self._rid_lock:
                for rid, req in list(self._requests.items()):
                    live.setdefault(rid, req)
            n_swept = 0
            for req in live.values():
                if req is not None and not req.finished:
                    n_swept += 1
                    with self.stats.lock:
                        self.stats.shed_total += 1
                    self._fail_req(
                        req, "engine stopped before the request completed",
                        kind="shutdown", retriable=True,
                    )
            self._prefilling.clear()
            if n_swept:
                logger.warning("shutdown swept %d unfinished requests", n_swept)

    # --- static shape lattice -----------------------------------------------

    def lattice_spec(self) -> shape_lattice.LatticeSpec:
        """The shape-relevant slice of this engine's config, as consumed
        by servers/shape_lattice.py — the single source of truth for
        which static-shape keys exist (warmup iterates it, graftlint's
        certifier cross-checks it, compile_audit --static-xcheck asserts
        runtime dispatches stay inside it)."""
        chunked = self._chunked
        return shape_lattice.LatticeSpec(
            buckets=self._buckets,
            max_seq_len=self.ecfg.max_seq_len,
            max_slots=self.ecfg.max_slots,
            max_admit=self._max_admit,
            decode_rungs=self._chunk_sizes,
            paged=self._paged,
            chunked=chunked,
            prefix=(self._prefix is not None
                    or self._paged_prefix is not None),
            prefix_block=self.ecfg.prefix_block,
            chunk_buckets=self._chunk_buckets if chunked else (),
            prefill_chunk=self._prefill_chunk if chunked else 0,
            token_budget=(
                self.ecfg.dispatch_token_budget or self._prefill_chunk
            ) if chunked else 0,
            ragged=self._ragged,
            ragged_chunk=self._ragged_chunk if self._ragged else 0,
            spec=self._spec,
            spec_rungs=self._spec_rungs if self._spec else (),
            spec_draft=self._jit_draft is not None,
        )

    def static_lattice(self) -> List[str]:
        """Canonical key strings of every variant live scheduling can
        dispatch — the /debug/compile "declared" set, exported so audits
        can compare against the runtime lattice without a ledger."""
        keys = shape_lattice.dispatch_keys(self.lattice_spec())
        return [compile_ledger.key_str(k)
                for k in shape_lattice.warmup_order(keys)]

    def warmup(self) -> None:
        """Pre-compile the full static shape lattice, so live traffic
        never eats a compile. The key set comes from lattice_spec() —
        the same closed form graftlint certifies against the scheduler
        arithmetic — so warmup covers exactly what live scheduling can
        dispatch: every reachable key (no live retraces, including the
        top-bucket == max_seq_len widths the old per-mode loops skipped)
        and no unreachable ones (no wasted prefill compiles). Not
        thread-safe against the scheduler: call before start() (or while
        no requests are in flight)."""
        keys = shape_lattice.warmup_order(
            shape_lattice.dispatch_keys(self.lattice_spec())
        )
        if self._cledger is not None:
            # Declare ahead of dispatching: a warmup crash mid-lattice
            # still leaves /debug/compile showing the full intended set.
            for key in keys:
                self._cledger.declare(key)
        for key in keys:
            self._warm_key(key)
        jax.block_until_ready(self._state["last_tok"])  # graftlint: allow(hot-sync) warmup runs before start(); the sync IS the point
        if self._cledger is not None:
            self._cledger.warmup_done()
        logger.info(
            "engine warmed: %d lattice variants across %d families",
            len(keys), len({k[0] for k in keys}),
        )

    def _warm_key(self, key: Tuple[Any, ...]) -> None:
        """Compile ONE lattice key: build zero-filled arrays of the
        key's static shapes and dispatch the matching jit entry point.
        max_new=1 everywhere -> rows are first_done; no slot state
        leaks. Traced scalars (plens/pref/starts) are clamped into the
        cache window — for top-bucket keys the bucket equals
        max_seq_len, so the nominal width+1 would index past it; the
        clamp only changes traced VALUES, never the static key."""
        kind = key[0]
        Smax = self.ecfg.max_seq_len
        if self._observe:
            t0 = time.perf_counter()
        if kind == "decode":
            # _dispatch_decode_chunk notes its own dispatch key.
            self._state, _, _, _ = self._dispatch_decode_chunk(key[1])  # graftlint: allow(holds-site) warmup runs before start(); no scheduler thread exists yet
            return
        if kind == "cow" and self._paged:
            # _cow notes its own dispatch key (traced src/dst scalars).
            self._cow(0, 0)
            return
        if kind == "deactivate":
            # All-True keep mask: identity freeze, so the first real
            # cancel/deadline reap never eats a compile mid-traffic.
            self._state = self._jit_deactivate(
                self._state, jnp.ones((self.ecfg.max_slots,), jnp.bool_)
            )
        elif kind == "admit" and not self._paged:
            _, Sb, G = key
            admit = self._jit_admit_sub if self._prefix is not None \
                else self._jit_admit
            out = admit(
                self.params,
                self._state,
                jnp.zeros((G, Sb), jnp.int32),
                jnp.ones((G,), jnp.int32),
                jnp.zeros((G,), jnp.uint32),
                jnp.ones((G,), jnp.float32),
                jnp.zeros((G,), jnp.int32),
                jnp.ones((G,), jnp.float32),
                jnp.ones((G,), jnp.int32),
                jnp.arange(G, dtype=jnp.int32),
            )
            self._state = out[0]
        elif kind == "admit-prefix" and self._prefix is not None:
            # Warm (prefix-hit) variant: zero prefix KV keeps it a pure
            # compile.
            _, Pb, Sb, G = key
            pkv = transformer.init_cache(self.cfg, G, Pb)
            pref = min(Pb, Smax - 1)
            self._state, _, _, _ = self._jit_admit_prefix(
                self.params,
                self._state,
                jnp.zeros((G, Sb), jnp.int32),
                jnp.full((G,), pref + 1, jnp.int32),
                jnp.full((G,), pref, jnp.int32),
                pkv,
                jnp.zeros((G,), jnp.uint32),
                jnp.ones((G,), jnp.float32),
                jnp.zeros((G,), jnp.int32),
                jnp.ones((G,), jnp.float32),
                jnp.ones((G,), jnp.int32),
                jnp.arange(G, dtype=jnp.int32),
            )
        elif kind == "admit-paged" and self._paged:
            # One paged admission kernel covers cold and warm; warm rows
            # just gather through an all-trash table (pure compile).
            _, Sb, G, W = key
            pref = min(W, Smax - 1)
            self._state, _, _ = self._jit_admit_paged(
                self.params,
                self._state,
                jnp.zeros((G, self._nbs), jnp.int32),
                jnp.zeros((G, Sb), jnp.int32),
                jnp.full((G,), pref + 1, jnp.int32),
                jnp.full((G,), pref, jnp.int32),
                jnp.zeros((G,), jnp.uint32),
                jnp.ones((G,), jnp.float32),
                jnp.zeros((G,), jnp.int32),
                jnp.ones((G,), jnp.float32),
                jnp.ones((G,), jnp.int32),
                jnp.arange(G, dtype=jnp.int32),
                prefix_width=W,
            )
        elif kind == "ragged" and self._ragged:
            # The ONE wave: all-trash tables (starts = Smax routes every
            # scatter past the table) and an all-False occupancy mask
            # keep the compile a pure no-op over real state.
            _, C = key
            B = self.ecfg.max_slots
            self._state, _, _, _, _, _ = self._jit_ragged(
                self.params,
                self._state,
                jnp.zeros((B, self._nbs), jnp.int32),
                jnp.zeros((B * C,), jnp.int32),
                jnp.ones((B,), jnp.int32),
                jnp.full((B,), Smax, jnp.int32),
                jnp.zeros((B,), jnp.uint32),
                jnp.ones((B,), jnp.float32),
                jnp.zeros((B,), jnp.int32),
                jnp.ones((B,), jnp.float32),
                jnp.ones((B,), jnp.int32),
                jnp.zeros((B,), jnp.bool_),
                jnp.zeros((B,), jnp.bool_),
            )
        elif kind == "chunk" and self._chunked:
            _, Sc, G, W = key
            start = min(W, Smax - Sc)
            args = (
                jnp.zeros((G, Sc), jnp.int32),
                jnp.full((G,), start + Sc, jnp.int32),
                jnp.full((G,), start, jnp.int32),
                jnp.zeros((G,), jnp.uint32),
                jnp.ones((G,), jnp.float32),
                jnp.zeros((G,), jnp.int32),
                jnp.ones((G,), jnp.float32),
                jnp.ones((G,), jnp.int32),
                jnp.arange(G, dtype=jnp.int32),
                jnp.ones((G,), jnp.bool_),
            )
            if self._paged:
                # All-trash tables keep the compile a no-op write.
                out = self._jit_admit_chunk_paged(
                    self.params,
                    self._state,
                    jnp.zeros((G, self._nbs), jnp.int32),
                    *args,
                    prefix_width=W,
                )
            else:
                out = self._jit_admit_chunk(
                    self.params, self._state, *args, prefix_width=W,
                )
            self._state = out[0]
        elif kind == "seed-prefix" and self._jit_seed_prefix is not None:
            W = key[1]
            pkv_full = transformer.init_cache(self.cfg, 1, W)
            pkv = {k: pkv_full[k][:, 0] for k in pkv_full}
            self._state = self._jit_seed_prefix(
                self._state, pkv, jnp.int32(0)
            )
        elif kind == "verify" and self._spec:
            # The wide spec wave at rung k: all-trash tables and an
            # all-False wave mask (every scatter routes past the table,
            # every acceptance chain is run=False) keep the compile a
            # pure no-op over real state.
            _, kk = key
            B = self.ecfg.max_slots
            self._state, _, _, _ = self._jit_verify(
                self.params,
                self._state,
                jnp.zeros((B, self._nbs), jnp.int32),
                jnp.zeros((B, kk), jnp.int32),
                jnp.zeros((B,), jnp.bool_),
            )
        elif kind == "draft" and self._jit_draft is not None:
            # Draft-model proposal at rung k over its scratch cache —
            # stateless by design, so the warm call touches no engine
            # state at all.
            _, kk = key
            B = self.ecfg.max_slots
            self._jit_draft[kk](
                jnp.zeros((B, self._spec_window), jnp.int32),
                jnp.ones((B,), jnp.int32),
            )
        else:
            raise ValueError(
                f"lattice key {key!r} has no warm recipe for this "
                f"config — shape_lattice.dispatch_keys and _warm_key "
                f"have drifted"
            )
        if self._observe:
            self._note_dispatch(key, -1, time.perf_counter() - t0)  # graftlint: allow(shape-lattice) key IS a lattice key — _warm_key iterates dispatch_keys()

    # --- compile/device observatory taps ------------------------------------

    def _note_dispatch(self, key: Tuple[Any, ...], rid: int,
                       seconds: float) -> None:
        """Observatory tap behind every jit dispatch. Callers are the
        warmup caller or the scheduler thread (same single-writer set as
        the ledger requires); hot sites guard the surrounding
        perf_counter pair on self._observe so the off path stays raw."""
        if self._cledger is not None:
            witness = self._cledger.dispatch(key, rid, seconds)
            if witness is not None:
                logger.warning(
                    "live retrace: variant %s compiled in %.1f ms on the "
                    "serving path (rid=%d)",
                    witness["key"], witness["compile_ms"], rid,
                )
                if self._recorder is not None:
                    self._recorder.record("retrace", rid, witness)
        if self._timing_on:
            self._wave_keys.append(key)
            if self._roof is not None:
                # Enqueue seconds feed the roofline's device component;
                # the host-pre residue is step span minus this.
                self._wave_enq_s += seconds

    def _cow(self, src: int, dst: int, rid: int = -1) -> None:
        """Copy-on-write block copy through the one shared jit variant
        (src/dst are traced scalars). Every call site — warmup and
        live — funnels through here so the ledger sees one "cow" key."""
        if not self._observe:
            self._state = self._jit_cow(
                self._state, jnp.int32(src), jnp.int32(dst)
            )
            return
        t0 = time.perf_counter()
        self._state = self._jit_cow(
            self._state, jnp.int32(src), jnp.int32(dst)
        )
        self._note_dispatch(("cow",), rid, time.perf_counter() - t0)

    # --- scheduler loop -----------------------------------------------------

    def _bucket(self, n: int) -> int:
        for b in self._buckets:
            if n <= b:
                return b
        return self.ecfg.max_seq_len

    def _admit_key(self, req: _Request) -> Tuple[int, int]:
        """(suffix bucket, prefix bucket) for grouping admissions. Cold
        requests (no prefix cache / no match) key as (full bucket, 0) —
        the pre-prefix grouping exactly. The trie lookup runs once per
        request and pins the matched path; the match is capped at
        plen - 1 so at least one suffix token remains to produce the
        next-token logits. Paged engines use the block-id trie — same
        lookup discipline, but a hit later shares blocks instead of
        gathering KV."""
        index = self._prefix if self._prefix is not None \
            else self._paged_prefix
        if index is None:
            return self._bucket(len(req.tokens)), 0
        if req.prefix_len is None:
            handle = index.lookup(
                req.tokens, max_len=len(req.tokens) - 1
            )
            req.prefix_handle = handle
            req.prefix_len = handle.match_len
            if handle.match_len:
                with self.stats.lock:
                    self.stats.prefix_hits += 1
                    self.stats.prefix_tokens_saved += handle.match_len
            if self._recorder is not None:
                self._recorder.record(
                    "trie-hit" if handle.match_len else "trie-miss",
                    req.rid,
                    {"matched_tokens": handle.match_len,
                     "prompt_tokens": len(req.tokens)},
                )
        if req.prefix_len:
            return (
                self._bucket(len(req.tokens) - req.prefix_len),
                self._bucket(req.prefix_len),
            )
        return self._bucket(len(req.tokens)), 0

    def _drain_pending(self) -> None:  # graftlint: holds(_book)
        while True:
            try:
                self._waiting.append(self._pending.get_nowait())
            except queue.Empty:
                break
        if self._pilot is not None:
            # EDF ordering (stable; no-deadline requests age via a
            # virtual deadline). An already-ordered queue — including
            # every all-FIFO workload — comes back as the same object.
            self._waiting = self._pilot.order_queue(self._waiting)
        with self.stats.lock:
            self.stats.queue_depth = len(self._waiting)

    def _admit_cap(self) -> int:  # graftlint: holds(_book)
        """Admission group-size cap: the pilot's live (power-of-two,
        clamped) value when flying, the static config cap otherwise."""
        if self._pilot is not None:
            return self._pilot.admit_cap()
        return self._max_admit

    def _shed_expired_head(self) -> bool:  # graftlint: holds(_book)
        """EDF pop-time margin re-check (pilot callers only): if the
        head of the admission queue already missed its deadline, fail
        it here — before it claims a slot, pool blocks, or budget a
        viable request could use — and return True so the caller
        re-examines the new head. The boundary-cadence reap still
        sheds mid-queue expiries; this closes the pop-time race where
        a request expires between the reap and its own admission."""
        req = self._waiting[0]
        now = time.perf_counter()
        if req.deadline is None or now < req.deadline:
            return False
        self._waiting.popleft()
        with self.stats.lock:
            self.stats.deadline_expired_total += 1
            self.stats.shed_total += 1
        self._fail_req(
            req,
            f"deadline exceeded after "
            f"{1000.0 * (now - req.submitted_at):.0f} ms in queue",
            kind="deadline",
        )
        self._pilot.note_expired_pop()
        return True

    def _pilot_signals(self) -> Dict[str, float]:  # graftlint: holds(_book)
        """Cumulative signal sample for the pilot's decision windows:
        sched-ledger counters (PILOT implies the ledger, so _sled is
        never None here), the stats SLO mirror, and instantaneous
        queue/slot levels. Keys are the controller's frozen
        signal_snapshot schema (controller.py docstring)."""
        sled = self._sled.snapshot()
        with self.stats.lock:
            budget_dispatches = self.stats.budget_dispatches
            expired = self.stats.deadline_expired_total
            met = self.stats.deadline_met_total
            missed = self.stats.deadline_missed_total
        finished = met + missed
        return {
            "boundaries": sled["dispatch_boundaries"],
            "dispatch_cells": sled["dispatch_cells"],
            "useful_tokens": sled["useful_tokens"],
            "frag_tokens": sled["frag_tokens"],
            "budget_dispatches": budget_dispatches,
            "budget_starved_passes": sled["budget_starved_passes"],
            "budget_offered_tokens": sled["budget_offered_tokens"],
            "budget_used_tokens": sled["budget_used_tokens"],
            "pool_stall_events": sled["pool_stall_events"],
            "preemptions": sled["preemptions"],
            "deadline_expired": expired,
            "goodput": met / finished if finished else 1.0,
            "queue_depth": len(self._waiting),
            "free_slots": len(self._free),
            "spec_drafted": sled["spec"]["drafted_tokens"],
            "spec_accepted": sled["spec"]["accepted_tokens"],
            "roof_backlog_ms": self._roof_backlog_ms(),
            "heal_pressure": (
                self._heal.pressure() if self._heal is not None else 0.0
            ),
        }

    def _roof_backlog_ms(self) -> float:  # graftlint: holds(_book)
        """Predicted roofline cost (ms) of everything still queued —
        the cost-model level the tier router consumes. 0.0 when the
        roof ledger is down (the signal key stays schema-stable)."""
        if self._roof is None:
            return 0.0
        total = 0.0
        for req in self._waiting:
            total += self._roof.predict_request_ms(
                len(req.tokens), req.params.max_new_tokens
            )
        return round(total, 3)

    def _pilot_tick(self) -> None:  # graftlint: holds(_book)
        """One pilot boundary: advance the control loop and mirror any
        new decisions into the flight recorder (the Perfetto decision
        lane in tools/trace_view.py)."""
        decisions = self._pilot.on_boundary(self._pilot_signals)
        if self._recorder is not None:
            for d in decisions:
                self._recorder.record(
                    "pilot", -1,
                    {"knob": d["knob"], "old": d["old"], "new": d["new"],
                     "rationale": d["rationale"],
                     "budget": self._pilot.dispatch_budget(),
                     "max_admit": self._pilot.admit_cap(),
                     "chunk_bias": self._pilot.chunk_bias()},
                )

    def _record_first_dispatch(self, group: List[_Request]) -> None:
        """Queue-wait accounting: submit -> first dispatch, once per
        request (chunked prefills dispatch the same request many times)."""
        now = time.perf_counter()
        wait = 0.0
        n = 0
        for req in group:
            if req.first_dispatch_at is None:
                req.first_dispatch_at = now
                wait += now - req.submitted_at
                n += 1
                if self._sled is not None:
                    self._sled.note_first_dispatch(
                        req.rid, req.submitted_at, now,
                        predicted_ms=(
                            self._roof.predict_request_ms(
                                len(req.tokens),
                                req.params.max_new_tokens,
                            ) if self._roof is not None else 0.0
                        ),
                    )
                if self._recorder is not None:
                    self._recorder.record(
                        "admit", req.rid,
                        {"queue_wait_ms":
                            round(1000.0 * (now - req.submitted_at), 3),
                         "prompt_tokens": len(req.tokens),
                         "prefix_tokens": req.prefix_len or 0},
                    )
        if n:
            with self.stats.lock:
                self.stats.queue_wait_sum += wait
                self.stats.queue_wait_count += n

    def _dispatch_admits(self) -> List[Tuple[List[_Request], Any, Any, Any]]:  # graftlint: holds(_book)
        """Admit FIFO prefix runs of same-bucket waiting requests as batched
        groups. Dispatches device work only — returns un-synced handles."""
        self._drain_pending()
        admits: List[Tuple[List[_Request], Any, Any, Any]] = []
        last_key: Optional[Tuple[int, int]] = None
        while self._free and self._waiting:
            if self._pilot is not None and self._shed_expired_head():
                continue  # expired head must not displace a viable one
            key = self._admit_key(self._waiting[0])
            max_g = min(self._admit_cap(), len(self._free))
            group: List[_Request] = []
            reserved = 0
            shed = False
            while (
                len(group) < max_g
                and self._waiting
                and self._admit_key(self._waiting[0]) == key
            ):
                if self._pilot is not None and self._shed_expired_head():
                    shed = True
                    continue  # loop condition re-keys on the new head
                if self._paged:
                    # Pool gate BEFORE the pop: the whole group's owned
                    # blocks must fit (after trie eviction), so dispatch-
                    # time allocation can never fail mid-group. A head
                    # request that cannot fit stays queued — admission
                    # blocks on pool exhaustion, it does not preempt.
                    need = self._owned_need(self._waiting[0])
                    if not self._pool_reserve(reserved + need):
                        break
                    reserved += need
                group.append(self._waiting.popleft())
            if not group:
                if shed:
                    continue  # head expired mid-fill, not a pool stall
                if not self._waiting:
                    break
                with self.stats.lock:
                    self.stats.pool_stalls += 1
                if self._recorder is not None:
                    self._recorder.record(
                        "pool-stall", self._waiting[0].rid,
                        {"waiting": len(self._waiting)},
                    )
                if self._sled is not None:
                    self._sled.note_pool_stall(self._waiting[0].rid)
                break
            try:
                admits.append(self._dispatch_admit_group(group, *key))
                last_key = key
            except Exception as e:  # bad batch must not kill the loop
                logger.exception(
                    "admission failed for requests %s",
                    [r.rid for r in group],
                )
                if not self._heal_requeue_group(group, str(e)):
                    for req in group:
                        slot = req.slot
                        if slot >= 0 and self._slots[slot] is not req \
                                and slot not in self._free:
                            # Popped but never registered.
                            self._free.append(slot)
                        self._fail_req(req, str(e), kind="internal")
        # Bucket-mismatch wait attribution: the engine filled up and the
        # head-of-line request buckets differently from the last group
        # admitted — it waits behind the lattice shape, not raw capacity.
        if (self._sled is not None and last_key is not None
                and self._waiting and not self._free):
            head = self._waiting[0]
            if self._bucket(
                len(head.tokens) - (head.prefix_len or 0)
            ) != last_key[0]:
                self._sled.note_bucket_defer(head.rid)
        return admits

    def _dispatch_admit_group(  # graftlint: holds(_book)
        self, group: List[_Request], Sb: int, Pb: int = 0
    ) -> Tuple[List[_Request], Any, Any, Any]:
        """Build host arrays for `group`, dispatch the fused admission.

        G is padded up to a power of two by replicating the last request
        (identical slot + data, so the duplicate scatter writes are
        harmless), bounding compile variants to log2(max_admit)+1 per
        bucket. Pb > 0 is a prefix-cache WARM group: `Sb` buckets the
        uncached suffix, `Pb` the reused prefix, and the token array
        carries only suffixes (so the jit variant is keyed on
        (Pb, Sb, G) — one compile per prefix bucket, mirroring the
        prompt-bucket discipline)."""
        self._chaos_dispatch("admit", [r.rid for r in group])
        G = len(group)
        Gp = 1
        while Gp < G:
            Gp *= 2
        if self._sled is not None:
            # Waste attribution for this group's static shape: every one
            # of the Gp*Sb offered token-slots is useful suffix, bucket
            # rounding, or pow2 group replication — exactly (the
            # conservation audit holds this to the cell).
            useful = sum(
                len(r.tokens) - (r.prefix_len if Pb else 0) for r in group
            )
            bpad = G * Sb - useful
            gpad = (Gp - G) * Sb
            fam = (
                ("admit-paged", Sb, Gp, Pb) if self._paged
                else ("admit-prefix", Pb, Sb, Gp) if Pb
                else ("admit", Sb, Gp)
            )
            self._sled.note_group(fam, Gp * Sb, useful, bpad, gpad)
            with self.stats.lock:
                self.stats.sched_useful_tokens += useful
                self.stats.sched_bucket_pad_tokens += bpad
                self.stats.sched_group_pad_tokens += gpad
        self._record_first_dispatch(group)
        for req in group:
            req.slot = self._free.pop()
            req.expected = 1  # the admission samples the first token
        toks = np.full((Gp, Sb), self.cfg.pad_token_id, np.int32)
        plens = np.empty((Gp,), np.int32)
        pref_lens = np.empty((Gp,), np.int32)
        seeds = np.empty((Gp,), np.uint32)
        temps = np.empty((Gp,), np.float32)
        top_ks = np.empty((Gp,), np.int32)
        top_ps = np.empty((Gp,), np.float32)
        max_news = np.empty((Gp,), np.int32)
        slots = np.empty((Gp,), np.int32)
        for i in range(Gp):
            req = group[min(i, G - 1)]
            sp = req.params
            off = req.prefix_len if Pb else 0
            toks[i, : len(req.tokens) - off] = req.tokens[off:]
            plens[i] = len(req.tokens)
            pref_lens[i] = off
            seeds[i] = np.uint32(int(sp.seed) & 0xFFFFFFFF)
            temps[i] = sp.temperature
            top_ks[i] = sp.top_k
            top_ps[i] = sp.top_p
            max_news[i] = sp.max_new_tokens
            slots[i] = req.slot
        if self._paged:
            # Zero-copy admission: fill each row's block table (shared
            # refs + CoW + fresh allocs — capacity was reserved at group
            # formation), dispatch any copy-on-write block copies FIRST
            # (device ordering pins them before the admission's suffix
            # writes), then run the unified paged admission. Warm rows'
            # prefix KV is gathered from the pool through the table inside
            # the kernel — no host-side gather, no seed scatter.
            cows: List[Tuple[int, int]] = []
            for req in group:
                self._paged_admit_blocks(req, cows, cover=len(req.tokens))
            for src, dst in cows:
                self._cow(src, dst, rid=group[0].rid)
            table = jnp.asarray(self._table_host[slots])
            if self._observe:
                t0 = time.perf_counter()
            self._state, first, first_done = self._jit_admit_paged(
                self.params,
                self._state,
                table,
                jnp.asarray(toks),
                jnp.asarray(plens),
                jnp.asarray(pref_lens),
                jnp.asarray(seeds),
                jnp.asarray(temps),
                jnp.asarray(top_ks),
                jnp.asarray(top_ps),
                jnp.asarray(max_news),
                jnp.asarray(slots),
                prefix_width=Pb,
            )
            if self._observe:
                self._note_dispatch(
                    ("admit-paged", Sb, Gp, Pb), group[0].rid,
                    time.perf_counter() - t0,
                )
            if self._hbm is not None:
                self._hbm.note_workspace(
                    int(toks.nbytes) + Gp * self.cfg.vocab_size * 4
                )
            for req in group:
                self._slots[req.slot] = req
                self._insert_paged_prompt(req, upto=len(req.tokens))
            return group, None, first, first_done
        if Pb:
            # Per-row device gather of the pinned trie path, zero-padded
            # to the prefix bucket and stacked on the batch axis (dim 1
            # of the [L, G, Hkv, Pb, ...] cache layout).
            rows = [
                self._prefix.gather(group[min(i, G - 1)].prefix_handle, Pb)
                for i in range(Gp)
            ]
            with self.stats.lock:
                # Dense warm admissions MOVE the prefix KV (device
                # gather + scatter); the paged path's zero-copy claim is
                # exactly that this counter stays 0 there.
                self.stats.prefix_seed_copies += G
            prefix_kv = {
                key: jnp.stack([r[key] for r in rows], axis=1)
                for key in rows[0]
            }
            if self._observe:
                t0 = time.perf_counter()
            self._state, first, first_done, writes = self._jit_admit_prefix(
                self.params,
                self._state,
                jnp.asarray(toks),
                jnp.asarray(plens),
                jnp.asarray(pref_lens),
                prefix_kv,
                jnp.asarray(seeds),
                jnp.asarray(temps),
                jnp.asarray(top_ks),
                jnp.asarray(top_ps),
                jnp.asarray(max_news),
                jnp.asarray(slots),
            )
            if self._observe:
                self._note_dispatch(
                    ("admit-prefix", Pb, Sb, Gp), group[0].rid,
                    time.perf_counter() - t0,
                )
        else:
            admit = self._jit_admit_sub if self._prefix is not None \
                else self._jit_admit
            if self._observe:
                t0 = time.perf_counter()
            out = admit(
                self.params,
                self._state,
                jnp.asarray(toks),
                jnp.asarray(plens),
                jnp.asarray(seeds),
                jnp.asarray(temps),
                jnp.asarray(top_ks),
                jnp.asarray(top_ps),
                jnp.asarray(max_news),
                jnp.asarray(slots),
            )
            if self._observe:
                self._note_dispatch(
                    ("admit", Sb, Gp), group[0].rid,
                    time.perf_counter() - t0,
                )
            if self._prefix is not None:
                self._state, first, first_done, writes = out
            else:
                self._state, first, first_done = out
                writes = None
        if self._hbm is not None:
            self._hbm.note_workspace(
                int(toks.nbytes) + Gp * self.cfg.vocab_size * 4
            )
        # Register rows now so an error path can fail them cleanly; the
        # active mirror is armed at boundary processing.
        for req in group:
            self._slots[req.slot] = req
        if self._prefix is not None:
            self._insert_prompt_kv(group, writes, warm=bool(Pb))
        # finals=None marks "every row is an armed admission" — the
        # non-chunked twin of the chunked path's per-row finals list.
        return group, None, first, first_done

    def _insert_prompt_kv(self, group: List[_Request], writes: Dict[str, Any],
                          warm: bool) -> None:
        """Insert each admitted prompt's KV into the prefix trie. `writes`
        holds cache-dtype KV [L, G(padded), Hkv, S, ...] — full prompts
        for cold groups, uncached suffixes for warm ones (warm block
        spans are rebased by the row's prefix_len; the prefix blocks
        themselves already live in the trie, pinned by the row's handle,
        so get_span is never asked for them). Insertion extends each
        handle's pin over the request's own path — a live slot keeps its
        whole prompt KV evict-proof."""
        for i, req in enumerate(group):
            off = req.prefix_len if warm else 0

            def get_span(s, e, i=i, off=off):
                return {
                    key: writes[key][:, i, :, s - off:e - off]
                    for key in writes
                }

            evicted = self._prefix.insert(
                req.tokens, get_span, handle=req.prefix_handle
            )
            if evicted:
                with self.stats.lock:
                    self.stats.prefix_evictions += evicted
                if self._recorder is not None:
                    self._recorder.record(
                        "trie-evict", req.rid, {"evicted": evicted}
                    )

    # --- paged-KV block bookkeeping ----------------------------------------

    def _pool_reserve(self, n: int) -> bool:
        """True iff n free blocks are (or can be made) available without
        touching live streams — evicts retained trie prefixes LRU-first.
        Frees can only ARRIVE between this check and the allocation
        (single scheduler thread allocates; the fetcher only releases),
        so a True answer cannot go stale."""
        if self._chaos is not None and (
            threading.current_thread() is self._thread
        ) and self._chaos.steal_alloc():
            return False  # injected exhaustion: admission stalls/preempts
        if self._allocator.free_count >= n:
            return True
        if self._paged_prefix is not None:
            evicted = self._paged_prefix.evict_for(n)
            if evicted:
                with self.stats.lock:
                    self.stats.prefix_evictions += evicted
                if self._recorder is not None:
                    self._recorder.record(
                        "trie-evict", -1, {"evicted": evicted}
                    )
        return self._allocator.free_count >= n

    def _secure_blocks(  # graftlint: holds(_book)
        self, n: int, requester: Optional[_Request] = None,
        allow_preempt: bool = True,
    ) -> Optional[List[int]]:
        """Allocate n blocks, freeing capacity as needed: retained trie
        prefixes go first (pure cache, LRU), then — decode must make
        progress — the YOUNGEST live stream is preempted (failed and
        released; its device row zombies harmlessly against the trash
        block until `remaining` runs out). Returns None only when even
        preemption cannot free enough."""
        while True:
            if self._pool_reserve(n):
                got = self._allocator.alloc_many(n)
                if got is not None:
                    return got
            if not allow_preempt:
                return None
            victim = None
            for r in self._slots:
                if r is None or r.finished or r is requester:
                    continue
                at = r.first_dispatch_at or float("inf")
                if victim is None or at > (
                    victim.first_dispatch_at or float("inf")
                ):
                    victim = r
            if victim is None:
                return None
            with self.stats.lock:
                self.stats.preemptions += 1
            if self._sled is not None:
                # Churn = prefill + decode work the victim throws away.
                self._sled.note_preempt(
                    victim.rid, len(victim.tokens) + victim.n_generated
                )
            if self._recorder is not None:
                self._recorder.record(
                    "preempt", victim.rid,
                    {"requester": requester.rid if requester else -1,
                     "need_blocks": n},
                )
            logger.warning(
                "preempting request %d: kv cache pool exhausted",
                victim.rid,
            )
            self._fail_req(
                victim, "preempted: kv cache pool exhausted",
                kind="preempted", retriable=True,
            )

    def _owned_need(self, req: _Request) -> int:
        """Blocks a one-shot admission must ALLOCATE (vs share): the
        prompt's full block count minus the zero-copy-shared fully
        matched blocks. The copy-on-write destination (partial match
        tail) counts as owned."""
        bs = self._kv_block
        total = -(-len(req.tokens) // bs)
        shared = (req.prefix_len or 0) // bs
        return total - shared

    def _paged_admit_blocks(self, req: _Request, cows: List[Tuple[int, int]],  # graftlint: holds(_book)
                            cover: int) -> None:
        """Fill req's block-table row for prompt positions [0, cover):
        fully matched kv blocks are SHARED by refcount (zero-copy), a
        partial-block match tail allocates a copy-on-write destination
        (the device copy is dispatched by the caller before the
        admission kernel), and the remainder is freshly allocated. Every
        resulting block id lands in req.block_ids with exactly one ref
        owned by this request. The caller has already reserved capacity
        via _pool_reserve/_secure_blocks."""
        bs = self._kv_block
        slot = req.slot
        total = -(-cover // bs)
        bids: List[int] = []
        m = req.prefix_len or 0
        if m and self._paged_prefix is not None:
            srcs, partial = self._paged_prefix.plan(req.prefix_handle)
            for i, sbid in enumerate(srcs):
                self._allocator.ref(sbid)
                self._table_host[slot, i] = sbid
                bids.append(sbid)
            if partial is not None:
                dst = self._allocator.alloc()
                if dst is None:
                    raise RuntimeError("kv cache pool exhausted (cow)")
                cows.append((partial, dst))
                self._table_host[slot, len(bids)] = dst
                bids.append(dst)
                with self.stats.lock:
                    self.stats.cow_copies += 1
                if self._recorder is not None:
                    self._recorder.record(
                        "cow", req.rid, {"src": partial, "dst": dst}
                    )
            with self.stats.lock:
                self.stats.zero_copy_admissions += 1
        for i in range(len(bids), total):
            bid = self._allocator.alloc()
            if bid is None:
                raise RuntimeError("kv cache pool exhausted (admit)")
            self._table_host[slot, i] = bid
            bids.append(bid)
        req.block_ids = bids

    def _release_blocks(self, req: _Request) -> None:  # graftlint: holds(_book)
        """Drop every allocator ref req's table row holds (idempotent).
        The row is zeroed so in-flight strays land in the trash block;
        actual block REUSE is ordering-safe because a new owner's
        admission scatter is dispatched after every kernel that could
        still read or scribble the block under this request."""
        if not self._paged or not req.block_ids:
            return
        slot = req.slot
        if 0 <= slot < len(self._slots) and (
            self._slots[slot] is req or self._slots[slot] is None
        ):
            self._table_host[slot, :] = 0
        for bid in req.block_ids:
            self._allocator.unref(bid)
        req.block_ids = []

    def _grow_decode_blocks(self, n: int) -> None:  # graftlint: holds(_book)
        """Before a decode chunk of n steps: extend each active slot's
        block table to cover the chunk's worst-case write positions
        (pos <= plen + expected - 1 by the recycling invariant, so this
        chunk writes at most to plen + expected + n - 2). Slots that
        cannot be grown even after trie eviction + preempting younger
        streams are failed — every active stream owns at least one
        exclusive block, so the loop always makes progress."""
        bs = self._kv_block
        for slot, req in enumerate(self._slots):
            if req is None or req.finished or req.prefilling:
                continue
            maxpos = min(
                len(req.tokens) + req.expected + n - 2,
                self.ecfg.max_seq_len - 1,
            )
            need = min(self._nbs, maxpos // bs + 1)
            have = len(req.block_ids)
            if need <= have:
                continue
            got = self._secure_blocks(need - have, requester=req)
            if got is None:
                self._fail_req(req, "kv cache pool exhausted",
                               kind="capacity", retriable=True)
                continue
            for j, bid in enumerate(got):
                self._table_host[slot, have + j] = bid
            req.block_ids.extend(got)

    def _insert_paged_prompt(self, req: _Request, upto: int) -> None:  # graftlint: holds(_book)
        """Extend the paged trie over req's prompt blocks [0, upto):
        new nodes record (and ref) the pool block the slot's table maps
        their span to — pure host bookkeeping, no device KV moves."""
        if self._paged_prefix is None:
            return
        bs, pb = self._kv_block, self.ecfg.prefix_block
        slot = req.slot

        def block_of(j: int) -> int:
            return int(self._table_host[slot, (j * pb) // bs])

        self._paged_prefix.insert(
            req.tokens[:upto], block_of, handle=req.prefix_handle
        )

    # --- chunked-prefill scheduling ----------------------------------------

    def _chunk_bucket(self, n: int) -> int:
        for b in self._chunk_buckets:
            if n <= b:
                return b
        return self._chunk_buckets[-1]

    def _admit_chunk_slot(self, req: _Request) -> None:  # graftlint: holds(_book)
        """Admit a request into a slot for chunked prefill: register it
        immediately (error paths then fail it through _slots), look up
        the prefix cache, and seed any warm hit's trie KV into the slot
        so chunk 0 starts at the first uncached block."""
        self._record_first_dispatch([req])
        req.slot = self._free.pop()
        req.prefilling = True
        self._slots[req.slot] = req
        if self._paged:
            if self._paged_prefix is not None:
                self._admit_key(req)  # trie lookup + pin; sets prefix_len
                if req.prefix_len:
                    # Warm start is pure table surgery: ref the matched
                    # blocks, CoW the partial tail — chunk 0 then starts
                    # at the first uncached token with zero device KV
                    # traffic. Later chunks allocate their blocks at
                    # dispatch (_dispatch_chunk_group).
                    cows: List[Tuple[int, int]] = []
                    self._paged_admit_blocks(
                        req, cows, cover=req.prefix_len
                    )
                    for src, dst in cows:
                        self._cow(src, dst, rid=req.rid)
                    req.prefill_done = req.prefix_len
            return
        if self._prefix is not None:
            self._admit_key(req)  # trie lookup + pin; sets prefix_len
            if req.prefix_len:
                W = self._bucket(req.prefix_len)
                pkv = self._prefix.gather(req.prefix_handle, W)
                if self._observe:
                    t0 = time.perf_counter()
                self._state = self._jit_seed_prefix(
                    self._state, pkv, jnp.int32(req.slot)
                )
                if self._observe:
                    self._note_dispatch(
                        ("seed-prefix", W), req.rid,
                        time.perf_counter() - t0,
                    )
                req.prefill_done = req.prefix_len
                with self.stats.lock:
                    self.stats.prefix_seed_copies += 1

    def _collect_chunk_work(  # graftlint: holds(_book)
        self, left: int
    ) -> List[Tuple[_Request, int, int, bool, int]]:
        """One budget pass: pop each dispatchable request at most once
        and size its next chunk. Continuing prefills go first (finish
        in-flight prompts before admitting new ones, round-robin via
        the deque); new admissions need a free slot and are gated on a
        cold-size estimate BEFORE the slot pop / trie lookup, so a
        request never ends up half-admitted outside the dispatch.
        Returns (req, Sc, prefix_width, final, chunk_len) rows."""
        C = self._prefill_chunk
        work: List[Tuple[_Request, int, int, bool, int]] = []
        while left > 0:
            if self._prefilling:
                req = self._prefilling.popleft()
                if req.finished:  # failed by an earlier error path
                    continue
            elif self._waiting and self._free:
                if self._pilot is not None and self._shed_expired_head():
                    continue  # expired head must not claim a slot
                req = self._waiting[0]
                rem = len(req.tokens)
                est = C if rem > C else self._chunk_bucket(rem)
                if est > left:
                    break
                if self._paged and not self._pool_reserve(
                    min(est, rem) // self._kv_block + 2
                ):
                    # First chunk's blocks (+ a possible CoW tail) must
                    # fit before the slot pop — admissions stall on pool
                    # exhaustion rather than half-admit.
                    with self.stats.lock:
                        self.stats.pool_stalls += 1
                    if self._recorder is not None:
                        self._recorder.record(
                            "pool-stall", req.rid,
                            {"waiting": len(self._waiting)},
                        )
                    if self._sled is not None:
                        self._sled.note_pool_stall(req.rid)
                    break
                self._waiting.popleft()
                self._admit_chunk_slot(req)
            else:
                break
            start = req.prefill_done
            rem = len(req.tokens) - start
            final = rem <= C
            Sc = self._chunk_bucket(rem) if final else C
            if Sc > left:
                # Keeps FIFO priority for the next dispatch's budget.
                self._prefilling.appendleft(req)
                break
            clen = rem if final else C
            W = 0 if start == 0 else self._bucket(start)
            work.append((req, Sc, W, final, clen))
            left -= Sc
        return work

    def _dispatch_chunk_group(  # graftlint: holds(_book)
        self, rows: List[Tuple[_Request, int, int, bool, int]]
    ) -> Tuple[List[_Request], Any, Any, Any]:
        """Build host arrays for one same-(Sc, W) run of chunk rows and
        dispatch the fused chunk kernel. G pads to a power of two by
        replicating the last row (identical slot + data — duplicate
        scatters are well-defined), mirroring _dispatch_admit_group."""
        self._chaos_dispatch("prefill-chunk", [r[0].rid for r in rows])
        group = [r[0] for r in rows]
        Sc, W = rows[0][1], rows[0][2]
        G = len(rows)
        Gp = 1
        while Gp < G:
            Gp *= 2
        if self._sled is not None:
            # Same exact cell split as _dispatch_admit_group: useful
            # chunk tokens + bucket rounding + pow2 row replication.
            useful = sum(r[4] for r in rows)
            bpad = G * Sc - useful
            gpad = (Gp - G) * Sc
            self._sled.note_group(
                ("chunk", Sc, Gp, W), Gp * Sc, useful, bpad, gpad
            )
            with self.stats.lock:
                self.stats.sched_useful_tokens += useful
                self.stats.sched_bucket_pad_tokens += bpad
                self.stats.sched_group_pad_tokens += gpad
        toks = np.full((Gp, Sc), self.cfg.pad_token_id, np.int32)
        plens = np.empty((Gp,), np.int32)
        starts = np.empty((Gp,), np.int32)
        seeds = np.empty((Gp,), np.uint32)
        temps = np.empty((Gp,), np.float32)
        top_ks = np.empty((Gp,), np.int32)
        top_ps = np.empty((Gp,), np.float32)
        max_news = np.empty((Gp,), np.int32)
        slots = np.empty((Gp,), np.int32)
        finals = np.zeros((Gp,), bool)
        for i in range(Gp):
            req, _, _, final, clen = rows[min(i, G - 1)]
            sp = req.params
            start = req.prefill_done
            toks[i, :clen] = req.tokens[start:start + clen]
            plens[i] = len(req.tokens)
            starts[i] = start
            seeds[i] = np.uint32(int(sp.seed) & 0xFFFFFFFF)
            temps[i] = sp.temperature
            top_ks[i] = sp.top_k
            top_ps[i] = sp.top_p
            max_news[i] = sp.max_new_tokens
            slots[i] = req.slot
            finals[i] = final
        if self._paged:
            # Append this chunk's pool blocks to each row's table before
            # dispatch (trie eviction, then preemption of younger
            # streams, backstop the allocation — a chunk must never
            # scatter real KV into the trash block).
            bs = self._kv_block
            for req, _, _, _, clen in rows:
                need = min(
                    self._nbs, -(-(req.prefill_done + clen) // bs)
                )
                have = len(req.block_ids)
                if need > have:
                    got = self._secure_blocks(need - have, requester=req)
                    if got is None:
                        raise RuntimeError(
                            "kv cache pool exhausted (prefill chunk)"
                        )
                    for j, bid in enumerate(got):
                        self._table_host[req.slot, have + j] = bid
                    req.block_ids.extend(got)
            if self._observe:
                t0 = time.perf_counter()
            out = self._jit_admit_chunk_paged(
                self.params,
                self._state,
                jnp.asarray(self._table_host[slots]),
                jnp.asarray(toks),
                jnp.asarray(plens),
                jnp.asarray(starts),
                jnp.asarray(seeds),
                jnp.asarray(temps),
                jnp.asarray(top_ks),
                jnp.asarray(top_ps),
                jnp.asarray(max_news),
                jnp.asarray(slots),
                jnp.asarray(finals),
                prefix_width=W,
            )
            self._state, first, first_done = out
            writes = None
        else:
            if self._observe:
                t0 = time.perf_counter()
            out = self._jit_admit_chunk(
                self.params,
                self._state,
                jnp.asarray(toks),
                jnp.asarray(plens),
                jnp.asarray(starts),
                jnp.asarray(seeds),
                jnp.asarray(temps),
                jnp.asarray(top_ks),
                jnp.asarray(top_ps),
                jnp.asarray(max_news),
                jnp.asarray(slots),
                jnp.asarray(finals),
                prefix_width=W,
            )
            if self._prefix is not None:
                self._state, first, first_done, writes = out
            else:
                self._state, first, first_done = out
                writes = None
        if self._observe:
            # Dense and paged chunk kernels are twins — the mode is fixed
            # per engine, so one "chunk" key family stays unambiguous.
            self._note_dispatch(
                ("chunk", Sc, Gp, W), group[0].rid,
                time.perf_counter() - t0,
            )
        if self._hbm is not None:
            self._hbm.note_workspace(
                int(toks.nbytes) + Gp * self.cfg.vocab_size * 4
            )
        finals_l = []
        for req, _, _, final, clen in rows:
            req.prefill_done += clen
            finals_l.append(final)
            if final:
                req.prefilling = False
                req.expected = 1  # the final chunk samples the first token
            else:
                self._prefilling.append(req)
            if self._paged:
                # Paged trie insertion is host bookkeeping: record the
                # blocks this chunk just filled (no device KV moves).
                self._insert_paged_prompt(req, upto=req.prefill_done)
        if writes is not None:
            self._insert_chunk_kv(rows, writes)
        return group, finals_l, first, first_done

    def _insert_chunk_kv(
        self,
        rows: List[Tuple[_Request, int, int, bool, int]],
        writes: Dict[str, Any],
    ) -> None:
        """Extend the trie with each chunk's freshly-written KV blocks.
        Blocks below the chunk's start already live in the trie (warm
        prefix + earlier chunks, pinned by the request's handle — chunk
        starts are block-aligned by the prefill_chunk % prefix_block
        validation), so get_span only ever covers [start, end)."""
        for i, (req, _, _, _, clen) in enumerate(rows):
            end = req.prefill_done  # already advanced past this chunk
            start = end - clen

            def get_span(s, e, i=i, start=start):
                return {
                    key: writes[key][:, i, :, s - start:e - start]
                    for key in writes
                }

            evicted = self._prefix.insert(
                req.tokens[:end], get_span, handle=req.prefix_handle
            )
            if evicted:
                with self.stats.lock:
                    self.stats.prefix_evictions += evicted

    def _dispatch_prefill_chunks(  # graftlint: holds(_book)
        self,
    ) -> List[Tuple[List[_Request], Any, Any, Any]]:
        """Chunked-prefill admission: pack at most dispatch_token_budget
        prefill tokens into THIS dispatch, then hand back to the decode
        chunk — instead of draining the whole queue. A request's chunks
        are sequential jit calls (chunk k+1 reads chunk k's KV from the
        slot cache), so each budget pass dispatches one chunk per
        request; repeated passes let a lone long prompt still use the
        full budget."""
        self._drain_pending()
        admits: List[Tuple[List[_Request], Any, Any, Any]] = []
        if self._pilot is not None:
            budget = self._pilot.dispatch_budget()
        else:
            budget = self.ecfg.dispatch_token_budget or self._prefill_chunk
        left = budget
        n_chunks = 0
        n_tokens = 0
        while left > 0:
            work = self._collect_chunk_work(left)
            if not work:
                break
            i = 0
            while i < len(work):
                j = i + 1
                while (
                    j < len(work)
                    and j - i < self._admit_cap()
                    and work[j][1:3] == work[i][1:3]
                ):
                    j += 1
                rows = work[i:j]
                try:
                    admits.append(self._dispatch_chunk_group(rows))
                    for _, Sc, _, _, clen in rows:
                        left -= Sc
                        n_chunks += 1
                        n_tokens += clen
                except Exception as e:  # bad batch must not kill the loop
                    logger.exception(
                        "chunk dispatch failed for requests %s",
                        [r[0].rid for r in rows],
                    )
                    if not self._heal_requeue_group(
                        [r[0] for r in rows], str(e)
                    ):
                        for req, *_ in rows:
                            self._fail_req(req, str(e), kind="internal")
                i = j
        if n_chunks:
            with self.stats.lock:
                self.stats.prefill_chunks += n_chunks
                self.stats.prefill_chunk_tokens += n_tokens
                self.stats.budget_dispatches += 1
                self.stats.budget_tokens += budget - left
                self.stats.budget_limit = budget
            if self._sled is not None:
                # Starved = the pass ended with prefill work still
                # queued; only then does unspent budget count as
                # fragmentation (an idle-queue surplus is light load,
                # not waste) or mark budget contention for waits.
                starved = bool(
                    self._prefilling or (self._waiting and self._free)
                )
                self._sled.note_budget(budget, budget - left, starved)
                if starved and left > 0:
                    with self.stats.lock:
                        self.stats.sched_frag_tokens += left
        return admits

    # --- ragged unified dispatch (graftragged) ------------------------------

    def _collect_ragged_work(  # graftlint: holds(_book)
        self, left: int
    ) -> List[Tuple[_Request, int, bool]]:
        """One wave's prefill packing: each dispatchable request claims
        its slot's fixed [ragged_chunk] segment of the token buffer, with
        EXACTLY its real token count — no bucket rounding, no pow2 group
        replication, so the ledger's padding attribution for a wave is
        zero by construction. Continuing prefills go first (same
        round-robin deque as the bucketed path), new admissions gate on
        a free slot + first-chunk pool reservation BEFORE the slot pop.
        Returns (req, chunk_len, final) rows; a request appears at most
        once (one segment per slot per wave)."""
        C = self._ragged_chunk
        work: List[Tuple[_Request, int, bool]] = []
        while left > 0:
            if self._prefilling:
                req = self._prefilling.popleft()
                if req.finished:  # failed by an earlier error path
                    continue
            elif self._waiting and self._free:
                if self._pilot is not None and self._shed_expired_head():
                    continue  # expired head must not claim a slot
                req = self._waiting[0]
                rem = len(req.tokens)
                est = min(C, rem)
                if est > left:
                    break
                if self._paged and not self._pool_reserve(
                    min(est, rem) // self._kv_block + 2
                ):
                    # First chunk's blocks (+ a possible CoW tail) must
                    # fit before the slot pop — admissions stall on pool
                    # exhaustion rather than half-admit.
                    with self.stats.lock:
                        self.stats.pool_stalls += 1
                    if self._recorder is not None:
                        self._recorder.record(
                            "pool-stall", req.rid,
                            {"waiting": len(self._waiting)},
                        )
                    if self._sled is not None:
                        self._sled.note_pool_stall(req.rid)
                    break
                self._waiting.popleft()
                self._admit_chunk_slot(req)
            else:
                break
            rem = len(req.tokens) - req.prefill_done
            final = rem <= C
            clen = rem if final else C
            if clen > left:
                # Keeps FIFO priority for the next wave's budget.
                self._prefilling.appendleft(req)
                break
            work.append((req, clen, final))
            left -= clen
        return work

    def _dispatch_ragged(self):  # graftlint: holds(_book)
        """One unified ragged wave (the whole scheduler step under
        RAGGED=1): pack any mix of cold admissions / chunk continuations
        into the flat token buffer, then dispatch ONE fused kernel that
        prefills every packed segment AND runs one decode step for every
        armed row — no admission groups, no bucket choice, no separate
        decode dispatch, so the only live variant is ("ragged", C).
        Returns the same (admits, chunk_handles, roster, timing)
        boundary tuple as the bucketed path (or None when idle), so
        boundary fetching/processing is shared unchanged."""
        self._drain_pending()
        B = self.ecfg.max_slots
        C = self._ragged_chunk
        if self._pilot is not None:
            budget = self._pilot.dispatch_budget()
        else:
            budget = self.ecfg.dispatch_token_budget or B * C
        work = self._collect_ragged_work(budget)
        if not work and not self._active_host.any():
            return None
        self._chaos_dispatch("ragged", self._live_wave_rids())
        Smax = self.ecfg.max_seq_len
        toks = np.full((B, C), self.cfg.pad_token_id, np.int32)
        plens = np.ones((B,), np.int32)
        # Idle rows' descriptors trash-route every KV write: start =
        # Smax puts the whole segment past the table (the paged pool's
        # write-before-read discipline, reused as the occupancy mask's
        # device-side half).
        starts = np.full((B,), Smax, np.int32)
        seeds = np.zeros((B,), np.uint32)
        temps = np.ones((B,), np.float32)
        top_ks = np.zeros((B,), np.int32)
        top_ps = np.ones((B,), np.float32)
        max_news = np.ones((B,), np.int32)
        finals = np.zeros((B,), bool)
        is_prefill = np.zeros((B,), bool)
        packed = 0
        for req, clen, final in work:
            s = req.slot
            sp = req.params
            start = req.prefill_done
            toks[s, :clen] = req.tokens[start:start + clen]
            plens[s] = len(req.tokens)
            starts[s] = start
            seeds[s] = np.uint32(int(sp.seed) & 0xFFFFFFFF)
            temps[s] = sp.temperature
            top_ks[s] = sp.top_k
            top_ps[s] = sp.top_p
            max_news[s] = sp.max_new_tokens
            finals[s] = final
            is_prefill[s] = True
            packed += clen
        # Append each packed row's pool blocks (trie eviction, then
        # preemption of younger streams, backstop the allocation — real
        # KV must never scatter into the trash block).
        bs = self._kv_block
        for req, clen, _ in work:
            need = min(self._nbs, -(-(req.prefill_done + clen) // bs))
            have = len(req.block_ids)
            if need > have:
                got = self._secure_blocks(need - have, requester=req)
                if got is None:
                    raise RuntimeError(
                        "kv cache pool exhausted (ragged wave)"
                    )
                for j, bid in enumerate(got):
                    self._table_host[req.slot, have + j] = bid
                req.block_ids.extend(got)
        if self._roof is not None:
            # graftkern live-occupancy pricing: count the work this wave
            # ACTUALLY does per descriptor (prefill segments + the
            # decode leg) before prefill_done advances. The ledger
            # consumes it when note_wave prices this boundary's
            # ("ragged", C) key; static max_slots x C capacity pricing
            # stays exported as the capacity_* fields.
            q_toks = attn_qk = kv_read = 0
            in_work = set()
            for req, clen, final in work:
                if req.finished:
                    continue
                in_work.add(req.slot)
                start = req.prefill_done
                q_toks += clen
                attn_qk += clen * start + clen * (clen + 1) // 2
                kv_read += start
                if final:
                    plen = len(req.tokens)
                    q_toks += 1
                    attn_qk += plen
                    kv_read += plen
            for slot, req in enumerate(self._slots):
                if (req is None or slot in in_work or req.finished
                        or req.prefilling or not self._active_host[slot]):
                    continue
                pos = min(
                    len(req.tokens) + max(req.n_generated, 1) - 1,
                    Smax - 1,
                )
                q_toks += 1
                attn_qk += pos
                kv_read += pos
            self._roof.note_ragged_occupancy(q_toks, kv_read, attn_qk)
        # Post-prefill bookkeeping BEFORE the roster/growth pass: final
        # rows flip to decoding so this wave's decode leg covers them
        # (their table rows grow to the first-token position), exactly
        # like the off path where the decode chunk follows the final
        # admission chunk inside one scheduler step.
        group: List[_Request] = []
        finals_l: List[bool] = []
        for req, clen, final in work:
            if req.finished:
                # Preempted by a later row's block grab: its table row
                # is zeroed (KV scatters to trash) — also drop its state
                # writes so the freed slot stays inert.
                finals[req.slot] = False
                is_prefill[req.slot] = False
                continue
            req.prefill_done += clen
            group.append(req)
            finals_l.append(final)
            if final:
                req.prefilling = False
                req.expected = 1  # the wave samples the first token
            else:
                self._prefilling.append(req)
            if self._paged_prefix is not None:
                self._insert_paged_prompt(req, upto=req.prefill_done)
        self._record_first_dispatch(group)
        roster = self._roster()
        self._dispatch_wreck = _PendingWave([], None, roster, None)
        self._grow_decode_blocks(1)
        if self._observe:
            t0 = time.perf_counter()
        out = self._jit_ragged(
            self.params,
            self._state,
            jnp.asarray(self._table_host),
            jnp.asarray(toks.reshape(-1)),
            jnp.asarray(plens),
            jnp.asarray(starts),
            jnp.asarray(seeds),
            jnp.asarray(temps),
            jnp.asarray(top_ks),
            jnp.asarray(top_ps),
            jnp.asarray(max_news),
            jnp.asarray(finals),
            jnp.asarray(is_prefill),
        )
        self._state, first, first_done, toks_d, valid_d, active_d = out
        if self._observe:
            self._note_dispatch(
                ("ragged", C), group[0].rid if group else -1,
                time.perf_counter() - t0,
            )
        if self._hbm is not None:
            self._hbm.note_workspace(
                int(toks.nbytes) + B * self.cfg.vocab_size * 4
            )
        admits = [(group, finals_l, first, first_done)] if group else []
        self._dispatch_wreck = _PendingWave(admits, None, roster, None)
        with self.stats.lock:
            self.stats.decode_dispatches += 1
            self.stats.decode_steps += 1
            if group:
                self.stats.prefill_chunks += len(group)
                self.stats.prefill_chunk_tokens += packed
                self.stats.budget_dispatches += 1
                self.stats.budget_tokens += packed
                self.stats.budget_limit = budget
        self._recycle_budget_spent(roster, 1)
        for h in (first, first_done, toks_d, valid_d, active_d):
            h.copy_to_host_async()
        wf = 0.0
        if self._sled is not None:
            # A wave's unused token-slots are NOT padding: the ragged
            # kernel walks per-request token counts, so cost scales with
            # packed tokens, not capacity (docs/benchmarking.md "Ragged
            # dispatch") — cells == useful, zero bucket/group pad.
            if packed:
                self._sled.note_group(("ragged", C), packed, packed, 0, 0)
                with self.stats.lock:
                    self.stats.sched_useful_tokens += packed
                starved = bool(
                    self._prefilling or (self._waiting and self._free)
                )
                self._sled.note_budget(budget, packed, starved)
                if starved and budget > packed:
                    with self.stats.lock:
                        self.stats.sched_frag_tokens += budget - packed
            self._sled.note_boundary()
            wf = self._sled.boundary_waste()
            with self.stats.lock:
                self.stats.record_waste_locked(wf)
        if self._pilot is not None:
            self._pilot_tick()
        if self._recorder is not None:
            detail = {
                "admits": len(group),
                "chunk": 1,
                "active": int(self._active_host.sum()),
                "packed_tokens": packed,
                "pool_free": int(self._allocator.free_count),
            }
            if self._sled is not None:
                detail["waste_frac"] = round(wf, 4)
            self._recorder.record("boundary", -1, detail)
        timing = self._make_timing() if self._timing_on else None
        self._dispatch_wreck = None
        return _PendingWave(
            admits, (toks_d, valid_d, active_d), roster, timing,
            self._wave_epoch,
        )

    # --- speculative decoding (graftspec) ----------------------------------

    def _pick_spec_k(self) -> int:  # graftlint: holds(_book)
        """Current verify rung: the top of the compiled pow2 ladder, or
        the pilot's spec_k knob when flying (the pilot's envelope is
        the ladder itself, so it never leaves compiled variants)."""
        k = self._spec_k_live
        if self._pilot is not None:
            k = self._pilot.spec_k(k)
        if k not in self._spec_rungs:
            k = self._spec_rungs[-1]
        self._spec_k_live = k
        return k

    def _collect_drafts(self, k: int):  # graftlint: holds(_book)
        """Host-side draft proposal for every armed decode row. Returns
        (drafts [B, k] int32, wave [B] bool, n_wave). Rows admitted
        THIS boundary are not yet in _active_host and sit the wave out
        (they join the next one) — per-row sequential keys make the
        emitted stream identical either way. The model drafter runs
        ONE ("draft", k) dispatch for the whole wave; the n-gram
        drafter is pure host arithmetic."""
        B = self.ecfg.max_slots
        drafts = np.zeros((B, k), np.int32)
        wave = self._active_host.copy()
        rows: List[Tuple[int, _Request]] = []
        for slot in np.flatnonzero(wave):
            req = self._slots[slot]
            if req is None or req.finished or req.prefilling:
                wave[slot] = False
                continue
            rows.append((int(slot), req))
        if not rows:
            return drafts, wave, 0
        if self._drafter.uses_model:
            # gen_hist[replayed:] — resurrection folds earlier tokens
            # into req.tokens, so the un-replayed tail IS the history.
            hists = [
                (slot, list(req.tokens) + req.gen_hist[req.replayed:])
                for slot, req in rows
            ]
            if self._observe:
                t0 = time.perf_counter()
            out = self._drafter.draft_batch(hists, k, B)
            if self._observe:
                self._note_dispatch(("draft", k), -1,
                                    time.perf_counter() - t0)
            for slot, _ in rows:
                drafts[slot] = out[slot]
        else:
            for slot, req in rows:
                drafts[slot] = self._drafter.draft(
                    req.tokens, req.gen_hist[req.replayed:], k
                )
        return drafts, wave, len(rows)

    def _dispatch_spec(self):  # graftlint: holds(_book)
        """graftspec scheduler step: admissions exactly as the bucketed
        engine, then — in place of the decode chunk — one host draft
        pass plus ONE wide ("verify", k) dispatch covering every armed
        decode row at k + 1 positions each. Every acceptance-dependent
        piece of bookkeeping (sled attribution, expected resync, block
        rollback, pilot tick) runs at process time
        (_spec_post_process): how many tokens a wave emitted is
        unknowable until its results land, which is also why the spec
        loop never pipelines (_loop_sync_spec)."""
        admits = (
            self._dispatch_prefill_chunks() if self._chunked
            else self._dispatch_admits()
        )
        self._dispatch_wreck = _PendingWave(admits, None, None, None)
        chunk_handles = None
        roster = None
        if admits or self._active_host.any():
            roster = self._roster()
            self._dispatch_wreck = _PendingWave(admits, None, roster, None)
            if self._active_host.any():
                k = self._pick_spec_k()
                drafts, wave, n_wave = self._collect_drafts(k)
                self._spec_wave = (k, wave, n_wave)
                self._chaos_dispatch("decode", self._live_wave_rids())
                # k + 1 worst-case new positions per row; expected is
                # EXACT under spec (resynced to n_generated every
                # boundary), so growth covers pos0 .. pos0 + k and
                # nothing beyond.
                self._grow_decode_blocks(k + 1)
                if self._observe:
                    t0 = time.perf_counter()
                self._state, toks, valid, active_after = self._jit_verify(
                    self.params,
                    self._state,
                    jnp.asarray(self._table_host),
                    jnp.asarray(drafts),
                    jnp.asarray(wave),
                )
                if self._observe:
                    self._note_dispatch(("verify", k), -1,
                                        time.perf_counter() - t0)
                chunk_handles = (toks, valid, active_after)
                with self.stats.lock:
                    self.stats.decode_dispatches += 1
                    self.stats.decode_steps += 1
                for h in chunk_handles:
                    h.copy_to_host_async()
            for _, _, f, d in admits:
                f.copy_to_host_async()
                d.copy_to_host_async()
        if admits or chunk_handles is not None:
            timing = self._make_timing() if self._timing_on else None
            self._dispatch_wreck = None
            return _PendingWave(admits, chunk_handles, roster, timing,
                                self._wave_epoch)
        self._dispatch_wreck = None
        return None

    def _spec_post_process(self, chunk_data, roster) -> None:  # graftlint: holds(_book)
        """Boundary tail under SPEC=1 (called from _process_boundary
        after _process_chunk delivered the wave's tokens): acceptance
        accounting, per-row rollback, and the observability taps the
        bucketed path runs at dispatch time.

        Acceptance convention: a row that emitted e tokens (1 <= e <=
        k + 1) accepted e - 1 drafts — the drafts that each saved a
        sequential decode step. A draft that matched but fell after a
        terminal token counts rejected: it saved nothing. Under this
        convention accepted + rejected == drafted and emitted +
        rejected == (k + 1) * wave rows hold exactly, which is what
        the sled's conservation audit re-checks every boundary.

        Rollback is pure host bookkeeping: the wave already committed
        all k + 1 positions through the block tables, but positions
        past a row's accepted prefix are dead — the next wave's
        in-layer view scatter rewrites them before any mask exposes
        them — so rejecting is: resync expected to the true
        n_generated and unref the table tail past the new position.
        Freed blocks may be re-owned immediately; the new owner's
        scatter is queued after this wave device-side."""
        wave_info, self._spec_wave = self._spec_wave, None
        emitted = accepted = rejected = drafted = 0
        k = n_wave = 0
        if chunk_data is not None and wave_info is not None:
            k, wave, n_wave = wave_info
            if n_wave:
                _, valid_h, _ = chunk_data
                emitted = int(valid_h.sum(axis=0)[wave].sum())
                cells = (k + 1) * n_wave
                drafted = k * n_wave
                accepted = emitted - n_wave
                rejected = cells - emitted
                self._spec_drafted += drafted
                self._spec_accepted += accepted
                self._spec_waves += 1
                if self._sled is not None:
                    self._sled.note_group(
                        ("verify", k), cells, emitted, 0, 0,
                        spec_rejected=rejected,
                    )
                    self._sled.note_spec(drafted, accepted, rejected)
                with self.stats.lock:
                    self.stats.sched_useful_tokens += emitted
            bs = self._kv_block
            for slot, req in enumerate(roster or []):
                if req is None or not wave_info[1][slot]:
                    continue
                if req.finished or self._slots[slot] is not req:
                    continue  # completed/failed rows released in full
                req.expected = req.n_generated
                pos_new = len(req.tokens) + req.n_generated - 1
                keep = min(self._nbs, pos_new // bs + 1)
                if len(req.block_ids) > keep:
                    for bid in req.block_ids[keep:]:
                        self._allocator.unref(bid)
                    self._table_host[slot, keep:len(req.block_ids)] = 0
                    del req.block_ids[keep:]
        wf = 0.0
        if self._sled is not None:
            self._sled.note_boundary()
            wf = self._sled.boundary_waste()
            with self.stats.lock:
                self.stats.record_waste_locked(wf)
        if self._pilot is not None:
            self._pilot_tick()
        if self._recorder is not None:
            detail = {
                "active": int(self._active_host.sum()),
                "pool_free": int(self._allocator.free_count),
            }
            if n_wave:
                detail.update(
                    verify_k=k, wave=n_wave, emitted=emitted,
                    accepted=accepted, rejected=rejected,
                )
            if self._sled is not None:
                detail["waste_frac"] = round(wf, 4)
            self._recorder.record("boundary", -1, detail)

    def _loop_sync_spec(self) -> None:
        """Synchronous UNPIPELINED scheduler loop under SPEC=1: every
        boundary is processed before the next dispatch. Pipelining is
        structurally off because the next wave depends on THIS wave's
        acceptance results three ways — the drafter reads the emitted
        history, _grow_decode_blocks sizes k + 1 positions from the
        resynced expected, and rollback trims the tables the next
        dispatch snapshots. The wide verify dispatch amortizes the
        round trip the pipeline used to hide: one sync per up-to-(k+1)
        tokens per row instead of one per chunk."""
        while not self._stop.is_set():
            try:
                with self._book:
                    work = self._dispatch_once()
                    if work is not None:
                        self._process_boundary(*work)
                    idle = (
                        work is None and not self._active_host.any()
                    )
                if self._profile_n and work is not None:
                    self._profile_tick()
                # Sleep outside the lock so drain()/cancel() never wait
                # on an idle tick.
                if idle and self._pending.empty():
                    if self._sled is not None:
                        self._sled.note_idle()
                        with self.stats.lock:
                            self.stats.sched_idle_boundaries += 1
                    time.sleep(self.ecfg.idle_sleep_s)
            except Exception as e:  # fail requests, reset, keep serving
                logger.exception("engine iteration failed")
                with self._book:
                    wreck, self._dispatch_wreck = (
                        self._dispatch_wreck, None
                    )
                    self._spec_wave = None
                    self._fail_or_heal(str(e), [wreck])

    # --- boundary processing -----------------------------------------------

    def _process_admits(  # graftlint: holds(_book)
        self,
        admits: List[Tuple[List[_Request], Any, Any, Any]],
        admit_data: List[Tuple[np.ndarray, np.ndarray]],
    ) -> None:
        for (group, finals, _, _), (first_h, done_h) in zip(
            admits, admit_data
        ):
            now = time.perf_counter()
            ttft_total = 0.0
            n_first = 0
            # finals=None: one-shot admission, every row armed. A chunked
            # group's non-final rows deposited KV only — no token exists
            # for them yet, so they are skipped wholesale here.
            n_armed = (
                len(group) if finals is None
                else sum(1 for f in finals if f)
            )
            for i, req in enumerate(group):
                if finals is not None and not finals[i]:
                    continue
                if req.finished:  # already failed by an error path
                    continue
                slot = req.slot
                # Ragged waves return [B] slot-indexed rows (the whole
                # batch IS the group); bucketed groups are group-indexed.
                idx = slot if self._ragged else i
                first_tok = int(first_h[idx])
                req.last_burst_at = now
                req.n_generated = 1
                if self._spec or self._heal is not None:
                    req.gen_hist.append(first_tok)
                if req.first_token_at is None:
                    req.first_token_at = now
                    ttft_ms = 1000.0 * (now - req.submitted_at)
                    ttft_total += ttft_ms
                    n_first += 1
                    req.out.put({"tokens": [first_tok], "ttft_ms": ttft_ms})
                else:
                    # Resurrected re-admission: the client saw its first
                    # token before the fault — no second TTFT sample.
                    req.out.put({"tokens": [first_tok]})
                if self._heal is not None:
                    self._heal.note_progress(req.rid)
                if bool(done_h[idx]):
                    self._complete(req)
                elif self._slots[slot] is req:
                    # Not armed when the slot was already optimistically
                    # recycled (budget spent within in-flight chunks).
                    self._active_host[slot] = True
            with self.stats.lock:
                self.stats.ttft_sum += ttft_total / 1000.0
                self.stats.ttft_count += n_first
                self.stats.tokens_out += n_armed

    def _process_chunk(self, toks_h, valid_h, active_h, roster) -> None:  # graftlint: holds(_book)
        """toks_h [K, B], valid_h [K, B], active_h [B] — host arrays;
        `roster` is the slot->request snapshot taken when THIS chunk was
        dispatched (the live slot table may have moved on: optimistic
        recycling hands freed slots to new requests before old results
        are read). `valid` is a True-prefix per column (rows stop and
        stay stopped within a chunk), so the first n_valid rows are the
        emitted tokens."""
        n_valid = valid_h.sum(axis=0)
        total = 0
        now = time.perf_counter()
        gaps_ms: List[float] = []
        for slot, req in enumerate(roster):
            if req is None or req.finished:
                continue
            n = int(n_valid[slot])
            if n:
                burst = toks_h[:n, slot].tolist()
                if self._spec or self._heal is not None:
                    req.gen_hist.extend(burst)
                req.out.put({"tokens": burst})
                req.n_generated += n
                total += n
                if self._heal is not None:
                    self._heal.note_progress(req.rid)
                if req.last_burst_at is not None:
                    # Burst-gap ITL: one sample per boundary burst — the
                    # client-visible stall a prefill interloper causes.
                    gaps_ms.append(1000.0 * (now - req.last_burst_at))
                req.last_burst_at = now
            if not active_h[slot]:
                self._complete(req)
        if total or gaps_ms:
            with self.stats.lock:
                self.stats.tokens_out += total
                for g in gaps_ms:
                    self.stats.record_itl_locked(g)

    def _live_wave_rids(self) -> List[int]:  # graftlint: holds(_book)
        """The rids riding a whole-batch (decode/ragged/verify) wave —
        the sticky chaos fault's membership test."""
        return [
            r.rid for r in self._slots
            if r is not None and not r.finished
        ]

    def _chaos_dispatch(self, site: str,
                        rids: Sequence[int] = ()) -> None:
        """Dispatch-failure injection point, active ONLY on the scheduler
        thread — warmup and direct test calls share the dispatch helpers
        and must neither fault nor consume draws (the seeded fault
        sequence is defined over scheduler-loop dispatches alone).
        `rids` is the dispatched wave's membership, for the sticky
        (per-request deterministic) fault."""
        if self._san is not None and (
            threading.current_thread() is self._thread
        ):
            self._san.perturb("dispatch")
        if self._chaos is not None and (
            threading.current_thread() is self._thread
        ):
            try:
                self._chaos.on_dispatch(site, rids)
            except Exception:
                # An injected dispatch fault is about to unwind the
                # scheduler iteration — pin it to the timeline first.
                if self._recorder is not None:
                    self._recorder.record("chaos", -1, {"site": site})
                raise

    def _fail_req(self, req: _Request, msg: str,  # graftlint: holds(_book)
                  kind: str = "internal", retriable: bool = False) -> None:
        """Fail one request with a typed error item (kind in {internal,
        capacity, preempted, cancelled, deadline, draining, shutdown,
        poison}), then finalize it — slot/blocks/trie refs freed, None
        sentinel queued. Idempotent like _complete."""
        if req.finished:
            return
        req.outcome = kind
        req.out.put({"error": msg, "kind": kind, "retriable": retriable})
        self._complete(req)

    def _complete(self, req: _Request) -> None:  # graftlint: holds(_book)
        """Finish a request (idempotent) and free its slot unless the
        slot has already been recycled to a newer request."""
        if self._san is not None:
            self._san.assert_holds("_book")
        if req.finished:
            return
        req.finished = True
        if self._heal is not None:
            self._heal.note_done(req.rid)
        now = time.perf_counter()
        margin_ms = (
            1000.0 * (req.deadline - now) if req.deadline is not None
            else None
        )
        if self._tracer.enabled:
            self._emit_request_spans(req, now, margin_ms)
        if self._recorder is not None:
            self._recorder.record(
                "terminal", req.rid,
                {"outcome": req.outcome or "ok",
                 "n_generated": req.n_generated},
            )
        with self._rid_lock:
            self._requests.pop(req.rid, None)
        if req.prefix_handle is not None:
            # Unpin the trie path — the slot no longer depends on it, so
            # LRU eviction may reclaim it under budget pressure.
            index = self._prefix if self._prefix is not None \
                else self._paged_prefix
            if index is not None:
                index.release(req.prefix_handle)
            req.prefix_handle = None
        if self._paged:
            self._release_blocks(req)
        req.out.put(None)
        slot = req.slot
        if 0 <= slot < len(self._slots) and self._slots[slot] is req:
            self._slots[slot] = None
            self._active_host[slot] = False
            self._free.append(slot)
        with self.stats.lock:
            self.stats.completed += 1
            self.stats.record_slo_locked(margin_ms, req.outcome == "")

    def _perf_ns(self, t: float) -> int:
        """perf_counter seconds -> wall-clock ns via the init-time epoch
        pairing (Span start/end are time_ns-domain)."""
        return self._epoch_ns + int((t - self._epoch_perf) * 1e9)

    def _emit_request_spans(self, req: _Request, now: float,  # graftlint: holds(_book)
                            margin_ms: Optional[float]) -> None:
        """Retro-emit the request's lifecycle spans — one `engine.request`
        root (adopting the caller's traceparent when one arrived) plus
        queued/prefill/decode children — from the timestamps _Request
        already carries. Runs exactly once per request, gated by the
        `req.finished` flip in _complete, so terminal spans have the
        same exactly-once guarantee as the out-queue sentinel."""
        outcome = req.outcome or "ok"
        attrs: Dict[str, Any] = {
            "rid": req.rid,
            "outcome": outcome,
            "prompt_tokens": len(req.tokens),
            "completion_tokens": req.n_generated,
        }
        if req.prefix_len:
            attrs["prefix_tokens"] = req.prefix_len
        if margin_ms is not None:
            attrs["deadline_margin_ms"] = round(margin_ms, 3)
        root = self._tracer.emit_span(
            "engine.request",
            self._perf_ns(req.submitted_at),
            self._perf_ns(now),
            parent=req.trace,
            attributes=attrs,
            status="OK" if outcome == "ok" else f"ERROR: {outcome}",
        )
        first = req.first_dispatch_at
        self._tracer.emit_span(
            "engine.queued",
            self._perf_ns(req.submitted_at),
            self._perf_ns(first if first is not None else now),
            parent=root,
        )
        if first is not None:
            tok = req.first_token_at
            self._tracer.emit_span(
                "engine.prefill",
                self._perf_ns(first),
                self._perf_ns(tok if tok is not None else now),
                parent=root,
            )
            if tok is not None:
                self._tracer.emit_span(
                    "engine.decode",
                    self._perf_ns(tok),
                    self._perf_ns(now),
                    parent=root,
                    attributes={"tokens": req.n_generated},
                )

    def _wave_retire(self, item) -> None:  # graftlint: holds(_book)
        """Remove one wave from the in-flight registry by identity
        (waves hold unhashable device arrays). No-op for waves never
        registered (sync-mode boundaries, partial wrecks)."""
        for i, wave in enumerate(self._inflight_waves):
            if wave is item:
                del self._inflight_waves[i]
                return

    def _gather_wrecked(self, pendings=()) -> Dict[int, _Request]:  # graftlint: holds(_book)
        """Every request a wrecked dispatch may have owned: the live
        slot table plus the in-flight pending waves, whose admit groups
        and rosters hold requests already optimistically recycled out of
        `_slots`. Pendings are normalized through _PendingWave so a
        future timing-tuple growth can't silently misalign failure
        accounting. The in-flight wave registry is folded in because it
        is the only complete census of dispatched-but-unretired waves:
        a wave sitting in `_fetch_q`, held by the fetcher pre-epoch-
        check, or built but not yet put by the scheduler is invisible
        to everything else, and the epoch guard will discard it unread
        — a request recycled out of `_slots` into such a wave exists
        nowhere else."""
        live: Dict[int, _Request] = {}
        for req in self._slots:
            if req is not None:
                live[req.rid] = req
        for pending in (*pendings, *self._inflight_waves):
            if pending is None:
                continue
            wave = _PendingWave(*pending)
            for group, _, _, _ in wave.admits:
                for req in group:
                    live[req.rid] = req
            for req in wave.roster or []:
                if req is not None:
                    live[req.rid] = req
        return live

    def _fail_all(self, err: str, pendings=()) -> None:  # graftlint: holds(_book)
        """Fail every live request and reset device + slot state — called
        when a dispatched computation errored (donated buffers are gone)
        and the heal supervisor is off (or the engine is stopping).
        `pendings`: in-flight _PendingWave tuples — requests
        optimistically recycled out of `_slots` live only there."""
        if self._san is not None:
            self._san.assert_holds("_book")
        if self._spec:
            self._spec_wave = None  # descriptor of a wave now wrecked
        if self._recorder is not None:
            self._recorder.record("fail-all", -1, {"error": err[:200]})
        for req in self._gather_wrecked(pendings).values():
            if not req.finished:
                # Engine-wreck failures are retriable: the device state is
                # rebuilt fresh right below and the request did nothing
                # wrong.
                self._fail_req(req, err, kind="internal", retriable=True)
        self._rebuild_device_state()

    def _rebuild_device_state(self) -> None:  # graftlint: holds(_book)
        """Reset device + slot state after a wrecked dispatch: the jit
        functions donated their argument buffers, so whatever the device
        held is gone — fresh slots, fresh paged pool bookkeeping, fresh
        carried state. Every live request must already be failed
        (_fail_all) or detached for resurrection (_prepare_resurrect)
        before this runs."""
        # Invalidate every dispatched-but-unretired boundary: rosters in
        # flight reference pre-rebuild slots, and the async fetcher may
        # surface one AFTER this rebuild. _fetch_loop discards waves
        # whose epoch is stale instead of delivering their tokens twice
        # — safe because the caller gathered every registered wave's
        # requests (_gather_wrecked) before bumping the epoch here.
        self._wave_epoch += 1
        B = self.ecfg.max_slots
        self._slots = [None] * B
        self._free = list(range(B))
        self._active_host[:] = False
        self._prefilling.clear()  # mid-prefill requests failed via _slots
        if self._paged:
            # The sweep above unreffed every live request's blocks into
            # the old allocator; rebuild pool bookkeeping wholesale so it
            # matches the fresh device state (trie refs included).
            from seldon_tpu.servers.block_pool import BlockAllocator
            self._allocator = BlockAllocator(self._num_blocks)
            with self.stats.lock:
                self.stats.pool_gauges = self._allocator.snapshot
            self._table_host[:] = 0
            if self._paged_prefix is not None:
                from seldon_tpu.servers.prefix_cache import \
                    PagedPrefixIndex
                self._paged_prefix = PagedPrefixIndex(
                    block=self.ecfg.prefix_block,
                    kv_block=self._kv_block,
                    allocator=self._allocator,
                )
            # Still-waiting requests may hold handles into the old trie;
            # drop them so admission re-looks-up against the new one.
            for req in self._waiting:
                req.prefix_handle = None
                req.prefix_len = None
                req.block_ids = []
            if self._san is not None:
                # Fresh allocator/trie carry fresh raw locks.
                graftsan.rewrap_pool(self, self._san)
        self._state = self._fresh_state()

    # --- graftheal: supervised fault recovery --------------------------------

    def _fail_or_heal(self, err: str, pendings=()) -> None:  # graftlint: holds(_book)
        """Route a wrecked wave: supervised recovery when the heal
        supervisor is armed and the engine is staying up, else the
        kill-everyone _fail_all sweep — the raw failure path, byte-
        identical to the pre-heal engine whenever HEAL is off."""
        if (self._heal is None or self._stop.is_set()
                or self._draining.is_set()):
            self._fail_all(err, pendings)
            return
        logger.warning("graftheal: wave faulted (%s); recovering", err)
        self._heal_recover(err, pendings)

    def _heal_recover(self, err: str, pendings=()) -> None:  # graftlint: holds(_book)
        """Supervised wave-fault recovery (the graftheal tentpole).
        Instead of failing every innocent in-flight request, classify
        the wrecked cohort through the supervisor — resurrect / pen
        (bisection hold or retry backoff) / poison (deterministically
        faults its wave; fails alone, non-retriable) / exhausted
        (resurrection budget spent) — fail only the convicted, rewrite
        the innocents for replay, then rebuild device state and re-queue
        them at the FRONT of the admission queue in ascending-rid order
        so replays stay ahead of fresh traffic. Deterministic
        per-position sampling keys (fold_in(key(seed), abs_pos)) make
        each replayed continuation bit-identical to its unfaulted run,
        greedy and sampled alike."""
        heal = self._heal
        if self._san is not None:
            self._san.assert_holds("_book")
        if self._spec:
            self._spec_wave = None  # descriptor of a wave now wrecked
        now = time.perf_counter()
        live = self._gather_wrecked(pendings)
        # A stale wave still in the in-flight registry at a SECOND
        # fault references requests an earlier
        # recovery already resurrected into _waiting or penned. Those
        # are safely parked, not wrecked: re-convicting them would
        # charge a fault they didn't take, and re-resurrecting would
        # duplicate them in the admission queue.
        parked = {r.rid for r in self._waiting}
        parked.update(r.rid for r in heal.pen_scan())
        verdicts = heal.plan_recovery(
            [rid for rid, r in live.items()
             if not r.finished and rid not in parked],
            now,
        )
        if self._recorder is not None:
            counts: Dict[str, int] = {}
            for v in verdicts.values():
                counts[v] = counts.get(v, 0) + 1
            self._recorder.record(
                "heal", -1,
                {"error": err[:200], "state": heal.state,
                 "mode": heal.mode, **counts},
            )
        # Terminal verdicts and replay rewrites run BEFORE the rebuild:
        # _fail_req unrefs blocks/trie pins into the old pool, which the
        # rebuild then discards wholesale (same ordering as _fail_all).
        queue_front: List[_Request] = []
        pen: List[_Request] = []
        for rid in sorted(verdicts):
            req = live[rid]
            if req.finished:
                continue
            v = verdicts[rid]
            if v == "poison":
                self._fail_req(
                    req,
                    f"quarantined: request deterministically faults its "
                    f"wave ({err[:160]})",
                    kind="poison", retriable=False,
                )
            elif v == "exhausted":
                self._fail_req(
                    req,
                    f"resurrection budget exhausted "
                    f"(heal_max_retries={heal.max_retries}): {err[:160]}",
                    kind="internal", retriable=False,
                )
            elif self._prepare_resurrect(req):
                (pen if v == "pen" else queue_front).append(req)
        self._rebuild_device_state()
        for req in reversed(queue_front):
            self._waiting.appendleft(req)
            heal.note_resurrected()
        for req in pen:
            heal.pen_put(req, now)

    def _prepare_resurrect(self, req: _Request) -> bool:  # graftlint: holds(_book)
        """Detach a wrecked-but-innocent request from the dead device
        state and rewrite it for replay: committed tokens fold into the
        prompt, the token budget shrinks by what the client already
        holds, and the request re-enters the normal prefill/chunked
        admission path as if freshly submitted — landing in an existing
        prefill bucket, so resurrection compiles nothing. Returns False
        when the request reached a terminal state here instead (fully
        delivered, or the folded prompt can no longer be admitted)."""
        fold = req.gen_hist[req.replayed:]
        if fold:
            req.tokens = list(req.tokens) + fold
            req.replayed += len(fold)
            remaining = req.params.max_new_tokens - len(fold)
            if remaining <= 0:
                # The client already holds every token the budget buys.
                self._complete(req)
                return False
            req.params = dataclasses.replace(
                req.params, max_new_tokens=remaining
            )
        if len(req.tokens) > max(self._buckets):
            self._fail_req(
                req,
                f"resurrection impossible: folded prompt "
                f"{len(req.tokens)} exceeds max bucket "
                f"{max(self._buckets)}",
                kind="internal", retriable=True,
            )
            return False
        if self._paged:
            need = -(-len(req.tokens) // self._kv_block)
            if need > self._num_blocks - 1:
                self._fail_req(
                    req,
                    f"resurrection impossible: folded prompt needs "
                    f"{need} kv blocks but the pool holds "
                    f"{self._num_blocks - 1}",
                    kind="internal", retriable=True,
                )
                return False
        # Detach from the wrecked device state. Paged block refs and
        # trie handles just drop — the pool is rebuilt wholesale right
        # after — but a DENSE prefix pin must be released: its trie
        # survives the rebuild, and admission re-looks the prompt up.
        if req.prefix_handle is not None and self._prefix is not None:
            self._prefix.release(req.prefix_handle)
        req.prefix_handle = None
        req.prefix_len = None
        req.block_ids = []
        req.slot = -1
        req.expected = 0
        req.n_generated = 0
        req.prefilling = False
        req.prefill_done = 0
        return True

    def _heal_tick(self) -> None:  # graftlint: holds(_book)
        """Boundary-time heal bookkeeping (scheduler thread, under
        _book): reap cancelled/expired requests parked in the pen —
        they sit in neither _slots nor _waiting, so the regular reap
        cannot see them — then release due pen entries back into the
        admission queue. Draining/stopping flushes the pen wholesale so
        shutdown never strands a parked request."""
        heal = self._heal
        now = time.perf_counter()
        for req in heal.pen_scan():
            if req.finished:
                continue
            if req.cancelled:
                with self.stats.lock:
                    self.stats.cancelled_total += 1
                self._fail_req(
                    req, f"cancelled after {req.replayed} tokens",
                    kind="cancelled",
                )
                heal.pen_drop(req.rid)
            elif req.deadline is not None and now >= req.deadline:
                with self.stats.lock:
                    self.stats.deadline_expired_total += 1
                self._fail_req(
                    req, f"deadline exceeded after {req.replayed} tokens",
                    kind="deadline",
                )
                heal.pen_drop(req.rid)
        flush = self._draining.is_set() or self._stop.is_set()
        for req in heal.pen_take(now, flush=flush):
            self._waiting.appendleft(req)
            heal.note_resurrected()

    def _heal_requeue_group(self, reqs: List[_Request],  # graftlint: holds(_book)
                            err: str) -> bool:
        """Admission-group fault path with the supervisor armed. Unlike
        a wrecked wave, a failed admission group never donated the
        carried state away, so there is no rebuild: release the group's
        slots/blocks/pins back into the LIVE pool and route each
        request through the same supervisor verdicts as any wrecked
        cohort. Returns False (caller falls back to the raw per-group
        _fail_req sweep) when healing is off or the engine is going
        down."""
        if (self._heal is None or self._stop.is_set()
                or self._draining.is_set()):
            return False
        heal = self._heal
        now = time.perf_counter()
        by_rid = {r.rid: r for r in reqs}
        verdicts = heal.plan_recovery(
            [r.rid for r in reqs if not r.finished], now
        )
        if self._recorder is not None:
            counts: Dict[str, int] = {}
            for v in verdicts.values():
                counts[v] = counts.get(v, 0) + 1
            self._recorder.record(
                "heal", -1,
                {"error": err[:200], "state": heal.state,
                 "mode": heal.mode, "site": "admit", **counts},
            )
        queue_front: List[_Request] = []
        for rid in sorted(verdicts):
            req = by_rid[rid]
            if req.finished:
                continue
            slot = req.slot
            if slot >= 0:
                if self._slots[slot] is req:
                    self._slots[slot] = None
                    self._active_host[slot] = False
                    self._free.append(slot)
                elif slot not in self._free:
                    self._free.append(slot)  # popped, never registered
            try:
                self._prefilling.remove(req)
            except ValueError:
                pass
            if self._paged:
                self._release_blocks(req)
            if req.prefix_handle is not None:
                index = self._prefix if self._prefix is not None \
                    else self._paged_prefix
                if index is not None:
                    index.release(req.prefix_handle)
                req.prefix_handle = None
            v = verdicts[rid]
            if v == "poison":
                self._fail_req(
                    req,
                    f"quarantined: request deterministically faults its "
                    f"wave ({err[:160]})",
                    kind="poison", retriable=False,
                )
            elif v == "exhausted":
                self._fail_req(
                    req,
                    f"resurrection budget exhausted "
                    f"(heal_max_retries={heal.max_retries}): {err[:160]}",
                    kind="internal", retriable=False,
                )
            elif self._prepare_resurrect(req):
                if v == "pen":
                    heal.pen_put(req, now)
                else:
                    queue_front.append(req)
        for req in reversed(queue_front):
            self._waiting.appendleft(req)
            heal.note_resurrected()
        return True

    def _fetch_boundary(self, admits, chunk_handles):
        """One boundary's device->host fetch wrapped in the graftheal
        guards: the chaos hang runs INSIDE the watchdog bound (an
        injected hang is observed exactly like a wedged transfer), the
        watchdog raises WatchdogError into the wreck path after
        heal_watchdog_ms, chaos token poisoning corrupts the fetched
        copies, and the NaN/garbage sentinel screens every token id
        before any reaches a client queue. Touches no engine
        bookkeeping — runs under _book on the sync path and lock-free
        on the fetcher thread."""
        def fetch():
            if self._chaos is not None:
                self._chaos.maybe_hang()
            return jax.device_get(  # graftlint: allow(hot-sync, lock-block) deliberate boundary fetch; handles were host-copied via copy_to_host_async at dispatch
                ([(f, d) for _, _, f, d in admits], chunk_handles)
            )

        if self._heal is not None and self._heal.watchdog_ms > 0:
            admit_data, chunk_data = self._heal.bounded_fetch(fetch)
        else:
            admit_data, chunk_data = fetch()
        if self._chaos is not None and self._chaos.cfg.nan_inject:
            # device_get host copies may be read-only views; poisoning
            # needs owned arrays (chaos-only path, never hot).
            admit_data = [
                (np.array(f), np.array(d)) for f, d in admit_data
            ]
            if chunk_data is not None:
                chunk_data = tuple(np.array(a) for a in chunk_data)
            self._chaos.poison_fetch(
                [f for f, _ in admit_data]
                + ([chunk_data[0]] if chunk_data is not None else [])
            )
        if self._heal is not None:
            self._heal.check_tokens(
                admit_data, chunk_data, self.cfg.vocab_size
            )
        return admit_data, chunk_data

    def _process_boundary(self, admits, chunk_handles, roster,  # graftlint: holds(_book)
                          timing=None, epoch=None) -> None:
        """Fetch one boundary's device results (one parallel transfer) and
        run host bookkeeping. `timing` is the wave's (dispatch t0,
        variant keys, roof rider) triple when DISPATCH_TIMING is on,
        None otherwise. A wave from a pre-rebuild epoch is discarded
        wholesale (see _PendingWave.epoch)."""
        if epoch is not None and epoch != self._wave_epoch:
            return
        if self._chaos is not None:
            self._chaos.maybe_slow_boundary()  # graftlint: allow(lock-block) deliberate chaos fault: a slow boundary under _book is exactly the race window being tested
        roofing = self._roof is not None and timing is not None
        f0 = time.perf_counter() if roofing else 0.0
        admit_data, chunk_data = self._fetch_boundary(
            admits, chunk_handles
        )
        f1 = time.perf_counter() if roofing else 0.0
        self._process_admits(admits, admit_data)
        if chunk_data is not None:
            self._process_chunk(*chunk_data, roster)
        if self._spec:
            self._spec_post_process(chunk_data, roster)
        self._record_wave_timing(timing)
        if roofing:
            self._roof_note_boundary(timing, f0, f1)
        if self._san is not None:
            self._san.audit(self)
        if self._sled is not None:
            self._sled.audit()
        if self._heal is not None:
            self._heal.note_boundary_ok()

    def _make_timing(self):  # graftlint: holds(_book)
        """Boundary timing token built at dispatch end: (stamp, wave
        keys, roof rider). The rider — (host_pre_s, enqueue_s) relative
        to the step-entry stamp — is the decomposition half the
        roofline joins with the boundary-side stamps; it stays None
        when the roof is down so the tuple costs nothing extra."""
        now = time.perf_counter()
        rider = None
        if self._roof is not None:
            enq = self._wave_enq_s
            rider = (max(0.0, now - self._step_t0 - enq), enq)
            self._wave_enq_s = 0.0
        keys = self._wave_keys
        self._wave_keys = []
        return (now, keys, rider)

    def _roof_note_boundary(self, timing, f0: float,
                            f1: float) -> None:  # graftlint: holds(_book)
        """Roofline boundary tap: close the step decomposition (host-
        pre from the dispatch rider, device = jit enqueue + boundary
        fetch, host-post = bookkeeping after the fetch, overlap = the
        pipelined in-flight gap) against the independently measured
        span, join the wave's keys with the device time, run the
        conservation audit, and mirror one flight-recorder "roof"
        record for the trace_view host/device lanes."""
        t0, keys, rider = timing
        if rider is None:
            return
        f2 = time.perf_counter()
        host_pre_s, enq_s = rider
        fetch_s = max(0.0, f1 - f0)
        gap_s = max(0.0, f0 - t0)
        post_s = max(0.0, f2 - f1)
        device_s = enq_s + fetch_s
        # Span re-derived from the same stamps the components use, so
        # the audit's 1% tolerance is a real accumulation-drift check,
        # not a tautology over one float.
        span_s = host_pre_s + enq_s + max(0.0, f2 - t0)
        self._roof.note_step(
            1000.0 * host_pre_s, 1000.0 * device_s,
            1000.0 * post_s, 1000.0 * span_s,
        )
        if keys:
            self._roof.note_wave(keys, 1000.0 * device_s)
        self._roof.audit()
        if self._recorder is not None:
            self._recorder.record(
                "roof", -1,
                {"pre_ms": round(1000.0 * host_pre_s, 3),
                 "enq_ms": round(1000.0 * enq_s, 3),
                 "gap_ms": round(1000.0 * gap_s, 3),
                 "fetch_ms": round(1000.0 * fetch_s, 3),
                 "post_ms": round(1000.0 * post_s, 3)},
            )

    def _record_wave_timing(self, timing) -> None:  # graftlint: holds(_book)
        """Per-variant boundary timing: the wave's dispatch keys against
        the dispatch -> boundary-processed wall time, measured at the
        deliberate device_get sync. Buckets into EngineStats and mirrors
        one flight-recorder "dispatch" record per key (single-writer:
        the scheduler thread or the fetcher under _book)."""
        if timing is None:
            return
        t0, keys = timing[0], timing[1]
        if not keys:
            return
        ms = 1000.0 * (time.perf_counter() - t0)
        with self.stats.lock:
            for key in keys:
                self.stats.record_variant_locked(
                    compile_ledger.key_str(key), ms
                )
        if self._recorder is not None:
            for key in keys:
                self._recorder.record(
                    "dispatch", -1,
                    {"variant": compile_ledger.key_str(key),
                     "ms": round(ms, 3)},
                )

    def _roster(self) -> List[Optional[_Request]]:  # graftlint: holds(_book)
        """Slot -> request snapshot for THIS wave's decode chunk. Mid-
        prefill requests hold slots but have produced no tokens and are
        device-inactive — masking them out keeps _process_chunk from
        reading their columns (and completing them on active=False) and
        keeps _recycle_budget_spent from charging them decode budget.
        Without chunked prefill no slot is ever mid-prefill, so this is
        exactly list(self._slots)."""
        return [
            None if (r is not None and r.prefilling) else r
            for r in self._slots
        ]

    def _pick_chunk(self) -> int:  # graftlint: holds(_book)
        """Prefill-priority chunk policy: admissions only happen at chunk
        boundaries, so a long chunk is admission LATENCY whenever an
        arrival could actually be admitted. Long chunks are therefore
        reserved for saturation — when fewer than max_admit slots are
        free, a mid-chunk arrival would have waited for completions
        anyway, so the full decode_chunk costs nothing and amortizes the
        host round trip. With real free capacity, boundaries stay at
        min_chunk so TTFT tracks the unloaded floor (one engine holds
        both the SLO and the saturated-throughput claims — the policy
        the old chunk-4-vs-64 mode switch approximated by hand)."""
        sizes = self._chunk_sizes
        if len(sizes) == 1:
            return sizes[0]
        n_slots = len(self._slots)
        free = sum(1 for r in self._slots if r is None)
        # Thresholds scale with the pool so tiny test engines (where
        # max_admit ~ max_slots) don't read "half empty" as saturated.
        sat = min(self._max_admit, (n_slots + 7) // 8)
        if free < sat:
            idx = len(sizes) - 1  # saturated: nothing admittable mid-chunk
        elif free < n_slots // 4:
            # Mid rung, capped below the top: with only two rungs
            # (e.g. decode_chunk=8, min_chunk=4 dedups to (4, 8)),
            # len//2 would resolve to the TOP rung and near-saturation
            # would silently lose its admission boundaries.
            idx = min(len(sizes) // 2, len(sizes) - 2)
        else:
            idx = 0
        if self._pilot is not None:
            # Deadline-pressure bias moves the occupancy pick at most
            # one rung (pilot never leaves the compiled ladder).
            idx = max(0, min(idx + self._pilot.chunk_bias(),
                             len(sizes) - 1))
        return sizes[idx]

    def _recycle_budget_spent(self, roster: List[Optional[_Request]],  # graftlint: holds(_book)
                              chunk_len: int) -> None:
        """Optimistic slot recycling: `expected` is an upper bound on the
        tokens a row will have produced once every dispatched chunk
        retires, and the device-side `remaining` counter guarantees a row
        NEVER exceeds its budget — so a slot whose budget is provably
        spent can take a new request immediately, without waiting for the
        chunk's results. The next admission's cache scatter is queued
        AFTER the chunk device-side, so ordering is exact. This removes
        the end-of-wave stall where the scheduler used to sync (one full
        host round trip with an idle device) before refilling slots."""
        for slot, req in enumerate(roster):
            if req is None or req.finished:
                continue
            req.expected += max(1, chunk_len)
            if req.expected >= req.params.max_new_tokens:
                if self._slots[slot] is req:
                    self._slots[slot] = None
                    self._active_host[slot] = False
                    self._free.append(slot)
                    if self._paged:
                        # Return the row's blocks now: the just-dispatched
                        # chunk freezes this row at its budget, and any new
                        # owner's admission scatter is queued after it —
                        # the zombie row only touches the trash block.
                        self._release_blocks(req)

    def _drain_and_fail(self, err: str, current=None) -> None:
        """Async-mode failure: fail — or, with the heal supervisor
        armed, resurrect — every request a wrecked boundary may have
        owned. In-flight waves are gathered from the registry (see
        _inflight_waves), NOT by draining _fetch_q: a queue drain here
        raced the scheduler's lock-free puts, so waves dispatched
        between the drain and the epoch bump were never gathered and
        their requests stranded when the fetcher later discarded them
        as stale. Stale waves stay queued; the fetcher retires them.
        `current` is a partial wreck (e.g. _dispatch_wreck) that never
        reached the registry. Called under NO lock; takes _book
        itself."""
        with self._book:
            self._fail_or_heal(
                err, [current] if current is not None else []
            )

    def _fetch_loop(self) -> None:
        """Boundary-fetcher thread: device_get (a full host<->device
        round trip) runs OUTSIDE the bookkeeping lock, so the scheduler
        keeps dispatching while results travel; only the host-side
        processing serializes with it. A request's first token therefore
        costs ~one round trip under load instead of two."""
        while True:
            item = self._fetch_q.get()
            if item is None:
                return
            admits, chunk_handles, roster, timing, epoch = item
            try:
                with self._book:
                    if epoch != self._wave_epoch:
                        # Dispatched against pre-rebuild device state
                        # while a fault was being healed: the roster
                        # references dead slots and its requests were
                        # already gathered from the registry and
                        # resurrected — fetching or screening it could
                        # only double tokens or re-trip recovery.
                        continue
                if self._san is not None:
                    self._san.perturb("boundary")
                if self._chaos is not None:
                    self._chaos.maybe_slow_boundary()
                roofing = self._roof is not None and timing is not None
                f0 = time.perf_counter() if roofing else 0.0
                admit_data, chunk_data = self._fetch_boundary(
                    admits, chunk_handles
                )
                f1 = time.perf_counter() if roofing else 0.0
                with self._book:
                    if epoch != self._wave_epoch:
                        continue  # rebuild raced the fetch: stale wave
                    self._process_admits(admits, admit_data)
                    if chunk_data is not None:
                        self._process_chunk(*chunk_data, roster)
                    self._record_wave_timing(timing)
                    if roofing:
                        self._roof_note_boundary(timing, f0, f1)
                    if self._san is not None:
                        self._san.audit(self)
                    if self._sled is not None:
                        self._sled.audit()
                    if self._heal is not None:
                        self._heal.note_boundary_ok()
            except Exception as e:
                logger.exception("boundary fetch failed")
                self._drain_and_fail(str(e), current=item)
            finally:
                # Retire exactly once on every path — processed, stale-
                # dropped, or faulted (after recovery gathered it).
                with self._book:
                    self._wave_retire(item)

    def _loop(self) -> None:
        # Software-pipelined scheduler: chunk N+1 is dispatched BEFORE
        # chunk N's results are fetched, so the host fetch (one device
        # round trip) and queue bookkeeping overlap with device compute.
        # This is safe because per-row termination is device-side: rows
        # that finished during chunk N are already frozen (active=False
        # in the carried state) when chunk N+1 runs — the host merely
        # learns about it one boundary late (per-chunk rosters keep
        # attribution exact). Length-bounded rows free their slots at
        # DISPATCH time (_recycle_budget_spent), so the pipeline never
        # drains at wave boundaries; EOS-finished rows free one boundary
        # late. With async_fetch (single-process), fetches run on a
        # dedicated thread (_fetch_loop) and this loop NEVER blocks on a
        # round trip; multi-process meshes keep the synchronous variant
        # so SPMD dispatch decisions stay timing-independent.
        if self._async_fetch:
            self._loop_async()
        else:
            self._loop_sync()
        if self._profile_active:
            # Window still open at shutdown: flush what was captured.
            try:
                jax.profiler.stop_trace()
            except (RuntimeError, OSError, ValueError):
                # Best-effort flush; no request state rides on it.
                logger.exception("TRACE_PROFILE_N flush failed")
            self._profile_active = False

    def _profile_tick(self) -> None:
        """TRACE_PROFILE_N device-profile window: start a jax.profiler
        capture at the first dispatched boundary, stop it after N — the
        device timeline (tools/profile_decode.py parses the same
        trace.json.gz) lines up against the recorder's wall-clock
        "boundary" records via the profile-start/-stop markers. Called
        from the scheduler loop OUTSIDE _book: profiler start/stop does
        host I/O and must not block bookkeeping."""
        if not self._profile_active:
            try:
                jax.profiler.start_trace(self._profile_dir)
            except (RuntimeError, OSError, ValueError):
                # Best-effort start; disables the window, never a request.
                logger.exception("TRACE_PROFILE_N start failed")
                self._profile_n = 0
                return
            self._profile_active = True
            if self._recorder is not None:
                self._recorder.record(
                    "profile-start", -1, {"dir": self._profile_dir}
                )
        self._profile_count += 1
        if self._profile_count >= self._profile_n:
            self._profile_n = 0  # window done; ticks stop
            self._profile_active = False
            try:
                jax.profiler.stop_trace()
            except (RuntimeError, OSError, ValueError):
                # Best-effort stop; no request state rides on it.
                logger.exception("TRACE_PROFILE_N stop failed")
            if self._recorder is not None:
                self._recorder.record(
                    "profile-stop", -1,
                    {"dir": self._profile_dir,
                     "boundaries": self._profile_count},
                )

    def _dispatch_decode_chunk(self, n: int):  # graftlint: holds(_book)
        """Dispatch one n-step decode chunk. Dense engines call the slab
        kernel unchanged; paged engines first grow each live row's block
        table to cover the chunk's worst-case positions (evicting /
        preempting on exhaustion), then pass the fresh tables alongside
        the donated state."""
        self._chaos_dispatch("decode", self._live_wave_rids())
        if self._paged:
            self._grow_decode_blocks(n)
            if not self._observe:
                return self._jit_chunks_paged[n](
                    self.params, self._state, jnp.asarray(self._table_host)
                )
            t0 = time.perf_counter()
            out = self._jit_chunks_paged[n](
                self.params, self._state, jnp.asarray(self._table_host)
            )
            self._note_dispatch(("decode", n), -1,
                                time.perf_counter() - t0)
            return out
        if not self._observe:
            return self._jit_chunks[n](self.params, self._state)
        t0 = time.perf_counter()
        out = self._jit_chunks[n](self.params, self._state)
        self._note_dispatch(("decode", n), -1, time.perf_counter() - t0)
        return out

    def _reap_lifecycle(self) -> None:  # graftlint: holds(_book)
        """Boundary-time lifecycle pass (scheduler thread, under _book):
        chaos disconnects, drain shedding, queued cancel/deadline
        shedding, then in-flight cancel/deadline finalization. Reaped
        in-flight rows are frozen device-side by ONE masked write —
        dispatched only when a reap actually happened, so engines that
        never see a cancel/deadline/drain keep their dispatch sequence
        byte-identical. A request already recycled out of _slots is
        within decode_chunk tokens of its budget and is left to retire
        naturally (its waiter already has every token it will get)."""
        if self._san is not None:
            self._san.perturb("reap")
        if self._chaos is not None:
            rids = [
                r.rid for r in self._slots
                if r is not None and not r.finished
            ]
            victim = self._chaos.pick_disconnect(rids)
            if victim is not None:
                self.cancel(victim)
        if self._draining.is_set():
            self._shed_queued_locked()
        if self._heal is not None:
            self._heal_tick()
        now = time.perf_counter()
        self._drain_pending()
        if self._waiting and any(
            r.cancelled or (r.deadline is not None and now >= r.deadline)
            for r in self._waiting
        ):
            kept: List[_Request] = []
            for req in self._waiting:
                if req.cancelled:
                    with self.stats.lock:
                        self.stats.cancelled_total += 1
                        self.stats.shed_total += 1
                    self._fail_req(req, "cancelled before admission",
                                   kind="cancelled")
                elif req.deadline is not None and now >= req.deadline:
                    with self.stats.lock:
                        self.stats.deadline_expired_total += 1
                        self.stats.shed_total += 1
                    self._fail_req(
                        req,
                        f"deadline exceeded after "
                        f"{1000.0 * (now - req.submitted_at):.0f} ms in "
                        f"queue",
                        kind="deadline",
                    )
                else:
                    kept.append(req)
            self._waiting = collections.deque(kept)
        dead: List[int] = []
        for slot, req in enumerate(self._slots):
            if req is None or req.finished:
                continue
            if req.cancelled:
                with self.stats.lock:
                    self.stats.cancelled_total += 1
                self._fail_req(
                    req, f"cancelled after {req.n_generated} tokens",
                    kind="cancelled",
                )
                dead.append(slot)
            elif req.deadline is not None and now >= req.deadline:
                with self.stats.lock:
                    self.stats.deadline_expired_total += 1
                self._fail_req(
                    req,
                    f"deadline exceeded after {req.n_generated} tokens",
                    kind="deadline",
                )
                dead.append(slot)
        if dead:
            keep = np.ones((self.ecfg.max_slots,), bool)
            keep[dead] = False
            if self._observe:
                t0 = time.perf_counter()
            self._state = self._jit_deactivate(
                self._state, jnp.asarray(keep)
            )
            if self._observe:
                self._note_dispatch(("deactivate",), -1,
                                    time.perf_counter() - t0)

    def _dispatch_once(self):  # graftlint: holds(_book)
        """One scheduling step under the bookkeeping lock. Returns the
        (admits, chunk_handles, roster, timing) boundary or None if
        idle. On an
        exception, self._dispatch_wreck holds the partial boundary so
        the error path can fail recycled-out-of-_slots requests."""
        self._dispatch_wreck = None
        if self._roof is not None:
            self._step_t0 = time.perf_counter()
        self._reap_lifecycle()
        if self._ragged:
            # graftragged: the whole step is ONE fused wave — no
            # separate admission groups or decode chunk below.
            return self._dispatch_ragged()
        if self._spec:
            # graftspec: admissions as usual, then a draft pass + one
            # wide verify dispatch instead of the decode chunk.
            return self._dispatch_spec()
        admits = (
            self._dispatch_prefill_chunks() if self._chunked
            else self._dispatch_admits()
        )
        self._dispatch_wreck = _PendingWave(admits, None, None, None)
        if admits or self._active_host.any():
            roster = self._roster()
            self._dispatch_wreck = _PendingWave(admits, None, roster, None)
            n = self._pick_chunk()
            self._state, toks, valid, active_after = (
                self._dispatch_decode_chunk(n)
            )
            with self.stats.lock:
                self.stats.decode_dispatches += 1
                self.stats.decode_steps += n
            self._recycle_budget_spent(roster, n)
            # Start the host copies NOW: the fetcher's device_get then
            # finds data already in flight, so boundary fetches overlap
            # each other instead of serializing one round trip each
            # (the fetcher was the pipeline bottleneck at small decode
            # chunks, where a chunk computes faster than one round trip).
            for _, _, f, d in admits:
                f.copy_to_host_async()
                d.copy_to_host_async()
            for h in (toks, valid, active_after):
                h.copy_to_host_async()
            wf = 0.0
            if self._sled is not None:
                self._sled.note_boundary()
                wf = self._sled.boundary_waste()
                with self.stats.lock:
                    self.stats.record_waste_locked(wf)
            if self._pilot is not None:
                self._pilot_tick()
            if self._recorder is not None:
                detail = {
                    "admits": sum(len(g) for g, _, _, _ in admits),
                    "chunk": n,
                    "active": int(self._active_host.sum()),
                }
                if self._paged:
                    detail["pool_free"] = int(self._allocator.free_count)
                if self._sled is not None:
                    detail["waste_frac"] = round(wf, 4)
                self._recorder.record("boundary", -1, detail)
            timing = self._make_timing() if self._timing_on else None
            self._dispatch_wreck = None
            return _PendingWave(
                admits, (toks, valid, active_after), roster, timing,
                self._wave_epoch,
            )
        self._dispatch_wreck = None
        return None

    def _loop_async(self) -> None:
        while not self._stop.is_set():
            work = None
            try:
                with self._book:
                    work = self._dispatch_once()
                    # Register the wave before releasing _book: requests
                    # recycled out of _slots this dispatch live only in
                    # its roster, and a recovery at ANY point before the
                    # fetcher retires it gathers it from this registry
                    # (see _gather_wrecked).
                    if work is not None:
                        self._inflight_waves.append(work)
            except Exception as e:
                logger.exception("engine dispatch failed")
                # _dispatch_once may have recycled requests out of
                # _slots before failing; they live only in its roster.
                with self._book:
                    wreck, self._dispatch_wreck = self._dispatch_wreck, None
                self._drain_and_fail(str(e), current=wreck)
                continue
            if work is not None:
                if self._profile_n:
                    self._profile_tick()
                # Bounded queue (maxsize=4): caps how far the host's
                # slot-state view may lag behind retired boundaries.
                # Blocks OUTSIDE the lock, so the fetcher keeps
                # draining; the wave stays registered until the fetcher
                # retires it.
                self._fetch_q.put(work)
            elif self._pending.empty():
                if self._sled is not None:
                    self._sled.note_idle()
                    with self.stats.lock:
                        self.stats.sched_idle_boundaries += 1
                time.sleep(self.ecfg.idle_sleep_s)

    def _loop_sync(self) -> None:
        # Slot/free-list/active bookkeeping runs under _book even in the
        # synchronous (no fetcher thread) mode: drain(), cancel paths and
        # debug_lifecycle_check() read the same state from other threads.
        if self._ragged:
            self._loop_sync_ragged()
            return
        if self._spec:
            self._loop_sync_spec()
            return
        pending: Optional[_PendingWave] = None
        while not self._stop.is_set():
            admits, roster = [], None  # visible to the except path
            try:
                with self._book:
                    if self._roof is not None:
                        self._step_t0 = time.perf_counter()
                    self._reap_lifecycle()
                    admits = (
                        self._dispatch_prefill_chunks() if self._chunked
                        else self._dispatch_admits()
                    )
                    if admits or self._active_host.any():
                        # Chunk consumes the post-admission state;
                        # device-side `active` is already armed even
                        # though _active_host lags until _process_admits.
                        roster = self._roster()
                        n = self._pick_chunk()
                        self._state, toks, valid, active_after = (
                            self._dispatch_decode_chunk(n)
                        )
                        chunk_handles = (toks, valid, active_after)
                        with self.stats.lock:
                            self.stats.decode_dispatches += 1
                            self.stats.decode_steps += n
                        self._recycle_budget_spent(roster, n)
                        wf = 0.0
                        if self._sled is not None:
                            self._sled.note_boundary()
                            wf = self._sled.boundary_waste()
                            with self.stats.lock:
                                self.stats.record_waste_locked(wf)
                        if self._pilot is not None:
                            self._pilot_tick()
                        if self._recorder is not None:
                            detail = {
                                "admits": sum(
                                    len(g) for g, _, _, _ in admits
                                ),
                                "chunk": n,
                                "active": int(self._active_host.sum()),
                            }
                            if self._paged:
                                detail["pool_free"] = int(
                                    self._allocator.free_count
                                )
                            if self._sled is not None:
                                detail["waste_frac"] = round(wf, 4)
                            self._recorder.record("boundary", -1, detail)
                    else:
                        chunk_handles = None
                    timing = (
                        self._make_timing()
                        if self._timing_on
                        and (admits or chunk_handles is not None)
                        else None
                    )
                    if pending is not None:
                        self._process_boundary(*pending)
                    pending = (
                        _PendingWave(admits, chunk_handles, roster, timing,
                                     self._wave_epoch)
                        if (admits or chunk_handles is not None)
                        else None
                    )
                    idle = (
                        pending is None and not self._active_host.any()
                    )
                if self._profile_n and pending is not None:
                    self._profile_tick()
                # Sleep outside the lock so drain()/cancel() never wait
                # on an idle tick.
                if idle and self._pending.empty():
                    if self._sled is not None:
                        self._sled.note_idle()
                        with self.stats.lock:
                            self.stats.sched_idle_boundaries += 1
                    time.sleep(self.ecfg.idle_sleep_s)
            except Exception as e:  # fail requests, reset, keep serving
                logger.exception("engine iteration failed")
                # The CURRENT iteration's admits/roster may hold requests
                # already recycled out of _slots — fail them too.
                with self._book:
                    self._fail_or_heal(
                        str(e),
                        [pending, _PendingWave(admits, None, roster, None)],
                    )
                pending = None
        # Drain the in-flight boundary so stop() doesn't strand requests.
        if pending is not None:
            try:
                with self._book:
                    self._process_boundary(*pending)
            except Exception as e:
                logger.exception("final boundary failed")
                with self._book:
                    self._fail_all(str(e), [pending])

    def _loop_sync_ragged(self) -> None:
        """Synchronous scheduler loop under RAGGED=1: each iteration is
        ONE fused wave (_dispatch_once routes to _dispatch_ragged),
        software-pipelined one boundary deep exactly like the bucketed
        loop — wave N+1 dispatches before wave N's results are
        fetched. Requests optimistically recycled out of _slots live in
        `pending` rosters and the dispatch wreck, so the error path
        fails both."""
        pending: Optional[_PendingWave] = None
        while not self._stop.is_set():
            try:
                with self._book:
                    work = self._dispatch_once()
                    if pending is not None:
                        self._process_boundary(*pending)
                    pending = work
                    idle = (
                        pending is None and not self._active_host.any()
                    )
                if self._profile_n and pending is not None:
                    self._profile_tick()
                # Sleep outside the lock so drain()/cancel() never wait
                # on an idle tick.
                if idle and self._pending.empty():
                    if self._sled is not None:
                        self._sled.note_idle()
                        with self.stats.lock:
                            self.stats.sched_idle_boundaries += 1
                    time.sleep(self.ecfg.idle_sleep_s)
            except Exception as e:  # fail requests, reset, keep serving
                logger.exception("engine iteration failed")
                with self._book:
                    wreck, self._dispatch_wreck = (
                        self._dispatch_wreck, None
                    )
                    self._fail_or_heal(str(e), [pending, wreck])
                pending = None
        # Drain the in-flight boundary so stop() doesn't strand requests.
        if pending is not None:
            try:
                with self._book:
                    self._process_boundary(*pending)
            except Exception as e:
                logger.exception("final boundary failed")
                with self._book:
                    self._fail_all(str(e), [pending])
