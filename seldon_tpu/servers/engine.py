"""Continuous-batching inference engine (the TPU serving hot loop).

Reference comparison: the reference has NO batching anywhere — each request
walks the graph and hits a Flask worker alone (SURVEY.md §7 "dynamic
batching ... the key new hot-loop component"). This engine is the TPU-native
answer, vLLM-style iteration-level scheduling mapped onto XLA's static-shape
world:

 * A fixed pool of B slots shares one pre-allocated KV cache
   [L, B, Smax, Hkv, Dh]; every decode iteration runs ONE jitted
   decode+sample step over all slots (MXU-batched), so new requests join
   and finished requests leave between steps without recompiling.
 * Prefill is per-request, bucketed to power-of-two prompt lengths (few
   compile variants, static shapes), then spliced into the slot cache with
   a jitted dynamic_update_slice.
 * The first token is sampled directly from prefill logits — TTFT is one
   prefill, never blocked behind other requests' decode steps.
 * All host<->device traffic per step is O(B) ints (sampled tokens out),
   so ICI/HBM stay busy and the Python loop stays off the critical path.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
import queue
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from seldon_tpu.models import transformer
from seldon_tpu.models.config import ModelConfig
from seldon_tpu.models.sampling import SamplingParams, sample, sample_per_row

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    max_slots: int = 8
    max_seq_len: int = 2048
    prompt_buckets: Sequence[int] = (32, 128, 512, 1024)
    idle_sleep_s: float = 0.002


@dataclasses.dataclass
class _Request:
    rid: int
    tokens: List[int]
    params: SamplingParams
    out: "queue.Queue[Optional[dict]]"
    submitted_at: float
    first_token_at: Optional[float] = None
    n_generated: int = 0
    slot: int = -1


class EngineStats:
    def __init__(self):
        self.lock = threading.Lock()
        self.requests = 0
        self.completed = 0
        self.tokens_out = 0
        self.ttft_sum = 0.0
        self.ttft_count = 0

    def snapshot(self) -> Dict[str, float]:
        with self.lock:
            return {
                "requests": self.requests,
                "completed": self.completed,
                "tokens_out": self.tokens_out,
                "mean_ttft_ms": (
                    1000.0 * self.ttft_sum / self.ttft_count
                    if self.ttft_count
                    else 0.0
                ),
            }


class InferenceEngine:
    """Slot-based continuous batching over a single sharded model."""

    def __init__(
        self,
        params: Any,
        cfg: ModelConfig,
        engine_cfg: Optional[EngineConfig] = None,
        mesh=None,
    ):
        self.cfg = cfg.validate()
        self.ecfg = engine_cfg or EngineConfig()
        self.params = params
        self.mesh = mesh
        B, Smax = self.ecfg.max_slots, self.ecfg.max_seq_len

        # Device-resident slot state.
        self._cache = transformer.init_cache(cfg, B, Smax)
        self._last_tok = jnp.zeros((B,), jnp.int32)
        self._pos = jnp.zeros((B,), jnp.int32)
        self._active = jnp.zeros((B,), jnp.bool_)
        self._active_host = np.zeros((B,), bool)  # control-flow mirror
        self._temp = jnp.ones((B,), jnp.float32)
        self._top_k = jnp.zeros((B,), jnp.int32)
        self._top_p = jnp.ones((B,), jnp.float32)
        self._seeds = jnp.zeros((B,), jnp.uint32)

        # Prompt buckets clamped to the cache window (empty -> whole window).
        self._buckets = tuple(
            b for b in self.ecfg.prompt_buckets if b <= Smax
        ) or (Smax,)

        # Host-side bookkeeping.
        self._slots: List[Optional[_Request]] = [None] * B
        self._free: List[int] = list(range(B))
        self._pending: "queue.Queue[_Request]" = queue.Queue()
        self._rid = 0
        self._rid_lock = threading.Lock()
        self.stats = EngineStats()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

        self._jit_prefill = jax.jit(
            functools.partial(self._prefill_impl, cfg=self.cfg),
            static_argnames=(),
        )
        self._jit_insert = jax.jit(self._insert_impl, donate_argnums=(0,))
        self._jit_decode = jax.jit(
            functools.partial(self._decode_impl, cfg=self.cfg),
            donate_argnums=(1,),
        )

    # --- jitted kernels -----------------------------------------------------

    @staticmethod
    def _prefill_impl(params, tokens, plen, key, temp, top_k, top_p, *, cfg):
        """tokens [1, Sb] -> (first sampled token [1], sub-cache k/v)."""
        sub = transformer.init_cache(cfg, 1, tokens.shape[1])
        logits, sub = transformer.prefill(params, tokens, plen, sub, cfg)
        tok = sample(logits, key, temp, top_k, top_p)
        return tok, sub["k"], sub["v"]

    @staticmethod
    def _insert_impl(cache, sub_k, sub_v, slot):
        """Splice a prefilled [L,1,Sb,...] sub-cache into batch slot `slot`."""
        k = jax.lax.dynamic_update_slice(
            cache["k"], sub_k.astype(cache["k"].dtype), (0, slot, 0, 0, 0)
        )
        v = jax.lax.dynamic_update_slice(
            cache["v"], sub_v.astype(cache["v"].dtype), (0, slot, 0, 0, 0)
        )
        return {"k": k, "v": v}

    @staticmethod
    def _decode_impl(params, cache, last_tok, pos, active, seeds,
                     temp, top_k, top_p, *, cfg):
        """One iteration over every slot: feed last tokens, sample next.
        Each row's key is (seed, position), so completions are reproducible
        no matter which requests share the batch."""
        logits, cache = transformer.decode_step(params, last_tok, pos, cache, cfg)
        keys = jax.vmap(
            lambda s, p: jax.random.fold_in(jax.random.key(s), p + 1)
        )(seeds, pos)
        tok = sample_per_row(logits, keys, temp, top_k, top_p)
        tok = jnp.where(active, tok, cfg.pad_token_id)
        pos = pos + active.astype(jnp.int32)
        return cache, tok, pos

    # --- public API ---------------------------------------------------------

    def submit(
        self, tokens: Sequence[int], params: Optional[SamplingParams] = None
    ) -> "queue.Queue[Optional[dict]]":
        """Enqueue a request. Returns a queue yielding
        {"token": int, "ttft_ms": float?} dicts, then None at end."""
        params = params or SamplingParams()
        if len(tokens) == 0:
            raise ValueError("empty prompt")
        max_prompt = max(self._buckets)
        if len(tokens) > max_prompt:
            raise ValueError(
                f"prompt length {len(tokens)} exceeds max bucket {max_prompt}"
            )
        with self._rid_lock:
            self._rid += 1
            rid = self._rid
        req = _Request(rid, list(tokens), params, queue.Queue(), time.perf_counter())
        with self.stats.lock:
            self.stats.requests += 1
        self._pending.put(req)
        return req.out

    def generate_blocking(
        self, tokens: Sequence[int], params: Optional[SamplingParams] = None
    ) -> Dict[str, Any]:
        """Submit and collect the full completion. Raises RuntimeError if the
        engine failed the request (bad params, decode error)."""
        out = self.submit(tokens, params)
        toks: List[int] = []
        ttft_ms = None
        error = None
        while True:
            item = out.get()
            if item is None:
                break
            if "error" in item:
                error = item["error"]
                continue
            toks.append(item["token"])
            if ttft_ms is None:
                ttft_ms = item.get("ttft_ms")
        if error is not None:
            raise RuntimeError(f"generation failed: {error}")
        return {"token_ids": toks, "ttft_ms": ttft_ms}

    def start(self):
        if self._thread is None:
            self._stop.clear()  # allow stop() -> start() restart
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    # --- scheduler loop -----------------------------------------------------

    def _bucket(self, n: int) -> int:
        for b in self._buckets:
            if n <= b:
                return b
        return self.ecfg.max_seq_len

    def _admit(self) -> None:
        while self._free and not self._pending.empty():
            try:
                req = self._pending.get_nowait()
            except queue.Empty:
                return
            try:
                self._admit_one(req)
            except Exception as e:  # bad request must not kill the loop
                logger.exception("admission failed for request %d", req.rid)
                slot = req.slot
                if slot >= 0:
                    # Reclaim the slot whether or not registration got as
                    # far as self._slots[slot] = req.
                    if self._slots[slot] is req:
                        self._slots[slot] = None
                        self._active = self._active.at[slot].set(False)
                        self._active_host[slot] = False
                    if slot not in self._free:
                        self._free.append(slot)
                req.out.put({"error": str(e)})
                req.out.put(None)

    def _admit_one(self, req: _Request) -> None:
        slot = self._free.pop()
        req.slot = slot
        Sb = self._bucket(len(req.tokens))
        toks = np.full((1, Sb), self.cfg.pad_token_id, np.int32)
        toks[0, : len(req.tokens)] = req.tokens
        plen = jnp.asarray([len(req.tokens)], jnp.int32)
        sp = req.params
        seed = int(sp.seed) & 0xFFFFFFFF  # clamp before jax.random.key
        # First token keyed by (seed, prompt position) — same seed +
        # same prompt reproduces the completion regardless of traffic.
        first, sub_k, sub_v = self._jit_prefill(
            self.params,
            jnp.asarray(toks),
            plen,
            jax.random.fold_in(jax.random.key(seed), len(req.tokens)),
            jnp.asarray([sp.temperature], jnp.float32),
            jnp.asarray([sp.top_k], jnp.int32),
            jnp.asarray([sp.top_p], jnp.float32),
        )
        self._cache = self._jit_insert(self._cache, sub_k, sub_v, slot)
        first_tok = int(np.asarray(first)[0])
        now = time.perf_counter()
        req.first_token_at = now
        ttft_ms = 1000.0 * (now - req.submitted_at)
        with self.stats.lock:
            self.stats.ttft_sum += ttft_ms / 1000.0
            self.stats.ttft_count += 1
            self.stats.tokens_out += 1
        req.n_generated = 1
        self._slots[slot] = req
        req.out.put({"token": first_tok, "ttft_ms": ttft_ms})
        if (
            first_tok == self.cfg.eos_token_id
            or req.params.max_new_tokens <= 1
            or len(req.tokens) + 1 >= self.ecfg.max_seq_len
        ):
            self._finish(slot)
            return
        # Arm the slot for decoding.
        self._last_tok = self._last_tok.at[slot].set(first_tok)
        self._pos = self._pos.at[slot].set(len(req.tokens))
        self._active = self._active.at[slot].set(True)
        self._active_host[slot] = True
        self._temp = self._temp.at[slot].set(sp.temperature)
        self._top_k = self._top_k.at[slot].set(sp.top_k)
        self._top_p = self._top_p.at[slot].set(sp.top_p)
        self._seeds = self._seeds.at[slot].set(np.uint32(seed))

    def _finish(self, slot: int) -> None:
        req = self._slots[slot]
        if req is None:
            return
        req.out.put(None)
        self._slots[slot] = None
        self._active = self._active.at[slot].set(False)
        self._active_host[slot] = False
        self._free.append(slot)
        with self.stats.lock:
            self.stats.completed += 1

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._admit()
            if not self._active_host.any():
                if self._pending.empty():
                    time.sleep(self.ecfg.idle_sleep_s)
                continue
            try:
                self._decode_once()
            except Exception as e:  # fail active requests, keep serving
                logger.exception("decode iteration failed")
                for slot, req in enumerate(self._slots):
                    if req is not None:
                        req.out.put({"error": str(e)})
                        self._finish(slot)

    def _decode_once(self) -> None:
        self._cache, toks, self._pos = self._jit_decode(
            self.params,
            self._cache,
            self._last_tok,
            self._pos,
            self._active,
            self._seeds,
            self._temp,
            self._top_k,
            self._top_p,
        )
        self._last_tok = toks
        toks_host = np.asarray(toks)
        pos_host = np.asarray(self._pos)
        for slot, req in enumerate(self._slots):
            if req is None or not self._active_host[slot]:
                continue
            t = int(toks_host[slot])
            req.out.put({"token": t})
            req.n_generated += 1
            with self.stats.lock:
                self.stats.tokens_out += 1
            if (
                t == self.cfg.eos_token_id
                or req.n_generated >= req.params.max_new_tokens
                or int(pos_host[slot]) >= self.ecfg.max_seq_len - 1
            ):
                self._finish(slot)
