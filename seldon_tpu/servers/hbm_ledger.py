"""HBM ledger: live-byte accounting for device memory, by category.

Where does HBM go?  Three places in this engine: model **weights**
(static, paid at load), the **KV cache** (static reservation — a dense
slot tensor or the paged block pool — plus a *live* fraction actually
holding request state), and transient **workspace** (activations and
logits materialised per dispatch).  The ledger tracks bytes per
category with high-watermarks, served at ``/debug/hbm`` and folded into
``tools/probe_hbm``.

Accounting is arithmetic over shapes the engine already knows —
``nbytes`` over param/cache trees at init, allocator block counts at
snapshot — never a device sync.  Rules of the house:

 * static categories are set once at engine init (``set_static``);
 * live categories register a zero-argument callable (``gauge``)
   evaluated ONLY at snapshot time, so the hot path never touches the
   ledger;
 * workspace is the one hot-path touch: ``note_workspace`` does a
   compare-and-max on a plain float (GIL-atomic) with bytes the
   dispatcher computes from host-side shape math;
 * env-gated ``HBM_LEDGER=1`` via ``from_env()`` -> None off, same
   zero-overhead-off contract as the flight recorder.

``snapshot()`` is the documented ``/debug/hbm`` schema::

    {
      "categories": {
        name: {"bytes": int, "bytes_per_device": int,
               "high_bytes": int, "static": bool}
      },
      "devices": int,                  # mesh devices accounted (1 = chip)
      "total_bytes": int,              # sum of current bytes (mesh-wide)
      "total_bytes_per_device": int,   # sum of per-device bytes
      "total_high_bytes": int,   # sum of per-category high-watermarks
    }

Expected category names: "weights", "kv_cache" (static reservation),
"kv_live" (bytes holding active request state), "prefix_cache",
"workspace".

graftmesh (tp > 1) grows per-device accounting, not a new schema mode:
``set_devices`` records the mesh size, ``set_static``/``gauge`` take an
optional per-device figure (weights: the committed shard bytes; KV:
logical // tp — the head axis shards exactly), and categories without
one report their full bytes per device (replicated / conservative —
workspace and host-gathered prefix KV live whole on every chip).  On a
single chip every ``bytes_per_device`` equals ``bytes``, so the tp=1
payload carries the same numbers it always did, plus the new keys.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, Optional


class HbmLedger:
    """Per-category device byte accounting with high-watermarks."""

    def __init__(self):
        self._static: Dict[str, int] = {}
        self._static_per_device: Dict[str, int] = {}
        self._gauges: Dict[str, Callable[[], int]] = {}
        self._gauge_per_device: Dict[str, Callable[[], int]] = {}
        self._gauge_high: Dict[str, int] = {}
        self._workspace = 0
        self._workspace_high = 0
        self._devices = 1

    def set_devices(self, n: int) -> None:
        """Record the mesh size the per-device figures divide over
        (engine init; 1 = single chip)."""
        self._devices = max(1, int(n))

    def set_static(self, name: str, nbytes: int,
                   per_device: Optional[int] = None) -> None:
        """Record a category whose size is fixed for the engine's life
        (weights, the KV reservation).  `per_device` is the resident
        bytes on EACH mesh device (None = fully replicated: the whole
        category on every chip)."""
        self._static[name] = int(nbytes)
        self._static_per_device[name] = int(
            nbytes if per_device is None else per_device
        )

    def gauge(self, name: str, fn: Callable[[], int],
              per_device_fn: Optional[Callable[[], int]] = None) -> None:
        """Register a live category.  `fn` is called only at snapshot —
        it must be sync-free (host-side counter math, e.g. allocator
        used-blocks x per-block bytes).  `per_device_fn` reports the
        per-mesh-device share (None = replicated: fn's value on every
        chip)."""
        self._gauges[name] = fn
        if per_device_fn is not None:
            self._gauge_per_device[name] = per_device_fn
        self._gauge_high.setdefault(name, 0)

    def note_workspace(self, nbytes: int) -> None:
        """Hot-path: fold one dispatch's transient footprint (padded
        activations + logits, from host shape math) into the workspace
        watermark.  Plain-float max; single scheduler-thread writer."""
        n = int(nbytes)
        self._workspace = n
        if n > self._workspace_high:
            self._workspace_high = n

    def snapshot(self) -> Dict[str, Any]:
        cats: Dict[str, Dict[str, Any]] = {}
        for name, nbytes in self._static.items():
            cats[name] = {"bytes": nbytes,
                          "bytes_per_device":
                              self._static_per_device.get(name, nbytes),
                          "high_bytes": nbytes,
                          "static": True}
        for name, fn in self._gauges.items():
            try:
                n = int(fn())
            except (TypeError, ValueError, AttributeError, KeyError):
                # A gauge reading engine internals mid-teardown may see
                # a half-built object; report what we can.
                n = 0
            pfn = self._gauge_per_device.get(name)
            if pfn is None:
                per_dev = n
            else:
                try:
                    per_dev = int(pfn())
                except (TypeError, ValueError, AttributeError, KeyError):
                    per_dev = 0
            if n > self._gauge_high.get(name, 0):
                self._gauge_high[name] = n
            cats[name] = {"bytes": n,
                          "bytes_per_device": per_dev,
                          "high_bytes": self._gauge_high[name],
                          "static": False}
        cats["workspace"] = {"bytes": self._workspace,
                             "bytes_per_device": self._workspace,
                             "high_bytes": self._workspace_high,
                             "static": False}
        return {
            "categories": cats,
            "devices": self._devices,
            "total_bytes": sum(c["bytes"] for c in cats.values()),
            "total_bytes_per_device": sum(
                c["bytes_per_device"] for c in cats.values()
            ),
            "total_high_bytes": sum(c["high_bytes"] for c in cats.values()),
        }


def from_env() -> Optional[HbmLedger]:
    """Ledger iff HBM_LEDGER=1; None otherwise."""
    if os.environ.get("HBM_LEDGER", "0") not in ("1", "true", "True"):
        return None
    return HbmLedger()
