"""graftsan: env-gated runtime concurrency sanitizer for the engine.

The dynamic half of the concurrency contract whose static half is
``tools/graftlint/lockorder.py``; both consume the same canonical table
(`seldon_tpu.servers.lock_order`), so the acquired-before relation the
two enforcers check can never drift apart.  The static pass proves lock
discipline over code the AST can see; this module catches what it
cannot — orders taken through callbacks, state shared across the
scheduler/fetcher boundary, refcount drift between the allocator, the
prefix trie, and live block tables.

Enabled by ``GRAFTSAN=1`` (never a config field, so manifests cannot
ship it by accident).  When the gate is off, :func:`instrument` returns
None and the engine keeps raw ``threading`` primitives — zero
added code on any hot path.  When on:

 * every engine lock is wrapped in an order-asserting proxy; an
   acquisition that breaks the documented order raises (and records) a
   :class:`GraftsanViolation` carrying TWO stacks — where the held lock
   was taken and where the violating acquisition happened;
 * ``# graftlint: holds(<lock>)`` contracts become runtime asserts via
   :meth:`Sanitizer.assert_holds`;
 * at every scheduler boundary :meth:`Sanitizer.audit` cross-checks the
   block allocator's refcounts against the live request block tables
   plus the paged prefix trie's pins, and the slot array against the
   free list;
 * each response queue enforces the terminal-item protocol (exactly one
   ``None`` sentinel, nothing after it);
 * a seeded interleaving explorer (``GRAFTSAN_SEED``, same
   scheduler/fetcher RNG-split discipline as `chaos.ChaosMonkey`)
   injects tiny sleeps at the chaos hook sites to widen race windows
   deterministically.  The sleeps are timing-only — no scheduling
   decision reads the draws — so greedy token output stays
   bit-identical with the sanitizer on or off.

``make sanitize`` runs the engine-facing tier-1 subset under
``GRAFTSAN=1`` with fixed seeds; a violation report names the invariant
and both participating call sites.
"""

from __future__ import annotations

import collections
import dataclasses
import os
import queue
import random
import threading
import time
import traceback
from typing import Any, Dict, List, Optional

from seldon_tpu.servers.lock_order import edge_violation

_RLOCK_TYPE = type(threading.RLock())


def _stack(skip: int = 2) -> str:
    """Formatted stack of the caller, minus graftsan's own frames."""
    return "".join(traceback.format_stack()[:-skip])


@dataclasses.dataclass
class Violation:
    kind: str  # lock-order | holds | refcount | slot-audit | terminal
    message: str
    stack: str  # where the violation was detected
    other_stack: str = ""  # the conflicting earlier event, when known

    def render(self) -> str:
        out = [f"graftsan [{self.kind}] {self.message}",
               "--- detected at:", self.stack.rstrip()]
        if self.other_stack:
            out += ["--- conflicting event at:", self.other_stack.rstrip()]
        return "\n".join(out)


class GraftsanViolation(AssertionError):
    """Raised at the violating call site; also recorded on the
    sanitizer so soaks can assert a clean run even when the engine's
    failure paths swallow the raise into `_fail_all`."""

    def __init__(self, violation: Violation):
        super().__init__(violation.render())
        self.violation = violation


@dataclasses.dataclass
class _Held:
    name: str
    proxy: "_OrderedLock"
    stack: str


class _OrderedLock:
    """Order-asserting proxy around a ``threading`` lock.  Supports the
    subset of the lock protocol the engine uses (``with``, explicit
    acquire/release, ``locked()``); everything else delegates to the
    wrapped primitive."""

    def __init__(self, san: "Sanitizer", inner: Any, name: str):
        self._san = san
        self._inner = inner
        self.name = name
        self._reentrant = isinstance(inner, _RLOCK_TYPE)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._san._check_acquire(self)
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._san._note_acquired(self)
        return got

    def release(self) -> None:
        self._inner.release()
        self._san._note_released(self)

    def __enter__(self) -> "_OrderedLock":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()


class TerminalQueue(queue.Queue):
    """Response queue asserting the engine's terminal-item protocol:
    exactly one ``None`` sentinel per request, and nothing — token
    burst, error item, or second sentinel — after it.  A violation
    reports both the original sentinel's put site and the late put."""

    def __init__(self, san: "Sanitizer"):
        super().__init__()
        self._san = san
        self._tlock = threading.Lock()  # meta-lock, deliberately raw
        self._terminal_stack: Optional[str] = None

    def put(self, item: Any, *args: Any, **kwargs: Any) -> None:
        with self._tlock:
            if self._terminal_stack is not None:
                what = ("second terminal sentinel" if item is None
                        else f"item {item!r}")
                self._san._fail(Violation(
                    "terminal",
                    f"{what} put after the response stream was already "
                    "terminated",
                    _stack(), self._terminal_stack))
            if item is None:
                self._terminal_stack = _stack()
        super().put(item, *args, **kwargs)


class Sanitizer:
    """One per engine; owns the per-thread held-lock stacks, the
    violation log, and the seeded perturbation RNGs."""

    def __init__(self, seed: int = 0):
        self.seed = seed
        self._tls = threading.local()
        self._vlock = threading.Lock()  # meta-lock, deliberately raw
        self.violations: List[Violation] = []
        # Same split discipline as chaos.ChaosMonkey: scheduler-side
        # draws and fetcher-side draws come from independent streams so
        # sleeping one thread never perturbs the other's sequence.
        self._sched_rng = random.Random(seed)
        self._fetch_rng = random.Random(seed + 1)
        self.audits = 0

    @classmethod
    def from_env(cls) -> Optional["Sanitizer"]:
        if os.environ.get("GRAFTSAN", "0") not in ("1", "true", "yes"):
            return None
        return cls(seed=int(os.environ.get("GRAFTSAN_SEED", "0") or 0))

    # --- lock-order witness -------------------------------------------------

    def wrap_lock(self, lock: Any, name: str) -> _OrderedLock:
        if isinstance(lock, _OrderedLock):
            return lock  # already instrumented (e.g. shared allocator)
        return _OrderedLock(self, lock, name)

    def _held(self) -> List[_Held]:
        st = getattr(self._tls, "held", None)
        if st is None:
            st = self._tls.held = []
        return st

    def _check_acquire(self, proxy: _OrderedLock) -> None:
        held = self._held()
        for h in reversed(held):
            if h.proxy is proxy:
                if proxy._reentrant:
                    return  # legal re-entry
                self._fail(Violation(
                    "lock-order",
                    f"re-acquisition of non-reentrant lock "
                    f"'{proxy.name}' (self-deadlock)",
                    _stack(), h.stack))
        for h in held:
            reason = edge_violation(h.name, proxy.name)
            if reason:
                self._fail(Violation(
                    "lock-order",
                    f"acquiring '{proxy.name}' while holding "
                    f"'{h.name}': {reason}",
                    _stack(), h.stack))

    def _note_acquired(self, proxy: _OrderedLock) -> None:
        self._held().append(_Held(proxy.name, proxy, _stack()))

    def _note_released(self, proxy: _OrderedLock) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i].proxy is proxy:
                del held[i]
                return
        # Released a lock acquired before instrumentation — harmless.

    def assert_holds(self, name: str) -> None:
        """Runtime half of the ``# graftlint: holds(<lock>)`` contract:
        the static pass proves annotated call sites it can see; this
        catches the ones it cannot (callbacks, tests poking privates)."""
        held = self._held()
        if any(h.name == name for h in held):
            return
        self._fail(Violation(
            "holds",
            f"method documented `holds({name})` entered without "
            f"'{name}' held (held: "
            f"{[h.name for h in held] or 'nothing'})",
            _stack()))

    def _fail(self, v: Violation) -> None:
        with self._vlock:
            self.violations.append(v)
        raise GraftsanViolation(v)

    # --- structural audits (caller holds _book) -----------------------------

    def audit(self, engine: Any) -> None:  # graftlint: allow(lock-guard) cross-object audit runs under _book by contract — asserted at entry below
        """Boundary-time cross-structure audit.  The caller holds
        ``_book``, so every structure below is quiescent: the slot array
        must mirror the free list, and (paged engines) every allocator
        refcount must equal live-request table references plus prefix
        trie pins — drift in either direction is a leak or a
        double-free in the making."""
        self.assert_holds("_book")
        self.audits += 1
        occupied = {
            i for i, r in enumerate(engine._slots) if r is not None
        }
        free = engine._free
        B = len(engine._slots)
        if occupied.intersection(free) or len(free) + len(occupied) != B \
                or len(set(free)) != len(free):
            self._fail(Violation(
                "slot-audit",
                f"slot array / free list incoherent: occupied="
                f"{sorted(occupied)} free={sorted(free)} max_slots={B}",
                _stack()))
        if not getattr(engine, "_paged", False):
            return
        expected: collections.Counter = collections.Counter()
        with engine._rid_lock:
            reqs = list(engine._requests.values())
        for r in reqs:
            expected.update(r.block_ids)
        trie = engine._paged_prefix
        if trie is not None:
            expected.update(trie.block_refs())
        actual = engine._allocator.refs_snapshot()
        if dict(expected) != actual:
            leaked = {b: c for b, c in actual.items()
                      if c != expected.get(b, 0) and c > expected.get(b, 0)}
            lost = {b: c for b, c in expected.items()
                    if c != actual.get(b, 0) and c > actual.get(b, 0)}
            self._fail(Violation(
                "refcount",
                "allocator refcounts diverge from live block tables + "
                f"trie pins: over-refed (leak) {leaked or '{}'}, "
                f"under-refed (double free) {lost or '{}'}",
                _stack()))
        # Every non-trash entry a live slot's table row points at must
        # be a block that slot's request actually owns a ref on.
        for i in occupied:
            r = engine._slots[i]
            if not r.block_ids:
                continue
            row = {int(b) for b in engine._table_host[i]} - {0}
            extra = row - set(r.block_ids)
            if extra:
                self._fail(Violation(
                    "refcount",
                    f"slot {i} block table references blocks "
                    f"{sorted(extra)} not owned by request "
                    f"{r.rid} (owned: {sorted(r.block_ids)})",
                    _stack()))

    # --- seeded interleaving explorer ---------------------------------------

    def perturb(self, site: str) -> None:
        """Tiny seeded sleep at a chaos hook site (``dispatch`` /
        ``reap`` on the scheduler thread, ``boundary`` on the fetcher).
        Deterministic per (seed, site sequence); timing-only, so token
        output is unchanged — only thread interleavings move."""
        rng = self._fetch_rng if site == "boundary" else self._sched_rng
        r = rng.random()
        if r < 0.25:
            time.sleep(r * 0.004)  # 0-1 ms, enough to swap a race

    def check(self) -> None:
        """Raise the first recorded violation, if any (soak epilogue)."""
        with self._vlock:
            if self.violations:
                raise GraftsanViolation(self.violations[0])


# --- engine instrumentation --------------------------------------------------

def instrument(engine: Any) -> Optional[Sanitizer]:
    """Wrap an engine's locks and return its Sanitizer, or None when
    GRAFTSAN is off.  Called once at the end of ``__init__``; the lock
    attributes are rebound in place, so every ``with self._book:`` in
    the engine goes through the proxy with no call-site changes."""
    san = Sanitizer.from_env()
    if san is None:
        return None
    engine._book = san.wrap_lock(engine._book, "_book")
    engine._rid_lock = san.wrap_lock(engine._rid_lock, "_rid_lock")
    engine.stats.lock = san.wrap_lock(engine.stats.lock, "stats.lock")
    if engine._chaos is not None:
        engine._chaos._lock = san.wrap_lock(
            engine._chaos._lock, "chaos._lock"
        )
    if engine._prefix is not None:
        engine._prefix._lock = san.wrap_lock(
            engine._prefix._lock, "trie._lock"
        )
    rewrap_pool(engine, san)
    return san


def rewrap_pool(engine: Any, san: Sanitizer) -> None:
    """(Re-)wrap the pool-side locks.  ``_fail_all`` rebuilds the
    allocator and the paged prefix trie wholesale after a wrecked
    dispatch; the fresh objects carry fresh raw locks, so the rebuild
    path calls this again to keep them witnessed."""
    if getattr(engine, "_allocator", None) is not None:
        engine._allocator._lock = san.wrap_lock(
            engine._allocator._lock, "allocator._lock"
        )
    if getattr(engine, "_paged_prefix", None) is not None:
        engine._paged_prefix._lock = san.wrap_lock(
            engine._paged_prefix._lock, "trie._lock"
        )
