"""Prepackaged model servers (reference: /root/reference/servers/).

The flagship is `jaxserver` — the TPU-native citizen the reference never
had (its GPU route was a TensorRT proxy, integrations/nvidia-inference-server/
TRTProxy.py): pjit-sharded transformer inference with slot-based continuous
batching. sklearn/xgboost/mlflow parity servers live alongside.
"""
