"""Static shape lattice: the closed-form model of every jit variant the
engine can dispatch.

The engine keys each jitted entry point on a static-shape tuple (family
tag + bucket / padded-group / resident-width components — the
CompileLedger key).  Three consumers need the SAME answer to "which
keys exist for this config":

 * ``InferenceEngine.warmup()`` iterates :func:`dispatch_keys` and
   compiles each key, so warmup covers exactly what live traffic can
   reach — nothing missing (a live retrace) and nothing extra (warmup
   waste: a multi-second prefill compile no request will ever use);
 * graftlint's shape-lattice certifier (``tools/graftlint/
   shapelattice.py``) cross-checks this closed form against
   :func:`simulate_keys`, an independent operational enumeration of the
   scheduler arithmetic, over a grid of representative configs — a key
   the simulation reaches that the closed form misses is a statically
   proven live retrace;
 * ``tools/compile_audit.py --static-xcheck`` asserts at runtime that
   every key the warmed tiny server actually dispatched is inside
   ``InferenceEngine.static_lattice()``.

Pure host math over ``int``s — no jax import, so the lint pass can load
it on any machine.  Every formula mirrors a named scheduler site in
``servers/engine.py``; drift between the two is exactly what the
certifier exists to catch.

Reachability facts the closed form encodes (each with its engine site):

 * prompts longer than ``max(buckets)`` are rejected at ``submit()``,
   so every live suffix/width bucket is in the bucket tuple — including
   ``max(buckets) == max_seq_len`` when the top bucket fills the cache
   window (``_bucket`` only falls through to ``max_seq_len`` for
   lengths above every bucket, which submit() forbids);
 * prefix matches are trie-block aligned (``prefix_block``) and capped
   at ``plen - 1``, so a (prefix bucket, suffix bucket) pair is live
   only if its minimum block-aligned prefix plus minimum suffix fit in
   one admissible prompt;
 * chunk groups are budget-bound: ``_collect_chunk_work`` subtracts
   each row's chunk bucket from the dispatch token budget, so a
   same-``Sc`` run never exceeds ``budget // Sc`` rows (then pads to
   the next power of two);
 * chunk resident widths are ``bucket(start)`` where ``start`` walks
   ``prefix_len + k * prefill_chunk`` — without a prefix cache only the
   ``k * prefill_chunk`` rungs exist;
 * copy-on-write block copies need a *shared* block, and blocks are
   only ever shared through the paged prefix trie, so ``("cow",)``
   exists only under paged + prefix.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import FrozenSet, List, Sequence, Set, Tuple

Key = Tuple[object, ...]

# Family tag -> full key tuple length (tag included), one entry per
# ``_note_dispatch`` key family in servers/engine.py.  graftlint's
# shape-lattice pass checks every dispatch site against this table, so
# a new jit entry point must register here (and in dispatch_keys /
# simulate_keys) before it can land.
FAMILIES = {
    "deactivate": 1,     # lifecycle-reap freeze, one masked write
    "admit": 3,          # (tag, suffix bucket, padded group)
    "admit-prefix": 4,   # (tag, prefix bucket, suffix bucket, group)
    "admit-paged": 4,    # (tag, suffix bucket, group, prefix width)
    "chunk": 4,          # (tag, chunk bucket, group, resident width)
    "seed-prefix": 2,    # (tag, prefix width)
    "cow": 1,            # copy-on-write block copy (traced scalars)
    "decode": 2,         # (tag, chunk-ladder rung)
    "ragged": 2,         # (tag, per-slot chunk capacity) — the ONE wave
    "draft": 2,          # (tag, spec rung k) — draft-model proposal
    "verify": 2,         # (tag, spec rung k) — the wide verify wave
}


@dataclasses.dataclass(frozen=True)
class LatticeSpec:
    """The shape-relevant slice of an engine's config — everything the
    variant lattice depends on and nothing else.  Built by
    ``InferenceEngine.lattice_spec()``; constructed directly in tests
    and in the certifier's config grid."""

    buckets: Tuple[int, ...]        # ascending, clamped <= max_seq_len
    max_seq_len: int
    max_slots: int
    max_admit: int                  # engine _max_admit (power of two)
    decode_rungs: Tuple[int, ...]   # engine _chunk_sizes
    paged: bool = False
    chunked: bool = False
    prefix: bool = False            # any prefix index (dense or paged)
    prefix_block: int = 16
    chunk_buckets: Tuple[int, ...] = ()   # engine _chunk_buckets
    prefill_chunk: int = 0          # engine _prefill_chunk (clamped C)
    token_budget: int = 0           # dispatch_token_budget or C
    # graftragged (models/ragged_attention.py): every scheduler wave is
    # ONE fused kernel of fixed shape [max_slots, ragged_chunk] —
    # bucketing, pow2 grouping, decode rungs and the whole admit/chunk
    # key space collapse to the single ("ragged", C) variant.
    ragged: bool = False
    ragged_chunk: int = 0           # engine _ragged_chunk (per-slot C)
    # graftspec (models/spec_decode.py): the decode chunk ladder never
    # dispatches — one ("verify", k) rung per pow2 k replaces it, plus
    # the ("draft", k) ladder when a draft checkpoint is resident.
    # Admission families are untouched (spec only changes the decode
    # leg of each boundary).
    spec: bool = False
    spec_rungs: Tuple[int, ...] = ()  # engine _spec_rungs (pow2 1..k)
    spec_draft: bool = False        # draft-model jit ladder exists

    def __post_init__(self):
        if not self.buckets:
            raise ValueError("buckets must be non-empty")
        if tuple(sorted(self.buckets)) != tuple(self.buckets):
            raise ValueError(f"buckets must ascend: {self.buckets}")
        if self.chunked and (not self.chunk_buckets
                             or self.prefill_chunk <= 0
                             or self.token_budget < self.prefill_chunk):
            raise ValueError(
                "chunked spec needs chunk_buckets, prefill_chunk and a "
                "token_budget >= prefill_chunk (EngineConfig validates "
                "the same)"
            )
        if self.ragged and (not self.paged or not self.chunked
                            or self.ragged_chunk <= 0):
            raise ValueError(
                "ragged spec needs paged + chunked engines and a "
                "positive ragged_chunk (EngineConfig validates the same)"
            )
        if self.spec:
            if not self.paged or self.ragged:
                raise ValueError(
                    "spec needs the paged engine and excludes ragged — "
                    "each replaces the decode dispatch (EngineConfig "
                    "validates the same)"
                )
            if not self.spec_rungs or any(
                kk <= 0 or kk & (kk - 1) for kk in self.spec_rungs
            ):
                raise ValueError(
                    f"spec_rungs must be non-empty powers of two: "
                    f"{self.spec_rungs!r}"
                )


def pow2ceil(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def _bucket(buckets: Sequence[int], smax: int, n: int) -> int:
    """engine._bucket: first bucket >= n, else the cache window."""
    for b in buckets:
        if n <= b:
            return b
    return smax


def _chunk_bucket(cbs: Sequence[int], n: int) -> int:
    """engine._chunk_bucket: first chunk rung >= n, else the top rung."""
    for b in cbs:
        if n <= b:
            return b
    return cbs[-1]


def _prev(rungs: Sequence[int], b: int) -> int:
    """The rung below `b` (0 below the first) — the largest length that
    does NOT bucket to `b`."""
    i = list(rungs).index(b)
    return rungs[i - 1] if i else 0


def _align_up(n: int, block: int) -> int:
    return -(-n // block) * block


def _min_prefix(spec: LatticeSpec, pb: int) -> int:
    """Shortest block-aligned prefix length that buckets to `pb`, or a
    value > pb when no aligned length lands in the bucket (then `pb` is
    unreachable as a prefix/width bucket)."""
    lo = _align_up(_prev(spec.buckets, pb) + 1, spec.prefix_block)
    return max(lo, spec.prefix_block)


def _group_rungs(gmax: int) -> List[int]:
    """Padded group sizes produced by groups of 1..gmax rows: the
    engine pads to the next power of two (duplicating the tail row), so
    the padded domain tops out at pow2ceil(gmax), not gmax."""
    out, g = [], 1
    top = pow2ceil(max(1, gmax))
    while g <= top:
        out.append(g)
        g *= 2
    return out


def _chunk_starts(spec: LatticeSpec) -> List[int]:
    """Every chunk start offset live scheduling can produce: chunk k of
    a request resumes at prefix_len + k * prefill_chunk, where
    prefix_len is 0 (cold) or a trie-block multiple (warm).  Bounded by
    max prompt - 1 (the final chunk covers at least one token)."""
    maxp = max(spec.buckets)
    c = spec.prefill_chunk
    starts: Set[int] = set()
    p0s = [0]
    if spec.prefix:
        p0s += list(range(spec.prefix_block, maxp, spec.prefix_block))
    for p0 in p0s:
        s = p0
        while s <= maxp - 1:
            starts.add(s)
            s += c
    return sorted(starts)


def dispatch_keys(spec: LatticeSpec) -> Set[Key]:
    """The closed-form lattice: every static-shape key live scheduling
    can dispatch under `spec`.  warmup() compiles exactly this set."""
    maxp = max(spec.buckets)
    if spec.ragged:
        # graftragged: the whole admit/chunk/decode key space is ONE
        # fixed-shape wave — the lattice is the lifecycle freeze plus
        # the wave itself (plus the traced-scalar CoW copy when the
        # paged prefix trie can share a partially-filled block).
        keys = {("deactivate",), ("ragged", spec.ragged_chunk)}
        if spec.prefix:
            keys.add(("cow",))
        return keys
    keys: Set[Key] = {("deactivate",)}
    if spec.spec:
        # graftspec: the decode ladder never dispatches — the verify
        # rungs (and the draft-model ladder, when resident) stand in.
        keys |= {("verify", kk) for kk in spec.spec_rungs}
        if spec.spec_draft:
            keys |= {("draft", kk) for kk in spec.spec_rungs}
    else:
        keys |= {("decode", n) for n in spec.decode_rungs}
    if spec.paged and spec.prefix:
        keys.add(("cow",))

    if spec.chunked:
        # Resident-width domain: bucket(start) over the reachable chunk
        # starts, with the minimum start per width bounding which chunk
        # buckets still fit in the prompt behind it.
        min_start = {0: 0}
        for s in _chunk_starts(spec):
            if s == 0:
                continue
            w = _bucket(spec.buckets, spec.max_seq_len, s)
            min_start.setdefault(w, s)
        for sc in spec.chunk_buckets:
            min_rem = _prev(spec.chunk_buckets, sc) + 1
            gmax = min(spec.max_admit, spec.max_slots,
                       spec.token_budget // sc)
            if gmax < 1:
                continue
            for w, ms in min_start.items():
                if ms + min_rem > maxp:
                    continue
                for g in _group_rungs(gmax):
                    keys.add(("chunk", sc, g, w))
        if spec.prefix and not spec.paged:
            # Dense warm starts seed the trie KV into the slot cache,
            # one scatter variant per matched-prefix width.
            for w in spec.buckets:
                mp = _min_prefix(spec, w)
                if mp <= w and mp + 1 <= maxp:
                    keys.add(("seed-prefix", w))
        return keys

    groups = _group_rungs(min(spec.max_admit, spec.max_slots))
    if spec.paged:
        for sb in spec.buckets:
            for g in groups:
                keys.add(("admit-paged", sb, g, 0))
                if not spec.prefix:
                    continue
                for w in spec.buckets:
                    mp = _min_prefix(spec, w)
                    if mp <= w and mp + _prev(spec.buckets, sb) + 1 <= maxp:
                        keys.add(("admit-paged", sb, g, w))
        return keys

    for sb in spec.buckets:
        for g in groups:
            keys.add(("admit", sb, g))
    if spec.prefix:
        for pb in spec.buckets:
            mp = _min_prefix(spec, pb)
            if mp > pb:
                continue
            for sb in spec.buckets:
                if mp + _prev(spec.buckets, sb) + 1 > maxp:
                    continue
                for g in groups:
                    keys.add(("admit-prefix", pb, sb, g))
    return keys


def simulate_keys(spec: LatticeSpec) -> Set[Key]:
    """Operational enumeration: walk every (prompt length, block-aligned
    prefix match) pair through the scheduler arithmetic — bucketing,
    chunk walks, budget packing, pow2 group padding — and collect the
    keys it dispatches.  Deliberately written scenario-style (loops over
    concrete lengths, transliterating the engine's code paths) rather
    than as set algebra, so it fails independently of dispatch_keys();
    the certifier's grid check is the two derivations agreeing."""
    maxp = max(spec.buckets)
    smax = spec.max_seq_len
    if spec.ragged:
        # Scenario walk: every prompt, at every prefix-match offset,
        # prefills in ceil(rem / C) waves and decodes one step per
        # wave — and EVERY one of those dispatches is the same fixed
        # [max_slots, ragged_chunk] kernel. Only warm partial-block
        # tails add the CoW copy.
        keys = {("deactivate",)}
        if spec.prefix:
            keys.add(("cow",))
        for plen in range(1, maxp + 1):
            start = 0
            while start < plen:
                keys.add(("ragged", spec.ragged_chunk))  # prefill wave
                start += spec.ragged_chunk
            keys.add(("ragged", spec.ragged_chunk))      # decode wave
        return keys
    keys: Set[Key] = {("deactivate",)}
    if spec.spec:
        # Scenario walk: every boundary's decode leg is ONE verify wave
        # at the rung the pilot currently flies — and the pilot's
        # envelope is the whole ladder, so every rung is reachable
        # (with its draft-model twin when one is resident).
        for kk in spec.spec_rungs:
            keys.add(("verify", kk))
            if spec.spec_draft:
                keys.add(("draft", kk))
    else:
        keys |= {("decode", n) for n in spec.decode_rungs}
    if spec.paged and spec.prefix:
        keys.add(("cow",))

    def prefix_lens(plen: int) -> List[int]:
        # trie matches are block-aligned and capped at plen - 1
        if not spec.prefix:
            return [0]
        return [0] + list(range(spec.prefix_block, plen,
                                spec.prefix_block))

    def admit_groups() -> List[int]:
        gmax = min(spec.max_admit, spec.max_slots)
        return sorted({pow2ceil(g) for g in range(1, gmax + 1)})

    if spec.chunked:
        c = spec.prefill_chunk
        for plen in range(1, maxp + 1):
            for p0 in prefix_lens(plen):
                if p0 and not spec.paged:
                    keys.add(
                        ("seed-prefix", _bucket(spec.buckets, smax, p0))
                    )
                start = p0
                while start < plen:
                    rem = plen - start
                    final = rem <= c
                    sc = _chunk_bucket(spec.chunk_buckets, rem) \
                        if final else c
                    w = 0 if start == 0 \
                        else _bucket(spec.buckets, smax, start)
                    gmax = min(spec.max_admit, spec.max_slots,
                               spec.token_budget // sc)
                    for g in range(1, gmax + 1):
                        keys.add(("chunk", sc, pow2ceil(g), w))
                    start += rem if final else c
        return keys

    for plen in range(1, maxp + 1):
        for p0 in prefix_lens(plen):
            sb = _bucket(spec.buckets, smax, plen - p0)
            if spec.paged:
                w = _bucket(spec.buckets, smax, p0) if p0 else 0
                for g in admit_groups():
                    keys.add(("admit-paged", sb, g, w))
            elif p0:
                pb = _bucket(spec.buckets, smax, p0)
                for g in admit_groups():
                    keys.add(("admit-prefix", pb, sb, g))
            else:
                for g in admit_groups():
                    keys.add(("admit", sb, g))
    return keys


# Warmup / report ordering: lifecycle freeze first, admission families
# in the middle, decode rungs last (matching the historical warmup
# sequence), numeric components ascending within a family.
_FAMILY_RANK = {
    "deactivate": 0, "admit": 1, "admit-prefix": 2, "admit-paged": 3,
    "seed-prefix": 4, "chunk": 5, "cow": 6, "decode": 7, "ragged": 8,
    "draft": 9, "verify": 10,
}

# The dispatch-family set in warmup order — THE exported constant for
# anything that enumerates families (tests, docs, audits). Derived from
# FAMILIES so a new family cannot be registered without appearing here.
FAMILY_TAGS: Tuple[str, ...] = tuple(
    sorted(FAMILIES, key=_FAMILY_RANK.__getitem__))


def warmup_order(keys: Set[Key]) -> List[Key]:
    return sorted(keys, key=lambda k: (_FAMILY_RANK[k[0]], k[1:]))


# --- certifier grid: single source of truth ---------------------------------
# PR 13 and PR 15 each shipped a one-line stale-pin fix because the
# grid size was hand-pinned in two different test files. The component
# constants below ARE the grid; tests derive counts from GRID_COUNT and
# membership from FAMILY_TAGS instead of re-pinning literals.

# (buckets, smax, slots, max_admit, C, budget)
GRID_SHAPES: Tuple[Tuple, ...] = (
    ((32, 128), 256, 8, 8, 64, 64),
    ((32, 128), 128, 8, 8, 64, 64),    # top bucket fills the window
    ((16, 64), 64, 4, 4, 32, 96),      # budget packs 3 chunks
    ((64,), 128, 2, 2, 64, 64),        # single bucket
)
# (paged, chunked, prefix) — the full flag cube.
GRID_FLAG_COMBOS: Tuple[Tuple[bool, bool, bool], ...] = tuple(
    itertools.product((False, True), repeat=3))
# Ragged leg: paged+chunked forced, prefix trie on/off.
GRID_RAGGED_COMBOS: Tuple[bool, ...] = (False, True)
# Spec leg: (chunked, draft-resident), over the first two shapes only.
GRID_SPEC_COMBOS: Tuple[Tuple[bool, bool], ...] = tuple(
    itertools.product((False, True), repeat=2))
GRID_SPEC_SHAPES = 2

GRID_COUNT = (len(GRID_FLAG_COMBOS) * len(GRID_SHAPES)
              + len(GRID_RAGGED_COMBOS) * len(GRID_SHAPES)
              + len(GRID_SPEC_COMBOS) * GRID_SPEC_SHAPES)


def grid() -> List[LatticeSpec]:
    """Representative spec grid for the certifier: all 8 flag combos
    over several bucket shapes, including the top-bucket == cache-window
    case (the historical warmup-width blind spot) and a multi-chunk
    dispatch budget. Built from the GRID_* constants above — len(grid())
    == GRID_COUNT by construction."""
    shapes = GRID_SHAPES
    specs = []
    for paged, chunked, prefix in GRID_FLAG_COMBOS:
        for buckets, smax, slots, ma, c, budget in shapes:
            specs.append(LatticeSpec(
                buckets=buckets, max_seq_len=smax, max_slots=slots,
                max_admit=ma, decode_rungs=(4, 8), paged=paged,
                chunked=chunked, prefix=prefix, prefix_block=16,
                chunk_buckets=tuple(sorted({min(b, c) for b in buckets}
                                           | {c})) if chunked else (),
                prefill_chunk=c if chunked else 0,
                token_budget=budget if chunked else 0,
            ))
    # graftragged collapse: same shapes, paged+chunked forced (the
    # ragged wave's preconditions), with and without the prefix trie.
    for prefix in GRID_RAGGED_COMBOS:
        for buckets, smax, slots, ma, c, budget in shapes:
            specs.append(LatticeSpec(
                buckets=buckets, max_seq_len=smax, max_slots=slots,
                max_admit=ma, decode_rungs=(4, 8), paged=True,
                chunked=True, prefix=prefix, prefix_block=16,
                chunk_buckets=tuple(sorted({min(b, c) for b in buckets}
                                           | {c})),
                prefill_chunk=c, token_budget=budget,
                ragged=True, ragged_chunk=c,
            ))
    # graftspec: the verify/draft ladders replace the decode rungs —
    # paged forced (spec's precondition), crossed with chunked prefill
    # and draft-model residency.
    for chunked, sdraft in GRID_SPEC_COMBOS:
        for buckets, smax, slots, ma, c, budget in shapes[:GRID_SPEC_SHAPES]:
            specs.append(LatticeSpec(
                buckets=buckets, max_seq_len=smax, max_slots=slots,
                max_admit=ma, decode_rungs=(4, 8), paged=True,
                chunked=chunked, prefix=False, prefix_block=16,
                chunk_buckets=tuple(sorted({min(b, c) for b in buckets}
                                           | {c})) if chunked else (),
                prefill_chunk=c if chunked else 0,
                token_budget=budget if chunked else 0,
                spec=True, spec_rungs=(1, 2, 4), spec_draft=sdraft,
            ))
    return specs


def check_spec(spec: LatticeSpec) -> Tuple[List[Key], List[Key]]:
    """(holes, waste) for one spec: holes are operationally reachable
    keys the closed form misses (live retraces in waiting — warmup
    would skip them); waste is closed-form keys the exhaustive
    enumeration never reaches (warmup would compile them for nothing)."""
    closed = dispatch_keys(spec)
    seen = simulate_keys(spec)
    return warmup_order(seen - closed), warmup_order(closed - seen)
