"""XGBoost parity server (reference servers/xgboostserver/xgboostserver/
XGBoostServer.py:10-26: Booster(model_file='model.bst') -> DMatrix predict).

TPU re-execution: the model ships as `model.json` (an xgboost
`get_dump(dump_format='json')` array, optionally wrapped with objective/
base_score) and runs through the vectorized JAX traversal in ops/trees.py —
branchless gathers on the chip instead of CPU pointer-chasing. Native
`model.bst` loads only if xgboost exists in the image (gated)."""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, Optional

import numpy as np

from seldon_tpu.ops import trees
from seldon_tpu.servers.storage import download


class XGBoostServer:
    def __init__(self, model_uri: str = "", objective: str = ""):
        self.model_uri = model_uri
        self.objective = objective
        self.booster = None
        self.ensemble: Optional[trees.TreeEnsemble] = None

    def load(self) -> None:
        local = download(self.model_uri)
        json_path = os.path.join(local, "model.json")
        bst_path = os.path.join(local, "model.bst")
        if os.path.exists(json_path):
            with open(json_path) as f:
                doc = json.load(f)
            if isinstance(doc, dict):  # wrapped form
                dump = doc["trees"]
                self.objective = self.objective or doc.get("objective", "reg")
                base = float(doc.get("base_score", 0.0))
                # xgboost stores base_score for logistic objectives in
                # PROBABILITY space (default 0.5 == margin 0); traversal sums
                # margins, so convert to margin space via logit.
                if "logistic" in (self.objective or "") and 0.0 < base < 1.0:
                    base = float(np.log(base / (1.0 - base)))
            else:
                dump = doc
                base = 0.0
            self.ensemble = trees.from_xgboost_json(dump, base_score=base)
        elif os.path.exists(bst_path):
            try:
                import xgboost as xgb
            except ImportError as e:
                raise RuntimeError(
                    "model.bst needs xgboost (not in this image); export the "
                    "booster with get_dump(dump_format='json') to model.json"
                ) from e
            self.booster = xgb.Booster(model_file=bst_path)
        else:
            raise FileNotFoundError(f"no model.json or model.bst under {local}")

    def predict(self, X: np.ndarray, names: Iterable[str],
                meta: Optional[Dict] = None):
        if self.booster is None and self.ensemble is None:
            self.load()
        X = np.asarray(X, dtype=np.float32)
        if self.ensemble is not None:
            obj = "binary" if "logistic" in (self.objective or "") else "reg"
            return np.asarray(trees.predict(self.ensemble, X, objective=obj))
        import xgboost as xgb

        return self.booster.predict(xgb.DMatrix(X))

    def tags(self) -> Dict:
        return {"server": "xgboostserver",
                "backend": "jax-trees" if self.ensemble is not None else "xgboost"}
